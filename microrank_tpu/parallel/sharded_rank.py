"""Sharded + batched window ranking (shard_map over the 2D mesh).

Layout (see mesh.py): a batch of window graphs is stacked with a leading
window axis; entry arrays are [B, E]. Under shard_map, B splits across the
``windows`` mesh axis (pure data parallelism — zero communication) and E
splits across the ``shard`` axis (each device holds a slice of the COO
entries; one psum per SpMV inside the power iteration combines the dense
partials). The per-op [V] / per-trace [T] arrays are replicated within a
window's shard group — they are the small axes; the entry list is the big
one (SURVEY.md §5 long-context row: the scaling axes of this workload are
T and the nnz, not sequence length).
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..analysis.contracts import contract
from ..config import PageRankConfig, SpectrumConfig
from ..graph.structures import PartitionGraph, WindowGraph
from ..rank_backends.jax_tpu import rank_window_core
from .mesh import SHARD_AXIS, WINDOW_AXIS

try:  # jax >= 0.6 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

# The kernels rank_windows_sharded accepts (one source of truth — the
# pipeline's kernel selection imports this). coo/csr shard the ENTRY
# axes, pcsr shards the PARTITION axis of its binned tables (each device
# scans a contiguous block of source partitions), packed shards the
# TRACE axis.
SHARD_KERNELS = ("coo", "csr", "pcsr", "packed", "packed_bf16", "kind")


def _pad_axis0(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    if arr.shape[0] == size:
        return arr
    out = np.full((size,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def _pad2d(arr: np.ndarray, rows: int, cols: int) -> np.ndarray:
    if arr.shape == (rows, cols):
        return arr
    out = np.zeros((rows, cols), dtype=arr.dtype)
    out[: arr.shape[0], : arr.shape[1]] = arr
    return out


def stack_window_graphs(
    graphs: Sequence[WindowGraph],
    shard_multiple: int = 1,
    trace_multiple: int = 1,
) -> WindowGraph:
    """Stack per-window graphs into one batched WindowGraph.

    Each field is re-padded to the batch maximum (rounded up so the entry
    axes divide ``shard_multiple`` — a shard_map requirement). Padding
    entries carry value 0 and are inert; per-window true extents live in
    the n_* scalars (stacked to [B]).

    ``trace_multiple``: round the trace axis up to this multiple — the
    trace-sharded packed kernel needs t_pad divisible by 8*S (whole
    bitmap BYTES per shard), so pass ``8 * mesh shard size`` there.
    """

    def stack_parts(parts: List[PartitionGraph]) -> PartitionGraph:
        def stack_entry(getter, dtype):
            """Stack one entry-sized field padded to ITS OWN rounded
            batch max — kernel-stripped ([0]-length) fields stay
            zero-length instead of being re-inflated to the sibling
            fields' extent (device_subset's whole point)."""
            arrs = [getter(p) for p in parts]
            size = _round_up(
                max(a.shape[0] for a in arrs), shard_multiple
            )
            return np.stack(
                [_pad_axis0(np.asarray(a, dtype), size) for a in arrs]
            )

        t = _round_up(max(p.kind.shape[0] for p in parts), trace_multiple)
        v = max(p.cov_unique.shape[0] for p in parts)
        # A batch mixing built and placeholder aux views degrades to
        # placeholders (all-or-none per view family; the batched kernel
        # chooser treats 0-sized views as "not available").
        have_csr = all(p.inc_indptr_op.shape[0] for p in parts)
        have_pc = all(p.pc_trace.shape[-1] for p in parts)
        # The two bitmap families degrade independently: the default
        # staging profile strips ss_bits (device rebuilds it from the
        # edge list) while cov_bits stays host-packed.
        have_cov = all(p.cov_bits.shape[1] for p in parts)
        have_ss = all(p.ss_bits.shape[1] for p in parts)
        # Kind-compressed views: the int8 pattern pads 2D like the
        # bitmaps (zero columns are inert); its ss row offsets ride
        # ss_indptr, which a "kind" build fills WITHOUT the other csr
        # views — stack it whenever present, independent of have_csr.
        have_kind = all(p.cov_i8.shape[-1] for p in parts)
        have_ssptr = all(p.ss_indptr.shape[0] for p in parts)
        # indptr re-padding: a row-offset array padded with its last real
        # value keeps every added row an empty range (the arrays end at the
        # true entry count, so repeating indptr[-1] is exact).
        def pad_indptr(arr: np.ndarray, size: int) -> np.ndarray:
            if arr.shape[0] == 0:  # aux="auto" placeholder (no CSR views)
                return np.zeros(0, np.int32)
            if arr.shape[0] == size + 1:
                return arr
            return np.concatenate(
                [arr, np.full(size + 1 - arr.shape[0], arr[-1], arr.dtype)]
            )

        def _pc_targets():
            """(P_target, Epb_target, W_target): the P axis re-tiles the
            (possibly re-padded) trace axis — appended partitions are
            empty (all-padding blocks, inert) — and divides the shard
            count for the sharded pcsr path, which the trace_multiple =
            PCSR_PART_TRACES * shards contract guarantees (t then tiles
            exactly into P partitions). The block/slab widths pad to
            the batch max (zero entries are inert)."""
            from ..graph.build import pcsr_partitions

            return (
                max(
                    pcsr_partitions(t),
                    max(p.pc_trace.shape[0] for p in parts),
                ),
                max(p.pc_trace.shape[1] for p in parts),
                max(p.pc_ell_op.shape[1] for p in parts),
            )

        def stack_pc_tab(getter, dtype):
            if not have_pc:
                return np.zeros((len(parts), 1, 0), dtype)
            p_target, e_target, _ = _pc_targets()
            return np.stack(
                [_pad2d(getter(p).astype(dtype), p_target, e_target)
                 for p in parts]
            )

        def stack_pc_indptr():
            """[P, V+1] block-offset tables: appended partitions are
            all-zero rows (every op an empty [0, 0) range); the op axis
            pads with each row's edge value (appended ops get empty
            ranges at the row's end)."""
            if not have_pc:
                return np.zeros((len(parts), 1, 0), np.int32)
            p_target, _, _ = _pc_targets()
            out = []
            for p in parts:
                arr = np.asarray(p.pc_blk_indptr, dtype=np.int32)
                if arr.shape[1] < v + 1:
                    arr = np.concatenate(
                        [
                            arr,
                            np.repeat(
                                arr[:, -1:], v + 1 - arr.shape[1], axis=1
                            ),
                        ],
                        axis=1,
                    )
                out.append(_pad2d(arr, p_target, v + 1))
            return np.stack(out)

        def stack_pc_ell(getter, dtype):
            if not have_pc:
                return np.zeros((len(parts), 1, 0), dtype)
            _, _, w_target = _pc_targets()
            return np.stack(
                [_pad2d(getter(p).astype(dtype), t, w_target)
                 for p in parts]
            )

        return PartitionGraph(
            inc_op=stack_entry(lambda p: p.inc_op, np.int32),
            inc_trace=stack_entry(lambda p: p.inc_trace, np.int32),
            sr_val=stack_entry(lambda p: p.sr_val, np.float32),
            rs_val=stack_entry(lambda p: p.rs_val, np.float32),
            ss_child=stack_entry(lambda p: p.ss_child, np.int32),
            ss_parent=stack_entry(lambda p: p.ss_parent, np.int32),
            ss_val=stack_entry(lambda p: p.ss_val, np.float32),
            inc_trace_opmajor=(
                stack_entry(lambda p: p.inc_trace_opmajor, np.int32)
                if have_csr
                else np.zeros((len(parts), 0), np.int32)
            ),
            sr_val_opmajor=(
                stack_entry(lambda p: p.sr_val_opmajor, np.float32)
                if have_csr
                else np.zeros((len(parts), 0), np.float32)
            ),
            inc_indptr_op=(
                np.stack([pad_indptr(p.inc_indptr_op, v) for p in parts])
                if have_csr
                else np.zeros((len(parts), 0), np.int32)
            ),
            inc_indptr_trace=(
                np.stack([pad_indptr(p.inc_indptr_trace, t) for p in parts])
                if have_csr
                else np.zeros((len(parts), 0), np.int32)
            ),
            ss_indptr=(
                np.stack([pad_indptr(p.ss_indptr, v) for p in parts])
                if have_ssptr
                else np.zeros((len(parts), 0), np.int32)
            ),
            # Bitmaps: 2D zero-pad is exact (absent rows/traces are 0 bits).
            cov_bits=(
                np.stack(
                    [_pad2d(p.cov_bits, v, (t + 7) // 8) for p in parts]
                )
                if have_cov
                else np.zeros((len(parts), v, 0), np.uint8)
            ),
            ss_bits=(
                np.stack(
                    [_pad2d(p.ss_bits, v, (v + 7) // 8) for p in parts]
                )
                if have_ss
                else np.zeros((len(parts), v, 0), np.uint8)
            ),
            inv_tracelen=np.stack(
                [_pad_axis0(p.inv_tracelen, t) for p in parts]
            ),
            inv_cov_dup=np.stack(
                [_pad_axis0(p.inv_cov_dup, v) for p in parts]
            ),
            inv_outdeg=np.stack(
                [_pad_axis0(p.inv_outdeg, v) for p in parts]
            ),
            kind=np.stack([_pad_axis0(p.kind, t, fill=1) for p in parts]),
            tracelen=np.stack(
                [_pad_axis0(p.tracelen, t, fill=1) for p in parts]
            ),
            cov_unique=np.stack([_pad_axis0(p.cov_unique, v) for p in parts]),
            op_present=np.stack(
                [_pad_axis0(p.op_present, v, fill=False) for p in parts]
            ),
            n_ops=np.stack([p.n_ops for p in parts]),
            n_traces=np.stack([p.n_traces for p in parts]),
            n_inc=np.stack([p.n_inc for p in parts]),
            n_ss=np.stack([p.n_ss for p in parts]),
            n_cols=np.stack([np.int32(p.n_cols) for p in parts]),
            pc_trace=stack_pc_tab(lambda p: p.pc_trace, np.int32),
            pc_sr_val=stack_pc_tab(lambda p: p.pc_sr_val, np.float32),
            pc_blk_indptr=stack_pc_indptr(),
            pc_ell_op=stack_pc_ell(lambda p: p.pc_ell_op, np.int32),
            pc_ell_rs=stack_pc_ell(lambda p: p.pc_ell_rs, np.float32),
            cov_i8=(
                np.stack([_pad2d(p.cov_i8, v, t) for p in parts])
                if have_kind
                else np.zeros((len(parts), v, 0), np.int8)
            ),
        )

    return WindowGraph(
        normal=stack_parts([g.normal for g in graphs]),
        abnormal=stack_parts([g.abnormal for g in graphs]),
    )


def resolve_shard_kernel(graphs, mesh: Mesh, runtime, log=None) -> str:
    """Kernel for a sharded dispatch over ``graphs`` (shared by the
    table runner's batch mode and the dispatch router): an explicit
    shard-capable config wins; otherwise resolve by the views EVERY
    graph in the batch carries (stacking degrades mixed-aux batches to
    the common denominator, so the choice must agree with that: all
    packed -> packed, all csr -> csr, mixed -> coo)."""
    from ..rank_backends.jax_tpu import choose_kernel

    if log is None:
        from ..utils.logging import get_logger

        log = get_logger("microrank_tpu.parallel")
    k = runtime.kernel
    if k in SHARD_KERNELS:
        return k
    if all(
        int(p.cov_bits.shape[-1]) > 0
        for g in graphs
        for p in (g.normal, g.abnormal)
    ):
        # Trace-sharded packed unpacks [V, T/S] coverage blocks plus
        # the replicated [V, V] call bitmap per device — budget-check
        # THAT footprint, not the single-device one. The footprint uses
        # the POST-STACK shapes: stage_sharded re-pads every trace axis
        # to the batch max rounded to 8*S, so the per-device block is
        # that rounded max / S, not each graph's own pad / S.
        from ..graph.build import packed_unpacked_bytes

        s = int(mesh.devices.shape[1])
        budget = runtime.dense_budget_bytes
        t_per_dev = tuple(
            -(-max(int(getattr(g, side).kind.shape[-1]) for g in graphs)
              // (8 * s)) * 8
            for side in ("normal", "abnormal")
        )
        v_max = max(int(g.normal.cov_unique.shape[-1]) for g in graphs)
        fits = packed_unpacked_bytes(v_max, t_per_dev) <= budget
        has_pc = all(
            int(p.pc_trace.shape[-1]) > 0
            for g in graphs
            for p in (g.normal, g.abnormal)
        )
        has_csr = all(
            int(p.inc_indptr_op.shape[-1]) > 0
            for g in graphs
            for p in (g.normal, g.abnormal)
        )
        if fits or not (has_pc or has_csr):
            # Bitmap-only builds (aux="packed") carry no fallback views,
            # so past-budget batches must still take the packed path
            # rather than crash at rank time.
            if not fits:
                log.warning(
                    "sharded packed footprint exceeds dense_budget_bytes "
                    "and no pcsr/CSR views were built; proceeding with "
                    "the packed family — build with aux='all' to enable "
                    "the memory-bounded fallback"
                )
            return "packed_bf16" if runtime.prefer_bf16 else "packed"
        # Past the per-shard packed budget: the partition-centric kernel
        # is the memory-bounded fallback of choice (entry-linear memory,
        # no T-range gathers); legacy csr only when pcsr wasn't built.
        return "pcsr" if has_pc else "csr"
    kernels = {
        choose_kernel(
            g, runtime.dense_budget_bytes, runtime.prefer_bf16
        )
        for g in graphs
    }
    # Without bitmaps choose_kernel only returns csr/coo here.
    return kernels.pop() if len(kernels) == 1 else "coo"


def stage_sharded(graphs, mesh: Mesh, kernel: str):
    """The one staging recipe for every sharded path: strip the arrays
    ``kernel`` never reads, stack with the mesh's shard (and, for
    packed, 8*S trace) alignment, and form global arrays with
    kernel-correct partition specs — global_put handles both
    single-process meshes (a sharded device_put) and multi-host ones
    (each process contributes its addressable shards)."""
    from ..utils.guards import assert_device_owner

    assert_device_owner("parallel.stage_sharded")
    from ..parallel.distributed import global_put
    from ..rank_backends.jax_tpu import device_subset

    from ..graph.build import PCSR_PART_TRACES

    shard_n = int(mesh.devices.shape[1])
    if kernel in ("packed", "packed_bf16"):
        trace_multiple = 8 * shard_n  # whole bitmap BYTES per shard
    elif kernel == "kind":
        # The int8 pattern has byte columns (no bit packing), so the
        # kind axis only needs to divide the shard count.
        trace_multiple = shard_n
    elif kernel == "pcsr":
        # The trace axis must tile exactly into whole source partitions
        # AND whole per-shard partition blocks, so each device's y_r
        # slabs land at its exact global trace offset.
        trace_multiple = PCSR_PART_TRACES * shard_n
    else:
        trace_multiple = 1
    stacked = stack_window_graphs(
        [device_subset(g, kernel) for g in graphs],
        shard_multiple=shard_n,
        trace_multiple=trace_multiple,
    )
    from ..obs.metrics import graph_staging_stats, record_staging

    total, pad = graph_staging_stats(stacked)
    record_staging("sharded", total, len(graphs), pad)
    pspecs = _partition_specs(WINDOW_AXIS, SHARD_AXIS, kernel)
    return global_put(
        stacked, mesh, WindowGraph(normal=pspecs, abnormal=pspecs)
    )


def _partition_specs(
    window_axis, shard_axis, kernel: str = "coo"
) -> PartitionGraph:
    entry = P(window_axis, shard_axis)   # big COO entry axes: sharded
    per_window = P(window_axis)          # [V]/[T]/scalar arrays: replicated
    if kernel == "kind":
        # Kind-column sharding — the trace-sharded packed layout on the
        # int8 pattern: each device holds a [V, K/S] COLUMN block of
        # cov_i8 plus the matching [K/S] blocks of the kind-axis
        # vectors (rv lives sharded through the whole iteration); the
        # ss edge list + row offsets and every [V] array replicate (the
        # O(C) row-sum is replicated work, the kernel's substitute for
        # the replicated b_ss matvec).
        trace = P(window_axis, shard_axis)
        return PartitionGraph(
            inc_op=entry,
            inc_trace=entry,
            sr_val=entry,
            rs_val=entry,
            ss_child=per_window,
            ss_parent=per_window,
            ss_val=per_window,
            inc_trace_opmajor=entry,
            sr_val_opmajor=entry,
            inc_indptr_op=per_window,
            inc_indptr_trace=per_window,
            ss_indptr=per_window,
            cov_bits=per_window,
            ss_bits=per_window,
            inv_tracelen=trace,
            inv_cov_dup=per_window,
            inv_outdeg=per_window,
            kind=trace,
            tracelen=trace,
            cov_unique=per_window,
            op_present=per_window,
            n_ops=per_window,
            n_traces=per_window,
            n_inc=per_window,
            n_ss=per_window,
            n_cols=per_window,
            pc_trace=per_window,
            pc_sr_val=per_window,
            pc_blk_indptr=per_window,
            pc_ell_op=per_window,
            pc_ell_rs=per_window,
            cov_i8=P(window_axis, None, shard_axis),
        )
    if kernel in ("packed", "packed_bf16"):
        # Trace-sharded layout: each device holds a COLUMN block of the
        # coverage bitmap ([V, T8/S] bytes) plus the matching [T/S]
        # blocks of the trace-axis vectors (rv lives sharded through the
        # whole iteration); sv-sized arrays and the call-graph bitmap
        # replicate — including the ss edge list, which the default
        # ss_stage="edges" staging keeps so each device can rebuild the
        # replicated b_ss (pack_edge_bits). The COO incidence arrays are
        # stripped to [B, 0] by device_subset before staging — the entry
        # spec on a zero-length axis is inert.
        trace = P(window_axis, shard_axis)
        return PartitionGraph(
            inc_op=entry,
            inc_trace=entry,
            sr_val=entry,
            rs_val=entry,
            ss_child=per_window,
            ss_parent=per_window,
            ss_val=per_window,
            inc_trace_opmajor=entry,
            sr_val_opmajor=entry,
            inc_indptr_op=per_window,
            inc_indptr_trace=per_window,
            ss_indptr=per_window,
            cov_bits=P(window_axis, None, shard_axis),
            ss_bits=per_window,
            inv_tracelen=trace,
            inv_cov_dup=per_window,
            inv_outdeg=per_window,
            kind=trace,
            tracelen=trace,
            cov_unique=per_window,
            op_present=per_window,
            n_ops=per_window,
            n_traces=per_window,
            n_inc=per_window,
            n_ss=per_window,
            n_cols=per_window,
            pc_trace=per_window,
            pc_sr_val=per_window,
            pc_blk_indptr=per_window,
            pc_ell_op=per_window,
            pc_ell_rs=per_window,
            cov_i8=per_window,
        )
    if kernel == "pcsr":
        # Partition-axis sharding: each device holds a contiguous block
        # of the [P, Ep] binned tables (its per-shard partition table)
        # plus the replicated trace/op vectors; the call-edge list
        # entry-shards like the coo path. Dense [V]/[T] partials psum.
        pc = P(window_axis, shard_axis)
        return PartitionGraph(
            inc_op=entry,
            inc_trace=entry,
            sr_val=entry,
            rs_val=entry,
            ss_child=entry,
            ss_parent=entry,
            ss_val=entry,
            inc_trace_opmajor=entry,
            sr_val_opmajor=entry,
            inc_indptr_op=per_window,
            inc_indptr_trace=per_window,
            ss_indptr=per_window,
            cov_bits=per_window,
            ss_bits=per_window,
            inv_tracelen=per_window,
            inv_cov_dup=per_window,
            inv_outdeg=per_window,
            kind=per_window,
            tracelen=per_window,
            cov_unique=per_window,
            op_present=per_window,
            n_ops=per_window,
            n_traces=per_window,
            n_inc=per_window,
            n_ss=per_window,
            n_cols=per_window,
            pc_trace=pc,
            pc_sr_val=pc,
            pc_blk_indptr=pc,
            pc_ell_op=pc,
            pc_ell_rs=pc,
            cov_i8=per_window,
        )
    return PartitionGraph(
        inc_op=entry,
        inc_trace=entry,
        sr_val=entry,
        rs_val=entry,
        ss_child=entry,
        ss_parent=entry,
        ss_val=entry,
        # The sharded csr kernel reads these: the entry-sized op-major
        # copies block-split across the shard axis like their COO
        # siblings, while the indptrs stay replicated — each device
        # prefix-sums its contiguous entry block and clamps the row
        # ranges to it (jax_tpu.csr_rowsum). The coo kernel ignores them.
        inc_trace_opmajor=entry,
        sr_val_opmajor=entry,
        inc_indptr_op=per_window,
        inc_indptr_trace=per_window,
        ss_indptr=per_window,
        cov_bits=per_window,
        ss_bits=per_window,
        inv_tracelen=per_window,
        inv_cov_dup=per_window,
        inv_outdeg=per_window,
        kind=per_window,
        tracelen=per_window,
        cov_unique=per_window,
        op_present=per_window,
        n_ops=per_window,
        n_traces=per_window,
        n_inc=per_window,
        n_ss=per_window,
        n_cols=per_window,
        pc_trace=per_window,
        pc_sr_val=per_window,
        pc_blk_indptr=per_window,
        pc_ell_op=per_window,
        pc_ell_rs=per_window,
        cov_i8=per_window,
    )


def _validate_sharded_pcsr(batched: WindowGraph, mesh: Mesh) -> None:
    """Static-shape checks for the partition-sharded pcsr dispatch: the
    binned tables must be present, their partition axis must divide the
    shard count, and the trace axis must tile EXACTLY into the
    partitions (a ragged last partition would shift every later shard's
    slab offset) — all guaranteed by stage_sharded's trace_multiple."""
    from ..graph.build import PCSR_PART_TRACES

    shard_n = int(dict(zip(mesh.axis_names, mesh.devices.shape))[SHARD_AXIS])
    for side in ("normal", "abnormal"):
        part = getattr(batched, side)
        if int(part.pc_trace.shape[-1]) == 0:
            raise ValueError(
                "sharded pcsr kernel needs partition-centric graphs — "
                "build with aux='pcsr'/'all'"
            )
        n_parts = int(part.pc_trace.shape[-2])
        t_pad = int(part.kind.shape[-1])
        t_ell = int(part.pc_ell_op.shape[-2])
        if (
            n_parts % shard_n
            or n_parts * PCSR_PART_TRACES != t_pad
            or t_ell != t_pad
            or t_pad % shard_n
        ):
            raise ValueError(
                f"sharded pcsr kernel needs the trace axis tiled by "
                f"whole per-shard partition blocks (t_pad {t_pad}, "
                f"{n_parts} partitions x {PCSR_PART_TRACES}, "
                f"{shard_n} shards); stack with "
                f"trace_multiple={PCSR_PART_TRACES * shard_n}"
            )


@contract(
    batched="windowgraph",
    returns=("int32[B,K]", "float32[B,K]", "int32[B]"),
)
def _rank_windows_sharded_impl(
    batched: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    mesh: Mesh,
    kernel: str = "coo",
):
    """Rank a batch of windows over the 2D (windows, shard) mesh.

    Input arrays carry a leading batch axis B (divisible by the windows
    axis size) with entry axes divisible by the shard axis size — use
    ``stack_window_graphs(graphs, shard_multiple=mesh.shape['shard'])``.
    ``kernel`` must be shard-capable:

    * "coo" — segment-sum partials over sharded entry axes, two psums
      per iteration;
    * "csr" — local-block prefix sums with clamped row ranges (needs
      graphs built with the CSR views, aux="csr"/"all"), two psums;
    * "pcsr" — the partition-centric kernel with its PARTITION axis
      sharded (per-shard partition tables; needs aux="pcsr"/"all"
      graphs stacked with ``trace_multiple = PCSR_PART_TRACES *
      mesh.shape['shard']`` — stage_sharded's recipe), two psums;
    * "packed" / "packed_bf16" — the MXU bitmap kernel with the TRACE
      axis sharded (bitmap column blocks; rv stays distributed), ONE
      psum per iteration. Needs aux="packed"/"all" graphs stacked with
      ``trace_multiple = 8 * mesh.shape['shard']``;
    * "kind" — the kind-compressed kernel with its KIND column axis
      sharded exactly like packed's trace axis (int8 pattern column
      blocks, ONE psum per iteration; the O(C) ss row-sum replicates).
      Needs aux="kind" graphs stacked with
      ``trace_multiple = mesh.shape['shard']``.

    Returns (top_idx [B, k], top_scores [B, k], n_valid [B]).
    """
    if kernel not in SHARD_KERNELS:
        raise ValueError(
            f"kernel {kernel!r} is not shard-capable; use one of "
            f"{SHARD_KERNELS}"
        )
    if kernel == "pcsr":
        _validate_sharded_pcsr(batched, mesh)
    if kernel == "kind":
        shard_n = int(
            dict(zip(mesh.axis_names, mesh.devices.shape))[SHARD_AXIS]
        )
        t_pad = int(batched.normal.kind.shape[-1])
        if int(batched.normal.cov_i8.shape[-1]) == 0:
            raise ValueError(
                "sharded kind kernel needs kind-compressed graphs — "
                "build with aux='kind'"
            )
        if t_pad % shard_n:
            raise ValueError(
                f"sharded kind kernel needs the kind axis divisible by "
                f"the shard count ({shard_n}); stack with "
                f"trace_multiple={shard_n}"
            )
    if kernel in ("packed", "packed_bf16"):
        shard_n = int(dict(zip(mesh.axis_names, mesh.devices.shape))[SHARD_AXIS])
        t_pad = int(batched.normal.kind.shape[-1])
        t8 = int(batched.normal.cov_bits.shape[-1])
        if t8 == 0:
            raise ValueError(
                "sharded packed kernel needs bitmap graphs — build with "
                "aux='packed'/'all'"
            )
        if t_pad % (8 * shard_n) or t8 % shard_n:
            raise ValueError(
                f"sharded packed kernel needs the trace axis divisible "
                f"by 8*shard ({8 * shard_n}); stack with "
                f"trace_multiple={8 * shard_n}"
            )
    specs = _partition_specs(WINDOW_AXIS, SHARD_AXIS, kernel)
    in_specs = (WindowGraph(normal=specs, abnormal=specs),)
    out_specs = (P(WINDOW_AXIS), P(WINDOW_AXIS), P(WINDOW_AXIS))

    def kernel_fn(graph: WindowGraph):
        return jax.vmap(
            lambda g: rank_window_core(
                g, pagerank_cfg, spectrum_cfg, SHARD_AXIS, kernel
            )
        )(graph)

    # check_rep=False: jax (as of 0.4.x) has no replication rule for
    # lax.while_loop, so the convergence-tol path (_iterate) would raise
    # NotImplementedError under the replication checker. The outputs ARE
    # replicated over the shard axis (every partial is psum'd/pmax'd
    # before leaving the kernel), and the parity tests pin the sharded
    # results against the single-device ranking — the check is redundant
    # here and disabling it unblocks tol on meshes.
    return shard_map(
        kernel_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=False,
    )(batched)


# The public sharded programs and their DONATED twins share one traced
# body; donation marks the staged batch's device buffers as consumable
# so XLA may reuse their HBM for outputs/scratch — under the dispatch
# router's double-buffering two staged batches are alive at once, and
# donation caps that at one batch plus the in-flight program's working
# set (the blob path has had this since PR 5; the sharded route only
# grew it in PR 11 — the "untested donation" thread from ROADMAP
# item 3). CPU backends ignore donation with a warning, so the router
# only requests it where it buys the HBM back.
rank_windows_sharded = functools.partial(
    jax.jit, static_argnums=(1, 2, 3, 4)
)(_rank_windows_sharded_impl)

_DONATED_SHARDED_JIT = None
_DONATED_SHARDED_TRACED_JIT = None


def _donated_sharded_jit():
    global _DONATED_SHARDED_JIT
    if _DONATED_SHARDED_JIT is None:
        _DONATED_SHARDED_JIT = jax.jit(
            _rank_windows_sharded_impl,
            static_argnums=(1, 2, 3, 4),
            donate_argnums=(0,),
        )
    return _DONATED_SHARDED_JIT


def _donated_sharded_traced_jit():
    global _DONATED_SHARDED_TRACED_JIT
    if _DONATED_SHARDED_TRACED_JIT is None:
        _DONATED_SHARDED_TRACED_JIT = jax.jit(
            _rank_windows_sharded_traced_impl,
            static_argnums=(1, 2, 3, 4),
            donate_argnums=(0,),
        )
    return _DONATED_SHARDED_TRACED_JIT


def sharded_donated_entry(conv_trace: bool):
    """The donated sharded program for (conv_trace,) — lazily jitted
    once per process (module singletons, like blob.batched_blob_entry)."""
    return (
        _donated_sharded_traced_jit()
        if conv_trace
        else _donated_sharded_jit()
    )


@contract(
    batched="windowgraph",
    returns=(
        "int32[B,K]", "float32[B,K]", "int32[B]", "float32[B,2,I]",
        "int32[B]",
    ),
)
def _rank_windows_sharded_traced_impl(
    batched: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    mesh: Mesh,
    kernel: str = "coo",
):
    """rank_windows_sharded plus the device convergence trace
    (jax_tpu.rank_window_traced_core): two extra outputs —
    residuals [B, 2, iterations] and n_iters [B] — replicated over the
    shard axis by construction (the per-step deltas are pmax'd whenever
    part of the carry is sharded), so the window-axis out_specs are
    sound exactly like the existing three."""
    from ..rank_backends.jax_tpu import rank_window_traced_core

    if kernel not in SHARD_KERNELS:
        raise ValueError(
            f"kernel {kernel!r} is not shard-capable; use one of "
            f"{SHARD_KERNELS}"
        )
    if kernel == "pcsr":
        _validate_sharded_pcsr(batched, mesh)
    specs = _partition_specs(WINDOW_AXIS, SHARD_AXIS, kernel)
    in_specs = (WindowGraph(normal=specs, abnormal=specs),)
    out_specs = tuple(P(WINDOW_AXIS) for _ in range(5))

    def kernel_fn(graph: WindowGraph):
        return jax.vmap(
            lambda g: rank_window_traced_core(
                g, pagerank_cfg, spectrum_cfg, SHARD_AXIS, kernel
            )
        )(graph)

    return shard_map(
        kernel_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=False,
    )(batched)


rank_windows_sharded_traced = functools.partial(
    jax.jit, static_argnums=(1, 2, 3, 4)
)(_rank_windows_sharded_traced_impl)


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
@contract(
    batched="windowgraph",
    returns=(
        "int32[B,K]", "float32[B,K]", "int32[B]", "float32[B,2,I]",
        "int32[B]", "float32[B,4,Ke]", "float32[B,M,Ke]",
        "float32[B,2,Ke]", "int32[B,2,Ke,J]", "float32[B,2,Ke,J]",
    ),
)
def rank_windows_explained_sharded(
    batched: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    explain_cfg,
    mesh: Mesh,
    kernel: str = "coo",
):
    """rank_windows_sharded_traced plus the rank-provenance epilogue
    (explain.extract.rank_window_explained_core) — attribution tensors
    for every window of a sharded batch in the same program. The
    epilogue's contribution matrix is replicated before it leaves the
    kernel (entry-sharded kernels psum their scatter partials; the
    trace-sharded packed kernels all-gather their column blocks), so
    the window-axis out_specs are sound exactly like the rank
    outputs'."""
    from ..explain.extract import rank_window_explained_core

    if kernel not in SHARD_KERNELS:
        raise ValueError(
            f"kernel {kernel!r} is not shard-capable; use one of "
            f"{SHARD_KERNELS}"
        )
    if kernel == "pcsr":
        _validate_sharded_pcsr(batched, mesh)
    specs = _partition_specs(WINDOW_AXIS, SHARD_AXIS, kernel)
    in_specs = (WindowGraph(normal=specs, abnormal=specs),)
    out_specs = tuple(P(WINDOW_AXIS) for _ in range(10))

    def kernel_fn(graph: WindowGraph):
        return jax.vmap(
            lambda g: rank_window_explained_core(
                g, pagerank_cfg, spectrum_cfg, explain_cfg,
                SHARD_AXIS, kernel,
            )
        )(graph)

    return shard_map(
        kernel_fn,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=False,
    )(batched)


# ---------------------------------------------------------------------------
# checkify instrumentation for the sharded path (PR 7). The single-device
# checked programs thread checkify's error state through the whole rank;
# composing checkify directly with shard_map's replication machinery is
# version-fragile, so the sharded checks run as a separate tiny jitted
# EPILOGUE program over the sharded outputs — still device-side, still
# before any host fetch, same invariants as rank_window_checked_traced_core
# (finite live scores, n_valid in [0,k], finite live residuals), just
# per-batch instead of inlined into the iteration program.


def _sharded_checked_core(top_idx, top_scores, n_valid):
    from jax.experimental import checkify

    live = (
        jnp.arange(top_scores.shape[-1])[None, :] < n_valid[:, None]
    )
    checkify.check(
        jnp.all(jnp.where(live, jnp.isfinite(top_scores), True)),
        "non-finite ranked score in a sharded batch "
        "(preference vector or spectrum formula produced NaN/inf)",
    )
    checkify.check(
        jnp.all(
            jnp.logical_and(
                n_valid >= 0, n_valid <= top_scores.shape[-1]
            )
        ),
        "n_valid outside [0, k] in a sharded batch",
    )
    return top_idx, top_scores, n_valid


def _sharded_checked_traced_core(
    top_idx, top_scores, n_valid, residuals, n_iters
):
    from jax.experimental import checkify

    _sharded_checked_core(top_idx, top_scores, n_valid)
    live_it = (
        jnp.arange(residuals.shape[-1])[None, None, :]
        < n_iters[:, None, None]
    )
    checkify.check(
        jnp.all(jnp.where(live_it, jnp.isfinite(residuals), True)),
        "non-finite power-iteration residual in a sharded batch "
        "(the ranking vectors diverged)",
    )
    return top_idx, top_scores, n_valid, residuals, n_iters


_SHARDED_CHECKED_JIT = None
_SHARDED_CHECKED_TRACED_JIT = None


def _sharded_checked_jit():
    global _SHARDED_CHECKED_JIT
    if _SHARDED_CHECKED_JIT is None:
        from jax.experimental import checkify

        _SHARDED_CHECKED_JIT = jax.jit(
            checkify.checkify(
                _sharded_checked_core, errors=checkify.user_checks
            )
        )
    return _SHARDED_CHECKED_JIT


def _sharded_checked_traced_jit():
    global _SHARDED_CHECKED_TRACED_JIT
    if _SHARDED_CHECKED_TRACED_JIT is None:
        from jax.experimental import checkify

        _SHARDED_CHECKED_TRACED_JIT = jax.jit(
            checkify.checkify(
                _sharded_checked_traced_core, errors=checkify.user_checks
            )
        )
    return _SHARDED_CHECKED_TRACED_JIT


@contract(
    batched="windowgraph",
    returns=("int32[B,K]", "float32[B,K]", "int32[B]"),
)
def rank_windows_sharded_checked(
    batched: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    mesh: Mesh,
    kernel: str = "coo",
):
    """rank_windows_sharded plus device-side checkify assertions —
    the sharded twin of ``rank_window_checked`` (RuntimeConfig.
    device_checks finally covers the mesh path). Raises
    ``checkify.JaxRuntimeError`` naming the failed check."""
    from jax.experimental import checkify

    outs = rank_windows_sharded(
        batched, pagerank_cfg, spectrum_cfg, mesh, kernel
    )
    err, outs = _sharded_checked_jit()(*outs)
    checkify.check_error(err)
    return outs


@contract(
    batched="windowgraph",
    returns=(
        "int32[B,K]", "float32[B,K]", "int32[B]", "float32[B,2,I]",
        "int32[B]",
    ),
)
def rank_windows_sharded_checked_traced(
    batched: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    mesh: Mesh,
    kernel: str = "coo",
):
    """rank_windows_sharded_traced plus device-side checkify assertions
    — device_checks AND the convergence trace on the mesh path in one
    dispatch, mirroring rank_window_checked_traced (the PR 6 regression
    test's single-device program)."""
    from jax.experimental import checkify

    outs = rank_windows_sharded_traced(
        batched, pagerank_cfg, spectrum_cfg, mesh, kernel
    )
    err, outs = _sharded_checked_traced_jit()(*outs)
    checkify.check_error(err)
    return outs


def resolve_sharded_rank_fn(
    conv_trace: bool, device_checks: bool, donate: bool = False
):
    """The one (conv, checks, donate) -> sharded-program policy, shared
    by the table lane and the dispatch router so they cannot disagree.
    ``donate`` selects the donated twin of the unchecked programs (the
    staged batch is consumed by the dispatch); the checked paths stay
    undonated — their epilogue jit re-reads nothing, but keeping the
    checked program identical to the long-tested one keeps the
    device_checks debugging path boring."""
    if device_checks:
        return (
            rank_windows_sharded_checked_traced
            if conv_trace
            else rank_windows_sharded_checked
        )
    if donate:
        return sharded_donated_entry(conv_trace)
    return (
        rank_windows_sharded_traced if conv_trace else rank_windows_sharded
    )


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _rank_windows_batched_jit(
    batched: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    kernel: str,
):
    # Module-level jit: cache keys on the config/kernel VALUES, so repeat
    # batches reuse the compilation (a per-call jax.jit(lambda ...) would
    # recompile every invocation — new closure, new cache entry).
    from ..rank_backends.jax_tpu import divide_block_budget

    pagerank_cfg = divide_block_budget(
        pagerank_cfg, kernel, batched.normal.kind.shape[0]
    )
    return jax.vmap(
        lambda g: rank_window_core(
            g, pagerank_cfg, spectrum_cfg, None, kernel
        )
    )(batched)


@contract(
    batched="windowgraph",
    returns=("int32[B,K]", "float32[B,K]", "int32[B]"),
)
def rank_windows_batched(
    batched: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    kernel: str = "auto",
):
    """Single-device vmapped batch ranking (BASELINE.json config 4)."""
    from ..rank_backends.jax_tpu import choose_kernel, device_subset

    if kernel == "auto":
        kernel = choose_kernel(batched)
    return _rank_windows_batched_jit(
        jax.device_put(device_subset(batched, kernel)),
        pagerank_cfg,
        spectrum_cfg,
        kernel,
    )


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _rank_windows_batched_traced_jit(
    batched: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    kernel: str,
):
    from ..rank_backends.jax_tpu import (
        divide_block_budget,
        rank_window_traced_core,
    )

    pagerank_cfg = divide_block_budget(
        pagerank_cfg, kernel, batched.normal.kind.shape[0]
    )
    return jax.vmap(
        lambda g: rank_window_traced_core(
            g, pagerank_cfg, spectrum_cfg, None, kernel
        )
    )(batched)


@contract(
    batched="windowgraph",
    returns=(
        "int32[B,K]", "float32[B,K]", "int32[B]", "float32[B,2,I]",
        "int32[B]",
    ),
)
def rank_windows_batched_traced(
    batched: WindowGraph,
    pagerank_cfg: PageRankConfig,
    spectrum_cfg: SpectrumConfig,
    kernel: str = "auto",
):
    """rank_windows_batched plus per-window convergence traces
    (residuals [B, 2, I], n_iters [B])."""
    from ..rank_backends.jax_tpu import choose_kernel, device_subset

    if kernel == "auto":
        kernel = choose_kernel(batched)
    return _rank_windows_batched_traced_jit(
        jax.device_put(device_subset(batched, kernel)),
        pagerank_cfg,
        spectrum_cfg,
        kernel,
    )
