"""Device-mesh helpers (components C18/C19 — new; the reference has no
parallelism or communication layer at all).

The framework's scaling axes map onto a 2D logical mesh:

* ``windows`` — data parallelism over detection windows (each window's
  ranking is independent: vmap + batch sharding);
* ``shard``  — graph parallelism within a window. The packed kernel
  shards the TRACE axis (bitmap column blocks, distributed rv, one psum
  per iteration); coo/csr shard the COO *entry* axes (dense [V]/[T]
  partials, two psums). On a TPU slice the collectives ride ICI; across
  slices, DCN — both compiled by XLA from the same program (no NCCL/MPI
  analogue needed).

Multi-host: ``parallel.distributed.initialize_distributed()`` (env- or
flag-driven ``jax.distributed.initialize`` — `cli run --distributed`)
before building the mesh; ``jax.devices()`` then spans all hosts and the
ranking code is identical. Proven by a real two-process CPU-mesh test
(tests/test_distributed.py) that must rank bit-identically to the
single-process path.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh

WINDOW_AXIS = "windows"
SHARD_AXIS = "shard"


def make_mesh(
    shape: Tuple[int, ...],
    axes: Tuple[str, ...] = (WINDOW_AXIS, SHARD_AXIS),
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh of the given logical shape.

    Uses ``mesh_utils.create_device_mesh`` when the requested size matches
    the full device count (gets ICI-topology-aware placement on real TPU
    slices); otherwise reshapes an explicit device list.
    """
    if len(shape) != len(axes):
        raise ValueError(f"mesh shape {shape} does not match axes {axes}")
    n = int(np.prod(shape))
    if devices is None:
        devices = jax.devices()
    if n > len(devices):
        raise ValueError(
            f"mesh of {n} devices requested but only {len(devices)} available"
        )
    if n == len(devices):
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(
                shape, devices=list(devices)
            )
            return Mesh(dev_array, axes)
        except Exception:  # pragma: no cover - topology helper unavailable
            pass
    dev_array = np.asarray(list(devices)[:n]).reshape(shape)
    return Mesh(dev_array, axes)


def single_axis_mesh(n: Optional[int] = None, axis: str = SHARD_AXIS) -> Mesh:
    devices = jax.devices()
    if n is None:
        n = len(devices)
    return make_mesh((n,), (axis,), devices[:n])
