// Native span-table loader: mmap CSV -> interned int32 arrays, one pass.
//
// The ingest stage of the framework (reference L1: the traces.csv contract
// of collect_data.py:36-46 / online_rca.py:221-248). The Python path is
// pandas read_csv + three factorize passes + a positional parent lookup;
// this does tokenization, canonical operation naming (including the
// strip-last-URL-segment rule for configured services,
// preprocess_data.py:27-31), vocabulary interning (trace ids, service-level
// ops, pod-level ops), duration/datetime parsing, and ParentSpanId->row
// resolution in a single scan over the memory-mapped file.
//
// Plain C ABI (ctypes-friendly); all output arrays are heap-allocated and
// released with mr_free_table. Strings in vocabularies are returned as one
// concatenated UTF-8 blob plus int64 offsets.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// String interner with an open-addressing probe table keyed by views into
// the growing blob — no per-lookup std::string allocation (the hot path
// runs 3x per span row).
struct Vocab {
  std::string blob;
  std::vector<int64_t> offsets{0};
  std::vector<int32_t> slots;  // id+1; 0 = empty
  size_t mask = 0;

  Vocab() : slots(1024, 0), mask(1023) {}

  static uint64_t hash(std::string_view s) {
    uint64_t h = 1469598103934665603ULL;  // FNV-1a
    for (char c : s) {
      h ^= static_cast<unsigned char>(c);
      h *= 1099511628211ULL;
    }
    return h;
  }

  std::string_view at(int32_t id) const {
    return std::string_view(blob)
        .substr(static_cast<size_t>(offsets[id]),
                static_cast<size_t>(offsets[id + 1] - offsets[id]));
  }

  void grow() {
    std::vector<int32_t> fresh(slots.size() * 2, 0);
    const size_t m = fresh.size() - 1;
    for (int32_t v : slots) {
      if (!v) continue;
      size_t i = hash(at(v - 1)) & m;
      while (fresh[i]) i = (i + 1) & m;
      fresh[i] = v;
    }
    slots.swap(fresh);
    mask = m;
  }

  int32_t intern(std::string_view s) {
    size_t i = hash(s) & mask;
    while (slots[i]) {
      const int32_t id = slots[i] - 1;
      if (at(id) == s) return id;
      i = (i + 1) & mask;
    }
    const int32_t id = static_cast<int32_t>(offsets.size()) - 1;
    blob.append(s.data(), s.size());
    offsets.push_back(static_cast<int64_t>(blob.size()));
    slots[i] = id + 1;
    if ((offsets.size() - 1) * 2 > slots.size()) grow();
    return id;
  }
  size_t size() const { return offsets.size() - 1; }
};

// Days-from-civil (Howard Hinnant's algorithm) -> epoch days.
int64_t days_from_civil(int y, int m, int d) {
  y -= m <= 2;
  const int era = (y >= 0 ? y : y - 399) / 400;
  const unsigned yoe = static_cast<unsigned>(y - era * 400);
  const unsigned doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  const unsigned doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return static_cast<int64_t>(era) * 146097 + static_cast<int64_t>(doe) -
         719468;
}

// Parse "YYYY-MM-DD HH:MM:SS[.frac]" (or 'T' separator) to epoch micros.
// Returns INT64_MIN on failure.
int64_t parse_datetime_us(std::string_view s) {
  if (s.size() < 19) return INT64_MIN;
  auto digit = [](char c) { return c >= '0' && c <= '9'; };
  for (int i : {0, 1, 2, 3, 5, 6, 8, 9, 11, 12, 14, 15, 17, 18})
    if (!digit(s[static_cast<size_t>(i)])) return INT64_MIN;
  int y = (s[0] - '0') * 1000 + (s[1] - '0') * 100 + (s[2] - '0') * 10 +
          (s[3] - '0');
  int mo = (s[5] - '0') * 10 + (s[6] - '0');
  int d = (s[8] - '0') * 10 + (s[9] - '0');
  int h = (s[11] - '0') * 10 + (s[12] - '0');
  int mi = (s[14] - '0') * 10 + (s[15] - '0');
  int se = (s[17] - '0') * 10 + (s[18] - '0');
  int64_t us = (days_from_civil(y, mo, d) * 86400LL +
                h * 3600LL + mi * 60LL + se) *
               1000000LL;
  if (s.size() > 20 && s[19] == '.') {
    int64_t frac = 0;
    int ndig = 0;
    for (size_t i = 20; i < s.size() && ndig < 6; ++i, ++ndig) {
      if (!digit(s[i])) break;
      frac = frac * 10 + (s[i] - '0');
    }
    while (ndig < 6) {
      frac *= 10;
      ++ndig;
    }
    us += frac;
  }
  return us;
}

int64_t parse_int(std::string_view s) {
  int64_t v = 0;
  bool neg = false;
  size_t i = 0;
  if (i < s.size() && (s[i] == '-' || s[i] == '+')) neg = s[i++] == '-';
  for (; i < s.size(); ++i) {
    char c = s[i];
    if (c < '0' || c > '9') break;
    v = v * 10 + (c - '0');
  }
  return neg ? -v : v;
}

struct CsvReader {
  const char* p;
  const char* end;
  std::string scratch;  // for quoted fields with escapes

  // Read one field; returns view (may point into scratch). Sets
  // end_of_line / end_of_file flags.
  std::string_view field(bool& eol, bool& eof) {
    eol = eof = false;
    if (p >= end) {
      eof = true;
      return {};
    }
    if (*p == '"') {
      ++p;
      scratch.clear();
      const char* start = p;
      bool used_scratch = false;
      while (p < end) {
        if (*p == '"') {
          if (p + 1 < end && p[1] == '"') {  // escaped quote
            if (!used_scratch) {
              scratch.assign(start, p - start);
              used_scratch = true;
            } else {
              scratch.append(start, p - start);
            }
            scratch.push_back('"');
            p += 2;
            start = p;
            continue;
          }
          std::string_view out;
          if (used_scratch) {
            scratch.append(start, p - start);
            out = scratch;
          } else {
            out = {start, static_cast<size_t>(p - start)};
          }
          ++p;  // closing quote
          consume_sep(eol, eof);
          return out;
        }
        ++p;
      }
      eof = true;
      return used_scratch ? std::string_view(scratch)
                          : std::string_view(start,
                                             static_cast<size_t>(p - start));
    }
    const char* start = p;
    while (p < end && *p != ',' && *p != '\n' && *p != '\r') ++p;
    std::string_view out{start, static_cast<size_t>(p - start)};
    consume_sep(eol, eof);
    return out;
  }

  void consume_sep(bool& eol, bool& eof) {
    if (p >= end) {
      eof = true;
      return;
    }
    if (*p == ',') {
      ++p;
      return;
    }
    if (*p == '\r') ++p;
    if (p < end && *p == '\n') {
      ++p;
      eol = true;
      if (p >= end) eof = true;
      return;
    }
    if (p >= end) eof = true;
  }
};

struct ColMap {
  int trace = -1, span = -1, parent = -1, opname = -1, service = -1,
      pod = -1, duration = -1, start = -1, endt = -1;
  int n_cols = 0;
};

bool match(std::string_view h, const char* a, const char* b) {
  return h == a || h == b;
}

}  // namespace

extern "C" {

struct MrSpanTable {
  int64_t n_spans;
  // per-span arrays
  int32_t* trace_id;
  int32_t* svc_op;     // service-level operation id (detector/SLO vocab)
  int32_t* pod_op;     // instance-level operation id (PageRank vocab)
  int64_t* duration_us;
  int64_t* start_us;   // trace-level start, epoch micros
  int64_t* end_us;     // trace-level end, epoch micros
  int64_t* parent_row; // row index of the parent span, -1 if absent
  // vocabularies (concatenated blob + offsets, len = n+1)
  char* trace_blob;
  int64_t* trace_offsets;
  int64_t n_traces;
  char* svc_blob;
  int64_t* svc_offsets;
  int64_t n_svc_ops;
  char* pod_blob;
  int64_t* pod_offsets;
  int64_t n_pod_ops;
  char* error;  // non-null on failure
};

static char* dup_error(const std::string& msg) {
  char* e = static_cast<char*>(std::malloc(msg.size() + 1));
  std::memcpy(e, msg.c_str(), msg.size() + 1);
  return e;
}

void mr_free_table(MrSpanTable* t) {
  if (!t) return;
  delete[] t->trace_id;
  delete[] t->svc_op;
  delete[] t->pod_op;
  delete[] t->duration_us;
  delete[] t->start_us;
  delete[] t->end_us;
  delete[] t->parent_row;
  delete[] t->trace_blob;
  delete[] t->trace_offsets;
  delete[] t->svc_blob;
  delete[] t->svc_offsets;
  delete[] t->pod_blob;
  delete[] t->pod_offsets;
  std::free(t->error);
  delete t;
}

// strip_services: comma-separated service names whose operation names lose
// their last '/'-segment (the reference hard-codes "ts-ui-dashboard").
MrSpanTable* mr_load_csv(const char* path, const char* strip_services) {
  auto* out = new MrSpanTable();
  std::memset(out, 0, sizeof(MrSpanTable));

  int fd = ::open(path, O_RDONLY);
  if (fd < 0) {
    out->error = dup_error(std::string("cannot open ") + path);
    return out;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    ::close(fd);
    out->error = dup_error("empty or unreadable file");
    return out;
  }
  size_t size = static_cast<size_t>(st.st_size);
  void* mem = ::mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
  ::close(fd);
  if (mem == MAP_FAILED) {
    out->error = dup_error("mmap failed");
    return out;
  }

  std::unordered_map<std::string, bool> strip;
  {
    std::string_view s(strip_services ? strip_services : "");
    while (!s.empty()) {
      size_t c = s.find(',');
      std::string_view tok = s.substr(0, c);
      if (!tok.empty()) strip.emplace(std::string(tok), true);
      s = (c == std::string_view::npos) ? std::string_view{} : s.substr(c + 1);
    }
  }

  CsvReader r{static_cast<const char*>(mem),
              static_cast<const char*>(mem) + size,
              {}};

  // Header: accept both the raw ClickHouse export names and the canonical
  // renamed schema (online_rca.py:222-232).
  ColMap cols;
  {
    bool eol = false, eof = false;
    int i = 0;
    while (!eol && !eof) {
      std::string_view h = r.field(eol, eof);
      if (match(h, "TraceId", "traceID")) cols.trace = i;
      else if (match(h, "SpanId", "spanID")) cols.span = i;
      else if (match(h, "ParentSpanId", "ParentSpanId")) cols.parent = i;
      else if (match(h, "SpanName", "operationName")) cols.opname = i;
      else if (match(h, "ServiceName", "serviceName")) cols.service = i;
      else if (match(h, "PodName", "podName")) cols.pod = i;
      else if (match(h, "Duration", "duration")) cols.duration = i;
      else if (match(h, "TraceStart", "startTime")) cols.start = i;
      else if (match(h, "TraceEnd", "endTime")) cols.endt = i;
      ++i;
    }
    cols.n_cols = i;
    if (cols.trace < 0 || cols.span < 0 || cols.parent < 0 ||
        cols.opname < 0 || cols.service < 0 || cols.pod < 0 ||
        cols.duration < 0 || cols.start < 0 || cols.endt < 0) {
      ::munmap(mem, size);
      out->error = dup_error("missing required columns in CSV header");
      return out;
    }
  }

  Vocab traces, svc_ops, pod_ops;
  std::unordered_map<std::string, int64_t> span_row;
  std::vector<int32_t> trace_id, svc_op, pod_op;
  std::vector<int64_t> duration_us, start_us, end_us;
  std::vector<std::string> parent_raw_arena;  // parent span ids per row
  std::string name_buf;

  bool eof = false;
  std::vector<std::string_view> fields(static_cast<size_t>(cols.n_cols));
  std::vector<std::string> field_copies(static_cast<size_t>(cols.n_cols));
  while (!eof) {
    bool eol = false;
    int i = 0;
    bool any = false;
    while (!eol && !eof && i < cols.n_cols) {
      std::string_view f = r.field(eol, eof);
      // Quoted fields may point into the shared scratch; copy them.
      if (f.data() == r.scratch.data()) {
        field_copies[static_cast<size_t>(i)].assign(f);
        f = field_copies[static_cast<size_t>(i)];
      }
      fields[static_cast<size_t>(i)] = f;
      any = any || !f.empty();
      ++i;
    }
    // Drain any extra fields on the line.
    while (!eol && !eof) r.field(eol, eof);
    if (!any || i < cols.n_cols) continue;

    int64_t row = static_cast<int64_t>(trace_id.size());
    std::string_view svc = fields[static_cast<size_t>(cols.service)];
    std::string_view op = fields[static_cast<size_t>(cols.opname)];
    std::string_view pod = fields[static_cast<size_t>(cols.pod)];
    std::string_view sp = fields[static_cast<size_t>(cols.span)];
    std::string_view pa = fields[static_cast<size_t>(cols.parent)];

    // Canonical naming (preprocess_data.py:27-31): strip the last
    // '/'-segment of the operation for configured services.
    std::string_view op_eff = op;
    if (!strip.empty() && strip.count(std::string(svc))) {
      size_t slash = op.rfind('/');
      if (slash != std::string_view::npos) op_eff = op.substr(0, slash);
    }
    name_buf.assign(svc.data(), svc.size());
    name_buf.push_back('_');
    name_buf.append(op_eff.data(), op_eff.size());
    svc_op.push_back(svc_ops.intern(name_buf));

    name_buf.assign(pod.data(), pod.size());
    name_buf.push_back('_');
    name_buf.append(op_eff.data(), op_eff.size());
    pod_op.push_back(pod_ops.intern(name_buf));

    trace_id.push_back(traces.intern(fields[static_cast<size_t>(cols.trace)]));
    duration_us.push_back(
        parse_int(fields[static_cast<size_t>(cols.duration)]));
    start_us.push_back(
        parse_datetime_us(fields[static_cast<size_t>(cols.start)]));
    end_us.push_back(parse_datetime_us(fields[static_cast<size_t>(cols.endt)]));

    span_row[std::string(sp)] = row;
    parent_raw_arena.emplace_back(pa);
  }
  ::munmap(mem, size);

  int64_t n = static_cast<int64_t>(trace_id.size());
  auto copy_i32 = [](const std::vector<int32_t>& v) {
    auto* a = new int32_t[v.size()];
    std::memcpy(a, v.data(), v.size() * sizeof(int32_t));
    return a;
  };
  auto copy_i64 = [](const std::vector<int64_t>& v) {
    auto* a = new int64_t[v.size()];
    std::memcpy(a, v.data(), v.size() * sizeof(int64_t));
    return a;
  };

  out->n_spans = n;
  out->trace_id = copy_i32(trace_id);
  out->svc_op = copy_i32(svc_op);
  out->pod_op = copy_i32(pod_op);
  out->duration_us = copy_i64(duration_us);
  out->start_us = copy_i64(start_us);
  out->end_us = copy_i64(end_us);

  out->parent_row = new int64_t[static_cast<size_t>(n)];
  for (int64_t i = 0; i < n; ++i) {
    const std::string& pa = parent_raw_arena[static_cast<size_t>(i)];
    if (pa.empty()) {
      out->parent_row[i] = -1;
      continue;
    }
    auto it = span_row.find(pa);
    out->parent_row[i] = (it == span_row.end()) ? -1 : it->second;
  }

  auto emit_vocab = [](Vocab& v, char** blob, int64_t** offsets,
                       int64_t* count) {
    *blob = new char[v.blob.size() + 1];
    std::memcpy(*blob, v.blob.data(), v.blob.size());
    (*blob)[v.blob.size()] = 0;
    *offsets = new int64_t[v.offsets.size()];
    std::memcpy(*offsets, v.offsets.data(),
                v.offsets.size() * sizeof(int64_t));
    *count = static_cast<int64_t>(v.size());
  };
  emit_vocab(traces, &out->trace_blob, &out->trace_offsets, &out->n_traces);
  emit_vocab(svc_ops, &out->svc_blob, &out->svc_offsets, &out->n_svc_ops);
  emit_vocab(pod_ops, &out->pod_blob, &out->pod_offsets, &out->n_pod_ops);
  return out;
}

}  // extern "C"
