// Native window-graph builder: interned span rows -> COO partition graphs.
//
// The graph-build stage of the framework (reference: get_pagerank_graph
// preprocess_data.py:146-171 plus the matrix fills of pagerank.py:35-52 and
// the kind dedup of pagerank.py:54-66). The numpy lane
// (graph/build.py:_build_partition) is O(n log n) via comparison sorts;
// every id here is a bounded small int (op vocab, window-local trace ids),
// so this builds both partitions in fused single scans: one stats pass
// over the rows for BOTH partitions, a bucket-scatter by trace, small
// in-cache per-trace sorts for the unique-op rows, and one counting sort
// for the call edges — O(n + V + T) total.
//
// Output order is kept identical to the numpy lane (incidence sorted by
// (local trace asc, op asc), call edges by (child asc, parent asc), local
// trace ids assigned in ascending global-id order) so the two lanes are
// array-for-array interchangeable.
//
// Plain C ABI (ctypes-friendly); all output arrays are heap-allocated and
// released with mr_free_window.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

// Splitmix64 finalizer — matches graph/build.py:_splitmix64 so both lanes
// group trace kinds through the same hash prefilter (equality is still
// decided by exact sequence compare below).
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

template <typename T>
T* copy_out(const std::vector<T>& v) {
  T* p = static_cast<T*>(std::malloc(v.size() * sizeof(T) + 1));
  if (p && !v.empty()) std::memcpy(p, v.data(), v.size() * sizeof(T));
  return p;
}

}  // namespace

extern "C" {

struct MrPartition {
  // Unique (trace, op) incidence, sorted by (trace asc, op asc).
  int64_t n_inc;
  int32_t* inc_op;
  int32_t* inc_trace;
  float* sr_val;  // 1 / tracelen_with_dups(trace)   (pagerank.py:42-45)
  float* rs_val;  // 1 / coverage_with_dups(op)      (pagerank.py:48-52)
  // Unique call edges, sorted by (child asc, parent asc).
  int64_t n_ss;
  int32_t* ss_child;
  int32_t* ss_parent;
  float* ss_val;  // 1 / outdeg_with_dups(parent)    (pagerank.py:35-39)
  // Per-local-trace stats.
  int64_t n_traces;
  int32_t* kind;           // kind-class size          (pagerank.py:54-66)
  int32_t* tracelen;       // span count with dups
  int32_t* local_uniques;  // global trace code of local trace i
  // Per-op stats over the full vocab.
  int32_t* cov_unique;  // #traces covering op (unique)
  uint8_t* op_present;
  int64_t n_ops;
};

struct MrWindowGraph {
  MrPartition parts[2];  // [0]=normal, [1]=abnormal
  const char* error;
};

}  // extern "C"

namespace {

// Scratch accumulated for one partition during the fused scans.
struct PartScratch {
  const uint8_t* flags;
  std::vector<int32_t> counts_global;  // [n_total_traces] span counts
  std::vector<int32_t> cov_dup;        // [vocab]
  std::vector<int32_t> outdeg_dup;     // [vocab]
  std::vector<int32_t> edge_child;     // call-edge instances
  std::vector<int32_t> edge_parent;
  int64_t n_p = 0;
};

bool finish_partition(PartScratch& sc, const int32_t* pod_op,
                      const int32_t* trace_id, const uint8_t* row_mask,
                      int64_t n_rows, int64_t n_total_traces, int64_t vocab,
                      MrPartition* out) {
  // Local trace interning in ascending global-id order (np.unique order).
  std::vector<int32_t> local_id(n_total_traces, -1);
  std::vector<int32_t> local_uniques;
  std::vector<int32_t> tracelen;
  for (int64_t t = 0; t < n_total_traces; ++t) {
    if (sc.counts_global[t] > 0) {
      local_id[t] = static_cast<int32_t>(local_uniques.size());
      local_uniques.push_back(static_cast<int32_t>(t));
      tracelen.push_back(sc.counts_global[t]);
    }
  }
  const int64_t n_traces = static_cast<int64_t>(local_uniques.size());

  // Bucket-scatter ops by local trace, then sort each trace's bucket —
  // buckets are small (avg spans/trace), so the sorts stay in cache.
  std::vector<int64_t> tr_off(n_traces + 1, 0);
  for (int64_t t = 0; t < n_traces; ++t) tr_off[t + 1] = tr_off[t] + tracelen[t];
  std::vector<int64_t> cursor(tr_off.begin(), tr_off.end());
  std::vector<int32_t> by_trace_op(sc.n_p);
  for (int64_t r = 0; r < n_rows; ++r) {
    if (row_mask && !row_mask[r]) continue;
    int32_t lt = local_id[trace_id[r]];
    if (lt < 0 || !sc.flags[trace_id[r]]) continue;
    by_trace_op[cursor[lt]++] = pod_op[r];
  }

  // Sort + dedup each trace group -> unique incidence; kind hash inline.
  std::vector<int32_t> inc_op, inc_trace;
  std::vector<float> sr_val;
  std::vector<int32_t> cov_unique(vocab, 0);
  std::vector<int64_t> u_start(n_traces + 1, 0);
  std::vector<uint64_t> trace_hash(n_traces, 0);
  inc_op.reserve(sc.n_p);
  inc_trace.reserve(sc.n_p);
  sr_val.reserve(sc.n_p);
  for (int64_t t = 0; t < n_traces; ++t) {
    int32_t* b = by_trace_op.data() + tr_off[t];
    int32_t* e = by_trace_op.data() + tr_off[t + 1];
    std::sort(b, e);
    const float inv_len = 1.0f / static_cast<float>(tracelen[t]);
    int32_t prev = -1;
    uint64_t h = 0;
    for (int32_t* p = b; p < e; ++p) {
      if (*p == prev) continue;
      prev = *p;
      inc_op.push_back(*p);
      inc_trace.push_back(static_cast<int32_t>(t));
      sr_val.push_back(inv_len);
      ++cov_unique[*p];
      h += splitmix64(static_cast<uint64_t>(*p));
    }
    const int64_t n_uniq = static_cast<int64_t>(inc_op.size()) - u_start[t];
    u_start[t + 1] = static_cast<int64_t>(inc_op.size());
    trace_hash[t] = h ^ splitmix64(static_cast<uint64_t>(tracelen[t])) ^
                    splitmix64(static_cast<uint64_t>(n_uniq) + 0x51ED270B9ULL);
  }
  const int64_t n_inc = static_cast<int64_t>(inc_op.size());
  std::vector<float> rs_val(n_inc);
  for (int64_t i = 0; i < n_inc; ++i)
    rs_val[i] = 1.0f / static_cast<float>(sc.cov_dup[inc_op[i]]);
  int64_t n_ops = 0;
  std::vector<uint8_t> op_present(vocab, 0);
  for (int64_t o = 0; o < vocab; ++o)
    if (cov_unique[o] > 0) {
      op_present[o] = 1;
      ++n_ops;
    }

  // Unique call edges via two-pass stable counting sort of the collected
  // (child, parent) instances: by parent, then by child.
  const int64_t m_p = static_cast<int64_t>(sc.edge_child.size());
  std::vector<int64_t> par_off(vocab + 1, 0);
  for (int64_t p = 0; p < m_p; ++p) ++par_off[sc.edge_parent[p] + 1];
  for (int64_t o = 0; o < vocab; ++o) par_off[o + 1] += par_off[o];
  std::vector<int64_t> pcur(par_off.begin(), par_off.end());
  std::vector<int32_t> by_parent_child(m_p);
  for (int64_t p = 0; p < m_p; ++p)
    by_parent_child[pcur[sc.edge_parent[p]]++] = sc.edge_child[p];
  std::vector<int64_t> ch_off(vocab + 1, 0);
  for (int64_t p = 0; p < m_p; ++p) ++ch_off[by_parent_child[p] + 1];
  for (int64_t o = 0; o < vocab; ++o) ch_off[o + 1] += ch_off[o];
  std::vector<int64_t> ccur(ch_off.begin(), ch_off.end());
  std::vector<int32_t> by_child_parent(m_p);
  {
    int64_t par = 0;
    for (int64_t p = 0; p < m_p; ++p) {
      while (p >= par_off[par + 1]) ++par;
      by_child_parent[ccur[by_parent_child[p]]++] = static_cast<int32_t>(par);
    }
  }
  std::vector<int32_t> ss_child, ss_parent;
  std::vector<float> ss_val;
  {
    int64_t child = 0;
    int32_t prev_parent = -1;
    for (int64_t p = 0; p < m_p; ++p) {
      while (p >= ch_off[child + 1]) {
        ++child;
        prev_parent = -1;
      }
      int32_t par = by_child_parent[p];
      if (par == prev_parent) continue;
      prev_parent = par;
      ss_child.push_back(static_cast<int32_t>(child));
      ss_parent.push_back(par);
      ss_val.push_back(1.0f / static_cast<float>(sc.outdeg_dup[par]));
    }
  }

  // Trace kinds: two traces are one kind iff identical unique-op sequence
  // AND identical span count (== p_sr-column equality, pagerank.py:54-66).
  // Hash prefilter + exact compare on collision — always exact.
  std::vector<int32_t> kind(n_traces, 0);
  {
    std::unordered_map<uint64_t, std::vector<int32_t>> groups;  // hash -> reps
    std::vector<int32_t> group_of(n_traces, -1);
    std::vector<int32_t> group_count;
    groups.reserve(static_cast<size_t>(n_traces) * 2);
    for (int64_t t = 0; t < n_traces; ++t) {
      const int64_t s = u_start[t], e = u_start[t + 1];
      auto& reps = groups[trace_hash[t]];
      int32_t g = -1;
      for (int32_t rep : reps) {
        const int64_t rs = u_start[rep], re = u_start[rep + 1];
        if (re - rs != e - s || tracelen[rep] != tracelen[t]) continue;
        if (std::memcmp(&inc_op[rs], &inc_op[s],
                        static_cast<size_t>(e - s) * sizeof(int32_t)) == 0) {
          g = group_of[rep];
          break;
        }
      }
      if (g < 0) {
        g = static_cast<int32_t>(group_count.size());
        group_count.push_back(0);
        reps.push_back(static_cast<int32_t>(t));
      }
      group_of[t] = g;
      ++group_count[g];
    }
    for (int64_t t = 0; t < n_traces; ++t) kind[t] = group_count[group_of[t]];
  }

  out->n_inc = n_inc;
  out->inc_op = copy_out(inc_op);
  out->inc_trace = copy_out(inc_trace);
  out->sr_val = copy_out(sr_val);
  out->rs_val = copy_out(rs_val);
  out->n_ss = static_cast<int64_t>(ss_child.size());
  out->ss_child = copy_out(ss_child);
  out->ss_parent = copy_out(ss_parent);
  out->ss_val = copy_out(ss_val);
  out->n_traces = n_traces;
  out->kind = copy_out(kind);
  out->tracelen = copy_out(tracelen);
  out->local_uniques = copy_out(local_uniques);
  out->cov_unique = copy_out(cov_unique);
  out->op_present = copy_out(op_present);
  out->n_ops = n_ops;
  return !(out->inc_op == nullptr || out->inc_trace == nullptr ||
           out->sr_val == nullptr || out->rs_val == nullptr ||
           out->ss_child == nullptr || out->ss_parent == nullptr ||
           out->ss_val == nullptr || out->kind == nullptr ||
           out->tracelen == nullptr || out->local_uniques == nullptr ||
           out->cov_unique == nullptr || out->op_present == nullptr);
}

}  // namespace

extern "C" {

MrWindowGraph* mr_build_window(const int32_t* pod_op, const int32_t* trace_id,
                               const int64_t* parent_row, int64_t n_rows,
                               const uint8_t* row_mask,
                               const uint8_t* normal_flag,
                               const uint8_t* abnormal_flag,
                               int64_t n_total_traces, int64_t vocab_size) {
  auto* g = static_cast<MrWindowGraph*>(std::calloc(1, sizeof(MrWindowGraph)));
  if (!g) return nullptr;

  PartScratch sc[2];
  sc[0].flags = normal_flag;
  sc[1].flags = abnormal_flag;
  for (PartScratch& s : sc) {
    s.counts_global.assign(n_total_traces, 0);
    s.cov_dup.assign(vocab_size, 0);
    s.outdeg_dup.assign(vocab_size, 0);
  }

  // Fused stats pass: one scan accumulates BOTH partitions' per-trace
  // counts, per-op duplicate coverage, and call-edge instances
  // (preprocess_data.py:157-158 linkage: child row in the partition,
  // parent span inside the window, parent's trace in the partition).
  for (int64_t r = 0; r < n_rows; ++r) {
    if (row_mask && !row_mask[r]) continue;
    const int32_t t = trace_id[r];
    const int32_t op = pod_op[r];
    const int64_t pr = parent_row[r];
    const bool parent_in_window = pr >= 0 && (!row_mask || row_mask[pr]);
    for (PartScratch& s : sc) {
      if (!s.flags[t]) continue;
      ++s.counts_global[t];
      ++s.cov_dup[op];
      ++s.n_p;
      if (parent_in_window && s.flags[trace_id[pr]]) {
        ++s.outdeg_dup[pod_op[pr]];
        s.edge_child.push_back(op);
        s.edge_parent.push_back(pod_op[pr]);
      }
    }
  }

  g->error = nullptr;
  for (int i = 0; i < 2; ++i)
    if (!finish_partition(sc[i], pod_op, trace_id, row_mask, n_rows,
                          n_total_traces, vocab_size, &g->parts[i]))
      g->error = "allocation failure in mr_build_window";
  return g;
}

void mr_free_window(MrWindowGraph* g) {
  if (!g) return;
  for (MrPartition& p : g->parts) {
    std::free(p.inc_op);
    std::free(p.inc_trace);
    std::free(p.sr_val);
    std::free(p.rs_val);
    std::free(p.ss_child);
    std::free(p.ss_parent);
    std::free(p.ss_val);
    std::free(p.kind);
    std::free(p.tracelen);
    std::free(p.local_uniques);
    std::free(p.cov_unique);
    std::free(p.op_present);
  }
  std::free(g);
}

}  // extern "C"
