// Native window-graph builder: interned span rows -> COO partition graphs.
//
// The graph-build stage of the framework (reference: get_pagerank_graph
// preprocess_data.py:146-171 plus the matrix fills of pagerank.py:35-52 and
// the kind dedup of pagerank.py:54-66). The numpy lane
// (graph/build.py:_build_partition) is O(n log n) via comparison sorts;
// every id here is a bounded small int (op vocab, window-local trace ids),
// so this builds both partitions in fused single scans: one stats pass
// over the rows for BOTH partitions, a bucket-scatter by trace, small
// in-cache per-trace sorts for the unique-op rows, and one counting sort
// for the call edges — O(n + V + T) total.
//
// Two-phase API: mr_build_window2 computes everything and returns an
// opaque handle; mr_window_sizes reports array lengths; mr_export_partition
// copies each partition once, directly into caller-allocated (padded)
// numpy buffers — no intermediate heap copies on either side.
//
// Output order is kept identical to the numpy lane (incidence sorted by
// (local trace asc, op asc), call edges by (child asc, parent asc), local
// trace ids assigned in ascending global-id order) so the two lanes are
// array-for-array interchangeable.

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <exception>
#include <mutex>
#include <new>
#include <optional>
#include <system_error>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// Splitmix64 finalizer (same mixer family as graph/build.py:_splitmix64).
// Only a prefilter here — kind equality is decided by exact compare.
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// MR_BUILD_PROFILE=1 prints per-phase wall times to stderr — the
// profiling hook behind DESIGN.md's build-cost numbers.
bool profile_enabled() {
  static const bool on = [] {
    const char* env = std::getenv("MR_BUILD_PROFILE");
    return env && env[0] == '1';
  }();
  return on;
}

struct PhaseTimer {
  const char* name;
  std::chrono::steady_clock::time_point start;
  explicit PhaseTimer(const char* n)
      : name(n), start(std::chrono::steady_clock::now()) {}
  ~PhaseTimer() {
    if (profile_enabled()) {
      const double ms =
          std::chrono::duration<double, std::milli>(
              std::chrono::steady_clock::now() - start)
              .count();
      std::fprintf(stderr, "[mr_build] %-12s %8.2f ms\n", name, ms);
    }
  }
};

struct BuiltPartition {
  std::vector<int32_t> inc_op, inc_trace;
  std::vector<float> sr_val, rs_val;
  std::vector<int32_t> ss_child, ss_parent;
  std::vector<float> ss_val;
  std::vector<int32_t> kind, tracelen, local_uniques;
  std::vector<int32_t> cov_unique;
  std::vector<uint8_t> op_present;
  int64_t n_ops = 0;
  // Kind grouping (analyze_partition's kinds phase): group id per trace,
  // size per group. Group ids are assigned in first-encounter order over
  // ascending trace ids, so they double as the collapsed column order.
  std::vector<int32_t> group_of;
  std::vector<int32_t> group_count;
  int64_t n_groups = 0;
  // Collapsed emit (emit_partition(collapse=true)): the TRUE trace
  // count — kind/tracelen then hold one entry per kind column
  // (mr_collapse_window reports it). -1 = per-trace layout.
  int64_t n_traces_true = -1;
};

}  // namespace

extern "C" {

// Opaque to callers; errors are signaled by a null handle from
// mr_build_window2 (allocation failure).
struct MrBuiltWindow {
  BuiltPartition parts[2];  // [0]=normal, [1]=abnormal
};

}  // extern "C"

namespace {

// Above this vocab size the per-partition edge bitmap (vocab^2 bits)
// would exceed 32 MB; fall back to the instance-list counting sorts.
// MR_EDGE_BITMAP_MAX_VOCAB overrides (tests force the fallback with 0).
int64_t edge_bitmap_max_vocab() {
  // Re-read per build (a handful of getenv calls) so tests can toggle
  // the fallback without a fresh process.
  if (const char* env = std::getenv("MR_EDGE_BITMAP_MAX_VOCAB"))
    return static_cast<int64_t>(std::atoll(env));
  return 16384;
}

// Scratch accumulated for one partition during the fused scans.
struct PartScratch {
  std::vector<int32_t> counts_global;  // [n_total_traces] span counts
  std::vector<int32_t> cov_dup;        // [vocab]
  std::vector<int32_t> outdeg_dup;     // [vocab]
  // Unique call edges, deduplicated AT SCAN TIME: bit key
  // child*vocab+parent in a child-major bitmap, so the ordered word
  // scan in finish_partition emits (child asc, parent asc) directly —
  // no instance lists, no counting sorts. Empty when vocab is past the
  // bitmap budget; the legacy instance lists below are used instead.
  std::vector<uint64_t> edge_bits;
  std::vector<int32_t> edge_child;     // call-edge instances (fallback)
  std::vector<int32_t> edge_parent;
  std::vector<int32_t> local_id;       // [n_total_traces] global -> local
  std::vector<int64_t> tr_off;         // [n_traces+1] bucket offsets
  std::vector<int32_t> by_trace_op;    // [n_p] ops bucketed by local trace
  int64_t n_p = 0;
  // analyze_partition outputs consumed by emit_partition: unique-op
  // count + set hash per trace, and the unique-entry prefix offsets.
  std::vector<int32_t> n_uniq;
  std::vector<uint64_t> trace_hash;
  std::vector<int64_t> u_start;
};

// Worker count for the intra-partition trace chunks: the hardware
// concurrency (this scales the 4M-span build on real multi-core TPU
// hosts; a 1-core container just runs the serial path), overridable via
// MR_BUILD_THREADS for testing the chunked code on any box.
int build_threads() {
  // Re-read per call so tests can exercise the chunked path without a
  // fresh process; the cost is a few getenv calls per window build.
  if (const char* env = std::getenv("MR_BUILD_THREADS")) {
    const long v = std::atol(env);
    if (v >= 1 && v <= 64) return static_cast<int>(v);
  }
  const unsigned hw = std::thread::hardware_concurrency();
  return static_cast<int>(std::max(1u, std::min(hw, 16u)));
}

// Run fn(lo, hi) over [0, n) split into k contiguous chunks with
// boundaries chosen so each chunk covers ~equal WEIGHT (weights given by
// the monotone prefix array `prefix` of length n+1). k==1 short-circuits
// to a plain call; the first worker exception is rethrown on the caller.
template <typename Fn>
void parallel_chunks(int64_t n, const int64_t* prefix, int k, Fn fn) {
  if (n <= 0) return;
  if (k <= 1 || n < 2 * k) {
    fn(static_cast<int64_t>(0), n);
    return;
  }
  const int64_t total = prefix[n];
  std::vector<int64_t> bounds(k + 1, 0);
  bounds[k] = n;
  for (int i = 1; i < k; ++i) {
    const int64_t target = total * i / k;
    // first index whose prefix exceeds target
    const int64_t* it = std::upper_bound(prefix, prefix + n + 1, target);
    bounds[i] = std::min<int64_t>(it - prefix - 1, n);
  }
  for (int i = 1; i <= k; ++i) bounds[i] = std::max(bounds[i], bounds[i - 1]);
  std::vector<std::thread> pool;
  std::mutex err_mu;
  std::exception_ptr first_err;
  auto record = [&](std::exception_ptr e) {
    std::lock_guard<std::mutex> lk(err_mu);
    if (!first_err) first_err = e;
  };
  pool.reserve(k - 1);
  try {
    for (int i = 1; i < k; ++i) {
      pool.emplace_back([&, i] {
        try {
          fn(bounds[i], bounds[i + 1]);
        } catch (...) {
          record(std::current_exception());
        }
      });
    }
  } catch (...) {
    // Thread creation failed mid-loop (EAGAIN under resource
    // exhaustion). Joinable threads in `pool` would std::terminate in
    // the vector destructor during unwind — join them first, then let
    // the caller's system_error fallback engage.
    record(std::current_exception());
  }
  try {
    fn(bounds[0], bounds[1]);
  } catch (...) {
    record(std::current_exception());
  }
  for (auto& th : pool) th.join();
  if (first_err) std::rethrow_exception(first_err);
}

void analyze_partition(PartScratch& sc, int64_t vocab, BuiltPartition* out) {
  const int64_t n_traces = static_cast<int64_t>(out->local_uniques.size());
  auto& tracelen = out->tracelen;
  const std::vector<int64_t>& tr_off = sc.tr_off;
  std::vector<int32_t>& by_trace_op = sc.by_trace_op;

  // Pass 1 — per-trace sort + IN-PLACE dedup + kind hash. Buckets are
  // disjoint, so trace chunks run on the thread pool (chunk boundaries
  // balanced by span counts via tr_off; the per-trace sorts are the
  // single-core hot spot at the 4M-span scale).
  auto& n_uniq = sc.n_uniq;
  auto& trace_hash = sc.trace_hash;
  n_uniq.assign(n_traces, 0);
  trace_hash.assign(n_traces, 0);
  // RAII phase scopes: .emplace() prints the previous phase (destructor)
  // and starts the next; unwinding destroys the active one.
  std::optional<PhaseTimer> tm;
  if (profile_enabled()) tm.emplace("sort+dedup");
  parallel_chunks(
      n_traces, tr_off.data(), build_threads(),
      [&](int64_t t0, int64_t t1) {
        for (int64_t t = t0; t < t1; ++t) {
          int32_t* b = by_trace_op.data() + tr_off[t];
          int32_t* e = by_trace_op.data() + tr_off[t + 1];
          std::sort(b, e);
          int32_t* w = b;
          int32_t prev = -1;
          uint64_t h = 0;
          for (int32_t* p = b; p < e; ++p) {
            if (*p == prev) continue;
            prev = *p;
            *w++ = *p;
            h += splitmix64(static_cast<uint64_t>(*p));
          }
          const int64_t uq = w - b;
          n_uniq[t] = static_cast<int32_t>(uq);
          trace_hash[t] =
              h ^ splitmix64(static_cast<uint64_t>(tracelen[t])) ^
              splitmix64(static_cast<uint64_t>(uq) + 0x51ED270B9ULL);
        }
      });

  if (profile_enabled()) tm.emplace("cov+kinds");

  // Unique-coverage histogram straight from the deduped buckets (the
  // emit may be collapsed, so the per-trace entries can't be counted
  // from the output arrays).
  auto& u_start = sc.u_start;
  u_start.assign(n_traces + 1, 0);
  for (int64_t t = 0; t < n_traces; ++t)
    u_start[t + 1] = u_start[t] + n_uniq[t];
  out->cov_unique.assign(vocab, 0);
  auto& cov_unique = out->cov_unique;
  for (int64_t t = 0; t < n_traces; ++t) {
    const int32_t* b = by_trace_op.data() + tr_off[t];
    for (int32_t j = 0; j < n_uniq[t]; ++j) ++cov_unique[b[j]];
  }
  out->op_present.assign(vocab, 0);
  for (int64_t o = 0; o < vocab; ++o)
    if (cov_unique[o] > 0) {
      out->op_present[o] = 1;
      ++out->n_ops;
    }

  // Trace kinds: two traces are one kind iff identical unique-op
  // sequence AND identical span count (== p_sr-column equality,
  // pagerank.py:54-66). Hash prefilter + exact bucket compare on
  // collision — always exact. Group ids in first-encounter order.
  {
    std::unordered_map<uint64_t, std::vector<int32_t>> groups;
    auto& group_of = out->group_of;
    auto& group_count = out->group_count;
    group_of.assign(n_traces, -1);
    group_count.clear();
    groups.reserve(static_cast<size_t>(n_traces) * 2);
    for (int64_t t = 0; t < n_traces; ++t) {
      auto& reps = groups[trace_hash[t]];
      int32_t g = -1;
      for (int32_t rep : reps) {
        if (n_uniq[rep] != n_uniq[t] || tracelen[rep] != tracelen[t])
          continue;
        if (std::memcmp(by_trace_op.data() + tr_off[rep],
                        by_trace_op.data() + tr_off[t],
                        static_cast<size_t>(n_uniq[t]) *
                            sizeof(int32_t)) == 0) {
          g = group_of[rep];
          break;
        }
      }
      if (g < 0) {
        g = static_cast<int32_t>(group_count.size());
        group_count.push_back(0);
        reps.push_back(static_cast<int32_t>(t));
      }
      group_of[t] = g;
      ++group_count[g];
    }
    out->n_groups = static_cast<int64_t>(group_count.size());
  }
}

void emit_partition(PartScratch& sc, BuiltPartition* out, bool collapse) {
  const int64_t n_traces = static_cast<int64_t>(out->local_uniques.size());
  const std::vector<int64_t>& tr_off = sc.tr_off;
  const std::vector<int32_t>& by_trace_op = sc.by_trace_op;
  const std::vector<int32_t>& n_uniq = sc.n_uniq;
  const std::vector<int64_t>& u_start = sc.u_start;
  auto& tracelen = out->tracelen;
  auto& inc_op = out->inc_op;
  auto& inc_trace = out->inc_trace;
  auto& sr_val = out->sr_val;
  auto& rs_val = out->rs_val;
  std::optional<PhaseTimer> tm;
  if (profile_enabled()) tm.emplace(collapse ? "emit-collapsed" : "emit");

  if (collapse) {
    // Emit ONE column per kind group, multiplicity folded into the
    // forward value (m/len in double, cast once — the numpy lane's
    // exact arithmetic). The 1M-entry per-trace emit never happens.
    const int64_t n_groups = out->n_groups;
    std::vector<int32_t> rep(n_groups, -1);
    for (int64_t t = 0; t < n_traces; ++t)
      if (rep[out->group_of[t]] < 0)
        rep[out->group_of[t]] = static_cast<int32_t>(t);
    int64_t n_inc = 0;
    for (int64_t g = 0; g < n_groups; ++g) n_inc += n_uniq[rep[g]];
    inc_op.resize(n_inc);
    inc_trace.resize(n_inc);
    sr_val.resize(n_inc);
    rs_val.resize(n_inc);
    std::vector<int32_t> new_kind(n_groups), new_len(n_groups);
    int64_t w = 0;
    for (int64_t g = 0; g < n_groups; ++g) {
      const int32_t r = rep[g];
      const float sr = static_cast<float>(
          static_cast<double>(out->group_count[g]) /
          static_cast<double>(tracelen[r]));
      const int32_t* b = by_trace_op.data() + tr_off[r];
      for (int32_t j = 0; j < n_uniq[r]; ++j, ++w) {
        const int32_t op = b[j];
        inc_op[w] = op;
        inc_trace[w] = static_cast<int32_t>(g);
        sr_val[w] = sr;
        rs_val[w] = 1.0f / static_cast<float>(sc.cov_dup[op]);
      }
      new_kind[g] = out->group_count[g];
      new_len[g] = tracelen[r];
    }
    out->kind.swap(new_kind);
    tracelen.swap(new_len);
    out->n_traces_true = n_traces;
    return;
  }

  const int64_t n_inc = u_start[n_traces];
  inc_op.resize(n_inc);
  inc_trace.resize(n_inc);
  sr_val.resize(n_inc);
  rs_val.resize(n_inc);
  parallel_chunks(
      n_traces, u_start.data(), build_threads(),
      [&](int64_t t0, int64_t t1) {
        for (int64_t t = t0; t < t1; ++t) {
          const int32_t* b = by_trace_op.data() + tr_off[t];
          const float inv_len = 1.0f / static_cast<float>(tracelen[t]);
          int64_t w = u_start[t];
          for (int32_t j = 0; j < n_uniq[t]; ++j, ++w) {
            const int32_t op = b[j];
            inc_op[w] = op;
            inc_trace[w] = static_cast<int32_t>(t);
            sr_val[w] = inv_len;
            rs_val[w] = 1.0f / static_cast<float>(sc.cov_dup[op]);
          }
        }
      });
  out->kind.assign(n_traces, 0);
  for (int64_t t = 0; t < n_traces; ++t)
    out->kind[t] = out->group_count[out->group_of[t]];
}

void edges_partition(PartScratch& sc, int64_t vocab, BuiltPartition* out) {
  std::optional<PhaseTimer> tm;
  if (profile_enabled()) tm.emplace("edges");

  if (!sc.edge_bits.empty()) {
    // Edges were deduplicated at scan time into the child-major bitmap;
    // an ascending word/bit scan IS (child asc, parent asc) order —
    // matching the numpy lane's packed-key np.unique.
    const int64_t n_words = static_cast<int64_t>(sc.edge_bits.size());
    for (int64_t w = 0; w < n_words; ++w) {
      uint64_t bits = sc.edge_bits[w];
      while (bits) {
        const int b = __builtin_ctzll(bits);
        bits &= bits - 1;
        const int64_t key = (w << 6) | b;
        const int32_t child = static_cast<int32_t>(key / vocab);
        const int32_t par = static_cast<int32_t>(key % vocab);
        out->ss_child.push_back(child);
        out->ss_parent.push_back(par);
        out->ss_val.push_back(1.0f /
                              static_cast<float>(sc.outdeg_dup[par]));
      }
    }
  } else {
    // Fallback (vocab past the bitmap budget): two-pass stable counting
    // sort of the (child, parent) instances — by parent, then by child.
    const int64_t m_p = static_cast<int64_t>(sc.edge_child.size());
    std::vector<int64_t> par_off(vocab + 1, 0);
    for (int64_t p = 0; p < m_p; ++p) ++par_off[sc.edge_parent[p] + 1];
    for (int64_t o = 0; o < vocab; ++o) par_off[o + 1] += par_off[o];
    std::vector<int64_t> pcur(par_off.begin(), par_off.end());
    std::vector<int32_t> by_parent_child(m_p);
    for (int64_t p = 0; p < m_p; ++p)
      by_parent_child[pcur[sc.edge_parent[p]]++] = sc.edge_child[p];
    std::vector<int64_t> ch_off(vocab + 1, 0);
    for (int64_t p = 0; p < m_p; ++p) ++ch_off[by_parent_child[p] + 1];
    for (int64_t o = 0; o < vocab; ++o) ch_off[o + 1] += ch_off[o];
    std::vector<int64_t> ccur(ch_off.begin(), ch_off.end());
    std::vector<int32_t> by_child_parent(m_p);
    {
      int64_t par = 0;
      for (int64_t p = 0; p < m_p; ++p) {
        while (p >= par_off[par + 1]) ++par;
        by_child_parent[ccur[by_parent_child[p]]++] =
            static_cast<int32_t>(par);
      }
    }
    {
      int64_t child = 0;
      int32_t prev_parent = -1;
      for (int64_t p = 0; p < m_p; ++p) {
        while (p >= ch_off[child + 1]) {
          ++child;
          prev_parent = -1;
        }
        const int32_t par = by_child_parent[p];
        if (par == prev_parent) continue;
        prev_parent = par;
        out->ss_child.push_back(static_cast<int32_t>(child));
        out->ss_parent.push_back(par);
        out->ss_val.push_back(1.0f /
                              static_cast<float>(sc.outdeg_dup[par]));
      }
    }
  }

}

}  // namespace

extern "C" {

// ``collapse_mode``: 0 = per-trace layout, 1 = kind-collapse when the
// combined trace axis shrinks (graph/build.py collapse="auto"), 2 =
// always collapse. Collapsing happens BEFORE the incidence emit, so the
// per-trace entry arrays are never materialized.
//
// ``parent_base``: value subtracted from each parent_row entry to map it
// into this call's row space (callers passing a [lo, hi) table slice
// hand the ABSOLUTE parent rows + lo; remapping inline here replaced an
// O(window) numpy pass that cost more than the whole build). Out-of-
// range results — absent parents (-1 absolute) and parents outside the
// slice — drop the edge exactly like the old -1 convention.
MrBuiltWindow* mr_build_window2(const int32_t* pod_op, const int32_t* trace_id,
                                const int64_t* parent_row, int64_t n_rows,
                                const uint8_t* row_mask,
                                const uint8_t* normal_flag,
                                const uint8_t* abnormal_flag,
                                int64_t n_total_traces, int64_t vocab_size,
                                int32_t collapse_mode, int64_t parent_base) {
  MrBuiltWindow* g = nullptr;
  try {
    g = new MrBuiltWindow();

    // Combined membership code per global trace: bit0=normal, bit1=abnormal
    // (one cache line probe per row instead of two).
    std::vector<uint8_t> part_bit(n_total_traces);
    for (int64_t t = 0; t < n_total_traces; ++t)
      part_bit[t] =
          static_cast<uint8_t>((normal_flag[t] != 0) | ((abnormal_flag[t] != 0) << 1));

    // Bitmap-vs-instance-list choice: the bitmap wins when its memset
    // (vocab^2/64 words per partition) is small next to the rows it
    // dedups — a small masked window over a big table vocab must NOT
    // pay a fixed multi-MB clear per build, so require the word count
    // to stay within 8x the effective row count.
    int64_t n_eff = n_rows;
    if (row_mask) {
      n_eff = 0;
      for (int64_t r = 0; r < n_rows; ++r) n_eff += row_mask[r] != 0;
    }
    const int64_t bitmap_words = (vocab_size * vocab_size + 63) / 64;
    const bool edge_bitmap = vocab_size <= edge_bitmap_max_vocab() &&
                             bitmap_words <= n_eff * 8;
    PartScratch sc[2];
    for (PartScratch& s : sc) {
      s.counts_global.assign(n_total_traces, 0);
      s.cov_dup.assign(vocab_size, 0);
      s.outdeg_dup.assign(vocab_size, 0);
      if (edge_bitmap) {
        s.edge_bits.assign(static_cast<size_t>(bitmap_words), 0);
      } else if (!row_mask) {  // full-table: edges ~ rows; windows grow
        s.edge_child.reserve(n_rows / 2);
        s.edge_parent.reserve(n_rows / 2);
      }
    }

    // Fused stats pass: one scan accumulates BOTH partitions' per-trace
    // counts, per-op duplicate coverage, and call-edge instances
    // (preprocess_data.py:157-158 linkage: child row in the partition,
    // parent span inside the window, parent's trace in the partition).
    std::optional<PhaseTimer> tm_scan;
    if (profile_enabled()) tm_scan.emplace("stats-scan");
    for (int64_t r = 0; r < n_rows; ++r) {
      if (row_mask && !row_mask[r]) continue;
      const int32_t t = trace_id[r];
      const uint8_t code = part_bit[t];
      if (!code) continue;
      const int32_t op = pod_op[r];
      const int64_t pr = parent_row[r] - parent_base;
      const auto record_edge = [&](PartScratch& s, int32_t child,
                                   int32_t parent) {
        ++s.outdeg_dup[parent];
        if (edge_bitmap) {
          const uint64_t key =
              static_cast<uint64_t>(child) * vocab_size + parent;
          s.edge_bits[key >> 6] |= 1ull << (key & 63);
        } else {
          s.edge_child.push_back(child);
          s.edge_parent.push_back(parent);
        }
      };
      if (code != 3) {
        // The common case: detection partitions are disjoint, so a row
        // belongs to exactly one partition — no inner loop.
        PartScratch& s = sc[code >> 1];
        ++s.counts_global[t];
        ++s.cov_dup[op];
        ++s.n_p;
        if (pr >= 0 && pr < n_rows && (!row_mask || row_mask[pr]) &&
            (part_bit[trace_id[pr]] & code)) {
          record_edge(s, op, pod_op[pr]);
        }
        continue;
      }
      // Rare: a caller listed the trace in BOTH partitions.
      uint8_t ecode = 0;
      int32_t pop = 0;
      if (pr >= 0 && pr < n_rows && (!row_mask || row_mask[pr])) {
        ecode = static_cast<uint8_t>(code & part_bit[trace_id[pr]]);
        pop = pod_op[pr];
      }
      for (int i = 0; i < 2; ++i) {
        PartScratch& s = sc[i];
        ++s.counts_global[t];
        ++s.cov_dup[op];
        ++s.n_p;
        if (ecode & (1 << i)) record_edge(s, op, pop);
      }
    }

    if (profile_enabled()) tm_scan.emplace("scatter");

    // Local trace interning in ascending global-id order (np.unique
    // order), then ONE more scan bucket-scatters both partitions' ops by
    // local trace — buckets are small (avg spans/trace), so the per-trace
    // sorts in finish_partition stay in cache.
    for (int i = 0; i < 2; ++i) {
      PartScratch& s = sc[i];
      s.local_id.assign(n_total_traces, -1);
      auto& lu = g->parts[i].local_uniques;
      auto& tl = g->parts[i].tracelen;
      for (int64_t t = 0; t < n_total_traces; ++t) {
        if (s.counts_global[t] > 0) {
          s.local_id[t] = static_cast<int32_t>(lu.size());
          lu.push_back(static_cast<int32_t>(t));
          tl.push_back(s.counts_global[t]);
        }
      }
      s.tr_off.assign(lu.size() + 1, 0);
      for (size_t t = 0; t < lu.size(); ++t)
        s.tr_off[t + 1] = s.tr_off[t] + tl[t];
      s.by_trace_op.resize(s.n_p);
    }
    {
      std::vector<int64_t> cur0(sc[0].tr_off.begin(), sc[0].tr_off.end());
      std::vector<int64_t> cur1(sc[1].tr_off.begin(), sc[1].tr_off.end());
      for (int64_t r = 0; r < n_rows; ++r) {
        if (row_mask && !row_mask[r]) continue;
        const int32_t t = trace_id[r];
        const uint8_t code = part_bit[t];
        if (!code) continue;
        const int32_t op = pod_op[r];
        if (code & 1) sc[0].by_trace_op[cur0[sc[0].local_id[t]]++] = op;
        if (code & 2) sc[1].by_trace_op[cur1[sc[1].local_id[t]]++] = op;
      }
    }

    tm_scan.reset();

    // Finish the partitions sequentially: each call parallelizes ACROSS
    // its trace chunks (parallel_chunks), which balances arbitrarily
    // skewed partitions — the old one-thread-per-partition overlap
    // bought nothing when one partition held 40x the entries (the usual
    // detection outcome). Analyze both first: the auto collapse
    // decision needs both partitions' kind-group counts before either
    // emits.
    analyze_partition(sc[0], vocab_size, &g->parts[0]);
    analyze_partition(sc[1], vocab_size, &g->parts[1]);
    const int64_t t_total =
        static_cast<int64_t>(g->parts[0].local_uniques.size()) +
        static_cast<int64_t>(g->parts[1].local_uniques.size());
    const int64_t grp_total = g->parts[0].n_groups + g->parts[1].n_groups;
    const bool do_collapse =
        collapse_mode == 2 || (collapse_mode == 1 && grp_total < t_total);
    emit_partition(sc[0], &g->parts[0], do_collapse);
    emit_partition(sc[1], &g->parts[1], do_collapse);
    edges_partition(sc[0], vocab_size, &g->parts[0]);
    edges_partition(sc[1], vocab_size, &g->parts[1]);
  } catch (const std::bad_alloc&) {
    delete g;
    return nullptr;
  } catch (const std::system_error&) {  // thread creation failure
    delete g;
    return nullptr;
  } catch (const std::exception& e) {
    // Never cross the C ABI with an exception. Allocation/thread
    // failures above stay silent (the Python side falls back to the
    // numpy lane); anything else is a real bug — say what it was
    // before reporting the generic build failure.
    std::fprintf(stderr, "mr_build_window2: unexpected error: %s\n",
                 e.what());
    delete g;
    return nullptr;
  } catch (...) {
    delete g;
    return nullptr;
  }
  return g;
}

// Query whether mr_build_window2 collapsed the trace axes (its
// collapse_mode argument): returns 1 and fills out_true[i] with each
// partition's TRUE trace count when kind-collapsed (mr_window_sizes then
// reports the kind-COLUMN counts), 0 when the per-trace layout was kept.
int32_t mr_collapse_window(const MrBuiltWindow* g, int32_t /*unused*/,
                           int64_t* out_true) {
  if (g->parts[0].n_traces_true < 0) return 0;
  out_true[0] = g->parts[0].n_traces_true;
  out_true[1] = g->parts[1].n_traces_true;
  return 1;
}

// sizes[8]: per partition (normal, abnormal): n_inc, n_ss, n_traces, n_ops.
// After mr_collapse_window, "n_traces" is the kind-COLUMN count (the
// padded trace-axis extent); the true counts come from that call.
void mr_window_sizes(const MrBuiltWindow* g, int64_t* sizes) {
  for (int i = 0; i < 2; ++i) {
    const BuiltPartition& p = g->parts[i];
    sizes[4 * i + 0] = static_cast<int64_t>(p.inc_op.size());
    sizes[4 * i + 1] = static_cast<int64_t>(p.ss_child.size());
    sizes[4 * i + 2] = static_cast<int64_t>(p.kind.size());
    sizes[4 * i + 3] = p.n_ops;
  }
}

// Copy partition idx into caller-allocated buffers (each at least the
// corresponding mr_window_sizes length; vocab-length for cov/present).
// Buffers beyond the filled length keep whatever the caller padded with.
void mr_export_partition(const MrBuiltWindow* g, int32_t idx, int32_t* inc_op,
                         int32_t* inc_trace, float* sr_val, float* rs_val,
                         int32_t* ss_child, int32_t* ss_parent, float* ss_val,
                         int32_t* kind, int32_t* tracelen,
                         int32_t* local_uniques, int32_t* cov_unique,
                         uint8_t* op_present) {
  const BuiltPartition& p = g->parts[idx];
  auto cp = [](auto* dst, const auto& src) {
    if (!src.empty())
      std::memcpy(dst, src.data(), src.size() * sizeof(src[0]));
  };
  cp(inc_op, p.inc_op);
  cp(inc_trace, p.inc_trace);
  cp(sr_val, p.sr_val);
  cp(rs_val, p.rs_val);
  cp(ss_child, p.ss_child);
  cp(ss_parent, p.ss_parent);
  cp(ss_val, p.ss_val);
  cp(kind, p.kind);
  cp(tracelen, p.tracelen);
  cp(local_uniques, p.local_uniques);
  cp(cov_unique, p.cov_unique);
  cp(op_present, p.op_present);
}

// Packed-kernel views: 0/1 pattern bitmaps (big-endian bit order, matching
// np.packbits) plus the three inverse vectors, written into caller-ZEROED
// padded buffers. ``t8``/``v8`` are the bitmap row strides in bytes
// (= t_pad/8, v_pad/8 rounded up). inv values copy the same f32 the COO
// value arrays carry, so the packed kernel is value-identical to it.
void mr_export_bitmaps(const MrBuiltWindow* g, int32_t idx, uint8_t* cov_bits,
                       int64_t t8, uint8_t* ss_bits, int64_t v8,
                       float* inv_len, float* inv_cov, float* inv_out) {
  const BuiltPartition& p = g->parts[idx];
  const int64_t n_inc = static_cast<int64_t>(p.inc_op.size());
  for (int64_t i = 0; i < n_inc; ++i) {
    const int32_t v = p.inc_op[i], t = p.inc_trace[i];
    cov_bits[static_cast<int64_t>(v) * t8 + (t >> 3)] |=
        static_cast<uint8_t>(128u >> (t & 7));
    inv_cov[v] = p.rs_val[i];
    // Scattered from the entry values (not recomputed as 1/len) so the
    // kind-collapsed layout's folded m/len forward weights carry over
    // exactly — identical to graph/build.py:packed_aux either way.
    inv_len[t] = p.sr_val[i];
  }
  const int64_t n_ss = static_cast<int64_t>(p.ss_child.size());
  for (int64_t i = 0; i < n_ss; ++i) {
    const int32_t c = p.ss_child[i], par = p.ss_parent[i];
    ss_bits[static_cast<int64_t>(c) * v8 + (par >> 3)] |=
        static_cast<uint8_t>(128u >> (par & 7));
    inv_out[par] = p.ss_val[i];
  }
}

// CSR-kernel views: op-major reorder of the incidence (stable counting
// scatter — entries are stored trace-major with ops ascending per trace,
// so op rows keep traces ascending) plus the three row-offset arrays.
// Caller-zeroed buffers: tr_om/sr_om e_pad-length, indptr_op/ss_indptr
// (v_pad+1)-length, indptr_trace (t_pad+1)-length.
void mr_export_csr(const MrBuiltWindow* g, int32_t idx, int64_t vocab,
                   int64_t v_pad, int64_t t_pad, int32_t* tr_om, float* sr_om,
                   int32_t* indptr_op, int32_t* indptr_trace,
                   int32_t* ss_indptr) {
  const BuiltPartition& p = g->parts[idx];
  const int64_t n_inc = static_cast<int64_t>(p.inc_op.size());
  // Histogram the CURRENT incidence — cov_unique keeps the true
  // per-trace coverage counts, which overcount the entries after a
  // kind collapse (one entry per covering kind, not per trace).
  std::vector<int32_t> op_count(vocab, 0);
  for (int64_t i = 0; i < n_inc; ++i) ++op_count[p.inc_op[i]];
  indptr_op[0] = 0;
  for (int64_t o = 0; o < v_pad; ++o)
    indptr_op[o + 1] = indptr_op[o] + (o < vocab ? op_count[o] : 0);
  std::vector<int32_t> cur(indptr_op, indptr_op + vocab);
  for (int64_t i = 0; i < n_inc; ++i) {
    const int32_t pos = cur[p.inc_op[i]]++;
    tr_om[pos] = p.inc_trace[i];
    sr_om[pos] = p.sr_val[i];
  }
  for (int64_t i = 0; i < n_inc; ++i) ++indptr_trace[p.inc_trace[i] + 1];
  for (int64_t t = 0; t < t_pad; ++t) indptr_trace[t + 1] += indptr_trace[t];
  const int64_t n_ss = static_cast<int64_t>(p.ss_child.size());
  for (int64_t i = 0; i < n_ss; ++i) ++ss_indptr[p.ss_child[i] + 1];
  for (int64_t o = 0; o < v_pad; ++o) ss_indptr[o + 1] += ss_indptr[o];
}

void mr_free_built(MrBuiltWindow* g) { delete g; }

}  // extern "C"
