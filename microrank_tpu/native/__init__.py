"""Native (C++) runtime with a ctypes binding.

Builds ``libmrspan.so`` from span_loader.cpp + graph_builder.cpp on first
use (g++ -O3; cached next to the sources) and exposes:

* ``load_span_table(path)`` — mmap CSV ingest to a ``SpanTable`` of
  interned numpy arrays;
* ``build_window_native(...)`` — fused counting-sort window-graph build
  (both partitions in single scans), array-compatible with the numpy lane
  (graph.build._build_partition).

Falls back cleanly: callers should catch ``NativeUnavailable`` and use the
pandas/numpy paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

_SRCS = [
    Path(__file__).parent / "span_loader.cpp",
    Path(__file__).parent / "graph_builder.cpp",
]
_LIB = Path(__file__).parent / "libmrspan.so"
_lib: Optional[ctypes.CDLL] = None


class NativeUnavailable(RuntimeError):
    pass


class SpanTable(NamedTuple):
    """One CSV dump, fully interned: the native ingest output.

    Times are epoch microseconds (trace-level start/end, as in the CSV
    contract); ``parent_row`` is the row index of each span's parent
    (-1 when absent) — the span linkage of preprocess_data.py:157-158
    resolved at load time.
    """

    trace_id: np.ndarray     # int32[S]
    svc_op: np.ndarray       # int32[S] service-level op (detector vocab)
    pod_op: np.ndarray       # int32[S] instance-level op (PageRank vocab)
    duration_us: np.ndarray  # int64[S]
    start_us: np.ndarray     # int64[S]
    end_us: np.ndarray       # int64[S]
    parent_row: np.ndarray   # int64[S]
    trace_names: List[str]
    svc_op_names: List[str]
    pod_op_names: List[str]

    @property
    def n_spans(self) -> int:
        return int(self.trace_id.shape[0])


class _MrSpanTable(ctypes.Structure):
    _fields_ = [
        ("n_spans", ctypes.c_int64),
        ("trace_id", ctypes.POINTER(ctypes.c_int32)),
        ("svc_op", ctypes.POINTER(ctypes.c_int32)),
        ("pod_op", ctypes.POINTER(ctypes.c_int32)),
        ("duration_us", ctypes.POINTER(ctypes.c_int64)),
        ("start_us", ctypes.POINTER(ctypes.c_int64)),
        ("end_us", ctypes.POINTER(ctypes.c_int64)),
        ("parent_row", ctypes.POINTER(ctypes.c_int64)),
        ("trace_blob", ctypes.c_char_p),
        ("trace_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_traces", ctypes.c_int64),
        ("svc_blob", ctypes.c_char_p),
        ("svc_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_svc_ops", ctypes.c_int64),
        ("pod_blob", ctypes.c_char_p),
        ("pod_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_pod_ops", ctypes.c_int64),
        ("error", ctypes.c_char_p),
    ]


class _MrPartition(ctypes.Structure):
    _fields_ = [
        ("n_inc", ctypes.c_int64),
        ("inc_op", ctypes.POINTER(ctypes.c_int32)),
        ("inc_trace", ctypes.POINTER(ctypes.c_int32)),
        ("sr_val", ctypes.POINTER(ctypes.c_float)),
        ("rs_val", ctypes.POINTER(ctypes.c_float)),
        ("n_ss", ctypes.c_int64),
        ("ss_child", ctypes.POINTER(ctypes.c_int32)),
        ("ss_parent", ctypes.POINTER(ctypes.c_int32)),
        ("ss_val", ctypes.POINTER(ctypes.c_float)),
        ("n_traces", ctypes.c_int64),
        ("kind", ctypes.POINTER(ctypes.c_int32)),
        ("tracelen", ctypes.POINTER(ctypes.c_int32)),
        ("local_uniques", ctypes.POINTER(ctypes.c_int32)),
        ("cov_unique", ctypes.POINTER(ctypes.c_int32)),
        ("op_present", ctypes.POINTER(ctypes.c_uint8)),
        ("n_ops", ctypes.c_int64),
    ]


class _MrWindowGraph(ctypes.Structure):
    _fields_ = [
        ("parts", _MrPartition * 2),
        ("error", ctypes.c_char_p),
    ]


def _build_library() -> None:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        *[str(s) for s in _SRCS], "-o", str(_LIB),
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=300
        )
    except FileNotFoundError as exc:
        raise NativeUnavailable("g++ not available") from exc
    except subprocess.CalledProcessError as exc:
        raise NativeUnavailable(
            f"native build failed:\n{exc.stderr}"
        ) from exc


def _load_library() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not _LIB.exists() or _LIB.stat().st_mtime < max(
        s.stat().st_mtime for s in _SRCS
    ):
        _build_library()
    lib = ctypes.CDLL(str(_LIB))
    lib.mr_load_csv.restype = ctypes.POINTER(_MrSpanTable)
    lib.mr_load_csv.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.mr_free_table.restype = None
    lib.mr_free_table.argtypes = [ctypes.POINTER(_MrSpanTable)]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.mr_build_window.restype = ctypes.POINTER(_MrWindowGraph)
    lib.mr_build_window.argtypes = [
        ctypes.POINTER(ctypes.c_int32),  # pod_op
        ctypes.POINTER(ctypes.c_int32),  # trace_id
        ctypes.POINTER(ctypes.c_int64),  # parent_row
        ctypes.c_int64,                  # n_rows
        u8p,                             # row_mask (nullable)
        u8p,                             # normal_flag
        u8p,                             # abnormal_flag
        ctypes.c_int64,                  # n_total_traces
        ctypes.c_int64,                  # vocab_size
    ]
    lib.mr_free_window.restype = None
    lib.mr_free_window.argtypes = [ctypes.POINTER(_MrWindowGraph)]
    _lib = lib
    return lib


def _decode_vocab(blob: bytes, offsets, n: int) -> List[str]:
    offs = np.ctypeslib.as_array(offsets, shape=(n + 1,))
    return [
        blob[offs[i]: offs[i + 1]].decode("utf-8", "replace")
        for i in range(n)
    ]


def native_available() -> bool:
    try:
        _load_library()
        return True
    except NativeUnavailable:
        return False


def load_span_table(
    path, strip_services=("ts-ui-dashboard",)
) -> SpanTable:
    """Load one traces.csv (raw ClickHouse export or canonical schema)."""
    lib = _load_library()
    res = lib.mr_load_csv(
        str(path).encode(), ",".join(strip_services).encode()
    )
    try:
        t = res.contents
        if t.error:
            raise ValueError(
                f"native loader failed for {path}: {t.error.decode()}"
            )
        n = int(t.n_spans)

        def arr(ptr, dtype):
            if n == 0:
                return np.zeros(0, dtype=dtype)
            return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)

        # blob pointers: ctypes c_char_p auto-converts to bytes
        table = SpanTable(
            trace_id=arr(t.trace_id, np.int32),
            svc_op=arr(t.svc_op, np.int32),
            pod_op=arr(t.pod_op, np.int32),
            duration_us=arr(t.duration_us, np.int64),
            start_us=arr(t.start_us, np.int64),
            end_us=arr(t.end_us, np.int64),
            parent_row=arr(t.parent_row, np.int64),
            trace_names=_decode_vocab(
                t.trace_blob, t.trace_offsets, int(t.n_traces)
            ),
            svc_op_names=_decode_vocab(
                t.svc_blob, t.svc_offsets, int(t.n_svc_ops)
            ),
            pod_op_names=_decode_vocab(
                t.pod_blob, t.pod_offsets, int(t.n_pod_ops)
            ),
        )
        return table
    finally:
        lib.mr_free_table(res)


class RawPartition(NamedTuple):
    """Unpadded arrays of one partition graph, as built by C++.

    Field semantics match graph.build._build_partition's outputs; callers
    (graph.table_ops) pad and assemble the PartitionGraph.
    """

    inc_op: np.ndarray       # int32[n_inc]
    inc_trace: np.ndarray    # int32[n_inc]
    sr_val: np.ndarray       # float32[n_inc]
    rs_val: np.ndarray       # float32[n_inc]
    ss_child: np.ndarray     # int32[n_ss]
    ss_parent: np.ndarray    # int32[n_ss]
    ss_val: np.ndarray       # float32[n_ss]
    kind: np.ndarray         # int32[n_traces]
    tracelen: np.ndarray     # int32[n_traces]
    local_uniques: np.ndarray  # int32[n_traces] global trace codes
    cov_unique: np.ndarray   # int32[vocab]
    op_present: np.ndarray   # bool[vocab]
    n_ops: int


def _take(ptr, n: int, dtype) -> np.ndarray:
    if n == 0:
        return np.zeros(0, dtype=dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


def build_window_native(
    pod_op: np.ndarray,
    trace_id: np.ndarray,
    parent_row: np.ndarray,
    row_mask: Optional[np.ndarray],
    normal_flag: np.ndarray,
    abnormal_flag: np.ndarray,
    vocab_size: int,
) -> Tuple[RawPartition, RawPartition]:
    """Build both partitions' raw COO graphs in C++ (fused single scans).

    ``normal_flag``/``abnormal_flag`` are bool arrays over the table's
    global trace codes; ``row_mask`` (bool over rows, or None for all)
    is the detection window (get_span semantics applied upstream).
    """
    lib = _load_library()
    pod_op = np.ascontiguousarray(pod_op, dtype=np.int32)
    trace_id = np.ascontiguousarray(trace_id, dtype=np.int32)
    parent_row = np.ascontiguousarray(parent_row, dtype=np.int64)
    nf = np.ascontiguousarray(normal_flag, dtype=np.uint8)
    af = np.ascontiguousarray(abnormal_flag, dtype=np.uint8)
    n_total = len(nf)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    if row_mask is None:
        mask_ptr = ctypes.cast(None, u8p)
    else:
        row_mask = np.ascontiguousarray(row_mask, dtype=np.uint8)
        mask_ptr = row_mask.ctypes.data_as(u8p)
    res = lib.mr_build_window(
        pod_op.ctypes.data_as(i32p),
        trace_id.ctypes.data_as(i32p),
        parent_row.ctypes.data_as(i64p),
        ctypes.c_int64(len(pod_op)),
        mask_ptr,
        nf.ctypes.data_as(u8p),
        af.ctypes.data_as(u8p),
        ctypes.c_int64(n_total),
        ctypes.c_int64(vocab_size),
    )
    if not res:
        raise NativeUnavailable("mr_build_window allocation failed")
    try:
        if res.contents.error:
            raise NativeUnavailable(res.contents.error.decode())
        out = []
        for p in res.contents.parts:
            n_inc, n_ss, n_tr = int(p.n_inc), int(p.n_ss), int(p.n_traces)
            out.append(
                RawPartition(
                    inc_op=_take(p.inc_op, n_inc, np.int32),
                    inc_trace=_take(p.inc_trace, n_inc, np.int32),
                    sr_val=_take(p.sr_val, n_inc, np.float32),
                    rs_val=_take(p.rs_val, n_inc, np.float32),
                    ss_child=_take(p.ss_child, n_ss, np.int32),
                    ss_parent=_take(p.ss_parent, n_ss, np.int32),
                    ss_val=_take(p.ss_val, n_ss, np.float32),
                    kind=_take(p.kind, n_tr, np.int32),
                    tracelen=_take(p.tracelen, n_tr, np.int32),
                    local_uniques=_take(p.local_uniques, n_tr, np.int32),
                    cov_unique=_take(p.cov_unique, vocab_size, np.int32),
                    op_present=_take(p.op_present, vocab_size, np.uint8).astype(
                        bool
                    ),
                    n_ops=int(p.n_ops),
                )
            )
        return out[0], out[1]
    finally:
        lib.mr_free_window(res)


__all__ = [
    "SpanTable",
    "RawPartition",
    "NativeUnavailable",
    "load_span_table",
    "build_window_native",
    "native_available",
]
