"""Native (C++) runtime with a ctypes binding.

Builds ``libmrspan.so`` from span_loader.cpp + graph_builder.cpp on first
use (g++ -O3; cached next to the sources) and exposes:

* ``load_span_table(path)`` — mmap CSV ingest to a ``SpanTable`` of
  interned numpy arrays;
* ``build_window_padded(...)`` — fused counting-sort window-graph build
  (both partitions in single scans), exported straight into padded numpy
  buffers; array-compatible with the numpy lane
  (graph.build._build_partition).

Falls back cleanly: callers should catch ``NativeUnavailable`` and use the
pandas/numpy paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

_SRCS = [
    Path(__file__).parent / "span_loader.cpp",
    Path(__file__).parent / "graph_builder.cpp",
    Path(__file__).parent / "detector.cpp",
]
_LIB = Path(__file__).parent / "libmrspan.so"
_lib: Optional[ctypes.CDLL] = None


class NativeUnavailable(RuntimeError):
    pass


class SpanTable(NamedTuple):
    """One CSV dump, fully interned: the native ingest output.

    Times are epoch microseconds (trace-level start/end, as in the CSV
    contract); ``parent_row`` is the row index of each span's parent
    (-1 when absent) — the span linkage of preprocess_data.py:157-158
    resolved at load time.
    """

    trace_id: np.ndarray     # int32[S]
    svc_op: np.ndarray       # int32[S] service-level op (detector vocab)
    pod_op: np.ndarray       # int32[S] instance-level op (PageRank vocab)
    duration_us: np.ndarray  # int64[S]
    start_us: np.ndarray     # int64[S]
    end_us: np.ndarray       # int64[S]
    parent_row: np.ndarray   # int64[S]
    trace_names: List[str]
    svc_op_names: List[str]
    pod_op_names: List[str]
    # Rows sorted by start_us ascending (sort_table_by_time — the loader
    # does it once per dump, sidecar-cached). Window seams then slice a
    # searchsorted row range instead of scanning every row per window —
    # O(window) detection/build on multi-window replays.
    time_sorted: bool = False

    @property
    def n_spans(self) -> int:
        return int(self.trace_id.shape[0])


class _MrSpanTable(ctypes.Structure):
    _fields_ = [
        ("n_spans", ctypes.c_int64),
        ("trace_id", ctypes.POINTER(ctypes.c_int32)),
        ("svc_op", ctypes.POINTER(ctypes.c_int32)),
        ("pod_op", ctypes.POINTER(ctypes.c_int32)),
        ("duration_us", ctypes.POINTER(ctypes.c_int64)),
        ("start_us", ctypes.POINTER(ctypes.c_int64)),
        ("end_us", ctypes.POINTER(ctypes.c_int64)),
        ("parent_row", ctypes.POINTER(ctypes.c_int64)),
        ("trace_blob", ctypes.c_char_p),
        ("trace_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_traces", ctypes.c_int64),
        ("svc_blob", ctypes.c_char_p),
        ("svc_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_svc_ops", ctypes.c_int64),
        ("pod_blob", ctypes.c_char_p),
        ("pod_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_pod_ops", ctypes.c_int64),
        ("error", ctypes.c_char_p),
    ]


def _build_library() -> None:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        *[str(s) for s in _SRCS], "-o", str(_LIB),
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=300
        )
    except FileNotFoundError as exc:
        raise NativeUnavailable("g++ not available") from exc
    except subprocess.CalledProcessError as exc:
        raise NativeUnavailable(
            f"native build failed:\n{exc.stderr}"
        ) from exc


def _load_library() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not _LIB.exists() or _LIB.stat().st_mtime < max(
        s.stat().st_mtime for s in _SRCS
    ):
        _build_library()
    lib = ctypes.CDLL(str(_LIB))
    lib.mr_load_csv.restype = ctypes.POINTER(_MrSpanTable)
    lib.mr_load_csv.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.mr_free_table.restype = None
    lib.mr_free_table.argtypes = [ctypes.POINTER(_MrSpanTable)]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.mr_build_window2.restype = ctypes.c_void_p
    lib.mr_build_window2.argtypes = [
        i32p,            # pod_op
        i32p,            # trace_id
        i64p,            # parent_row
        ctypes.c_int64,  # n_rows
        u8p,             # row_mask (nullable)
        u8p,             # normal_flag
        u8p,             # abnormal_flag
        ctypes.c_int64,  # n_total_traces
        ctypes.c_int64,  # vocab_size
        ctypes.c_int32,  # collapse_mode (0 off / 1 auto / 2 on)
        ctypes.c_int64,  # parent_base (slice offset for parent_row)
    ]
    lib.mr_window_sizes.restype = None
    lib.mr_window_sizes.argtypes = [ctypes.c_void_p, i64p]
    lib.mr_export_partition.restype = None
    lib.mr_export_partition.argtypes = [
        ctypes.c_void_p, ctypes.c_int32,
        i32p, i32p, f32p, f32p,          # inc_op, inc_trace, sr, rs
        i32p, i32p, f32p,                # ss_child, ss_parent, ss_val
        i32p, i32p, i32p,                # kind, tracelen, local_uniques
        i32p, u8p,                       # cov_unique, op_present
    ]
    lib.mr_export_bitmaps.restype = None
    lib.mr_export_bitmaps.argtypes = [
        ctypes.c_void_p, ctypes.c_int32,
        u8p, ctypes.c_int64,             # cov_bits, t8
        u8p, ctypes.c_int64,             # ss_bits, v8
        f32p, f32p, f32p,                # inv_len, inv_cov, inv_out
    ]
    lib.mr_export_csr.restype = None
    lib.mr_export_csr.argtypes = [
        ctypes.c_void_p, ctypes.c_int32,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,  # vocab, v_pad, t_pad
        i32p, f32p,                      # tr_om, sr_om
        i32p, i32p, i32p,                # indptr_op, indptr_trace, ss_indptr
    ]
    lib.mr_collapse_window.restype = ctypes.c_int32
    lib.mr_collapse_window.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i64p
    ]
    lib.mr_free_built.restype = None
    lib.mr_free_built.argtypes = [ctypes.c_void_p]
    lib.mr_detect_window.restype = ctypes.c_int
    lib.mr_detect_window.argtypes = [
        ctypes.c_int64,   # n_spans
        i32p,             # trace_id
        i32p,             # svc_op
        i64p,             # duration_us
        i64p,             # start_us
        i64p,             # end_us
        ctypes.c_int64,   # w0_us
        ctypes.c_int64,   # w1_us
        i32p,             # remap
        ctypes.c_int64,   # n_svc_vocab
        f32p,             # thresh_ms
        ctypes.c_int64,   # n_slo_vocab
        ctypes.c_float,   # slack_ms
        ctypes.c_int64,   # n_traces_total
        u8p,              # mask out
        i32p,             # nrm out
        i32p,             # abn out
        i64p,             # counts out
    ]
    _lib = lib
    return lib


def _decode_vocab(blob: bytes, offsets, n: int) -> List[str]:
    offs = np.ctypeslib.as_array(offsets, shape=(n + 1,))
    return [
        blob[offs[i]: offs[i + 1]].decode("utf-8", "replace")
        for i in range(n)
    ]


def native_available() -> bool:
    try:
        _load_library()
        return True
    except NativeUnavailable:
        return False


# v2: op vocabularies canonicalized to name-sorted order (the vocab index
# is the device ranking's tie key — it must equal ascending op name).
# v3: rows time-sorted at load (sort_table_by_time) so window seams can
# slice searchsorted row ranges; older sidecars reload + re-sort.
_SIDECAR_VERSION = 3


def sort_table_by_time(table: SpanTable) -> SpanTable:
    """Reorder rows by ascending start_us (stable) and remap parent_row.

    Every consumer is row-order independent: detection accumulates
    per-trace sums (float64 over exact int durations), the graph build's
    counting sorts key on interned ids, and window masks are pure time
    predicates — so sorting changes no result, it only makes window row
    ranges contiguous. Already-sorted inputs return unchanged (flag set).
    """
    if table.time_sorted:
        return table
    start = table.start_us
    n = int(start.shape[0])
    if n == 0 or bool(np.all(start[1:] >= start[:-1])):
        return table._replace(time_sorted=True)
    order = np.argsort(start, kind="stable")
    inv = np.empty(n, dtype=np.int64)
    inv[order] = np.arange(n, dtype=np.int64)
    old_parent = table.parent_row[order]
    parent = np.where(
        old_parent >= 0, inv[np.clip(old_parent, 0, None)], -1
    )
    return table._replace(
        trace_id=np.ascontiguousarray(table.trace_id[order]),
        svc_op=np.ascontiguousarray(table.svc_op[order]),
        pod_op=np.ascontiguousarray(table.pod_op[order]),
        duration_us=np.ascontiguousarray(table.duration_us[order]),
        start_us=np.ascontiguousarray(start[order]),
        end_us=np.ascontiguousarray(table.end_us[order]),
        parent_row=np.ascontiguousarray(parent),
        time_sorted=True,
    )


def _sort_vocab(codes: np.ndarray, names: List[str]):
    """Remap one interned column onto the name-sorted canonical vocab.

    The C++ interner assigns ids in first-appearance order; downstream the
    pod-op vocab index doubles as the ranking's deterministic tie key, so
    it must order by name (Python ``sorted`` semantics — the same
    comparison the numpy oracle's tiebreak="name" sort uses).
    """
    if len(names) <= 1:
        return codes, list(names)
    perm = sorted(range(len(names)), key=names.__getitem__)
    inv = np.empty(len(names), dtype=codes.dtype)
    inv[np.asarray(perm, dtype=np.int64)] = np.arange(
        len(names), dtype=codes.dtype
    )
    return inv[codes], [names[i] for i in perm]


def _sidecar_path(path: Path, strip_services) -> Path:
    import hashlib

    tag = hashlib.sha1(
        ",".join(sorted(strip_services)).encode()
    ).hexdigest()[:8]
    return path.with_suffix(path.suffix + f".mrt-{tag}.npz")


def _load_sidecar(path: Path, side: Path) -> Optional[SpanTable]:
    import zipfile

    try:
        st = path.stat()
        with np.load(side, allow_pickle=False) as z:
            if int(z["version"][0]) != _SIDECAR_VERSION:
                return None
            # Freshness: the sidecar records the source CSV's (mtime, size)
            # at save time — a replaced dump with a preserved/older mtime
            # still invalidates via the size (and any mtime change does).
            src = z["source_stat"]
            if int(src[0]) != st.st_mtime_ns or int(src[1]) != st.st_size:
                return None
            return SpanTable(
                trace_id=z["trace_id"],
                svc_op=z["svc_op"],
                pod_op=z["pod_op"],
                duration_us=z["duration_us"],
                start_us=z["start_us"],
                end_us=z["end_us"],
                parent_row=z["parent_row"],
                trace_names=list(z["trace_names"]),
                svc_op_names=list(z["svc_op_names"]),
                pod_op_names=list(z["pod_op_names"]),
                time_sorted=True,  # v3 sidecars store sorted rows
            )
    except (OSError, KeyError, ValueError, zipfile.BadZipFile):
        return None


def _save_sidecar(side: Path, source: Path, table: SpanTable) -> None:
    try:
        st = source.stat()
        tmp = side.with_suffix(".tmp.npz")
        np.savez(
            tmp,
            version=np.array([_SIDECAR_VERSION]),
            source_stat=np.array([st.st_mtime_ns, st.st_size], dtype=np.int64),
            trace_id=table.trace_id,
            svc_op=table.svc_op,
            pod_op=table.pod_op,
            duration_us=table.duration_us,
            start_us=table.start_us,
            end_us=table.end_us,
            parent_row=table.parent_row,
            trace_names=np.array(table.trace_names, dtype=np.str_),
            svc_op_names=np.array(table.svc_op_names, dtype=np.str_),
            pod_op_names=np.array(table.pod_op_names, dtype=np.str_),
        )
        os.replace(tmp, side)
    except OSError:  # cache is best-effort (read-only dirs, full disk)
        pass


def load_span_table(
    path, strip_services=("ts-ui-dashboard",), cache: bool = True
) -> SpanTable:
    """Load one traces.csv (raw ClickHouse export or canonical schema).

    With ``cache`` (default), the interned arrays are persisted to an
    ``.mrt-*.npz`` sidecar next to the CSV and reused on later loads when
    fresher than the CSV — repeat replays of the same dump skip the parse
    entirely.
    """
    path = Path(path)
    side = _sidecar_path(path, strip_services)
    if cache:
        cached = _load_sidecar(path, side)
        if cached is not None:
            return cached
    lib = _load_library()
    res = lib.mr_load_csv(
        str(path).encode(), ",".join(strip_services).encode()
    )
    try:
        t = res.contents
        if t.error:
            raise ValueError(
                f"native loader failed for {path}: {t.error.decode()}"
            )
        n = int(t.n_spans)

        def arr(ptr, dtype):
            if n == 0:
                return np.zeros(0, dtype=dtype)
            return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)

        # blob pointers: ctypes c_char_p auto-converts to bytes
        svc_op, svc_names = _sort_vocab(
            arr(t.svc_op, np.int32),
            _decode_vocab(t.svc_blob, t.svc_offsets, int(t.n_svc_ops)),
        )
        pod_op, pod_names = _sort_vocab(
            arr(t.pod_op, np.int32),
            _decode_vocab(t.pod_blob, t.pod_offsets, int(t.n_pod_ops)),
        )
        table = sort_table_by_time(
            SpanTable(
                trace_id=arr(t.trace_id, np.int32),
                svc_op=svc_op,
                pod_op=pod_op,
                duration_us=arr(t.duration_us, np.int64),
                start_us=arr(t.start_us, np.int64),
                end_us=arr(t.end_us, np.int64),
                parent_row=arr(t.parent_row, np.int64),
                trace_names=_decode_vocab(
                    t.trace_blob, t.trace_offsets, int(t.n_traces)
                ),
                svc_op_names=svc_names,
                pod_op_names=pod_names,
            )
        )
        if cache:
            _save_sidecar(side, path, table)
        return table
    finally:
        lib.mr_free_table(res)


class PaddedPartition(NamedTuple):
    """One partition graph with arrays pre-padded by the caller's policy.

    Array semantics match graph.build._build_partition's outputs after
    pad1d; ``local_uniques`` (global trace code per local trace id) is
    exact-length. C++ fills the leading true-length prefix of each array;
    the padding keeps the allocation-time fill (zeros, or ones for
    kind/tracelen — the same fills pad1d uses).
    """

    inc_op: np.ndarray       # int32[e_pad]
    inc_trace: np.ndarray    # int32[e_pad]
    sr_val: np.ndarray       # float32[e_pad]
    rs_val: np.ndarray       # float32[e_pad]
    ss_child: np.ndarray     # int32[c_pad]
    ss_parent: np.ndarray    # int32[c_pad]
    ss_val: np.ndarray       # float32[c_pad]
    kind: np.ndarray         # int32[t_pad], padded with 1
    tracelen: np.ndarray     # int32[t_pad], padded with 1
    local_uniques: np.ndarray  # int32[n_traces]
    cov_unique: np.ndarray   # int32[v_pad]
    op_present: np.ndarray   # bool[v_pad]
    # Auxiliary kernel views (see graph.structures.PartitionGraph), filled
    # per the resolved aux mode; unbuilt views are [0]-shaped ([x, 0] for
    # bitmaps) placeholders.
    inc_trace_opmajor: np.ndarray  # int32[e_pad]
    sr_val_opmajor: np.ndarray     # float32[e_pad]
    inc_indptr_op: np.ndarray      # int32[v_pad+1]
    inc_indptr_trace: np.ndarray   # int32[t_pad+1]
    ss_indptr: np.ndarray          # int32[v_pad+1]
    cov_bits: np.ndarray           # uint8[v_pad, t_pad/8]
    ss_bits: np.ndarray            # uint8[v_pad, v_pad/8]
    inv_tracelen: np.ndarray       # float32[t_pad]
    inv_cov_dup: np.ndarray        # float32[v_pad]
    inv_outdeg: np.ndarray         # float32[v_pad]
    n_ops: int
    n_traces: int
    n_inc: int
    n_ss: int
    # Kind-collapsed trace axis (mr_collapse_window): -1 = per-trace
    # layout; >= 0 = the axis holds this many kind columns while
    # n_traces still counts TRUE traces (graph.structures.PartitionGraph
    # n_cols semantics).
    n_cols: int = -1
    # Partition-centric binned views (kernel="pcsr"; see
    # graph.structures.PartitionGraph). Built by a vectorized binning
    # pass over the C++-exported trace-major entries — the export is
    # already (trace, op) sorted, so the binning is a contiguous split.
    pc_trace: np.ndarray = np.zeros((1, 0), np.int32)
    pc_sr_val: np.ndarray = np.zeros((1, 0), np.float32)
    pc_blk_indptr: np.ndarray = np.zeros((1, 0), np.int32)
    pc_ell_op: np.ndarray = np.zeros((1, 0), np.int32)
    pc_ell_rs: np.ndarray = np.zeros((1, 0), np.float32)
    # Kind-compressed reduced-precision view (kernel="kind"): int8
    # coverage pattern over the collapsed kind axis, derived from the
    # C++-exported bitmap by graph.build.kind_aux (shared with the
    # pandas lane so the two builders cannot diverge).
    cov_i8: np.ndarray = np.zeros((1, 0), np.int8)


def build_window_padded(
    pod_op: np.ndarray,
    trace_id: np.ndarray,
    parent_row: np.ndarray,
    row_mask: Optional[np.ndarray],
    normal_flag: np.ndarray,
    abnormal_flag: np.ndarray,
    vocab_size: int,
    v_pad: int,
    pad,
    mode: str = "none",
    collapse: str = "off",
    dense_budget_bytes: Optional[int] = None,
    parent_base: int = 0,
    kind_dedup_threshold: Optional[float] = None,
) -> Tuple[PaddedPartition, PaddedPartition]:
    """Build both partitions' COO graphs in C++ (fused single scans),
    exported directly into padded numpy buffers (single copy).

    ``normal_flag``/``abnormal_flag`` are bool arrays over the table's
    global trace codes; ``row_mask`` (bool over rows, or None for all)
    is the detection window (get_span semantics applied upstream);
    ``pad`` maps a true length to its padded length (>= the true length).
    ``mode`` is an aux mode: RESOLVED ("packed" | "csr" | "all" | "none")
    — which kernel views the C++ side additionally exports — or, with
    ``collapse`` enabled, the unresolved "auto"/"auto_all" request, which
    is resolved here AGAINST THE COLLAPSED trace shapes (the collapse
    happens in C++ before the views are exported, so the per-trace
    bitmaps are never built).

    ``collapse`` ("off" | "auto" | "on"): kind-collapse the trace axes in
    C++ (mr_collapse_window — the native twin of
    graph.build.collapse_window_graph, array-identical outputs).

    ``parent_base``: subtracted from each parent_row entry inside the
    C++ scan — callers passing a [lo, hi) table slice hand the ABSOLUTE
    parent rows plus lo instead of remapping in numpy (the O(window)
    np.where cost more than the whole build). Out-of-range parents drop
    their edge, same as -1.
    """
    if mode not in (
        "packed", "csr", "pcsr", "kind", "all", "none", "auto", "auto_all"
    ):
        raise ValueError(f"unknown aux mode {mode!r}")
    if mode in ("auto", "auto_all") and collapse == "off":
        raise ValueError(
            "aux mode 'auto'/'auto_all' is resolved here only under "
            "collapse; resolve_aux it at the call site otherwise"
        )
    lib = _load_library()
    pod_op = np.ascontiguousarray(pod_op, dtype=np.int32)
    trace_id = np.ascontiguousarray(trace_id, dtype=np.int32)
    parent_row = np.ascontiguousarray(parent_row, dtype=np.int64)
    nf = np.ascontiguousarray(normal_flag, dtype=np.uint8)
    af = np.ascontiguousarray(abnormal_flag, dtype=np.uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    if row_mask is None:
        mask_ptr = ctypes.cast(None, u8p)
    else:
        row_mask = np.ascontiguousarray(row_mask, dtype=np.uint8)
        mask_ptr = row_mask.ctypes.data_as(u8p)
    handle = lib.mr_build_window2(
        pod_op.ctypes.data_as(i32p),
        trace_id.ctypes.data_as(i32p),
        parent_row.ctypes.data_as(i64p),
        ctypes.c_int64(len(pod_op)),
        mask_ptr,
        nf.ctypes.data_as(u8p),
        af.ctypes.data_as(u8p),
        ctypes.c_int64(len(nf)),
        ctypes.c_int64(vocab_size),
        # The collapse happens INSIDE the build (before the incidence
        # emit — the per-trace entry arrays are never materialized);
        # mr_collapse_window below then just reports the true counts.
        ctypes.c_int32({"off": 0, "auto": 1, "on": 2}[collapse]),
        ctypes.c_int64(int(parent_base)),
    )
    if not handle:
        raise NativeUnavailable("mr_build_window2 allocation failed")
    try:
        true_traces = None
        if collapse != "off":
            true_out = np.zeros(2, dtype=np.int64)
            rc = int(
                lib.mr_collapse_window(
                    handle,
                    ctypes.c_int32(1 if collapse == "auto" else 0),
                    true_out.ctypes.data_as(i64p),
                )
            )
            if rc < 0:
                raise NativeUnavailable(
                    "mr_collapse_window allocation failed"
                )
            if rc == 1:
                true_traces = (int(true_out[0]), int(true_out[1]))
        sizes = np.zeros(8, dtype=np.int64)
        lib.mr_window_sizes(handle, sizes.ctypes.data_as(i64p))
        if mode in ("auto", "auto_all"):
            from ..graph.build import (
                DEFAULT_KIND_DEDUP_THRESHOLD,
                resolve_aux,
            )

            t_pads = (pad(int(sizes[2])), pad(int(sizes[6])))
            # The collapse already ran, so the measured dedup factor
            # (true traces / kind columns) is known here — the same
            # auto -> "kind" decision the pandas lane's collapse
            # post-pass makes (resolve_aux holds the one policy).
            dedup = None
            if true_traces is not None:
                cols = int(sizes[2]) + int(sizes[6])
                dedup = float(sum(true_traces)) / float(max(cols, 1))
            mode = resolve_aux(
                mode, v_pad, t_pads,
                *(() if dense_budget_bytes is None
                  else (dense_budget_bytes,)),
                dedup=dedup,
                kind_dedup_threshold=(
                    DEFAULT_KIND_DEDUP_THRESHOLD
                    if kind_dedup_threshold is None
                    else kind_dedup_threshold
                ),
            )
        out = []
        want_bits = mode in ("packed", "kind", "all")
        want_csr = mode in ("csr", "all")
        want_pc = mode in ("pcsr", "all")
        for idx in range(2):
            n_inc, n_ss, n_tr, n_ops = (int(x) for x in sizes[4 * idx: 4 * idx + 4])
            true_tr = true_traces[idx] if true_traces is not None else n_tr
            e_pad, c_pad, t_pad = pad(n_inc), pad(n_ss), pad(n_tr)
            t8 = (t_pad + 7) // 8
            v8 = (v_pad + 7) // 8
            p = PaddedPartition(
                inc_op=np.zeros(e_pad, np.int32),
                inc_trace=np.zeros(e_pad, np.int32),
                sr_val=np.zeros(e_pad, np.float32),
                rs_val=np.zeros(e_pad, np.float32),
                ss_child=np.zeros(c_pad, np.int32),
                ss_parent=np.zeros(c_pad, np.int32),
                ss_val=np.zeros(c_pad, np.float32),
                kind=np.ones(t_pad, np.int32),
                tracelen=np.ones(t_pad, np.int32),
                # The true trace list survives the collapse (codes are
                # the caller's partition contract, not column labels).
                local_uniques=np.zeros(true_tr, np.int32),
                cov_unique=np.zeros(v_pad, np.int32),
                op_present=np.zeros(v_pad, np.bool_),
                inc_trace_opmajor=np.zeros(e_pad if want_csr else 0, np.int32),
                sr_val_opmajor=np.zeros(e_pad if want_csr else 0, np.float32),
                inc_indptr_op=np.zeros(v_pad + 1 if want_csr else 0, np.int32),
                inc_indptr_trace=np.zeros(
                    t_pad + 1 if want_csr else 0, np.int32
                ),
                ss_indptr=np.zeros(v_pad + 1 if want_csr else 0, np.int32),
                cov_bits=np.zeros((v_pad, t8 if want_bits else 0), np.uint8),
                ss_bits=np.zeros((v_pad, v8 if want_bits else 0), np.uint8),
                inv_tracelen=np.zeros(t_pad, np.float32),
                inv_cov_dup=np.zeros(v_pad, np.float32),
                inv_outdeg=np.zeros(v_pad, np.float32),
                n_ops=n_ops,
                n_traces=true_tr,
                n_inc=n_inc,
                n_ss=n_ss,
                n_cols=(n_tr if true_traces is not None else -1),
            )
            lib.mr_export_partition(
                handle, ctypes.c_int32(idx),
                p.inc_op.ctypes.data_as(i32p),
                p.inc_trace.ctypes.data_as(i32p),
                p.sr_val.ctypes.data_as(f32p),
                p.rs_val.ctypes.data_as(f32p),
                p.ss_child.ctypes.data_as(i32p),
                p.ss_parent.ctypes.data_as(i32p),
                p.ss_val.ctypes.data_as(f32p),
                p.kind.ctypes.data_as(i32p),
                p.tracelen.ctypes.data_as(i32p),
                p.local_uniques.ctypes.data_as(i32p),
                p.cov_unique.ctypes.data_as(i32p),
                p.op_present.ctypes.data_as(u8p),
            )
            if want_bits:
                lib.mr_export_bitmaps(
                    handle, ctypes.c_int32(idx),
                    p.cov_bits.ctypes.data_as(u8p), ctypes.c_int64(t8),
                    p.ss_bits.ctypes.data_as(u8p), ctypes.c_int64(v8),
                    p.inv_tracelen.ctypes.data_as(f32p),
                    p.inv_cov_dup.ctypes.data_as(f32p),
                    p.inv_outdeg.ctypes.data_as(f32p),
                )
            else:
                # The inverse vectors are cheap and also wanted by "csr"
                # callers for completeness — fill from the value arrays.
                p.inv_tracelen[p.inc_trace[:n_inc]] = p.sr_val[:n_inc]
                p.inv_cov_dup[p.inc_op[:n_inc]] = p.rs_val[:n_inc]
                p.inv_outdeg[p.ss_parent[:n_ss]] = p.ss_val[:n_ss]
            if want_csr:
                lib.mr_export_csr(
                    handle, ctypes.c_int32(idx),
                    ctypes.c_int64(vocab_size),
                    ctypes.c_int64(v_pad), ctypes.c_int64(t_pad),
                    p.inc_trace_opmajor.ctypes.data_as(i32p),
                    p.sr_val_opmajor.ctypes.data_as(f32p),
                    p.inc_indptr_op.ctypes.data_as(i32p),
                    p.inc_indptr_trace.ctypes.data_as(i32p),
                    p.ss_indptr.ctypes.data_as(i32p),
                )
            if want_pc:
                # Partition-centric binning over the exported trace-major
                # entries (the C++ counting sort guarantees the order; a
                # contiguous searchsorted split, numpy-vectorized —
                # shared with the pandas lane so the two builders cannot
                # diverge).
                from ..graph.build import pcsr_auxiliary

                pc_trace, pc_sr, pc_blk, pc_eop, pc_ers = pcsr_auxiliary(
                    p.inc_op, p.inc_trace, p.sr_val, p.rs_val,
                    n_inc, v_pad, t_pad,
                )
                p = p._replace(
                    pc_trace=pc_trace, pc_sr_val=pc_sr,
                    pc_blk_indptr=pc_blk, pc_ell_op=pc_eop,
                    pc_ell_rs=pc_ers,
                )
            if mode == "kind":
                # Kind-compressed views from the exported bitmap + edge
                # list (the shared constructor — graph.build.kind_aux).
                from ..graph.build import kind_aux

                cov_i8, ss_indptr = kind_aux(
                    p.cov_bits, p.ss_child, n_ss, v_pad, t_pad
                )
                p = p._replace(cov_i8=cov_i8, ss_indptr=ss_indptr)
            out.append(p)
        return out[0], out[1]
    finally:
        lib.mr_free_built(handle)


def detect_window_native(
    table: SpanTable,
    w0_us: int,
    w1_us: int,
    remap: np.ndarray,
    thresh_ms: np.ndarray,
    slack_ms: float,
):
    """Fused one-scan window detection (detector.cpp): window mask +
    per-trace expected/real + normal/abnormal partition, numerically
    identical to detect_batch_from_table + detect_numpy (parity-tested).

    ``remap`` maps table svc-op ids into the SLO vocab (int32, -1 for
    unseen); ``thresh_ms`` is the float32 mu + k*sigma array over that
    vocab. Returns (mask bool[S], nrm int32[], abn int32[],
    n_window_spans, n_traces_seen). Raises NativeUnavailable when the
    library can't build.
    """
    lib = _load_library()
    n_spans = table.n_spans
    n_total = len(table.trace_names)
    mask = np.empty(n_spans, dtype=np.uint8)
    nrm = np.empty(n_total, dtype=np.int32)
    abn = np.empty(n_total, dtype=np.int32)
    counts = np.zeros(4, dtype=np.int64)
    remap = np.ascontiguousarray(remap, dtype=np.int32)
    thresh_ms = np.ascontiguousarray(thresh_ms, dtype=np.float32)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    rc = lib.mr_detect_window(
        ctypes.c_int64(n_spans),
        table.trace_id.ctypes.data_as(i32p),
        table.svc_op.ctypes.data_as(i32p),
        table.duration_us.ctypes.data_as(i64p),
        table.start_us.ctypes.data_as(i64p),
        table.end_us.ctypes.data_as(i64p),
        ctypes.c_int64(int(w0_us)),
        ctypes.c_int64(int(w1_us)),
        remap.ctypes.data_as(i32p),
        ctypes.c_int64(len(remap)),
        thresh_ms.ctypes.data_as(f32p),
        ctypes.c_int64(len(thresh_ms)),
        ctypes.c_float(float(slack_ms)),
        ctypes.c_int64(n_total),
        mask.ctypes.data_as(u8p),
        nrm.ctypes.data_as(i32p),
        abn.ctypes.data_as(i32p),
        counts.ctypes.data_as(i64p),
    )
    if rc != 0:
        raise NativeUnavailable(f"mr_detect_window failed (rc={rc})")
    n_nrm, n_abn, n_window, n_seen = (int(c) for c in counts)
    return (
        mask.view(np.bool_),
        nrm[:n_nrm].copy(),
        abn[:n_abn].copy(),
        n_window,
        n_seen,
    )


__all__ = [
    "SpanTable",
    "PaddedPartition",
    "NativeUnavailable",
    "load_span_table",
    "build_window_padded",
    "detect_window_native",
    "native_available",
]
