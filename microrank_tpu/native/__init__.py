"""Native (C++) ingest runtime with a ctypes binding.

Builds ``libmrspan.so`` from span_loader.cpp on first use (g++ -O3; cached
next to the source) and exposes ``load_span_table(path)`` returning a
``SpanTable`` of interned numpy arrays. Falls back cleanly: callers should
catch ``NativeUnavailable`` and use the pandas path
(microrank_tpu.io.load_traces_csv).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import List, NamedTuple, Optional

import numpy as np

_SRC = Path(__file__).parent / "span_loader.cpp"
_LIB = Path(__file__).parent / "libmrspan.so"
_lib: Optional[ctypes.CDLL] = None


class NativeUnavailable(RuntimeError):
    pass


class SpanTable(NamedTuple):
    """One CSV dump, fully interned: the native ingest output.

    Times are epoch microseconds (trace-level start/end, as in the CSV
    contract); ``parent_row`` is the row index of each span's parent
    (-1 when absent) — the span linkage of preprocess_data.py:157-158
    resolved at load time.
    """

    trace_id: np.ndarray     # int32[S]
    svc_op: np.ndarray       # int32[S] service-level op (detector vocab)
    pod_op: np.ndarray       # int32[S] instance-level op (PageRank vocab)
    duration_us: np.ndarray  # int64[S]
    start_us: np.ndarray     # int64[S]
    end_us: np.ndarray       # int64[S]
    parent_row: np.ndarray   # int64[S]
    trace_names: List[str]
    svc_op_names: List[str]
    pod_op_names: List[str]

    @property
    def n_spans(self) -> int:
        return int(self.trace_id.shape[0])


class _MrSpanTable(ctypes.Structure):
    _fields_ = [
        ("n_spans", ctypes.c_int64),
        ("trace_id", ctypes.POINTER(ctypes.c_int32)),
        ("svc_op", ctypes.POINTER(ctypes.c_int32)),
        ("pod_op", ctypes.POINTER(ctypes.c_int32)),
        ("duration_us", ctypes.POINTER(ctypes.c_int64)),
        ("start_us", ctypes.POINTER(ctypes.c_int64)),
        ("end_us", ctypes.POINTER(ctypes.c_int64)),
        ("parent_row", ctypes.POINTER(ctypes.c_int64)),
        ("trace_blob", ctypes.c_char_p),
        ("trace_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_traces", ctypes.c_int64),
        ("svc_blob", ctypes.c_char_p),
        ("svc_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_svc_ops", ctypes.c_int64),
        ("pod_blob", ctypes.c_char_p),
        ("pod_offsets", ctypes.POINTER(ctypes.c_int64)),
        ("n_pod_ops", ctypes.c_int64),
        ("error", ctypes.c_char_p),
    ]


def _build_library() -> None:
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        str(_SRC), "-o", str(_LIB),
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, text=True, timeout=300
        )
    except FileNotFoundError as exc:
        raise NativeUnavailable("g++ not available") from exc
    except subprocess.CalledProcessError as exc:
        raise NativeUnavailable(
            f"native build failed:\n{exc.stderr}"
        ) from exc


def _load_library() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not _LIB.exists() or _LIB.stat().st_mtime < _SRC.stat().st_mtime:
        _build_library()
    lib = ctypes.CDLL(str(_LIB))
    lib.mr_load_csv.restype = ctypes.POINTER(_MrSpanTable)
    lib.mr_load_csv.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.mr_free_table.restype = None
    lib.mr_free_table.argtypes = [ctypes.POINTER(_MrSpanTable)]
    _lib = lib
    return lib


def _decode_vocab(blob: bytes, offsets, n: int) -> List[str]:
    offs = np.ctypeslib.as_array(offsets, shape=(n + 1,))
    return [
        blob[offs[i]: offs[i + 1]].decode("utf-8", "replace")
        for i in range(n)
    ]


def native_available() -> bool:
    try:
        _load_library()
        return True
    except NativeUnavailable:
        return False


def load_span_table(
    path, strip_services=("ts-ui-dashboard",)
) -> SpanTable:
    """Load one traces.csv (raw ClickHouse export or canonical schema)."""
    lib = _load_library()
    res = lib.mr_load_csv(
        str(path).encode(), ",".join(strip_services).encode()
    )
    try:
        t = res.contents
        if t.error:
            raise ValueError(
                f"native loader failed for {path}: {t.error.decode()}"
            )
        n = int(t.n_spans)

        def arr(ptr, dtype):
            if n == 0:
                return np.zeros(0, dtype=dtype)
            return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)

        # blob pointers: ctypes c_char_p auto-converts to bytes
        table = SpanTable(
            trace_id=arr(t.trace_id, np.int32),
            svc_op=arr(t.svc_op, np.int32),
            pod_op=arr(t.pod_op, np.int32),
            duration_us=arr(t.duration_us, np.int64),
            start_us=arr(t.start_us, np.int64),
            end_us=arr(t.end_us, np.int64),
            parent_row=arr(t.parent_row, np.int64),
            trace_names=_decode_vocab(
                t.trace_blob, t.trace_offsets, int(t.n_traces)
            ),
            svc_op_names=_decode_vocab(
                t.svc_blob, t.svc_offsets, int(t.n_svc_ops)
            ),
            pod_op_names=_decode_vocab(
                t.pod_blob, t.pod_offsets, int(t.n_pod_ops)
            ),
        )
        return table
    finally:
        lib.mr_free_table(res)


__all__ = [
    "SpanTable",
    "NativeUnavailable",
    "load_span_table",
    "native_available",
]
