// Fused window detector (reference components C1+C4+C5 in one scan).
//
// The numpy detect path (graph/table_ops.py detect_batch_from_table +
// detect/detector.py detect_numpy) makes several full passes over the
// window's spans: window mask, fancy-index gathers of op/trace/duration,
// per-trace bincount of SLO thresholds, and a per-trace duration max. At
// 1M spans that is ~45 ms; at the 16M-span stress shape it reaches
// ~1.7 s and dominates the window. This fused scan computes the SAME
// quantities in one pass over the table — window mask, per-trace
// expected = sum of mu+k*sigma over known ops (anormaly_detector.py:
// 64-65; unknown ops contribute 0 via the bare-except rule :66-67),
// per-trace real = max span duration (preprocess_data.py:110) — and then
// emits the normal/abnormal trace-id partitions ascending.
//
// Numeric parity with detect_numpy is exact by construction:
//   * expected accumulates float64 over float32 thresholds in row order
//     (numpy: bincount weights promote f32->f64, summed in row order),
//     compared as float32;
//   * real converts the int64 max to float32 then divides by 1000.0f
//     (numpy converts each duration to f32 BEFORE the max — f32
//     conversion is monotone, so max-then-convert is value-identical);
//   * abnormal iff real_ms > float32(expected) + slack_ms, valid iff
//     real_ms > 0 (detect/detector.py:56-66).

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Returns 0 on success. Caller allocates:
//   mask      uint8[n_spans]       (1 = span inside [w0, w1])
//   nrm, abn  int32[n_traces_total] (filled prefixes, ascending ids)
//   counts    int64[4] = {n_nrm, n_abn, n_window_spans, n_traces_seen}
int mr_detect_window(
    int64_t n_spans,
    const int32_t* trace_id,
    const int32_t* svc_op,
    const int64_t* duration_us,
    const int64_t* start_us,
    const int64_t* end_us,
    int64_t w0_us,
    int64_t w1_us,
    const int32_t* remap,      // table svc-op id -> SLO vocab id or -1
    int64_t n_svc_vocab,
    const float* thresh_ms,    // mu + k*sigma per SLO vocab id
    int64_t n_slo_vocab,
    float slack_ms,
    int64_t n_traces_total,
    uint8_t* mask,
    int32_t* nrm,
    int32_t* abn,
    int64_t* counts) {
  std::vector<double> expected(static_cast<size_t>(n_traces_total), 0.0);
  std::vector<int64_t> real_us(static_cast<size_t>(n_traces_total),
                               INT64_MIN);
  std::vector<uint8_t> seen(static_cast<size_t>(n_traces_total), 0);

  int64_t n_window = 0;
  for (int64_t i = 0; i < n_spans; ++i) {
    const bool in = start_us[i] >= w0_us && end_us[i] <= w1_us;
    mask[i] = in ? 1 : 0;
    if (!in) continue;
    ++n_window;
    const int32_t t = trace_id[i];
    if (t < 0 || t >= n_traces_total) continue;  // defensive; loader ids
    seen[t] = 1;
    const int32_t op = svc_op[i];
    if (op >= 0 && op < n_svc_vocab) {
      const int32_t m = remap[op];
      if (m >= 0 && m < n_slo_vocab) {
        expected[t] += static_cast<double>(thresh_ms[m]);
      }
    }
    if (duration_us[i] > real_us[t]) real_us[t] = duration_us[i];
  }

  int64_t n_nrm = 0, n_abn = 0, n_seen = 0;
  for (int64_t t = 0; t < n_traces_total; ++t) {
    if (!seen[t]) continue;
    ++n_seen;
    const float real_ms = static_cast<float>(real_us[t]) / 1000.0f;
    if (!(real_ms > 0.0f)) continue;  // valid traces only, like numpy
    const float exp_ms = static_cast<float>(expected[t]);
    if (real_ms > exp_ms + slack_ms) {
      abn[n_abn++] = static_cast<int32_t>(t);
    } else {
      nrm[n_nrm++] = static_cast<int32_t>(t);
    }
  }
  counts[0] = n_nrm;
  counts[1] = n_abn;
  counts[2] = n_window;
  counts[3] = n_seen;
  return 0;
}

}  // extern "C"
