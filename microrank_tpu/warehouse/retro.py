"""Retroactive scenario scoring over STORED incidents.

``cli scenarios --from-warehouse DIR`` treats a warehouse as a scenario
source: every stored ranked window is re-ranked under ALL 13 spectrum
formulas in one device dispatch per window
(``rank_window_all_methods_device`` on the stored blob, spectrum
widened so every op gets an exact rank), scored tie-aware
(MAP/MRR/top-k) against the run's recorded ground truth, aggregated in
the scenario harness's exact ``formulas`` shape, and fed through
``select_policy`` — so the policy engine tunes on REAL incident
outcomes, not only synthetic matrices. Truth comes from the manifest
(the engine records the fault source's pod:ops when it has one); runs
without recorded truth fall back to the consensus live top-1 across the
stored incidents (``outcome_source="incident_top1"``).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, List, Optional

RETRO_MATRIX_NAME = "retro_matrix.json"

_KS = (1, 2, 3, 5)


def run_retro(path, config=None, seed: Optional[int] = None,
              persist_policy: bool = True, name: Optional[str] = None,
              out_path=None) -> dict:
    """Score a warehouse's stored incidents across all formulas.

    Returns ``{"record": <harness-shaped scenario record>, "policy":
    <selected policy doc>, "truth", "outcome_source", ...}`` and writes
    ``retro_matrix.json`` into the warehouse dir (or ``out_path``).
    """
    import jax
    import numpy as np

    from ..config import MicroRankConfig
    from ..evaluation import ranking_metrics
    from ..rank_backends.jax_tpu import rank_window_all_methods_device
    from ..scenarios.policy import (
        profile_from_counts,
        resolve_policy_dir,
        save_policy,
        select_policy,
    )
    from ..spectrum.formulas import METHODS
    from ..utils.atomic import atomic_write_json
    from ..utils.guards import claim_device_owner
    from .store import TraceWarehouse, resolve_warehouse_dir

    if config is None:
        config = MicroRankConfig()
    claim_device_owner("warehouse-retro")
    whdir = resolve_warehouse_dir(path)
    store = TraceWarehouse(whdir, config.warehouse)
    windows = store.query()
    ranked = [w for w in windows if w.outcome == "ranked" and w.ranking]

    truth, outcome_source = _resolve_truth(store, ranked)

    per_method: Dict[str, List[dict]] = {m: [] for m in METHODS}
    scored_windows = 0
    spans_total = 0
    dedup_vals = []
    vocab_sizes = []
    for w in ranked:
        g = w.graph()
        op_names = w.op_names
        if g is None or not op_names or not truth:
            continue
        # Full-depth ranking: widen top_max so every op gets an exact
        # rank (the harness's _widen move, anchored to the stored blob's
        # own op table).
        widened = dataclasses.replace(
            config.spectrum, top_max=len(op_names)
        )
        top_idx, top_scores, n_valid = jax.device_get(
            rank_window_all_methods_device(
                jax.device_put(g),
                config.pagerank,
                widened,
                None,
                w.kernel or "coo",
            )
        )
        n = int(n_valid)
        for mi, m in enumerate(METHODS):
            names = [op_names[int(i)] for i in top_idx[mi, :n]]
            scores = [float(s) for s in top_scores[mi, :n]]
            per_method[m].append(
                ranking_metrics(names, scores, truth, ks=_KS)
            )
        scored_windows += 1
        spans_total += int(w.meta.get("spans", 0))
        if w.meta.get("kind_dedup"):
            dedup_vals.append(float(w.meta["kind_dedup"]))
        vocab = w.vocab_names
        vocab_sizes.append(len(vocab) if vocab else len(op_names))

    formulas = _aggregate(per_method, truth)

    profile = None
    if scored_windows:
        profile = profile_from_counts(
            n_spans=int(spans_total / scored_windows),
            n_ops=int(np.mean(vocab_sizes)),
            dedup_factor=(
                float(np.mean(dedup_vals)) if dedup_vals else None
            ),
        ).key()

    run_name = name or Path(whdir).resolve().parent.name or "run"
    record = {
        "scenario": f"warehouse:{run_name}",
        "family": "warehouse",
        "seed": seed,
        "profile": profile,
        "spans": int(spans_total),
        "truth": list(truth),
        "outcome_source": outcome_source,
        "windows": scored_windows,
        "formulas": formulas,
    }

    policy = select_policy([record], None, matrix_seed=seed)
    policy_path = None
    if persist_policy and formulas and profile:
        policy_path = str(
            save_policy(resolve_policy_dir(config.runtime), policy)
        )

    result = {
        "record": record,
        "policy": policy,
        "policy_path": policy_path,
        "truth": list(truth),
        "outcome_source": outcome_source,
        "windows_stored": len(windows),
        "windows_ranked": len(ranked),
        "windows_scored": scored_windows,
    }
    artifact = Path(out_path) if out_path else whdir / RETRO_MATRIX_NAME
    atomic_write_json(artifact, result)
    result["artifact"] = str(artifact)
    return result


def _resolve_truth(store, ranked):
    """Manifest-recorded truth, else the consensus live top-1 across
    stored incidents (self-referential but still useful as a formula
    stability probe — flagged via ``outcome_source``)."""
    truth = store.truth
    if truth:
        if isinstance(truth, dict):
            truth = sorted(
                {str(v) for vals in truth.values()
                 for v in (vals if isinstance(vals, list) else [vals])}
            )
        return [str(t) for t in truth], "manifest"
    counts: Dict[str, int] = {}
    for w in ranked:
        if w.ranking:
            top1 = w.ranking[0][0]
            counts[top1] = counts.get(top1, 0) + 1
    if not counts:
        return [], "none"
    best = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))[0][0]
    return [best], "incident_top1"


def _aggregate(per_method: Dict[str, List[dict]], truth) -> Dict[str, dict]:
    """The scenario harness's ``formulas`` aggregation, verbatim shape —
    retro records must be drop-in ``select_policy`` food."""
    formulas: Dict[str, dict] = {}
    for m, rows in per_method.items():
        if not rows:
            continue
        n = len(rows)
        mean = lambda vals: sum(vals) / n  # noqa: E731
        topk_rate = {
            int(k): mean([float(r["topk_exact"][int(k)]) for r in rows])
            for k in _KS
        }
        found = [
            r2 for r in rows for r2 in r["ranks"].values()
            if r2 is not None
        ]
        formulas[m] = {
            "map": round(mean([r["ap"] for r in rows]), 4),
            "mrr": round(mean([r["rr"] for r in rows]), 4),
            "top1_rate": round(topk_rate.get(1, 0.0), 4),
            "topc_rate": round(
                mean([
                    float(all(
                        r3 is not None and r3 <= max(1, len(truth))
                        for r3 in r["ranks"].values()
                    ))
                    for r in rows
                ]),
                4,
            ),
            "topk_rate": topk_rate,
            "mean_rank": (
                round(sum(found) / len(found), 2) if found else None
            ),
            "unranked": sum(
                1 for r in rows for r2 in r["ranks"].values()
                if r2 is None
            ),
            "windows": n,
        }
    return formulas


def render_retro_table(result: dict) -> str:
    """Small fixed-width per-formula table for the CLI."""
    formulas = (result.get("record") or {}).get("formulas") or {}
    lines = [
        f"warehouse retro-score: {result.get('windows_scored', 0)} "
        f"windows, truth={result.get('truth')} "
        f"({result.get('outcome_source')})",
    ]
    if not formulas:
        lines.append("  (no scored windows)")
        return "\n".join(lines)
    hdr = (
        f"  {'formula':<16} {'MAP':>7} {'MRR':>7} {'top1':>6} "
        f"{'top3':>6} {'top5':>6} {'meanrk':>7}"
    )
    lines.append(hdr)
    for m in sorted(
        formulas, key=lambda m: -float(formulas[m]["map"] or 0)
    ):
        row = formulas[m]
        tk = row.get("topk_rate") or {}
        mr = row.get("mean_rank")
        lines.append(
            f"  {m:<16} {row['map']:>7.4f} {row['mrr']:>7.4f} "
            f"{tk.get(1, 0):>6.2f} {tk.get(3, 0):>6.2f} "
            f"{tk.get(5, 0):>6.2f} {mr if mr is not None else '-':>7}"
        )
    winner = (
        (result.get("policy") or {}).get("profiles") or {}
    )
    for prof, entry in winner.items():
        lines.append(
            f"  policy: {prof} -> method={entry['method']} "
            f"(MAP {entry['evidence']['map']})"
        )
    return "\n".join(lines)
