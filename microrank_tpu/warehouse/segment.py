"""Segment codec: window records <-> dictionary-compressed ``.npz``.

One segment holds one or more window records. Each record stores:

* the admitted span frame, columnar: string columns as per-segment
  dictionaries + int32 codes (``spanID``/``ParentSpanId`` share one
  dictionary — parents reference span ids), integer/datetime columns
  delta-encoded against their minimum so deflate sees mostly-zero high
  bytes;
* for ranked windows, the packed rank blob + its static layout + the
  op-name table + kernel — the staged device format IS the at-rest
  format (the measured 71.2x kind dedup + int8 ``cov_i8`` make it
  near-ideal), so replay is a blob load, not a parse/build;
* the detection context the verdict was computed under: op-vocab
  snapshot, SLO-baseline mean/std (bit-faithful float32 arrays), and
  the admission counters from the live window.

The file is a ``np.savez_compressed`` zip (no pickle anywhere): arrays
under ``w<i>_``-prefixed keys plus one JSON ``meta`` member describing
every window. Writes go through tmp + fsync + rename, so a torn
segment can never carry a segment's final name.
"""

from __future__ import annotations

import io
import json
import math
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

SEGMENT_SCHEMA = 1

#: np.savez member names for one window record (prefixed ``w<i>_``).
_BLOB_KEY = "blob"
_OPS_KEY = "ops"
_VOCAB_KEY = "vocab"
_SLO_MEAN_KEY = "slo_mean"
_SLO_STD_KEY = "slo_std"
_IDDICT_KEY = "iddict"

#: Columns sharing one id dictionary (parents reference span ids).
_SHARED_ID_COLS = ("spanID", "ParentSpanId")


# ------------------------------------------------------------- frame codec


def encode_frame(frame) -> Tuple[Dict[str, np.ndarray], dict]:
    """Columnar-encode one span DataFrame.

    Returns ``(arrays, frame_meta)``; ``frame_meta["columns"]`` records
    per-column encoding so :func:`decode_frame` reconstructs values
    exactly (dictionary codes for strings, delta-from-base for
    integer/datetime columns, raw arrays otherwise).
    """
    import pandas as pd

    arrays: Dict[str, np.ndarray] = {}
    cols_meta: List[dict] = []
    shared = [
        c for c in _SHARED_ID_COLS
        if c in frame.columns
        and not pd.api.types.is_numeric_dtype(frame[c])
    ]
    if len(shared) == 2:
        parts = []
        for c in shared:
            ser = frame[c]
            mask = ser.notna().to_numpy()
            if mask.any():
                parts.append(ser[mask].astype(str).to_numpy(dtype=str))
        uniq = np.unique(
            np.concatenate(parts)
            if parts
            else np.asarray([], dtype=str)
        )
        arrays[_IDDICT_KEY] = uniq
    else:
        shared = []

    for col in frame.columns:
        ser = frame[col]
        dt = ser.dtype
        meta: dict = {"name": str(col), "dtype": str(dt)}
        key = f"col_{col}"
        if col in shared:
            meta["enc"] = "dict_shared"
            codes = _dict_codes(ser, arrays[_IDDICT_KEY])
            arrays[key] = codes
        elif pd.api.types.is_datetime64_any_dtype(dt):
            meta["enc"] = "datetime"
            vals = ser.to_numpy().view("int64")
            base = int(vals.min()) if len(vals) else 0
            meta["base"] = base
            arrays[key] = (vals - base).astype(np.int64)
        elif pd.api.types.is_bool_dtype(dt):
            meta["enc"] = "bool"
            arrays[key] = ser.to_numpy().astype(np.uint8)
        elif pd.api.types.is_integer_dtype(dt):
            meta["enc"] = "int"
            vals = ser.to_numpy().astype(np.int64)
            base = int(vals.min()) if len(vals) else 0
            meta["base"] = base
            arrays[key] = vals - base
        elif pd.api.types.is_float_dtype(dt):
            meta["enc"] = "float"
            arrays[key] = ser.to_numpy()
        else:
            meta["enc"] = "dict"
            nn = ser.dropna()
            uniq = np.unique(nn.astype(str).to_numpy(dtype=str))
            arrays[f"dict_{col}"] = uniq
            arrays[key] = _dict_codes(ser, uniq)
        cols_meta.append(meta)
    return arrays, {"columns": cols_meta, "rows": int(len(frame))}


def _dict_codes(ser, uniq: np.ndarray) -> np.ndarray:
    """int32 codes into a sorted dictionary; -1 marks nulls."""
    mask = ser.notna().to_numpy()
    codes = np.full(len(ser), -1, dtype=np.int32)
    if mask.any() and len(uniq):
        vals = ser[mask].astype(str).to_numpy(dtype=str)
        codes[mask] = np.searchsorted(uniq, vals).astype(np.int32)
    return codes


def _object_lut(uniq: np.ndarray) -> np.ndarray:
    """Dictionary -> object lookup table with a trailing NaN slot.

    Boxing the ``<U`` dictionary into Python strings happens ONCE here
    (len(dict) allocations); row decode is then a pure pointer gather,
    and code -1 (null) indexes the last slot — no per-row Python. The
    shared id dictionary reuses one LUT for both span-id columns, so
    the two columns also share their string objects."""
    lut = np.empty(len(uniq) + 1, dtype=object)
    if len(uniq):
        lut[:-1] = uniq
    lut[-1] = np.nan
    return lut


def decode_frame(arrays: Dict[str, np.ndarray], frame_meta: dict):
    """Inverse of :func:`encode_frame`."""
    import pandas as pd

    data = {}
    luts: Dict[str, np.ndarray] = {}
    for meta in frame_meta["columns"]:
        col = meta["name"]
        enc = meta["enc"]
        raw = arrays[f"col_{col}"]
        if enc in ("dict", "dict_shared"):
            dict_key = _IDDICT_KEY if enc == "dict_shared" else f"dict_{col}"
            lut = luts.get(dict_key)
            if lut is None:
                lut = luts[dict_key] = _object_lut(arrays[dict_key])
            data[col] = lut[raw]
        elif enc == "datetime":
            ns = raw.astype(np.int64) + int(meta.get("base", 0))
            data[col] = ns.view(meta["dtype"])
        elif enc == "bool":
            data[col] = raw.astype(bool)
        elif enc == "int":
            vals = raw.astype(np.int64) + int(meta.get("base", 0))
            data[col] = vals.astype(meta["dtype"])
        else:
            data[col] = raw
    frame = pd.DataFrame(data)
    for meta in frame_meta["columns"]:
        if meta["enc"] == "float":
            frame[meta["name"]] = frame[meta["name"]].astype(meta["dtype"])
    return frame


# -------------------------------------------------------------- blob codec


def unpack_graph_blob_host(blob: np.ndarray, layout) -> "WindowGraph":
    """Host mirror of ``rank_backends.blob.unpack_graph_blob``: rebuild
    a WindowGraph from the packed uint32 buffer with numpy view-casts
    (4-byte dtypes) and uint8 slices (sub-word dtypes) — bit-exact, so
    dispatching the rebuilt graph through the SAME programs reproduces
    the live scores."""
    from ..graph.structures import PartitionGraph, WindowGraph

    u8 = np.ascontiguousarray(blob, dtype=np.uint32).view(np.uint8)
    parts = []
    for entries in layout:
        leaves = []
        for _f, dtype_str, shape, off, n_words in entries:
            n = int(math.prod(shape)) if shape else 1
            b = u8[off * 4 : (off + n_words) * 4]
            if dtype_str in ("float32", "int32"):
                leaf = b.view(dtype_str)[:n].reshape(shape)
            elif dtype_str == "bool":
                leaf = (b[:n] != 0).reshape(shape)
            elif dtype_str == "int8":
                leaf = b[:n].view(np.int8).reshape(shape)
            elif dtype_str == "uint8":
                leaf = b[:n].reshape(shape)
            else:
                raise TypeError(
                    f"warehouse blob: unsupported leaf dtype {dtype_str!r}"
                )
            leaves.append(leaf)
        parts.append(PartitionGraph(*leaves))
    return WindowGraph(normal=parts[0], abnormal=parts[1])


def layout_to_json(layout) -> list:
    return [
        [[f, d, list(s), int(o), int(n)] for f, d, s, o, n in part]
        for part in layout
    ]


def layout_from_json(data) -> tuple:
    return tuple(
        tuple(
            (str(f), str(d), tuple(int(x) for x in s), int(o), int(n))
            for f, d, s, o, n in part
        )
        for part in data
    )


# ---------------------------------------------------------- window records


@dataclass
class StoredWindow:
    """One window as read back from a segment: per-window meta plus the
    raw (prefix-stripped) arrays; frame/graph materialize lazily."""

    meta: dict
    arrays: Dict[str, np.ndarray] = field(default_factory=dict)
    segment: str = ""

    @property
    def start_us(self) -> int:
        return int(self.meta["start_us"])

    @property
    def end_us(self) -> int:
        return int(self.meta["end_us"])

    @property
    def outcome(self) -> str:
        return str(self.meta.get("outcome", ""))

    @property
    def ranking(self) -> list:
        return [
            (str(n), float(s)) for n, s in self.meta.get("ranking") or []
        ]

    @property
    def kernel(self) -> Optional[str]:
        return self.meta.get("kernel")

    @property
    def op_names(self) -> Optional[List[str]]:
        ops = self.arrays.get(_OPS_KEY)
        return None if ops is None else [str(o) for o in ops]

    @property
    def vocab_names(self) -> Optional[List[str]]:
        v = self.arrays.get(_VOCAB_KEY)
        return None if v is None else [str(n) for n in v]

    def slo_baseline(self):
        """The stored SLO snapshot as a ``SloBaseline`` (float32 arrays,
        bit-faithful), or None for pre-detection (warmup) windows."""
        mean = self.arrays.get(_SLO_MEAN_KEY)
        if mean is None:
            return None
        from ..graph.structures import SloBaseline

        return SloBaseline(
            mean_ms=np.asarray(mean, np.float32),
            std_ms=np.asarray(self.arrays[_SLO_STD_KEY], np.float32),
        )

    def frame(self):
        """The admitted span frame, or None when spans were not stored."""
        fm = self.meta.get("frame")
        if fm is None:
            return None
        return decode_frame(self.arrays, fm)

    def graph(self):
        """The rank-ready WindowGraph rebuilt from the stored blob, or
        None for windows without one (non-ranked, or blobs disabled)."""
        blob = self.arrays.get(_BLOB_KEY)
        if blob is None or self.meta.get("layout") is None:
            return None
        return unpack_graph_blob_host(
            blob, layout_from_json(self.meta["layout"])
        )


def encode_window(rec: dict) -> Tuple[Dict[str, np.ndarray], dict]:
    """Encode one hot-tier window record (see ``store.TraceWarehouse
    .observe``) into (arrays, per-window meta)."""
    arrays: Dict[str, np.ndarray] = {}
    meta = dict(rec["meta"])
    meta["schema"] = SEGMENT_SCHEMA
    frame = rec.get("frame")
    if frame is not None:
        f_arrays, f_meta = encode_frame(frame)
        arrays.update(f_arrays)
        meta["frame"] = f_meta
    graph_pack = rec.get("graph_pack")
    if graph_pack is not None:
        blob, layout, op_names = graph_pack
        arrays[_BLOB_KEY] = np.asarray(blob, np.uint32)
        arrays[_OPS_KEY] = np.asarray(list(op_names), dtype=str)
        meta["layout"] = layout_to_json(layout)
    snapshot = rec.get("snapshot")
    if snapshot is not None:
        vocab, slo = snapshot
        names = vocab.names if hasattr(vocab, "names") else list(vocab)
        arrays[_VOCAB_KEY] = np.asarray(list(names), dtype=str)
        arrays[_SLO_MEAN_KEY] = np.asarray(slo.mean_ms, np.float32)
        arrays[_SLO_STD_KEY] = np.asarray(slo.std_ms, np.float32)
    return arrays, meta


# ------------------------------------------------------------ segment file


def write_segment(path, windows: List[Tuple[Dict[str, np.ndarray], dict]]):
    """Write one segment (list of encoded windows) atomically: tmp +
    fsync + rename, then directory fsync — a crash can leave a stale
    tmp, never a torn file under the final name. Returns bytes
    written."""
    path = Path(path)
    arrays: Dict[str, np.ndarray] = {}
    metas = []
    for i, (w_arrays, w_meta) in enumerate(windows):
        for k, v in w_arrays.items():
            arrays[f"w{i}_{k}"] = v
        metas.append(w_meta)
    doc = {"schema": SEGMENT_SCHEMA, "windows": metas}
    arrays["meta"] = np.frombuffer(
        json.dumps(doc).encode("utf-8"), dtype=np.uint8
    )
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    data = buf.getvalue()
    tmp = path.with_name(f"{path.name}.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(path.parent)
    return len(data)


def _fsync_dir(dirpath) -> None:
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:  # pragma: no cover - platform without dir fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def read_segment_meta(path) -> dict:
    """The segment's JSON meta document (windows list) without loading
    the column arrays. Raises on a torn/unreadable file."""
    with np.load(path, allow_pickle=False) as z:
        return json.loads(bytes(z["meta"]).decode("utf-8"))


def load_segment(path) -> List[StoredWindow]:
    """Read every window record of one segment."""
    path = Path(path)
    out: List[StoredWindow] = []
    with np.load(path, allow_pickle=False) as z:
        doc = json.loads(bytes(z["meta"]).decode("utf-8"))
        for i, meta in enumerate(doc["windows"]):
            prefix = f"w{i}_"
            arrays = {
                k[len(prefix):]: z[k]
                for k in z.files
                if k.startswith(prefix)
            }
            out.append(
                StoredWindow(meta=meta, arrays=arrays, segment=path.name)
            )
    return out
