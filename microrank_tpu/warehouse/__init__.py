"""Trace warehouse: tiered columnar span store + time-travel RCA.

Everything else in the system is a moving window — once a window seals,
its spans, vocab and baseline context are gone. The warehouse makes
history a first-class workload (ROADMAP item 4): the stream engine
feeds it at window-seal time, and every stored window carries its OWN
detection context (op-vocab snapshot, SLO-baseline snapshot, admission
counters), so any time range is re-rankable later with byte-faithful
context.

Tiers:

* **hot** — in-memory sealed windows, flushed at every pipeline-drained
  checkpoint boundary;
* **warm** — one dictionary-compressed ``seg-<start>-<end>.npz`` per
  window: the admitted span frame (per-column dictionaries + int32
  codes, delta-encoded timestamps) plus, for ranked windows, the packed
  rank blob (``rank_backends.blob``) — replay is a blob load + a
  DispatchRouter dispatch, not a CSV parse + graph build;
* **cold** — compacted multi-window ``cold-<start>-<end>.npz`` segments
  (same per-window records, one zip), with optional retention.

A checkpoint-style manifest (version + sha256, atomic seal through
``utils.atomic``) indexes the segments; corruption is rejected WHOLE
and the store rebuilds the manifest by cold re-scanning the segment
files. The seal order is pinned: segment data first, then the
``warehouse_seal`` chaos seam, then the manifest — a crash between
segment flush and checkpoint write neither loses nor duplicates spans
on ``--resume`` (deterministic per-window file names make the re-seal
idempotent).
"""

from .manifest import (
    MANIFEST_NAME,
    WAREHOUSE_DIR,
    WAREHOUSE_VERSION,
    WarehouseError,
    load_manifest,
    rescan_segments,
    seal_manifest,
)
from .replay import parse_time_range, replay_range
from .retro import RETRO_MATRIX_NAME, render_retro_table, run_retro
from .segment import (
    StoredWindow,
    decode_frame,
    encode_frame,
    load_segment,
    unpack_graph_blob_host,
    write_segment,
)
from .store import TraceWarehouse, load_warehouse_frame, resolve_warehouse_dir

__all__ = [
    "MANIFEST_NAME",
    "RETRO_MATRIX_NAME",
    "StoredWindow",
    "TraceWarehouse",
    "WAREHOUSE_DIR",
    "WAREHOUSE_VERSION",
    "WarehouseError",
    "decode_frame",
    "encode_frame",
    "load_manifest",
    "load_segment",
    "load_warehouse_frame",
    "parse_time_range",
    "render_retro_table",
    "replay_range",
    "rescan_segments",
    "resolve_warehouse_dir",
    "run_retro",
    "seal_manifest",
    "unpack_graph_blob_host",
    "write_segment",
]
