"""TraceWarehouse: hot buffer -> warm segments -> cold compaction.

Seal protocol (the exactly-once contract the crash test pins):

1. every hot window is written to its own ``seg-<start_us>-<end_us>.npz``
   (atomic tmp+fsync+rename; the name is a pure function of the window
   bounds, so a re-seal after a crash OVERWRITES the orphan instead of
   duplicating it);
2. the ``warehouse_seal`` chaos seam fires — ``kill`` exits the process
   here, raising kinds propagate ``InjectedFault`` to the engine, which
   then SKIPS the checkpoint write (the previous checkpoint stands, the
   source replays the same windows, step 1 makes the re-seal a no-op);
3. the manifest is sealed (checkpoint-style version+sha256, atomic) —
   only now do the segments exist as far as readers are concerned;
4. the hot buffer clears, then compaction folds the oldest warm
   segments into a cold multi-window segment (warm files are deleted
   only AFTER the manifest listing the cold segment is sealed) and
   retention drops the oldest cold segments past the configured cap.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from ..chaos.faults import maybe_inject
from .manifest import (
    MANIFEST_NAME,
    WAREHOUSE_DIR,
    WarehouseError,
    load_manifest,
    rescan_segments,
    seal_manifest,
)
from .segment import StoredWindow, encode_window, load_segment, write_segment


def resolve_warehouse_dir(path, cfg=None) -> Path:
    """Resolve a warehouse directory from an explicit config, a run
    output dir, or the warehouse dir itself (CLI accepts either)."""
    if cfg is not None and getattr(cfg, "dir", None):
        return Path(cfg.dir)
    p = Path(path)
    if (p / MANIFEST_NAME).exists() or p.name == WAREHOUSE_DIR:
        return p
    sub = p / WAREHOUSE_DIR
    if cfg is not None or (sub / MANIFEST_NAME).exists() or sub.is_dir():
        return sub
    return p


def _to_us(val) -> int:
    """Window bound -> epoch microseconds (bounds arrive as the strings
    WindowResult carries, or as timestamps in direct API use)."""
    if isinstance(val, (int, np.integer)):
        return int(val)
    import pandas as pd

    return int(pd.Timestamp(val).value // 1000)


def _jsonable_truth(truth):
    if truth is None:
        return None
    if isinstance(truth, (set, frozenset, tuple)):
        return sorted(str(t) for t in truth)
    if isinstance(truth, dict):
        return {str(k): _jsonable_truth(v) for k, v in truth.items()}
    if isinstance(truth, list):
        return [str(t) for t in truth]
    return str(truth)


class TraceWarehouse:
    """One run's tiered segment store rooted at ``<out_dir>/warehouse``
    (or ``WarehouseConfig.dir``)."""

    def __init__(self, base_dir, cfg, truth=None):
        self.cfg = cfg
        self.dir = resolve_warehouse_dir(base_dir, cfg)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.truth = _jsonable_truth(truth)
        self._hot: List[dict] = []
        self._segments: List[dict] = []
        self._counters: Dict[str, int] = {
            "windows": 0, "spans": 0, "ingest_rejected": 0,
        }
        self.sealed_through_us = 0
        try:
            payload = load_manifest(self.dir)
        except WarehouseError as exc:
            # Rejected whole -> rebuild from the segment files and
            # re-seal so readers get a provably-intact index again.
            from ..obs.journal import emit_current

            emit_current("warehouse_manifest_rejected", error=str(exc))
            self._segments = rescan_segments(self.dir)
            self._recount()
            self._seal()
            return
        if payload is not None:
            self._segments = list(payload.get("segments", []))
            self.sealed_through_us = int(payload.get("sealed_through_us", 0))
            self._counters.update(payload.get("counters", {}))
            if self.truth is None:
                self.truth = payload.get("truth")

    # ------------------------------------------------------------- ingest

    def observe(self, result, outcome: str, frame=None, graph=None,
                op_names=None, kernel=None, snapshot=None) -> None:
        """Buffer one sealed window (hot tier). Called by the stream
        engine at finalize time, BEFORE the baseline absorbs the window,
        so the stored snapshot is the exact detection context."""
        spans = 0 if frame is None else int(len(frame))
        meta = {
            "start": str(result.start),
            "end": str(result.end),
            "start_us": _to_us(result.start),
            "end_us": _to_us(result.end),
            "outcome": outcome,
            "anomaly": bool(result.anomaly),
            "skipped_reason": result.skipped_reason,
            "n_traces": int(result.n_traces),
            "n_abnormal": int(result.n_abnormal),
            "ranking": (
                [[str(n), float(s)] for n, s in result.ranking]
                if result.ranking else None
            ),
            "kernel": kernel or result.kernel,
            "kind_dedup": result.kind_dedup,
            "ingest_rejected": int(getattr(result, "ingest_rejected", 0)),
            "degraded_input": bool(getattr(result, "degraded_input", False)),
            "spans": spans,
            "baseline_ready": snapshot is not None,
        }
        rec: dict = {"meta": meta}
        if frame is not None and self.cfg.store_spans:
            rec["frame"] = frame
        if graph is not None and op_names is not None and self.cfg.store_blobs:
            from ..rank_backends.blob import pack_graph_blob

            blob, layout = pack_graph_blob(graph)
            rec["graph_pack"] = (np.asarray(blob), layout, list(op_names))
        if snapshot is not None:
            rec["snapshot"] = snapshot
        self._hot.append(rec)

    # --------------------------------------------------------------- seal

    def flush(self) -> int:
        """Seal every hot window into warm segments + the manifest.

        Raises ``InjectedFault`` when the ``warehouse_seal`` seam is
        armed with a raising kind — crucially AFTER the segment files
        hit disk and BEFORE the manifest/checkpoint, the torn state the
        crash-consistency test drives through.
        """
        if not self._hot:
            return 0
        flushed = 0
        rows: List[dict] = []
        for rec in self._hot:
            meta = rec["meta"]
            name = f"seg-{meta['start_us']}-{meta['end_us']}.npz"
            path = self.dir / name
            nbytes = write_segment(path, [encode_window(rec)])
            rows.append({
                "file": name,
                "tier": "warm",
                "start_us": meta["start_us"],
                "end_us": meta["end_us"],
                "windows": 1,
                "spans": meta["spans"],
                "bytes": int(nbytes),
                "outcomes": {meta["outcome"]: 1},
            })
            flushed += 1
        act = maybe_inject("warehouse_seal")
        if isinstance(act, dict) and act.get("kind") == "kill":
            # Simulated hard crash between segment flush and manifest/
            # checkpoint write. 137 = SIGKILL's conventional exit code.
            os._exit(137)
        for row in rows:
            self._adopt_row(row)
            self._counters["windows"] += 1
            self._counters["spans"] += row["spans"]
        self._counters["ingest_rejected"] += sum(
            r["meta"]["ingest_rejected"] for r in self._hot
        )
        self.sealed_through_us = max(
            [self.sealed_through_us] + [r["end_us"] for r in rows]
        )
        self._seal()
        self._hot = []
        self._record_seal("warm", flushed, sum(r["spans"] for r in rows),
                          sum(r["bytes"] for r in rows))
        self._compact()
        self._retain()
        return flushed

    def _adopt_row(self, row: dict) -> None:
        """Insert/replace by file name — the idempotence point: a
        re-seal after a crash replaces the manifest row instead of
        appending a duplicate."""
        for i, existing in enumerate(self._segments):
            if existing["file"] == row["file"]:
                self._counters["windows"] -= existing["windows"]
                self._counters["spans"] -= existing["spans"]
                self._segments[i] = row
                return
        self._segments.append(row)
        self._segments.sort(
            key=lambda r: (r["start_us"], r["end_us"], r["file"])
        )

    def _seal(self) -> None:
        seal_manifest(self.dir, self.manifest_payload())

    def manifest_payload(self) -> dict:
        return {
            "segments": self._segments,
            "sealed_through_us": self.sealed_through_us,
            "counters": dict(self._counters),
            "truth": self.truth,
        }

    def _recount(self) -> None:
        self._counters["windows"] = sum(
            r["windows"] for r in self._segments
        )
        self._counters["spans"] = sum(r["spans"] for r in self._segments)
        if self._segments:
            self.sealed_through_us = max(
                r["end_us"] for r in self._segments
            )

    # ---------------------------------------------------- compact / retain

    def _compact(self) -> None:
        """Fold the oldest ``compact_after`` warm segments into one cold
        multi-window segment. Warm files are deleted only after the
        manifest naming the cold segment is sealed; the rescan path
        ignores warm files covered by a cold range, so a crash anywhere
        in between cannot double-count."""
        n = int(getattr(self.cfg, "compact_after", 0) or 0)
        if n <= 0:
            return
        while True:
            warm = [r for r in self._segments if r["tier"] == "warm"]
            if len(warm) < n:
                return
            batch = warm[:n]
            windows = []
            for row in batch:
                for w in load_segment(self.dir / row["file"]):
                    windows.append((w.arrays, w.meta))
            start = min(r["start_us"] for r in batch)
            end = max(r["end_us"] for r in batch)
            name = f"cold-{start}-{end}.npz"
            nbytes = write_segment(self.dir / name, windows)
            cold_row = {
                "file": name,
                "tier": "cold",
                "start_us": start,
                "end_us": end,
                "windows": sum(r["windows"] for r in batch),
                "spans": sum(r["spans"] for r in batch),
                "bytes": int(nbytes),
                "outcomes": _merge_outcomes(r["outcomes"] for r in batch),
            }
            drop = {r["file"] for r in batch}
            self._segments = [
                r for r in self._segments if r["file"] not in drop
            ]
            self._segments.append(cold_row)
            self._segments.sort(
                key=lambda r: (r["start_us"], r["end_us"], r["file"])
            )
            self._seal()
            for fname in drop:
                try:
                    (self.dir / fname).unlink()
                except OSError:
                    pass
            self._record_seal(
                "cold", cold_row["windows"], cold_row["spans"], nbytes
            )

    def _retain(self) -> None:
        cap = int(getattr(self.cfg, "retention_segments", 0) or 0)
        if cap <= 0 or len(self._segments) <= cap:
            return
        dropped = []
        while len(self._segments) > cap:
            cold = [r for r in self._segments if r["tier"] == "cold"]
            if not cold:
                return
            victim = cold[0]
            self._segments.remove(victim)
            self._counters["windows"] -= victim["windows"]
            self._counters["spans"] -= victim["spans"]
            dropped.append(victim["file"])
        self._seal()
        for fname in dropped:
            try:
                (self.dir / fname).unlink()
            except OSError:
                pass

    # --------------------------------------------------- checkpoint seam

    def cursor_state(self) -> dict:
        """Embedded in the engine checkpoint payload."""
        return {"sealed_through_us": int(self.sealed_through_us)}

    def restore_cursor(self, state) -> None:
        if isinstance(state, dict):
            self.sealed_through_us = max(
                self.sealed_through_us,
                int(state.get("sealed_through_us", 0)),
            )

    def reset_hot(self) -> None:
        self._hot = []

    # -------------------------------------------------------------- query

    def query(self, t0_us: Optional[int] = None,
              t1_us: Optional[int] = None) -> List[StoredWindow]:
        """Stored windows overlapping ``[t0_us, t1_us]`` (either bound
        None = open), in time order. Reads only manifest-listed
        segments — the manifest is the commit record."""
        out: List[StoredWindow] = []
        for row in self._segments:
            if t1_us is not None and row["start_us"] > t1_us:
                continue
            if t0_us is not None and row["end_us"] < t0_us:
                continue
            for w in load_segment(self.dir / row["file"]):
                if t1_us is not None and w.start_us > t1_us:
                    continue
                if t0_us is not None and w.end_us < t0_us:
                    continue
                out.append(w)
        out.sort(key=lambda w: (w.start_us, w.end_us))
        return out

    def summary(self) -> dict:
        by_tier: Dict[str, int] = {}
        for r in self._segments:
            by_tier[r["tier"]] = by_tier.get(r["tier"], 0) + 1
        return {
            "segments": len(self._segments),
            "by_tier": by_tier,
            "windows": self._counters["windows"],
            "spans": self._counters["spans"],
            "bytes": sum(r["bytes"] for r in self._segments),
        }

    # ------------------------------------------------------------- obs

    def _record_seal(self, tier, windows, spans, nbytes) -> None:
        try:
            from ..obs.journal import emit_current
            from ..obs.metrics import record_warehouse_seal

            record_warehouse_seal(tier, windows, spans, nbytes)
            emit_current(
                "warehouse_seal", tier=tier, windows=int(windows),
                spans=int(spans), bytes=int(nbytes),
                segments=len(self._segments),
            )
        except Exception:  # pragma: no cover - obs must never fail seal
            pass


def _merge_outcomes(dicts) -> dict:
    out: Dict[str, int] = {}
    for d in dicts:
        for k, v in (d or {}).items():
            out[k] = out.get(k, 0) + int(v)
    return out


def load_warehouse_frame(path, t0_us=None, t1_us=None):
    """Reassemble one span DataFrame from a warehouse's stored frames
    (the ``ReplaySource`` warehouse-segment mode): decode every stored
    window's columnar frame and concatenate in time order."""
    import pandas as pd

    whdir = resolve_warehouse_dir(path)
    payload = load_manifest(whdir)
    if payload is not None:
        rows = payload.get("segments", [])
    else:
        rows = rescan_segments(whdir)
    if not rows:
        raise WarehouseError(f"no warehouse segments under {whdir}")
    frames = []
    for row in sorted(rows, key=lambda r: (r["start_us"], r["end_us"])):
        if t1_us is not None and row["start_us"] > t1_us:
            continue
        if t0_us is not None and row["end_us"] < t0_us:
            continue
        for w in load_segment(whdir / row["file"]):
            f = w.frame()
            if f is not None and len(f):
                frames.append(f)
    if not frames:
        raise WarehouseError(
            f"warehouse under {whdir} stored no span frames "
            "(store_spans disabled?)"
        )
    return _concat_frames(frames)


def _concat_frames(frames):
    """Concatenate decoded window frames. When every frame carries the
    same columns with the same dtypes (the overwhelmingly common case —
    one codec wrote them all), concatenate column-wise with numpy and
    build the result in one shot; ``pd.concat``'s block realignment is
    several times slower at warehouse scale. Mixed schemas fall back."""
    import numpy as np
    import pandas as pd

    if len(frames) == 1:
        return frames[0].reset_index(drop=True)
    first = frames[0]
    cols = list(first.columns)
    uniform = all(
        list(f.columns) == cols
        and all(f.dtypes[c] == first.dtypes[c] for c in cols)
        for f in frames[1:]
    )
    if not uniform:
        return pd.concat(frames, ignore_index=True)
    data = {
        c: np.concatenate([f[c].to_numpy() for f in frames])
        for c in cols
    }
    return pd.DataFrame(data, columns=cols)
