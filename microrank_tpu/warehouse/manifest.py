"""Warehouse manifest: checkpoint-style atomic seal + rejected-whole load.

The manifest is the warehouse's commit record: a segment EXISTS once it
is listed here, whatever files sit in the directory. Same envelope as
``chaos.checkpoint`` (version + sha256 over canonical JSON, written via
``utils.atomic``): a torn, truncated, version-skewed or bit-flipped
manifest is rejected WHOLE — no partial trust — and the store rebuilds
it by cold re-scanning the segment files themselves (each segment's
meta member carries enough to re-derive its manifest row).
"""

from __future__ import annotations

import hashlib
import json
import time
from pathlib import Path
from typing import List, Optional

WAREHOUSE_VERSION = 1
WAREHOUSE_DIR = "warehouse"
MANIFEST_NAME = "manifest.json"


class WarehouseError(Exception):
    """A warehouse artifact failed validation (torn/corrupt/skewed)."""


def _digest(payload: dict) -> str:
    canon = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()


def seal_manifest(warehouse_dir, payload: dict) -> Path:
    """Atomically write the manifest envelope. The caller orders this
    AFTER segment-file writes (write-ahead data, commit record last)."""
    from ..utils.atomic import atomic_write_json

    path = Path(warehouse_dir) / MANIFEST_NAME
    doc = {
        "version": WAREHOUSE_VERSION,
        "ts": time.time(),
        "sha256": _digest(payload),
        "payload": payload,
    }
    atomic_write_json(path, doc)
    return path


def load_manifest(warehouse_dir) -> Optional[dict]:
    """The manifest payload, or None when no manifest exists yet.

    Raises :class:`WarehouseError` on ANY defect — unparsable JSON,
    wrong envelope shape, version skew, checksum mismatch. Rejected
    whole: a manifest that cannot be proven intact indexes nothing.
    """
    path = Path(warehouse_dir) / MANIFEST_NAME
    if not path.exists():
        return None
    try:
        doc = json.loads(path.read_text())
    except (OSError, ValueError) as exc:
        raise WarehouseError(f"manifest unreadable: {exc}") from exc
    if not isinstance(doc, dict):
        raise WarehouseError("manifest: not an object")
    if doc.get("version") != WAREHOUSE_VERSION:
        raise WarehouseError(
            f"manifest: version {doc.get('version')!r} != {WAREHOUSE_VERSION}"
        )
    payload = doc.get("payload")
    if not isinstance(payload, dict):
        raise WarehouseError("manifest: missing payload")
    if doc.get("sha256") != _digest(payload):
        raise WarehouseError("manifest: checksum mismatch")
    return payload


def rescan_segments(warehouse_dir) -> List[dict]:
    """Rebuild manifest segment rows by reading every segment file's
    meta member (corruption recovery / adoption of orphan seals).

    Unreadable files are skipped (a torn tmp rename never lands under a
    final name, so anything unreadable here is damage, not a crash
    artifact). When a cold segment and the warm segments it compacted
    both survive, the wider cold range wins and the overlapped warm
    files are ignored — re-listing both would double-count spans.
    """
    from .segment import read_segment_meta

    root = Path(warehouse_dir)
    rows: List[dict] = []
    for path in sorted(root.glob("*.npz")):
        if ".tmp." in path.name:
            continue
        try:
            doc = read_segment_meta(path)
            windows = doc["windows"]
        except Exception:
            continue
        if not windows:
            continue
        outcomes: dict = {}
        spans = 0
        for w in windows:
            outcomes[w.get("outcome", "")] = (
                outcomes.get(w.get("outcome", ""), 0) + 1
            )
            spans += int(w.get("spans", 0))
        rows.append({
            "file": path.name,
            "tier": "cold" if path.name.startswith("cold-") else "warm",
            "start_us": min(int(w["start_us"]) for w in windows),
            "end_us": max(int(w["end_us"]) for w in windows),
            "windows": len(windows),
            "spans": spans,
            "bytes": path.stat().st_size,
            "outcomes": outcomes,
        })
    # Cold segments absorb the warm files they compacted; drop warm rows
    # fully covered by a cold row.
    cold = [r for r in rows if r["tier"] == "cold"]
    kept = []
    for r in rows:
        if r["tier"] == "warm" and any(
            c["start_us"] <= r["start_us"] and r["end_us"] <= c["end_us"]
            for c in cold
        ):
            continue
        kept.append(r)
    kept.sort(key=lambda r: (r["start_us"], r["end_us"], r["file"]))
    return kept
