"""Time-travel replay: re-rank stored windows through the live lane.

``cli replay --at START..END`` loads the stored rank blobs for the
range, rebuilds each window graph on the host (bit-exact inverse of the
device blob codec), routes them through the SAME DispatchRouter the
stream engine uses (coalesced into same-bucket batches, at bench speed
— no CSV parse, no graph build), and verifies every window's fresh
ranking against the stored verdict with the tie-aware comparator. A
mismatch means history is not reproducible — the CLI exits nonzero and
CI fails the warehouse-smoke job.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

from ..utils.ranking_compare import tie_aware_topk_agreement


def parse_time_range(spec: str) -> Tuple[Optional[int], Optional[int]]:
    """``"all"`` -> open range; ``"START..END"`` with each side an epoch
    microsecond integer, any pandas-parsable timestamp, or empty (open
    bound); a single instant selects the window(s) containing it."""
    spec = (spec or "").strip()
    if spec in ("", "all", "*"):
        return None, None

    def _bound(s: str) -> Optional[int]:
        s = s.strip()
        if not s:
            return None
        if s.lstrip("+-").isdigit():
            return int(s)
        import pandas as pd

        return int(pd.Timestamp(s).value // 1000)

    if ".." in spec:
        left, right = spec.split("..", 1)
        return _bound(left), _bound(right)
    point = _bound(spec)
    return point, point


def replay_range(path, t0_us: Optional[int] = None,
                 t1_us: Optional[int] = None, config=None,
                 k: int = 5, sched=None) -> dict:
    """Replay stored ranked windows in ``[t0_us, t1_us]``; returns a
    report dict (``report["verdict"]`` is "match"/"mismatch").

    ``sched`` (co-deploy): the unified DeviceScheduler — each coalesced
    group dispatches as a BACKFILL-lane thunk on its thread, so replay
    backfill shares the device with serve/stream without ever jumping
    ahead of them."""
    from ..config import MicroRankConfig
    from ..dispatch.router import DispatchRouter, bucket_key
    from ..utils.guards import claim_device_owner
    from .store import TraceWarehouse

    if config is None:
        config = MicroRankConfig()
    if sched is None:
        claim_device_owner("warehouse-replay")
    store = TraceWarehouse(path, config.warehouse)
    windows = store.query(t0_us, t1_us)
    ranked = []
    skipped_no_blob = 0
    for w in windows:
        if w.outcome != "ranked" or not w.ranking:
            continue
        g = w.graph()
        if g is None:
            skipped_no_blob += 1
            continue
        ranked.append((w, g))

    router = DispatchRouter(config)
    coalesce = max(1, int(getattr(config.dispatch, "coalesce_windows", 1)))
    mismatches: List[dict] = []
    matched = 0
    spans = sum(w.meta.get("spans", 0) for w, _ in ranked)
    t_start = time.perf_counter()
    i = 0
    while i < len(ranked):
        w0, g0 = ranked[i]
        kernel = w0.kernel or "coo"
        key = bucket_key(g0, kernel)
        group = [(w0, g0)]
        j = i + 1
        while (
            j < len(ranked)
            and len(group) < coalesce
            and (ranked[j][0].kernel or "coo") == kernel
            and bucket_key(ranked[j][1], kernel) == key
        ):
            group.append(ranked[j])
            j += 1
        i = j
        graphs = [g for _, g in group]
        if sched is None:
            outs, _info = router.rank_batch(graphs, kernel)
        else:
            from ..sched import LANE_BACKFILL

            outs, _info = sched.run_on(
                LANE_BACKFILL, config.sched.backfill_tenant,
                lambda: router.rank_batch(graphs, kernel),
                cost=float(len(graphs)),
            )
        top_idx, top_scores, n_valid = outs[:3]
        for b, (w, _g) in enumerate(group):
            op_names = w.op_names or []
            n = int(n_valid[b])
            new_names = [op_names[int(x)] for x in top_idx[b][:n]]
            new_scores = [float(s) for s in top_scores[b][:n]]
            stored = w.ranking
            kk = min(k, len(stored), len(new_names)) or 1
            ok, reason = tie_aware_topk_agreement(
                [n_ for n_, _ in stored], [s for _, s in stored],
                new_names, new_scores, kk,
            )
            _record("match" if ok else "mismatch")
            if ok:
                matched += 1
            else:
                mismatches.append({
                    "start": w.meta.get("start"),
                    "end": w.meta.get("end"),
                    "reason": reason,
                    "stored_top": stored[:kk],
                    "replayed_top": list(
                        zip(new_names[:kk], new_scores[:kk])
                    ),
                })
    elapsed = time.perf_counter() - t_start

    report = {
        "range": [t0_us, t1_us],
        "windows": len(windows),
        "ranked": len(ranked),
        "matched": matched,
        "mismatched": mismatches,
        "skipped_no_blob": skipped_no_blob,
        "spans": int(spans),
        "elapsed_s": round(elapsed, 4),
        "spans_per_sec": (
            round(spans / elapsed, 1) if elapsed > 0 else None
        ),
        "windows_per_sec": (
            round(len(ranked) / elapsed, 2) if elapsed > 0 else None
        ),
        "k": k,
        "verdict": "match" if not mismatches else "mismatch",
    }
    try:
        from ..obs.journal import emit_current

        emit_current(
            "warehouse_replay", windows=len(ranked), matched=matched,
            mismatched=len(mismatches), spans=int(spans),
            elapsed_s=report["elapsed_s"], verdict=report["verdict"],
        )
    except Exception:  # pragma: no cover
        pass
    return report


def _record(verdict: str) -> None:
    try:
        from ..obs.metrics import record_warehouse_replay

        record_warehouse_replay(verdict)
    except Exception:  # pragma: no cover
        pass
