"""mrsan — the runtime sanitizer that validates mrlint's static model.

mrlint R8 (device ownership) and R9 (collective order) are *static*
claims about a concurrent system; this module is their runtime
cross-check, armed by ``RuntimeConfig.sanitizers``:

* **Thread ownership** — run entries claim the device
  (``utils.guards.claim_device_owner``), every staging/dispatch/fetch
  seam asserts (``assert_device_owner``), violations raise
  ``DeviceOwnershipError`` and count into
  ``microrank_mrsan_violations_total{kind="cross-thread-device"}``.
  The checks themselves count into ``microrank_mrsan_checks_total`` so
  a clean run proves the sanitizer actually looked.

* **Collective schedule** — arming interposes on the ``jax.lax`` mesh
  collectives (psum/pmax/pmean/all_gather/ppermute/...): each wrapped
  call records its op into a trace-time sequence AND emits a
  ``jax.debug.callback`` carrying ``lax.axis_index(axis)``, so on the
  CPU mesh every shard reports which collectives it actually executed.
  ``verify_collective_uniformity()`` compares the per-shard op
  multisets — a shard that skipped a psum (the R9 bug class: a
  data-dependent branch around a collective) diverges and trips the
  sanitizer. Ordering within a shard is validated statically by R9;
  participation is what only the runtime can see.

* **Locksets & lock order** (mrrace, R10/R11's runtime twin) —
  production locks wrap in ``utils.guards.TrackedLock``; armed, every
  acquire records into a per-thread held-lockset, an Eraser-style
  checker validates registered shared objects on access
  (``register_shared``/``note_shared_access``, candidates seeded from
  the static lock catalog, violations =
  ``microrank_mrsan_violations_total{kind="shared-state-race"}``), and
  a process-wide watchdog asserts the observed acquisition order stays
  a DAG (``kind="lock-order"``, raised as ``LockOrderError``). Checks
  count into ``microrank_mrsan_lockset_checks_total{object}``.

* **Compile witness** (R13-R16's runtime twin) — armed with the
  statically predicted ``analysis.shapes.CompileKeySpace``, every
  dispatch seam reports its (kernel, occupancy, leaf-shapes) compile
  signature via ``observe_compile_key``; first-seen keys count into
  ``microrank_jit_cache_misses_total{program}`` and journal as
  ``jit_cache_miss`` events, and a key outside the predicted space is
  ``microrank_mrsan_violations_total{kind="compile-witness"}`` — the
  static shape lattice missed a flow, or a live measurement escaped
  the pad-bucket registry.

The CI contract (mrsan-smoke + race-smoke): the repo lints clean ⇔ a
sanitized stream run observes zero violations; the injected-bug
fixtures (a jax call from a webhook-sink thread; a shard-divergent
psum; an unlocked cross-thread counter; an A/B-B/A lock inversion)
flip BOTH detectors.

Debug-mode cost: the interposition is baked into traces made while
armed (programs retrace on arm/disarm), and each collective pays one
host callback per shard per execution — micro-benchmarked at ~1-2% of
a CPU-mesh rank dispatch, not meant for the hot path.
"""

from __future__ import annotations

import functools
import threading
from collections import Counter
from typing import Dict, List, Optional

from ..utils.guards import (  # noqa: F401  (re-exported: the seam API)
    DeviceOwnershipError,
    LockOrderError,
    LocksetError,
    TrackedLock,
    assert_device_owner,
    authorize_device_thread,
    claim_device_owner,
    held_locks,
    note_shared_access,
    published,
    register_shared,
    release_device_owner,
    reset_device_ownership,
    reset_lock_tracking,
    sanitizers_enabled,
    set_sanitizers,
)

_COLLECTIVE_OPS = (
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "all_gather",
    "ppermute",
    "psum_scatter",
    "all_to_all",
)

_lock = threading.Lock()
_originals: Dict[str, object] = {}
_trace_schedule: List[str] = []          # trace-time op sequence
_shard_ops: Dict[int, Counter] = {}      # shard index -> op multiset


def armed() -> bool:
    return bool(_originals) and sanitizers_enabled()


def _record_trace(op: str, axis: str) -> None:
    with _lock:
        _trace_schedule.append(f"{op}@{axis}")


def _record_runtime(op: str, idx) -> None:
    """debug.callback target: one shard reporting one collective. Under
    vmap the index arrives batched — every element is the same shard."""
    import numpy as np

    shard = int(np.ravel(np.asarray(idx))[0])
    with _lock:
        _shard_ops.setdefault(shard, Counter())[op] += 1
    from ..obs.metrics import record_mrsan_collective

    record_mrsan_collective(op)


def _wrap(op: str, orig):
    @functools.wraps(orig)
    def wrapped(*args, **kwargs):
        axis = kwargs.get("axis_name")
        if axis is None and len(args) > 1:
            axis = args[1]
        if sanitizers_enabled() and isinstance(axis, str):
            import jax

            _record_trace(op, axis)
            try:
                idx = jax.lax.axis_index(axis)
                jax.debug.callback(
                    functools.partial(_record_runtime, op), idx
                )
            except NameError:
                # Called outside a named-axis context (oracle/test code
                # exercising the wrapper directly): record trace only.
                pass
        return orig(*args, **kwargs)

    wrapped.__mrsan_wrapped__ = True
    return wrapped


def arm_collectives() -> None:
    """Interpose on the jax.lax mesh collectives (idempotent)."""
    import jax

    with _lock:
        if _originals:
            return
        for op in _COLLECTIVE_OPS:
            orig = getattr(jax.lax, op, None)
            if orig is None or getattr(orig, "__mrsan_wrapped__", False):
                continue
            _originals[op] = orig
            setattr(jax.lax, op, _wrap(op, orig))
    # Executables traced BEFORE arming carry no recording callbacks —
    # drop the jit caches so every collective-bearing program re-traces
    # through the interposition (the documented arm-time retrace cost).
    jax.clear_caches()


def disarm_collectives() -> None:
    import jax

    with _lock:
        if not _originals:
            return
        for op, orig in _originals.items():
            setattr(jax.lax, op, orig)
        _originals.clear()
    # Symmetric: armed traces keep paying the callback unless dropped.
    jax.clear_caches()


def reset_schedule() -> None:
    with _lock:
        _trace_schedule.clear()
        _shard_ops.clear()


def trace_schedule() -> List[str]:
    """The trace-time collective sequence (uniform by construction —
    what the static R9 model predicts)."""
    with _lock:
        return list(_trace_schedule)


def collective_schedule() -> Dict[int, Dict[str, int]]:
    """Per-shard op multisets observed at RUNTIME on the mesh."""
    with _lock:
        return {s: dict(c) for s, c in _shard_ops.items()}


def verify_collective_uniformity(record: bool = True) -> List[str]:
    """Compare the per-shard collective multisets; returns violation
    descriptions (empty = uniform). Counts into
    microrank_mrsan_violations_total{kind="collective-divergence"}."""
    with _lock:
        shards = {s: Counter(c) for s, c in _shard_ops.items()}
    if len(shards) < 2:
        return []
    baseline_shard = min(shards)
    baseline = shards[baseline_shard]
    violations: List[str] = []
    for shard in sorted(shards):
        if shards[shard] != baseline:
            missing = baseline - shards[shard]
            extra = shards[shard] - baseline
            violations.append(
                f"shard {shard} diverged from shard {baseline_shard}: "
                f"missing {dict(missing)}, extra {dict(extra)} — a "
                "data-dependent branch let this shard fall out of the "
                "collective schedule (mrlint R9's runtime bug class)"
            )
    if violations and record:
        from ..obs.metrics import record_mrsan_violation

        record_mrsan_violation("collective-divergence", len(violations))
    return violations


def verify_and_reset(log=None) -> List[str]:
    """Post-dispatch hook (dispatch router): verify, log, clear."""
    violations = verify_collective_uniformity()
    if violations and log is not None:
        for v in violations:
            log.error("mrsan: %s", v)
    reset_schedule()
    return violations


# ------------------------------------------------------- compile witness
#
# R13-R16's runtime twin: mrlint's shape analysis claims the compile-key
# space is finite and warm (static args enumerable, every extent a pad
# bucket, warmup covering production keys). The witness validates the
# claim where it actually bites — the jit cache. Each dispatch seam
# reports its (kernel, occupancy, leaf shapes) signature; a first-seen
# key is a cache miss (counted + journalled as ``jit_cache_miss``), and
# a miss outside the statically predicted ``CompileKeySpace`` is a
# sanitizer violation (kind="compile-witness"): either the static model
# has a gap or a live measurement escaped the bucket registry at
# runtime.

_witness_space = None                     # CompileKeySpace | None = armed
_witness_owner: Optional[str] = None      # "external" (bench/tests) | "config"
_witness_keys: Dict[str, set] = {}        # program -> observed key set
_witness_unpredicted: List[dict] = []


def arm_witness(space, owner: str = "external") -> None:
    """Arm the compile witness with a predicted key space
    (``analysis.shapes.CompileKeySpace``); resets observed state.
    ``owner`` records who armed it: ``configure_sanitizers`` (run
    entries, owner="config") must not disarm a witness the bench or a
    test armed explicitly around the run (owner="external")."""
    global _witness_space, _witness_owner
    with _lock:
        _witness_space = space
        _witness_owner = owner
        _witness_keys.clear()
        _witness_unpredicted.clear()


def disarm_witness(owner: Optional[str] = None) -> None:
    """Disarm; with ``owner`` given, only if that owner armed it."""
    global _witness_space, _witness_owner
    with _lock:
        if owner is not None and _witness_owner != owner:
            return
        _witness_space = None
        _witness_owner = None
        _witness_keys.clear()
        _witness_unpredicted.clear()


def witness_armed() -> bool:
    with _lock:
        return _witness_space is not None


def observe_compile_key(
    program: str,
    kernel: Optional[str] = None,
    graph=None,
    occupancy: Optional[int] = None,
) -> None:
    """One dispatch through a seam: dedupe its compile-key signature,
    and on first sight count a cache miss + check the prediction.

    The signature deliberately mirrors the jit cache key modulo config
    (``dispatch.router.bucket_key``): kernel, batch occupancy, and the
    *set* of leaf shapes — order and multiplicity don't change what
    XLA compiles for the homogeneous window batches this repo stages.
    """
    with _lock:
        if _witness_space is None:
            return
    shapes: tuple = ()
    if graph is not None:
        import jax
        import numpy as np

        shapes = tuple(sorted(set(
            tuple(int(d) for d in np.asarray(leaf).shape)
            for leaf in jax.tree.leaves(graph)
        )))
    key = (kernel, occupancy, shapes)
    with _lock:
        space = _witness_space
        if space is None:
            return
        seen = _witness_keys.setdefault(program, set())
        if key in seen:
            return
        seen.add(key)
    reason = space.admits(program, kernel, occupancy, shapes)
    from ..obs.metrics import record_jit_cache_miss, record_mrsan_violation

    record_jit_cache_miss(
        program,
        kernel=kernel,
        occupancy=occupancy,
        key=[list(s) for s in shapes],
        predicted=reason is None,
    )
    if reason is not None:
        with _lock:
            _witness_unpredicted.append({
                "program": program,
                "kernel": kernel,
                "occupancy": occupancy,
                "shapes": [list(s) for s in shapes],
                "reason": reason,
            })
        record_mrsan_violation("compile-witness")


def witness_report() -> Dict[str, object]:
    """Observed-key summary: per-program first-seen key counts plus the
    unpredicted escapes (empty ``unpredicted`` = the static key-space
    model held for this run — the bench acceptance criterion)."""
    with _lock:
        return {
            "programs": {p: len(k) for p, k in _witness_keys.items()},
            "keys_total": sum(len(k) for k in _witness_keys.values()),
            "unpredicted": [dict(u) for u in _witness_unpredicted],
        }


def configure_sanitizers(config) -> None:
    """The one wiring point, called next to ``configure_tracer`` at
    every run entry (TableRCA.run, StreamEngine.run, ServeService.
    start): arm or disarm from ``RuntimeConfig.sanitizers`` and reset
    the ownership + schedule state for the new run. Accepts a
    MicroRankConfig or a RuntimeConfig."""
    runtime = getattr(config, "runtime", config)
    enabled = bool(getattr(runtime, "sanitizers", False))
    set_sanitizers(enabled)
    reset_device_ownership()
    reset_lock_tracking()
    reset_schedule()
    if enabled:
        arm_collectives()
        if _witness_owner != "external":
            from .shapes import predict_key_space

            arm_witness(predict_key_space(
                config,
                cache_dir=getattr(runtime, "compile_cache_dir", None),
            ), owner="config")
    else:
        disarm_collectives()
        disarm_witness(owner="config")
