"""Shape/dtype contracts for the rank/spectrum entry points (mrlint R5).

A contract is a declarative spec attached to a function::

    @contract(
        graph="windowgraph",
        returns=("int32[K]", "float32[K]", "int32[]"),
    )
    def rank_window_core(graph, pagerank_cfg, spectrum_cfg, ...): ...

Spec grammar (strings, parsed at import time so typos fail fast):

* ``"float32[K]"``   — dtype + symbolic dims; same letter must unify to
  the same extent across the whole signature (``K`` here ties the two
  return vectors together);
* ``"int32[]"``      — 0-d scalar array;
* ``"uint32[N]"``    — any one axis, bound to ``N``;
* ``"float32[*]"``   — dtype checked, rank/shape free;
* ``"windowgraph"``  — a ``WindowGraph``: every field of both partitions
  is dtype-checked against the layout in graph/structures.py (the
  host<->device data contract), shapes free (padding varies);
* ``"any"``          — presence only.

Checks run on ``.shape``/``.dtype`` ONLY — never on values — so they
are trace-compatible: under ``jax.jit`` the wrapper executes once per
compilation (trace time) against abstract tracers and costs nothing per
cached call; on host arrays it validates eagerly. Enabled via
``utils.guards.contract_checks`` (the backends gate it on
``RuntimeConfig.validate_numerics``); disabled, the wrapper is a few
nanoseconds of flag check.

Violations raise :class:`microrank_tpu.utils.guards.ContractError`.
"""

from __future__ import annotations

import functools
import inspect
import re
from dataclasses import dataclass
from typing import Dict, Optional, Tuple, Union

from ..utils.guards import ContractError, contracts_enabled

_SPEC_RE = re.compile(r"^([a-z0-9_]+)(?:\[([A-Za-z0-9_,* ]*)\])?$")

# The canonical field dtypes of a PartitionGraph (graph/structures.py) —
# the host<->device data contract the builders, blob codec and kernels
# all assume. n_* dynamic extents are int32 0-d; bitmaps uint8.
PARTITION_FIELD_DTYPES: Dict[str, str] = {
    "inc_op": "int32",
    "inc_trace": "int32",
    "sr_val": "float32",
    "rs_val": "float32",
    "ss_child": "int32",
    "ss_parent": "int32",
    "ss_val": "float32",
    "inc_trace_opmajor": "int32",
    "sr_val_opmajor": "float32",
    "inc_indptr_op": "int32",
    "inc_indptr_trace": "int32",
    "ss_indptr": "int32",
    "cov_bits": "uint8",
    "ss_bits": "uint8",
    "inv_tracelen": "float32",
    "inv_cov_dup": "float32",
    "inv_outdeg": "float32",
    "kind": "int32",
    "tracelen": "int32",
    "cov_unique": "int32",
    "op_present": "bool",
    "n_ops": "int32",
    "n_traces": "int32",
    "n_inc": "int32",
    "n_ss": "int32",
    "n_cols": "int32",
    "pc_trace": "int32",
    "pc_sr_val": "float32",
    "pc_blk_indptr": "int32",
    "pc_ell_op": "int32",
    "pc_ell_rs": "float32",
    "cov_i8": "int8",
}


@dataclass(frozen=True)
class ArraySpec:
    dtype: Optional[str]                       # None = any dtype
    dims: Optional[Tuple[Union[str, int], ...]]  # None = any rank; () = 0-d

    def describe(self) -> str:
        if self.dims is None:
            d = "[*]"
        else:
            d = "[" + ",".join(str(x) for x in self.dims) + "]"
        return f"{self.dtype or 'any'}{d}"


@dataclass(frozen=True)
class GraphSpec:
    """Dtype contract over every field of a WindowGraph's partitions."""


@dataclass(frozen=True)
class DetectBatchSpec:
    """Dtype contract over a DetectBatch (the detector's input seam)."""


@dataclass(frozen=True)
class AnySpec:
    pass


Spec = Union[ArraySpec, GraphSpec, DetectBatchSpec, AnySpec]

# The canonical DetectBatch field dtypes (graph/structures.py) — the
# detector seam's data contract (spec "detectbatch"). op/trace span
# arrays must share one extent; the n_* extents are 0-d int32.
DETECT_FIELD_DTYPES: Dict[str, str] = {
    "op": "int32",
    "trace": "int32",
    "duration_us": "float32",
    "n_spans": "int32",
    "n_traces": "int32",
}


def parse_spec(text: str) -> Spec:
    t = text.strip()
    if t.lower() == "any":
        return AnySpec()
    if t.lower() == "windowgraph":
        return GraphSpec()
    if t.lower() == "detectbatch":
        return DetectBatchSpec()
    m = _SPEC_RE.match(t)
    if not m:
        raise ValueError(f"unparseable contract spec {text!r}")
    dtype, dims_text = m.group(1).lower(), m.group(2)
    if dims_text is None:
        return ArraySpec(dtype=dtype, dims=None)
    dims_text = dims_text.strip()
    if dims_text == "*":
        return ArraySpec(dtype=dtype, dims=None)
    if not dims_text:
        return ArraySpec(dtype=dtype, dims=())
    dims: list = []
    for part in dims_text.split(","):
        part = part.strip()
        dims.append(int(part) if part.isdigit() else part)
    return ArraySpec(dtype=dtype, dims=tuple(dims))


def _dtype_name(value) -> Optional[str]:
    dt = getattr(value, "dtype", None)
    return None if dt is None else str(dt)


def check_value(value, spec: Spec, where: str, env: Dict[str, int]) -> None:
    """Validate one value against one spec, unifying symbolic dims into
    ``env``. Raises ContractError with the argument/return path named."""
    if isinstance(spec, AnySpec):
        return
    if isinstance(spec, DetectBatchSpec):
        fields = getattr(value, "_fields", None)
        if fields != tuple(DETECT_FIELD_DTYPES):
            raise ContractError(
                f"{where}: expected a DetectBatch, got "
                f"{type(value).__name__}"
            )
        span_extent = None
        for fname, want in DETECT_FIELD_DTYPES.items():
            field = getattr(value, fname)
            got = _dtype_name(field)
            if got != want:
                raise ContractError(
                    f"{where}.{fname}: dtype {got} != contract {want} "
                    "(the detector seam's layout in graph/structures.py)"
                )
            shape = tuple(getattr(field, "shape", ()))
            if fname in ("op", "trace", "duration_us"):
                if len(shape) != 1:
                    raise ContractError(
                        f"{where}.{fname}: rank {len(shape)} != 1 "
                        "(padded span axis)"
                    )
                if span_extent is None:
                    span_extent = shape[0]
                elif shape[0] != span_extent:
                    raise ContractError(
                        f"{where}.{fname}: span axis {shape[0]} != "
                        f"{span_extent} bound by a sibling field"
                    )
            elif shape != ():
                raise ContractError(
                    f"{where}.{fname}: expected a 0-d extent, got "
                    f"shape {shape}"
                )
        return
    if isinstance(spec, GraphSpec):
        parts = getattr(value, "_fields", None)
        if parts != ("normal", "abnormal"):
            raise ContractError(
                f"{where}: expected a WindowGraph, got {type(value).__name__}"
            )
        for pname in ("normal", "abnormal"):
            part = getattr(value, pname)
            for fname, want in PARTITION_FIELD_DTYPES.items():
                field = getattr(part, fname, None)
                if field is None:
                    continue
                got = _dtype_name(field)
                if got != want:
                    raise ContractError(
                        f"{where}.{pname}.{fname}: dtype {got} != "
                        f"contract {want} (the host<->device graph "
                        "layout in graph/structures.py)"
                    )
        return
    got_dtype = _dtype_name(value)
    if got_dtype is None:
        raise ContractError(
            f"{where}: expected an array ({spec.describe()}), got "
            f"{type(value).__name__}"
        )
    if spec.dtype is not None and got_dtype != spec.dtype:
        raise ContractError(
            f"{where}: dtype {got_dtype} != contract {spec.describe()}"
        )
    if spec.dims is None:
        return
    shape = tuple(getattr(value, "shape", ()))
    if len(shape) != len(spec.dims):
        raise ContractError(
            f"{where}: rank {len(shape)} (shape {shape}) != contract "
            f"{spec.describe()}"
        )
    for axis, (dim, extent) in enumerate(zip(spec.dims, shape)):
        if isinstance(dim, int):
            if extent != dim:
                raise ContractError(
                    f"{where}: axis {axis} has extent {extent} != "
                    f"contract {spec.describe()}"
                )
        else:
            bound = env.setdefault(dim, int(extent))
            if bound != int(extent):
                raise ContractError(
                    f"{where}: axis {axis} extent {extent} conflicts "
                    f"with {dim}={bound} bound elsewhere in the "
                    "signature"
                )


def contract(returns=None, **arg_specs):
    """Attach (and, when enabled, enforce) a shape/dtype contract.

    ``arg_specs`` map parameter names to spec strings; ``returns`` is a
    spec string or tuple of them (matched elementwise against a tuple
    result). Parsed at decoration time; enforced only under
    ``utils.guards.contract_checks(True)`` — which the backends enter
    when ``RuntimeConfig.validate_numerics`` is on.
    """
    parsed_args = {k: parse_spec(v) for k, v in arg_specs.items()}
    parsed_returns = None
    if returns is not None:
        if isinstance(returns, (tuple, list)):
            parsed_returns = tuple(parse_spec(r) for r in returns)
        else:
            parsed_returns = parse_spec(returns)

    def deco(fn):
        sig = inspect.signature(fn)
        unknown = set(parsed_args) - set(sig.parameters)
        if unknown:
            raise ValueError(
                f"@contract on {fn.__name__}: unknown parameters {unknown}"
            )

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not contracts_enabled():
                return fn(*args, **kwargs)
            env: Dict[str, int] = {}
            bound = sig.bind_partial(*args, **kwargs)
            for name, spec in parsed_args.items():
                if name in bound.arguments:
                    check_value(
                        bound.arguments[name],
                        spec,
                        f"{fn.__name__}({name})",
                        env,
                    )
            out = fn(*args, **kwargs)
            if parsed_returns is not None:
                if isinstance(parsed_returns, tuple):
                    if not isinstance(out, (tuple, list)) or len(out) != len(
                        parsed_returns
                    ):
                        raise ContractError(
                            f"{fn.__name__} -> expected a {len(parsed_returns)}"
                            f"-tuple, got {type(out).__name__}"
                        )
                    for i, (val, spec) in enumerate(
                        zip(out, parsed_returns)
                    ):
                        check_value(
                            val, spec, f"{fn.__name__} -> [{i}]", env
                        )
                else:
                    check_value(out, parsed_returns, f"{fn.__name__} ->", env)
            return out

        wrapper.__mrlint_contract__ = {
            "args": parsed_args,
            "returns": parsed_returns,
        }
        return wrapper

    return deco
