"""Cross-thread concurrency analysis: which thread executes each
function, and what the collective schedule looks like inside
``shard_map``-traced code.

The pipeline is a three-thread system sharing one device: the serve
scheduler (``BatchScheduler.run``), the build worker pool
(``stream.pool.BuildWorkerPool``), and the stream engine / table lane
(whatever thread drives ``run()``). The device-ownership rule — every
jax dispatch happens on exactly one thread, the program-order guarantee
collectives need — was documented prose until this analysis. It builds
an interprocedural call graph over the linted module set and classifies
each function by the thread class that can execute it:

* ``threading.Thread`` subclasses: the ``run`` method roots a thread
  named after the class; it is a device OWNER iff its body calls
  ``claim_device_owner`` (utils.guards) — the runtime mrsan twin of
  this static model.
* ``threading.Thread(target=f)``: ``f`` roots a thread (owner iff it
  claims).
* ``pool.submit(f, ...)`` / ``executor.submit(f, ...)``: ``f`` runs on
  a POOL WORKER — never a device owner, unless the executor was
  constructed with ``initializer=authorize_device_thread`` (the table
  lane's sanctioned async staging/fetch workers, RuntimeConfig.
  async_dispatch). ``functools.partial(f, ...)`` and bound-method
  targets resolve through to ``f``.
* ``async def`` functions: the asyncio event-loop (HTTP handler)
  thread — never a device owner.
* ``*Sink.emit`` methods: incident-sink callbacks — they run inside
  the dispatch lifecycle (and may be retried from helper threads) and
  must stay host-only.

R8 fires on any device-touching call — ``jax.numpy``/``jax.lax``/
``jax.device_put``/``device_get``, a known jit-wrapper call, or one of
the staging seams (``stage_rank_window``, ``stage_sharded``,
``rank_batch``, compile-cache warmers) — reachable from a non-owner
root. ``jax.tree``/``jax.profiler``/``jax.config`` are exempt: host
utilities that never dispatch.

R9 (collective order) analyzes ``shard_map`` call sites: the wrapped
kernel and everything it reaches is SPMD code whose per-iteration
psum/all_gather schedule must be identical on every shard. A collective
issued under data-dependent control flow (a Python ``if``/``while`` on
a traced value), or a call path that only reaches a collective-issuing
kernel under such a branch, lets shards fall out of the schedule —
deadlock on a real mesh, silent wrong answers with single-controller
emulation. Taint comes from the same forward walk R1 uses, seeded from
the shard_map operands and propagated through the call graph.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .traced import Event, FuncDef, _TaintWalker, _identity_test

# Device-touching call prefixes (dotted, resolved through import
# aliases). jax.tree/jax.profiler/jax.config and friends are host-side
# utilities — never a dispatch — and are exempted.
_DEVICE_PREFIXES = (
    "jax.numpy",
    "jax.lax",
    "jax.device_put",
    "jax.device_get",
    "jax.block_until_ready",
    "jax.jit",
    "jax.pjit",
    "jax.vmap",
    "jax.pmap",
    "jax.make_array_from_callback",
    "jax.make_array_from_single_device_arrays",
    "jax.experimental",
)
_EXEMPT_PREFIXES = (
    "jax.tree",
    "jax.profiler",
    "jax.config",
    "jax.dtypes",
    "jax.debug",
    "jax.typing",
    "jax.experimental.compilation_cache",
)
# Cross-module device seams: the staging/dispatch entry points every
# caller funnels through. Flagged by NAME so a per-subsystem lint run
# (e.g. `cli lint microrank_tpu/serve/`) still sees the touch even when
# the defining module is outside the linted set.
_DEVICE_SEAMS = {
    "stage_rank_window",
    "stage_windows_batched",
    "dispatch_windows_staged",
    "stage_sharded",
    "warm_occupancies",
    "rank_batch",
}
_OWNER_CLAIMS = {"claim_device_owner"}
_AUTHORIZE_INITIALIZERS = {"authorize_device_thread"}
_EXECUTOR_CTORS = {
    "ThreadPoolExecutor",
    "ProcessPoolExecutor",
    "BuildWorkerPool",
}
# Mesh collectives whose per-shard issue order IS the program contract.
_COLLECTIVES = {
    "jax.lax.psum",
    "jax.lax.pmean",
    "jax.lax.pmax",
    "jax.lax.pmin",
    "jax.lax.all_gather",
    "jax.lax.ppermute",
    "jax.lax.pshuffle",
    "jax.lax.psum_scatter",
    "jax.lax.all_to_all",
}
_SHARD_MAP_NAMES = {
    "shard_map",
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
}


@dataclass
class FuncInfo:
    """One function or method in the linted set."""

    module: object                   # core.ModuleInfo
    node: ast.FunctionDef
    name: str
    cls: Optional[str] = None        # enclosing class name, methods only

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name

    @property
    def is_async(self) -> bool:
        return isinstance(self.node, ast.AsyncFunctionDef)


@dataclass
class ThreadRoot:
    """One place a thread class starts executing project code."""

    func: FuncInfo
    label: str                       # thread-class label for messages
    owner: bool                      # may touch the device
    reason: str                      # how the root was derived
    line: int = 0


def _call_name(func_node) -> Optional[str]:
    """Trailing identifier of a call target (``x.y.z`` -> ``z``)."""
    if isinstance(func_node, ast.Name):
        return func_node.id
    if isinstance(func_node, ast.Attribute):
        return func_node.attr
    return None


class ThreadAnalysis:
    """Interprocedural thread classification + collective-order model.

    Exposes ``events`` — kinds ``cross-thread-device`` (R8),
    ``collective-data-dep`` and ``collective-divergent-path`` (R9) —
    plus the root/classification tables the tests introspect.
    """

    def __init__(self, project):
        self.project = project
        self.traced = project.traced
        self.funcs: List[FuncInfo] = []
        self._module_level: Dict[Tuple[int, str], FuncInfo] = {}
        self._methods_by_name: Dict[str, List[FuncInfo]] = {}
        self._class_methods: Dict[Tuple[int, str], Dict[str, FuncInfo]] = {}
        self._attr_types: Dict[Tuple[int, str], Dict[str, str]] = {}
        self._local_types_cache: Dict[int, Dict[str, str]] = {}
        self.edges: Dict[int, Set[int]] = {}      # id(FuncInfo) -> callees
        self._by_id: Dict[int, FuncInfo] = {}
        self.roots: List[ThreadRoot] = []
        self.events: List[Event] = []
        self._index()
        self._build_edges()
        self._find_roots()
        self._collect_device_events()
        self._collect_collective_events()

    # ------------------------------------------------------------ indexing

    def _index(self) -> None:
        for mod in self.project.modules:
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FuncInfo(module=mod, node=node, name=node.name)
                    self.funcs.append(fi)
                    self._module_level[(id(mod), node.name)] = fi
                elif isinstance(node, ast.ClassDef):
                    table: Dict[str, FuncInfo] = {}
                    for item in node.body:
                        if isinstance(
                            item, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            fi = FuncInfo(
                                module=mod, node=item, name=item.name,
                                cls=node.name,
                            )
                            self.funcs.append(fi)
                            table[item.name] = fi
                            self._methods_by_name.setdefault(
                                item.name, []
                            ).append(fi)
                    self._class_methods[(id(mod), node.name)] = table
                    self._attr_types[(id(mod), node.name)] = (
                        self._scan_attr_types(table)
                    )
        for fi in self.funcs:
            self._by_id[id(fi)] = fi

    @staticmethod
    def _scan_attr_types(methods: Dict[str, FuncInfo]) -> Dict[str, str]:
        """``self.X = ClassName(...)`` assignments anywhere in the class:
        attr name -> constructing callable's trailing name."""
        types: Dict[str, str] = {}
        for fi in methods.values():
            for node in ast.walk(fi.node):
                if not (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                ctor = _call_name(node.value.func)
                if ctor is None:
                    continue
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        types[tgt.attr] = ctor
        return types

    def _local_types(self, fi: FuncInfo) -> Dict[str, str]:
        """``x = ClassName(...)`` locals of one function body."""
        cached = self._local_types_cache.get(id(fi))
        if cached is not None:
            return cached
        types: Dict[str, str] = {}
        for node in ast.walk(fi.node):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                continue
            ctor = _call_name(node.value.func)
            if ctor:
                types[node.targets[0].id] = ctor
        self._local_types_cache[id(fi)] = types
        return types

    # ---------------------------------------------------------- resolution

    def resolve_callable(
        self, fi: FuncInfo, node
    ) -> Optional[FuncInfo]:
        """Resolve a callable expression at a call/submit site to a
        project function: bare names (incl. relative imports),
        ``self.method``, bound methods of typed locals/attrs,
        unique-name methods, and ``functools.partial(f, ...)``."""
        mod = fi.module
        if isinstance(node, ast.Call):
            # functools.partial(f, ...) — unwrap to f.
            dotted = mod.dotted(node.func)
            if (
                dotted == "functools.partial"
                or _call_name(node.func) == "partial"
            ) and node.args:
                return self.resolve_callable(fi, node.args[0])
            return None
        if isinstance(node, ast.Name):
            fd = self.traced.resolve(mod, node.id)
            if fd is not None:
                found = self._module_level.get((id(fd.module), fd.name))
                if found is not None:
                    return found
            return None
        if isinstance(node, ast.Attribute):
            # self.method — same class first.
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and fi.cls is not None
            ):
                table = self._class_methods.get((id(mod), fi.cls), {})
                if node.attr in table:
                    return table[node.attr]
            # obj.method with a typed receiver (local or self-attr).
            recv_cls = self._receiver_class(fi, node.value)
            if recv_cls is not None:
                for key, table in self._class_methods.items():
                    if key[1] == recv_cls and node.attr in table:
                        return table[node.attr]
            # Unique-name fallback: exactly one method in the whole
            # project bears the name and no module-level def shadows it.
            candidates = self._methods_by_name.get(node.attr, [])
            module_defs = [
                f
                for (mid, name), f in self._module_level.items()
                if name == node.attr
            ]
            if len(candidates) == 1 and not module_defs:
                return candidates[0]
        return None

    def _receiver_class(self, fi: FuncInfo, node) -> Optional[str]:
        """Class name of a receiver expression, when statically known."""
        if isinstance(node, ast.Name):
            return self._local_types(fi).get(node.id)
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and fi.cls is not None
        ):
            mod = fi.module
            return self._attr_types.get((id(mod), fi.cls), {}).get(node.attr)
        return None

    # ---------------------------------------------------------- call graph

    def _build_edges(self) -> None:
        for fi in self.funcs:
            out = self.edges.setdefault(id(fi), set())
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                target = self.resolve_callable(fi, node.func)
                if target is not None and target is not fi:
                    out.add(id(target))

    def reachable(self, fi: FuncInfo) -> List[FuncInfo]:
        seen = {id(fi)}
        stack = [id(fi)]
        while stack:
            cur = stack.pop()
            for nxt in self.edges.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return [self._by_id[i] for i in seen]

    # -------------------------------------------------------------- roots

    def _claims_owner(self, fi: FuncInfo) -> bool:
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                if _call_name(node.func) in _OWNER_CLAIMS:
                    return True
        return False

    def _is_thread_base(self, mod, base) -> bool:
        dotted = mod.dotted(base)
        if dotted == "threading.Thread":
            return True
        return isinstance(base, ast.Name) and base.id == "Thread"

    def _executor_authorized(self, fi: FuncInfo, recv) -> Optional[bool]:
        """For ``recv.submit(fn)``: was ``recv`` constructed as an
        executor, and with ``initializer=authorize_device_thread``?
        Returns None when the receiver's construction is unknown."""
        ctor_call = None
        if isinstance(recv, ast.Name):
            # Local: find `recv = Executor(...)` in this function.
            for node in ast.walk(fi.node):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == recv.id
                    and isinstance(node.value, ast.Call)
                ):
                    ctor_call = node.value
        elif (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and fi.cls is not None
        ):
            for m in self._class_methods.get(
                (id(fi.module), fi.cls), {}
            ).values():
                for node in ast.walk(m.node):
                    if (
                        isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and any(
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and t.attr == recv.attr
                            for t in node.targets
                        )
                    ):
                        ctor_call = node.value
        if ctor_call is None:
            if isinstance(recv, ast.Name):
                return self._param_authorized(fi, recv.id)
            return None
        if _call_name(ctor_call.func) not in _EXECUTOR_CTORS:
            return None
        for kw in ctor_call.keywords:
            if kw.arg == "initializer" and (
                _call_name(kw.value) in _AUTHORIZE_INITIALIZERS
                or (
                    isinstance(kw.value, ast.Name)
                    and kw.value.id in _AUTHORIZE_INITIALIZERS
                )
            ):
                return True
        return False

    def _param_authorized(self, fi: FuncInfo, name: str) -> Optional[bool]:
        """Executor received as a PARAMETER of ``fi``: resolve its
        construction through the callers — find same-class/module calls
        to ``fi`` and evaluate the argument bound to ``name`` in each
        caller's scope. Returns the verdict when every resolving call
        site agrees; None when no call site resolves."""
        params = [
            a.arg
            for a in fi.node.args.posonlyargs + fi.node.args.args
        ]
        if name not in params:
            return None
        idx = params.index(name)
        verdicts: List[bool] = []
        for caller in self.funcs:
            if caller.module is not fi.module or caller is fi:
                continue
            for node in ast.walk(caller.node):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                hits = (
                    isinstance(f, ast.Name) and f.id == fi.name
                ) or (
                    isinstance(f, ast.Attribute)
                    and f.attr == fi.name
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and caller.cls == fi.cls
                )
                if not hits:
                    continue
                # Bound-method calls drop the leading `self`.
                pos = idx - 1 if (fi.cls and params[0] == "self") else idx
                arg = None
                for kw in node.keywords:
                    if kw.arg == name:
                        arg = kw.value
                if arg is None and 0 <= pos < len(node.args):
                    arg = node.args[pos]
                if arg is None:
                    continue
                verdict = self._executor_authorized(caller, arg)
                if verdict is not None:
                    verdicts.append(verdict)
        if verdicts:
            return all(verdicts)
        return None

    def _add_root(self, fi, label, owner, reason, line) -> None:
        self.roots.append(
            ThreadRoot(
                func=fi, label=label, owner=owner, reason=reason, line=line
            )
        )

    def _find_roots(self) -> None:
        for mod in self.project.modules:
            for node in mod.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._class_roots(mod, node)
            for fi in self.funcs:
                if fi.module is not mod:
                    continue
                if fi.is_async:
                    self._add_root(
                        fi,
                        "async-handler",
                        self._claims_owner(fi),
                        "async def (event-loop thread)",
                        fi.node.lineno,
                    )
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call):
                    self._call_roots(mod, node)

    def _class_roots(self, mod, cls: ast.ClassDef) -> None:
        table = self._class_methods.get((id(mod), cls.name), {})
        if any(self._is_thread_base(mod, b) for b in cls.bases):
            run = table.get("run")
            if run is not None:
                self._add_root(
                    run,
                    cls.name,
                    self._claims_owner(run),
                    f"threading.Thread subclass `{cls.name}`",
                    run.node.lineno,
                )
        if cls.name.endswith("Sink") and "emit" in table:
            emit = table["emit"]
            self._add_root(
                emit,
                "sink-callback",
                self._claims_owner(emit),
                f"incident sink `{cls.name}.emit`",
                emit.node.lineno,
            )

    def _enclosing_func(self, mod, call: ast.Call) -> Optional[FuncInfo]:
        best = None
        for fi in self.funcs:
            if fi.module is not mod:
                continue
            if (
                fi.node.lineno <= call.lineno
                and call.lineno <= max(
                    (n.lineno for n in ast.walk(fi.node) if hasattr(n, "lineno")),
                    default=fi.node.lineno,
                )
            ):
                if best is None or fi.node.lineno > best.node.lineno:
                    best = fi
        return best

    def _call_roots(self, mod, call: ast.Call) -> None:
        enclosing = self._enclosing_func(mod, call)
        scope = enclosing or FuncInfo(module=mod, node=mod.tree, name="<module>")
        dotted = mod.dotted(call.func)
        # threading.Thread(target=f)
        if dotted == "threading.Thread" or (
            isinstance(call.func, ast.Name) and call.func.id == "Thread"
        ):
            target = next(
                (k.value for k in call.keywords if k.arg == "target"), None
            )
            name = next(
                (
                    k.value.value
                    for k in call.keywords
                    if k.arg == "name"
                    and isinstance(k.value, ast.Constant)
                ),
                None,
            )
            if target is not None:
                fi = self.resolve_callable(scope, target)
                if fi is not None:
                    self._add_root(
                        fi,
                        name or "thread-target",
                        self._claims_owner(fi),
                        "threading.Thread target",
                        call.lineno,
                    )
            return
        # pool.submit(f, ...) / executor.submit(f, ...)
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "submit"
            and call.args
        ):
            fi = self.resolve_callable(scope, call.args[0])
            if fi is None:
                return
            authorized = self._executor_authorized(scope, call.func.value)
            if authorized:
                self._add_root(
                    fi,
                    "authorized-worker",
                    True,
                    "executor with initializer=authorize_device_thread",
                    call.lineno,
                )
            else:
                self._add_root(
                    fi,
                    "pool-worker",
                    self._claims_owner(fi),
                    "submitted to a worker pool",
                    call.lineno,
                )
            return
        # fut.add_done_callback(f): the callback runs on the worker that
        # completed the future.
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "add_done_callback"
            and call.args
        ):
            fi = self.resolve_callable(scope, call.args[0])
            if fi is not None:
                self._add_root(
                    fi,
                    "pool-worker",
                    self._claims_owner(fi),
                    "future done-callback (runs on the completing worker)",
                    call.lineno,
                )

    # --------------------------------------------------------- R8 events

    def _device_touches(self, fi: FuncInfo) -> List[Tuple[ast.Call, str]]:
        mod = fi.module
        touches: List[Tuple[ast.Call, str]] = []
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = mod.dotted(node.func)
            if dotted is not None:
                if dotted.startswith(_EXEMPT_PREFIXES):
                    continue
                if dotted == "jax" or dotted.startswith(_DEVICE_PREFIXES):
                    touches.append((node, f"`{dotted}`"))
                    continue
            name = _call_name(node.func)
            if name is None:
                continue
            if (id(mod), name) in {
                (id(w.module), w.bound_name)
                for w in self.traced.wrappers
                if w.bound_name
            }:
                touches.append((node, f"jit wrapper `{name}`"))
            elif name in _DEVICE_SEAMS:
                touches.append((node, f"device seam `{name}()`"))
        return touches

    def _collect_device_events(self) -> None:
        seen = set()
        for root in self.roots:
            if root.owner:
                continue
            for fi in self.reachable(root.func):
                for call, desc in self._device_touches(fi):
                    key = (id(fi.module), call.lineno, call.col_offset)
                    if key in seen:
                        continue
                    seen.add(key)
                    via = (
                        ""
                        if fi is root.func
                        else f" (reached via `{root.func.qualname}`)"
                    )
                    self.events.append(
                        Event(
                            kind="cross-thread-device",
                            module=fi.module,
                            line=call.lineno,
                            col=call.col_offset,
                            message=(
                                f"{desc} in `{fi.qualname}` is reachable "
                                f"from the non-owner thread class "
                                f"`{root.label}` ({root.reason}, line "
                                f"{root.line}){via} — only the device-"
                                "owner thread may stage/dispatch/fetch "
                                "(one-thread-owns-the-device program-"
                                "order rule); move the device touch to "
                                "the owner loop, or make the root an "
                                "owner with claim_device_owner()/"
                                "initializer=authorize_device_thread"
                            ),
                        )
                    )

    # --------------------------------------------------------- R9 events

    def _shard_roots(self) -> List[FuncDef]:
        roots: List[FuncDef] = []
        for mod in self.project.modules:
            enclosing_stack: List[ast.FunctionDef] = []

            def visit(node):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    enclosing_stack.append(node)
                    for child in ast.iter_child_nodes(node):
                        visit(child)
                    enclosing_stack.pop()
                    return
                if isinstance(node, ast.Call):
                    dotted = mod.dotted(node.func)
                    name = _call_name(node.func)
                    if (
                        dotted in _SHARD_MAP_NAMES
                        or name in _SHARD_MAP_NAMES
                    ) and node.args:
                        fd = self._resolve_shard_body(
                            mod, enclosing_stack, node.args[0]
                        )
                        if fd is not None:
                            roots.append(fd)
                for child in ast.iter_child_nodes(node):
                    visit(child)

            visit(mod.tree)
        return roots

    def _resolve_shard_body(
        self, mod, enclosing_stack, arg
    ) -> Optional[FuncDef]:
        if isinstance(arg, ast.Name):
            # Nested def in the enclosing function(s), innermost first.
            for fn in reversed(enclosing_stack):
                for item in ast.walk(fn):
                    if (
                        isinstance(item, ast.FunctionDef)
                        and item.name == arg.id
                    ):
                        return FuncDef(module=mod, node=item, name=item.name)
            return self.traced.resolve(mod, arg.id)
        return None

    def _collective_functions(self) -> Set[int]:
        """ids of module-level FuncDefs that (transitively) issue a mesh
        collective — the kernels whose call paths R9 compares."""
        direct: Set[int] = set()
        calls: Dict[int, Set[int]] = {}
        for fd in self.traced.defs.values():
            out: Set[int] = set()
            for node in ast.walk(fd.node):
                if not isinstance(node, ast.Call):
                    continue
                if fd.module.dotted(node.func) in _COLLECTIVES:
                    direct.add(id(fd))
                elif isinstance(node.func, ast.Name):
                    callee = self.traced.resolve(fd.module, node.func.id)
                    if callee is not None:
                        out.add(id(callee))
            calls[id(fd)] = out
        # Propagate collective-ness up the call graph to a fixpoint.
        changed = True
        while changed:
            changed = False
            for fid, out in calls.items():
                if fid not in direct and out & direct:
                    direct.add(fid)
                    changed = True
        return direct

    def _collect_collective_events(self) -> None:
        roots = self._shard_roots()
        if not roots:
            return
        collective_fns = self._collective_functions()
        # Shard-traced taint fixpoint, seeded from the shard_map bodies
        # (operands are device shards by construction).
        tainted: Dict[int, Set[str]] = {}
        by_id: Dict[int, FuncDef] = {}
        for fd in roots:
            by_id[id(fd)] = fd
            tainted[id(fd)] = set(fd.params)
        changed = True
        while changed:
            changed = False
            for fid in list(tainted):
                fd = by_id[fid]
                walker = _TaintWalker(self.traced, fd, set(tainted[fid]))
                walker.run()
                for callee, callee_tainted in walker.calls:
                    if id(callee) not in tainted:
                        by_id[id(callee)] = callee
                        tainted[id(callee)] = set()
                        changed = True
                    cur = tainted[id(callee)]
                    if callee_tainted - cur:
                        cur |= callee_tainted
                        changed = True
        seen = set()
        for fid, taint in tainted.items():
            fd = by_id[fid]
            walker = _CollectiveWalker(
                self.traced, fd, set(taint), collective_fns
            )
            walker.run()
            for ev in walker.col_events:
                key = (id(ev.module), ev.line, ev.col, ev.kind)
                if key not in seen:
                    seen.add(key)
                    self.events.append(ev)


class _CollectiveWalker(_TaintWalker):
    """Taint walk over shard-traced code tracking data-dependent control
    flow, emitting R9's collective-order events."""

    def __init__(self, analysis, fd: FuncDef, tainted, collective_fns):
        super().__init__(analysis, fd, tainted, emit=False)
        self.collective_fns = collective_fns
        self.depth = 0                     # tainted-branch nesting
        self.col_events: List[Event] = []

    def _stmt(self, stmt) -> None:
        import ast as _ast

        if isinstance(stmt, (_ast.FunctionDef, _ast.AsyncFunctionDef)):
            # Nested defs are the scan/while bodies of the kernels —
            # walk them with THIS walker class so collectives under
            # tainted branches inside them still surface.
            inner = _CollectiveWalker(
                self.analysis,
                FuncDef(module=self.module, node=stmt, name=stmt.name),
                self.tainted
                | {
                    a.arg
                    for a in (
                        stmt.args.posonlyargs
                        + stmt.args.args
                        + stmt.args.kwonlyargs
                    )
                },
                self.collective_fns,
            )
            inner.depth = self.depth
            inner.run()
            self.col_events.extend(inner.col_events)
            self.calls.extend(inner.calls)
            return
        if isinstance(stmt, (_ast.If, _ast.While)):
            self._scan_expr(stmt.test)
            dep = self.is_tainted(stmt.test) and not _identity_test(
                stmt.test
            )
            self.depth += 1 if dep else 0
            for s in stmt.body:
                self._stmt(s)
            for s in stmt.orelse:
                self._stmt(s)
            self.depth -= 1 if dep else 0
            return
        if isinstance(stmt, _ast.For):
            self._scan_expr(stmt.iter)
            dep = self.is_tainted(stmt.iter)
            self._assign_target(stmt.target, dep)
            self.depth += 1 if dep else 0
            for s in stmt.body:
                self._stmt(s)
            for s in stmt.orelse:
                self._stmt(s)
            self.depth -= 1 if dep else 0
            return
        super()._stmt(stmt)

    def _scan_expr(self, expr) -> None:
        import ast as _ast

        super()._scan_expr(expr)
        for node in _ast.walk(expr):
            if not isinstance(node, _ast.Call):
                continue
            dotted = self.module.dotted(node.func)
            if dotted in _COLLECTIVES:
                if self.depth > 0:
                    op = dotted.rsplit(".", 1)[-1]
                    self.col_events.append(
                        Event(
                            kind="collective-data-dep",
                            module=self.module,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"`{op}` under data-dependent control "
                                "flow inside shard_map-traced code — "
                                "shards whose operands branch "
                                "differently fall out of the collective "
                                "schedule (deadlock on a real mesh); "
                                "hoist the collective out of the branch "
                                "or make the predicate trace-static"
                            ),
                        )
                    )
                continue
            if self.depth > 0 and isinstance(node.func, _ast.Name):
                target = self.analysis.resolve(self.module, node.func.id)
                if target is not None and id(target) in self.collective_fns:
                    self.col_events.append(
                        Event(
                            kind="collective-divergent-path",
                            module=self.module,
                            line=node.lineno,
                            col=node.col_offset,
                            message=(
                                f"`{node.func.id}()` issues mesh "
                                "collectives but is reached under data-"
                                "dependent control flow inside "
                                "shard_map-traced code — two call paths "
                                "to the same kernel carry divergent "
                                "collective sequences per shard; make "
                                "the call unconditional (mask its "
                                "inputs instead) or the predicate "
                                "trace-static"
                            ),
                        )
                    )
