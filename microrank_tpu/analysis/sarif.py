"""SARIF 2.1.0 rendering of mrlint findings.

``cli lint --sarif out.sarif`` writes the run in the Static Analysis
Results Interchange Format so GitHub code scanning (and any SARIF
viewer) annotates PR diffs with the findings in place. One run, one
tool (``mrlint``), one result per violation; the rule catalog rides
along as ``tool.driver.rules`` so the UI shows slug + summary next to
each annotation. R0 (unjustified disable) is reported at ``warning``
level — it marks a missing audit trail, not a device hazard; every
numbered rule is ``error``.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Iterable, List

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)


def _rule_meta(rule) -> dict:
    meta = {
        "id": rule.name,
        "name": rule.slug,
        "shortDescription": {"text": rule.summary},
        "defaultConfiguration": {"level": "error"},
    }
    doc = " ".join((type(rule).__doc__ or "").split())
    if doc:
        meta["fullDescription"] = {"text": doc}
    return meta


def to_sarif(violations: Iterable["Violation"]) -> dict:  # noqa: F821
    """Render violations as one SARIF run. The rule index includes every
    registered rule plus R0 (which has no Rule class — the framework
    emits it for unjustified disables)."""
    from .core import RULES

    rules: List[dict] = [
        {
            "id": "R0",
            "name": "bare-disable",
            "shortDescription": {
                "text": "mrlint disable pragma without a justification"
            },
            "defaultConfiguration": {"level": "warning"},
        }
    ]
    rules.extend(
        _rule_meta(r) for r in sorted(RULES.values(), key=lambda r: r.name)
    )
    index = {r["id"]: i for i, r in enumerate(rules)}
    results = []
    for v in violations:
        results.append(
            {
                "ruleId": v.rule,
                "ruleIndex": index.get(v.rule, -1),
                "level": "warning" if v.rule == "R0" else "error",
                "message": {"text": v.message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": str(v.path).replace("\\", "/"),
                                "uriBaseId": "SRCROOT",
                            },
                            "region": {
                                "startLine": max(1, v.line),
                                # SARIF columns are 1-based; ast's are
                                # 0-based. Clamp: a synthetic violation
                                # (framework R0, interprocedural events)
                                # may carry col 0 or -1, and SARIF
                                # consumers reject startColumn < 1.
                                "startColumn": max(1, v.col + 1),
                            },
                        }
                    }
                ],
            }
        )
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "mrlint",
                        "informationUri": (
                            "https://github.com/microrank-tpu/microrank-tpu"
                        ),
                        "rules": rules,
                    }
                },
                "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
                "results": results,
            }
        ],
    }


def write_sarif(violations, path) -> Path:
    out = Path(path)
    out.write_text(json.dumps(to_sarif(violations), indent=2) + "\n")
    return out
