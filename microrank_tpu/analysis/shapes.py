"""mrshape — interprocedural shape/dtype/static-arg provenance analysis,
and the compile-cache key-space model it predicts.

The compile cache is keyed on (static args, argument shapes, dtypes).
mrlint R3 catches *local* leaks of live measurements into that key; this
module tracks provenance through the whole project call graph on a
finite lattice, so the four rules built on it (R13-R16, analysis.rules)
can make *global* claims:

Provenance lattice (one abstract value per local/parameter/return)::

    BOT  <  CONST  <  BUCKET  <  TOP

* ``BOT`` — nothing known (unanalyzed input); never fires a rule.
* ``CONST`` — a statically-determined constant. Carries the enumerable
  value set when small; joining past ``WIDEN_LIMIT`` distinct values
  widens to "constant, set unenumerable" (values=None) — still bounded,
  still cache-safe, no longer enumerable for R16.
* ``BUCKET`` — drawn from the pad-bucket registry
  (``graph.structures.pad_to`` or a ``pad*/bucket*/pow2*/round*/
  align*/next_*`` helper): a finite shape family by construction.
* ``TOP`` — a raw host measurement of live data (``len()``/``int()``/
  a measured extent): unbounded, one compile-cache entry per distinct
  value. TOP reaching a static argument of a jit wrapper is R13; an
  array whose shape is TOP reaching a dispatch seam is R15.

Dtype lattice: the precision ladder is the powerset of
``{"float32", "bfloat16", "int8"}`` ordered by inclusion (join =
union). Two distinct ladder levels meeting at one fused program
boundary without an explicit cast (``astype``/``asarray(dtype=...)``)
is R14 — inside the program XLA inserts the upcast where it lands, not
where the kernel contract says (arxiv 2009.10443's mixed-ladder drift).

Propagation mirrors ``analysis.traced.TracedAnalysis``: a monotone
fixpoint over module-level functions joins argument provenance into
callee parameters and uses callee return summaries at call sites; it
terminates because both lattices are finite and joins only go up.

The runtime half (``CompileKeySpace``/``predict_key_space``) is the
numeric model the mrsan compile-witness checker (analysis.mrsan)
cross-checks observed compile keys against: every observed array extent
must be a pad-bucket fixed point (or a batch-occupancy axis), every
kernel a known kernel — an observed key outside the space is a
sanitizer failure, the dynamic twin of R13/R15/R16.
"""

from __future__ import annotations

import ast
import itertools
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

# ------------------------------------------------------------ the lattice

BOT, CONST, BUCKET, TOP = 0, 1, 2, 3
_LEVEL_NAMES = {BOT: "⊥", CONST: "const", BUCKET: "bucket", TOP: "⊤"}

# Past this many enumerated constants the set widens to "unenumerable"
# (values=None): still CONST (bounded), no longer usable by R16.
WIDEN_LIMIT = 8

# The precision ladder (PageRankConfig.kind_precision et al.).
LADDER_DTYPES = ("float32", "bfloat16", "int8")


@dataclass(frozen=True)
class Prov:
    """One provenance lattice element; ``values`` only at CONST level."""

    level: int = BOT
    values: Optional[FrozenSet] = None

    def join(self, other: "Prov") -> "Prov":
        level = max(self.level, other.level)
        if level != CONST:
            return Prov(level)
        if self.values is None or other.values is None:
            return Prov(CONST, None)
        merged = self.values | other.values
        if len(merged) > WIDEN_LIMIT:
            return Prov(CONST, None)  # widen: bounded but unenumerable
        return Prov(CONST, merged)

    @property
    def enumerable(self) -> bool:
        return self.level == CONST and self.values is not None

    def describe(self) -> str:
        if self.enumerable:
            vals = sorted(map(repr, self.values))
            return f"const{{{', '.join(vals)}}}"
        return _LEVEL_NAMES[self.level]


P_BOT = Prov(BOT)
P_TOP = Prov(TOP)
P_BUCKET = Prov(BUCKET)


def p_const(value) -> Prov:
    try:
        return Prov(CONST, frozenset([value]))
    except TypeError:  # unhashable constant — bounded, unenumerable
        return Prov(CONST, None)


@dataclass(frozen=True)
class AbsVal:
    """Abstract value: for scalars ``prov`` is the VALUE's provenance;
    for arrays it is the provenance of the array's SHAPE (what keys the
    compile cache). ``dtypes`` holds the ladder levels flowing through;
    ``cast`` marks an explicit boundary cast at this expression."""

    prov: Prov = P_BOT
    dtypes: FrozenSet[str] = frozenset()
    is_array: bool = False
    cast: bool = False

    def join(self, other: "AbsVal") -> "AbsVal":
        return AbsVal(
            prov=self.prov.join(other.prov),
            dtypes=self.dtypes | other.dtypes,
            is_array=self.is_array or other.is_array,
            cast=self.cast and other.cast,
        )


V_BOT = AbsVal()

# ----------------------------------------------------- source recognition

_MEASURES = {"len", "int", "float"}
_BUCKET_HINTS = ("pad", "bucket", "pow2", "round", "align", "next_")
_ARRAY_CTORS = {"zeros", "ones", "empty", "full", "arange"}
# Project functions that return pad-bucketed window graphs: everything
# they build is shaped through graph.structures.pad_to by construction.
_GRAPH_BUILDERS = (
    "build_window_graph",
    "prepare_window_graph",
    "stack_window_graphs",
    "collapse_window_graph",
    "synthetic_prepared",
)
# Device dispatch seams whose argument shapes key the compile cache
# (R15): the router and the blob staging entry points.
_DISPATCH_SEAMS = {
    "rank_batch",
    "stage_rank_window",
    "stage_rank_windows_batched",
    "stage_windows_batched",
    "dispatch_windows_staged",
    "stage_sharded",
}
# Functions whose call subtree is warmup (R16): the statically
# enumerated keys dispatched from here are "declared warm".
_WARM_MARKERS = ("warm",)


def _is_bucket_name(name: Optional[str]) -> bool:
    if not name:
        return False
    low = name.lower()
    return low == "pad_to" or any(h in low for h in _BUCKET_HINTS)


def _dtype_of_node(module, node) -> Optional[str]:
    """A ladder-dtype *designator* expression (``jnp.bfloat16``,
    ``"int8"``), or None."""
    if isinstance(node, ast.Constant) and node.value in LADDER_DTYPES:
        return node.value
    if isinstance(node, ast.Attribute) and node.attr in LADDER_DTYPES:
        return node.attr
    dotted = module.dotted(node)
    if dotted:
        tail = dotted.rsplit(".", 1)[-1]
        if tail in LADDER_DTYPES:
            return tail
    return None


# -------------------------------------------------------------- the walk


@dataclass
class WrapperSite:
    """One call of a known jit wrapper, with per-argument analysis."""

    wrapper: object               # traced.JitWrapper
    call: ast.Call
    module: object                # core.ModuleInfo
    enclosing: Optional[object]   # traced.FuncDef of the calling function
    static_provs: List[Tuple[int, str, Prov]] = field(default_factory=list)
    arg_vals: List[AbsVal] = field(default_factory=list)
    # Per-argument: the arg expression ITSELF is an explicit cast at
    # this boundary (x.astype(d) / asarray(x, dtype=d) / jnp.f32(x)).
    boundary_casts: List[bool] = field(default_factory=list)


@dataclass
class SeamSite:
    """One call of a dispatch seam with the graph argument's value."""

    seam: str
    call: ast.Call
    module: object
    graph_val: AbsVal = V_BOT


class _ShapeWalker:
    """Forward abstract interpretation of one function body on the
    Prov/dtype lattice. Mirrors traced._TaintWalker's statement set."""

    def __init__(self, analysis: "ShapeAnalysis", fd, env: Dict[str, AbsVal]):
        self.analysis = analysis
        self.fd = fd
        self.module = fd.module
        self.env = dict(env)
        self.ret: AbsVal = V_BOT
        self.calls: List[Tuple[object, Dict[str, AbsVal]]] = []
        self.wrapper_sites: List[WrapperSite] = []
        self.seam_sites: List[SeamSite] = []

    def run(self) -> None:
        for stmt in self.fd.node.body:
            self._stmt(stmt)

    # ------------------------------------------------------------- eval

    def eval(self, node) -> AbsVal:
        if node is None:
            return V_BOT
        if isinstance(node, ast.Constant):
            if isinstance(node.value, (int, str, bool, float)):
                return AbsVal(prov=p_const(node.value))
            return V_BOT
        if isinstance(node, ast.Name):
            return self.env.get(node.id, V_BOT)
        if isinstance(node, ast.Attribute):
            return self._eval_attribute(node)
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node)
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.BinOp):
            return self._eval_binop(node)
        if isinstance(node, ast.UnaryOp):
            inner = self.eval(node.operand)
            if (
                isinstance(node.op, ast.USub)
                and inner.prov.enumerable
            ):
                vals = frozenset(
                    -v for v in inner.prov.values
                    if isinstance(v, (int, float))
                )
                if vals:
                    return AbsVal(prov=Prov(CONST, vals))
            return inner
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = V_BOT
            for e in node.elts:
                out = out.join(self.eval(e))
            return out
        if isinstance(node, ast.IfExp):
            return self.eval(node.body).join(self.eval(node.orelse))
        if isinstance(node, ast.BoolOp):
            out = V_BOT
            for v in node.values:
                out = out.join(self.eval(v))
            return out
        if isinstance(node, ast.Compare):
            return V_BOT  # booleans don't shape compile keys
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        return V_BOT

    def _eval_attribute(self, node: ast.Attribute) -> AbsVal:
        if node.attr == "shape":
            # An array's .shape inherits the array's SHAPE provenance —
            # a bucketed array's measured extent is still bucketed; an
            # unknown array's stays unknown (BOT: never fires).
            base = self.eval(node.value)
            if base.is_array:
                return AbsVal(prov=base.prov)
            return V_BOT
        base = self.eval(node.value)
        if base.is_array:
            # x.T / x.real / config-attr chains off arrays keep shape
            # provenance; scalar attrs of arrays (.size) stay unknown.
            if node.attr in ("T", "real", "imag"):
                return base
            return V_BOT
        return V_BOT

    def _eval_subscript(self, node: ast.Subscript) -> AbsVal:
        base = self.eval(node.value)
        if base.is_array:
            return AbsVal(dtypes=base.dtypes, is_array=True)
        if isinstance(node.value, ast.Attribute) and node.value.attr == "shape":
            # x.shape[i]: provenance of the shape itself (see above).
            return self.eval(node.value)
        return V_BOT

    def _call_name(self, node: ast.Call) -> Optional[str]:
        if isinstance(node.func, ast.Name):
            return node.func.id
        if isinstance(node.func, ast.Attribute):
            return node.func.attr
        return None

    def _eval_call(self, node: ast.Call) -> AbsVal:
        name = self._call_name(node)
        arg_vals = [self.eval(a) for a in node.args]
        kw_vals = {k.arg: self.eval(k.value) for k in node.keywords if k.arg}
        joined = V_BOT
        for v in list(arg_vals) + list(kw_vals.values()):
            joined = joined.join(v)

        # Bucket registry: pad_to / pad* / pow2* helpers — output drawn
        # from the finite bucket family regardless of the input.
        if _is_bucket_name(name):
            return AbsVal(prov=P_BUCKET)

        # Graph builders: every array inside is pad_to-shaped.
        if name and name.startswith(_GRAPH_BUILDERS):
            return AbsVal(prov=P_BUCKET, is_array=True)

        # Host measurement of live data: len()/int()/float() over
        # anything not statically constant is TOP (the R3d semantics,
        # now interprocedural).
        if name in _MEASURES and node.args:
            inner = arg_vals[0]
            if inner.prov.level in (CONST,):
                return AbsVal(prov=Prov(CONST, None))
            if inner.prov.level == BUCKET:
                return AbsVal(prov=P_BUCKET)  # int(pad_to(..)) stays bucketed
            return AbsVal(prov=P_TOP)

        # Explicit precision-ladder casts: x.astype(d) / asarray(x, dtype=d)
        # / jnp.float32(x).
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and node.args
        ):
            d = _dtype_of_node(self.module, node.args[0])
            recv = self.eval(node.func.value)
            return AbsVal(
                prov=recv.prov,
                dtypes=frozenset([d]) if d else recv.dtypes,
                is_array=True,
                cast=True,
            )
        dtype_kw = next(
            (k.value for k in node.keywords if k.arg == "dtype"), None
        )
        kw_dtype = _dtype_of_node(self.module, dtype_kw)
        direct = _dtype_of_node(self.module, node.func)
        if direct and node.args:
            return AbsVal(
                prov=arg_vals[0].prov,
                dtypes=frozenset([direct]),
                is_array=arg_vals[0].is_array,
                cast=True,
            )

        # Array constructors: shape provenance from the shape argument,
        # dtype from the dtype kwarg.
        if name in _ARRAY_CTORS:
            shape_prov = arg_vals[0].prov if arg_vals else P_BOT
            return AbsVal(
                prov=shape_prov,
                dtypes=frozenset([kw_dtype]) if kw_dtype else frozenset(),
                is_array=True,
                cast=bool(kw_dtype),
            )
        if name in ("asarray", "array") and node.args:
            return AbsVal(
                prov=arg_vals[0].prov,
                dtypes=(
                    frozenset([kw_dtype]) if kw_dtype else arg_vals[0].dtypes
                ),
                is_array=True,
                cast=bool(kw_dtype),
            )

        # Project-internal call: record for the fixpoint, use the
        # callee's return summary.
        if isinstance(node.func, ast.Name):
            target = self.analysis.traced.resolve(self.module, node.func.id)
            if target is not None:
                params = target.params
                bind: Dict[str, AbsVal] = {}
                for i, v in enumerate(arg_vals):
                    if i < len(params) and not isinstance(
                        node.args[i], ast.Starred
                    ):
                        bind[params[i]] = v
                for k, v in kw_vals.items():
                    if k in params:
                        bind[k] = v
                self.calls.append((target, bind))
                return self.analysis.ret_summary(target)

        # Method on an array keeps its dtype set; unknown call joins
        # its operands (the monotone default — matches R3's recursion).
        if isinstance(node.func, ast.Attribute):
            recv = self.eval(node.func.value)
            if recv.is_array:
                return AbsVal(
                    prov=recv.prov, dtypes=recv.dtypes, is_array=True
                )
        return AbsVal(prov=joined.prov, dtypes=joined.dtypes)

    _ARITH = {
        ast.Add: lambda a, b: a + b,
        ast.Sub: lambda a, b: a - b,
        ast.Mult: lambda a, b: a * b,
        ast.FloorDiv: lambda a, b: a // b if b else None,
        ast.Mod: lambda a, b: a % b if b else None,
    }

    def _eval_binop(self, node: ast.BinOp) -> AbsVal:
        left, right = self.eval(node.left), self.eval(node.right)
        if left.is_array != right.is_array and isinstance(
            node.op, (ast.Mult, ast.Add)
        ):
            # ``[graph] * occ`` / list concat: replication changes the
            # batch occupancy, not the element shapes — the array
            # side's shape provenance carries.
            return left if left.is_array else right
        op = self._ARITH.get(type(node.op))
        if (
            op is not None
            and left.prov.enumerable
            and right.prov.enumerable
        ):
            vals = set()
            for a, b in itertools.product(
                left.prov.values, right.prov.values
            ):
                if isinstance(a, (int, float)) and isinstance(
                    b, (int, float)
                ):
                    try:
                        r = op(a, b)
                    except (ZeroDivisionError, OverflowError):
                        r = None
                    if r is not None:
                        vals.add(r)
            if vals and len(vals) <= WIDEN_LIMIT:
                return AbsVal(
                    prov=Prov(CONST, frozenset(vals)),
                    dtypes=left.dtypes | right.dtypes,
                )
        return left.join(right)

    # ------------------------------------------------------- statements

    def _assign(self, target, val: AbsVal) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = val
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                # Per-element values are lost in the join; keep it
                # conservative (BOT never fires).
                self._assign(e, AbsVal(dtypes=val.dtypes))
        elif isinstance(target, ast.Starred):
            self._assign(target.value, val)

    def _stmt(self, stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs analyzed only via direct calls
        if isinstance(stmt, ast.Assign):
            self._scan_calls(stmt.value)
            val = self.eval(stmt.value)
            for t in stmt.targets:
                self._assign(t, val)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._scan_calls(stmt.value)
                self._assign(stmt.target, self.eval(stmt.value))
            return
        if isinstance(stmt, ast.AugAssign):
            self._scan_calls(stmt.value)
            if isinstance(stmt.target, ast.Name):
                cur = self.env.get(stmt.target.id, V_BOT)
                self.env[stmt.target.id] = cur.join(self.eval(stmt.value))
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_calls(stmt.test)
            for s in stmt.body:
                self._stmt(s)
            for s in stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.For):
            self._scan_calls(stmt.iter)
            self._assign(stmt.target, self.eval(stmt.iter))
            for s in stmt.body:
                self._stmt(s)
            for s in stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._scan_calls(item.context_expr)
                if item.optional_vars is not None:
                    self._assign(
                        item.optional_vars, self.eval(item.context_expr)
                    )
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body:
                self._stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    self._stmt(s)
            for s in stmt.orelse + stmt.finalbody:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_calls(stmt.value)
                self.ret = self.ret.join(self.eval(stmt.value))
            return
        if isinstance(stmt, ast.Expr):
            self._scan_calls(stmt.value)
            return
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self._scan_calls(node)

    def _scan_calls(self, expr) -> None:
        """Record project calls, jit-wrapper sites and dispatch-seam
        sites inside one expression (evaluating args on the lattice)."""
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            # Side effect: _eval_call records project-call bindings.
            self.eval(node)
            self._note_wrapper_site(node)
            self._note_seam_site(node)

    def _note_wrapper_site(self, call: ast.Call) -> None:
        if not isinstance(call.func, ast.Name):
            return
        w = self.analysis.wrapper_index.get(
            (id(self.module), call.func.id)
        )
        if w is None:
            return
        params = w.target.params if w.target is not None else ()
        site = WrapperSite(
            wrapper=w, call=call, module=self.module, enclosing=self.fd
        )
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            v = self.eval(arg)
            site.arg_vals.append(v)
            site.boundary_casts.append(self._is_boundary_cast(arg))
            pname = params[i] if i < len(params) else f"arg{i}"
            if i in w.static_argnums or (
                i < len(params) and params[i] in w.static_argnames
            ):
                site.static_provs.append((i, pname, v.prov))
        for k in call.keywords:
            if k.arg and k.arg in w.static_argnames:
                site.static_provs.append((-1, k.arg, self.eval(k.value).prov))
        self.analysis.wrapper_sites.append(site)

    def _is_boundary_cast(self, arg) -> bool:
        if not isinstance(arg, ast.Call):
            return False
        if (
            isinstance(arg.func, ast.Attribute)
            and arg.func.attr == "astype"
        ):
            return True
        if any(
            k.arg == "dtype"
            and _dtype_of_node(self.module, k.value) is not None
            for k in arg.keywords
        ):
            return True
        return _dtype_of_node(self.module, arg.func) is not None

    def _note_seam_site(self, call: ast.Call) -> None:
        name = self._call_name(call)
        if name not in _DISPATCH_SEAMS or not call.args:
            return
        graph_val = self.eval(call.args[0])
        if isinstance(call.args[0], (ast.List, ast.Tuple)):
            gv = V_BOT
            for e in call.args[0].elts:
                gv = gv.join(self.eval(e))
            graph_val = gv
        self.analysis.seam_sites.append(
            SeamSite(
                seam=name,
                call=call,
                module=self.module,
                graph_val=graph_val,
            )
        )


# ----------------------------------------------------------- the analysis


@dataclass
class ShapeEvent:
    # "recompile-bomb" (R13) | "ladder-break" (R14) |
    # "bucket-escape" (R15) | "warmup-gap" (R16)
    kind: str
    module: object
    line: int
    col: int
    message: str


class ShapeAnalysis:
    """Interprocedural shape/dtype provenance over one lint Project.

    Built lazily via ``Project.shapes``; rules R13-R16 read ``events``.
    """

    def __init__(self, project):
        self.project = project
        self.traced = project.traced
        self.wrapper_index = {
            (id(w.module), w.bound_name): w
            for w in self.traced.wrappers
            if w.bound_name
        }
        # id(FuncDef) -> {param: AbsVal} / return AbsVal summaries.
        self.param_env: Dict[int, Dict[str, AbsVal]] = {}
        self.ret_env: Dict[int, AbsVal] = {}
        self._by_id: Dict[int, object] = {}
        self.wrapper_sites: List[WrapperSite] = []
        self.seam_sites: List[SeamSite] = []
        self.events: List[ShapeEvent] = []
        self._fixpoint()
        self._emit_r13()
        self._emit_r14()
        self._emit_r15()
        self._emit_r16()

    # ------------------------------------------------------------ engine

    def ret_summary(self, fd) -> AbsVal:
        return self.ret_env.get(id(fd), V_BOT)

    def _all_defs(self) -> List[object]:
        return list(self.traced.defs.values())

    def _fixpoint(self) -> None:
        for fd in self._all_defs():
            self._by_id[id(fd)] = fd
            self.param_env.setdefault(id(fd), {})
        changed = True
        rounds = 0
        while changed and rounds < 50:  # belt over the monotone proof
            changed = False
            rounds += 1
            self.wrapper_sites.clear()
            self.seam_sites.clear()
            for fd in self._all_defs():
                walker = _ShapeWalker(self, fd, self.param_env[id(fd)])
                walker.run()
                if self._join_ret(fd, walker.ret):
                    changed = True
                for callee, bind in walker.calls:
                    self._by_id.setdefault(id(callee), callee)
                    env = self.param_env.setdefault(id(callee), {})
                    for pname, val in bind.items():
                        cur = env.get(pname, V_BOT)
                        new = cur.join(val)
                        if new != cur:
                            env[pname] = new
                            changed = True

    def _join_ret(self, fd, ret: AbsVal) -> bool:
        cur = self.ret_env.get(id(fd), V_BOT)
        new = cur.join(ret)
        if new != cur:
            self.ret_env[id(fd)] = new
            return True
        return False

    # -------------------------------------------------------- R13 events

    def _emit_r13(self) -> None:
        for site in self.wrapper_sites:
            for pos, pname, prov in site.static_provs:
                if prov.level != TOP:
                    continue
                wname = site.wrapper.bound_name or "<jit>"
                self.events.append(
                    ShapeEvent(
                        kind="recompile-bomb",
                        module=site.module,
                        line=site.call.lineno,
                        col=site.call.col_offset,
                        message=(
                            f"static argument `{pname}` of jit wrapper "
                            f"`{wname}` has ⊤ provenance — a raw host "
                            "measurement of live data reaches a compile-"
                            "cache key interprocedurally, so every "
                            "distinct value recompiles (the recompile "
                            "bomb R3 only sees locally); route the "
                            "measurement through the bucket registry "
                            "(graph.structures.pad_to) before it "
                            "becomes static"
                        ),
                    )
                )

    # -------------------------------------------------------- R14 events

    def _emit_r14(self) -> None:
        for site in self.wrapper_sites:
            uncast_levels: Dict[str, int] = {}
            for i, v in enumerate(site.arg_vals):
                if i < len(site.boundary_casts) and site.boundary_casts[i]:
                    continue  # explicitly cast at the boundary
                for d in v.dtypes:
                    if d in LADDER_DTYPES:
                        uncast_levels.setdefault(d, i)
            if len(uncast_levels) < 2:
                continue
            wname = site.wrapper.bound_name or "<jit>"
            levels = ", ".join(sorted(uncast_levels))
            self.events.append(
                ShapeEvent(
                    kind="ladder-break",
                    module=site.module,
                    line=site.call.lineno,
                    col=site.call.col_offset,
                    message=(
                        f"mixed precision-ladder dtypes ({levels}) flow "
                        f"into one fused program boundary `{wname}` "
                        "without an explicit cast — XLA inserts the "
                        "upcast where the values meet, not where the "
                        "kernel contract says, so accumulation "
                        "precision silently drifts per call site; cast "
                        "at the boundary (`x.astype(...)` / "
                        "`jnp.asarray(x, dtype=...)`) to pin one "
                        "ladder level"
                    ),
                )
            )

    # -------------------------------------------------------- R15 events

    def _emit_r15(self) -> None:
        for site in self.seam_sites:
            v = site.graph_val
            if not (v.is_array and v.prov.level == TOP):
                continue
            self.events.append(
                ShapeEvent(
                    kind="bucket-escape",
                    module=site.module,
                    line=site.call.lineno,
                    col=site.call.col_offset,
                    message=(
                        f"array shaped by a raw host measurement "
                        f"reaches dispatch seam `{site.seam}` — its "
                        "shape keys the compile cache outside the pad-"
                        "bucket registry, so the DispatchRouter "
                        "compiles one program per distinct window "
                        "(pad-bucket escape); build the array through "
                        "graph.structures.pad_to (or a build_window_"
                        "graph*/prepare_window_graph helper) so the "
                        "shape is drawn from the bucket family"
                    ),
                )
            )

    # -------------------------------------------------------- R16 events

    def _warm_defs(self) -> Set[int]:
        """FuncDefs reachable from warm*-named roots (name-level BFS
        over project-resolved calls)."""
        edges: Dict[int, Set[int]] = {}
        for fd in self._all_defs():
            outs: Set[int] = set()
            for node in ast.walk(fd.node):
                if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Name
                ):
                    callee = self.traced.resolve(fd.module, node.func.id)
                    if callee is not None:
                        self._by_id.setdefault(id(callee), callee)
                        outs.add(id(callee))
            edges[id(fd)] = outs
        warm = {
            id(fd)
            for fd in self._all_defs()
            if any(m in fd.name.lower() for m in _WARM_MARKERS)
        }
        frontier = list(warm)
        while frontier:
            cur = frontier.pop()
            for nxt in edges.get(cur, ()):
                if nxt not in warm:
                    warm.add(nxt)
                    frontier.append(nxt)
        return warm

    @staticmethod
    def _site_keys(site: WrapperSite) -> Optional[Set[Tuple]]:
        """The statically enumerated compile-key set of one call site
        (cartesian product of static-arg value sets), or None when any
        static argument is unenumerable (delegated to the runtime
        compile witness)."""
        if not site.static_provs:
            return None
        axes = []
        for _pos, pname, prov in site.static_provs:
            if not prov.enumerable:
                return None
            axes.append([(pname, v) for v in sorted(prov.values, key=repr)])
        keys = set()
        for combo in itertools.product(*axes):
            keys.add(tuple(combo))
            if len(keys) > 64:
                return None  # key space too large to enumerate
        return keys

    def _emit_r16(self) -> None:
        warm = self._warm_defs()
        by_wrapper: Dict[int, List[WrapperSite]] = {}
        for site in self.wrapper_sites:
            by_wrapper.setdefault(id(site.wrapper), []).append(site)
        for sites in by_wrapper.values():
            warm_keys: Set[Tuple] = set()
            has_warm_site = False
            for site in sites:
                if site.enclosing is not None and id(site.enclosing) in warm:
                    has_warm_site = True
                    keys = self._site_keys(site)
                    if keys:
                        warm_keys |= keys
            if not has_warm_site:
                continue  # no warmup declared for this wrapper at all
            for site in sites:
                if site.enclosing is not None and id(site.enclosing) in warm:
                    continue
                keys = self._site_keys(site)
                if not keys:
                    continue  # unenumerable: the runtime witness owns it
                missing = keys - warm_keys
                if not missing:
                    continue
                wname = site.wrapper.bound_name or "<jit>"
                sample = sorted(
                    "(" + ", ".join(f"{k}={v!r}" for k, v in key) + ")"
                    for key in missing
                )[:3]
                self.events.append(
                    ShapeEvent(
                        kind="warmup-gap",
                        module=site.module,
                        line=site.call.lineno,
                        col=site.call.col_offset,
                        message=(
                            f"compile keys {', '.join(sample)} of jit "
                            f"wrapper `{wname}` are dispatched here but "
                            "never by the warmup path — the statically "
                            "enumerated key set must be a subset of "
                            "what warmup declares (warmup manifest "
                            "coverage), or the first production request "
                            "pays the compile; add the key to the "
                            "warm* call (dispatch/warmup.py) or make "
                            "the argument reach this site through it"
                        ),
                    )
                )


# ----------------------------------------------- runtime key-space model


def is_bucketed_extent(
    n: int,
    policy: str = "pow2q",
    min_pad: int = 8,
    occupancy: Optional[int] = None,
) -> bool:
    """True when one array extent is explainable by the pad-bucket
    registry: small (≤ the pad floor), a batch-occupancy axis, a
    ``pad_to`` fixed point under ``policy``, an indptr row (bucket+1),
    or a packed-bitmap byte column (bucket/8)."""
    from ..graph.structures import pad_to

    n = int(n)
    if n <= max(int(min_pad), 8):
        return True
    if occupancy is not None and n == int(occupancy):
        return True
    if pad_to(n, policy, min_pad) == n:
        return True
    if n >= 1 and pad_to(n - 1, policy, min_pad) == n - 1:
        return True  # indptr arrays carry one extra row
    if pad_to(n * 8, policy, min_pad) == n * 8:
        return True  # np.packbits byte columns: bucket / 8
    return False


KNOWN_KERNELS = (
    "auto",
    "dense",
    "dense_bf16",
    "coo",
    "csr",
    "pcsr",
    "packed",
    "packed_bf16",
    "packed_blocked",
    "kind",
    "pallas",
)


@dataclass
class CompileKeySpace:
    """The statically predicted compile-key space for one run: observed
    keys (program, kernel, occupancy, leaf shapes) must fall inside it.
    ``kernels``/``occupancies`` of None mean "any" — the load-bearing
    claim is always the shape predicate: every extent is drawn from the
    pad-bucket registry."""

    pad_policy: str = "pow2q"
    min_pad: int = 8
    kernels: Optional[FrozenSet[str]] = None
    occupancies: Optional[FrozenSet[int]] = None

    def admits(
        self,
        program: str,
        kernel: Optional[str],
        occupancy: Optional[int],
        shapes,
    ) -> Optional[str]:
        """None when the observed key is inside the predicted space,
        else a human-readable reason it escaped."""
        if kernel is not None:
            allowed = (
                self.kernels if self.kernels is not None
                else frozenset(KNOWN_KERNELS)
            )
            if kernel not in allowed:
                return (
                    f"kernel {kernel!r} of program {program!r} is outside "
                    f"the predicted kernel set {sorted(allowed)}"
                )
        if (
            occupancy is not None
            and self.occupancies is not None
            and int(occupancy) not in self.occupancies
        ):
            return (
                f"occupancy {occupancy} of program {program!r} is outside "
                f"the declared warmup occupancies "
                f"{sorted(self.occupancies)}"
            )
        if self.pad_policy == "exact":
            return None  # exact padding predicts nothing about extents
        for shape in shapes or ():
            for dim in shape:
                if not is_bucketed_extent(
                    dim, self.pad_policy, self.min_pad, occupancy
                ):
                    return (
                        f"extent {int(dim)} in shape {tuple(shape)} of "
                        f"program {program!r} is not a "
                        f"pad_to(policy={self.pad_policy!r}) bucket — a "
                        "live measurement escaped the bucket registry"
                    )
        return None


def predict_key_space(
    config=None,
    occupancies=None,
    cache_dir: Optional[str] = None,
    pipeline: Optional[str] = None,
) -> CompileKeySpace:
    """Build the run's predicted key space from its config (pad policy,
    forced kernel) plus — when a warmup manifest is available — the
    declared occupancies. Occupancies stay open (None) unless the
    caller or the manifest pins them: the shape-bucket predicate is the
    invariant the witness enforces everywhere."""
    runtime = getattr(config, "runtime", config)
    policy = str(getattr(runtime, "pad_policy", "pow2q") or "pow2q")
    min_pad = int(getattr(runtime, "min_pad", 8) or 8)
    kernels = None
    forced = getattr(runtime, "kernel", "auto")
    if forced and forced != "auto":
        # A forced kernel still auto-resolves on the sharded route, so
        # the prediction keeps the full shard-capable set plus it.
        kernels = frozenset(KNOWN_KERNELS) | frozenset([str(forced)])
    occs = set(int(o) for o in occupancies) if occupancies else set()
    if cache_dir and pipeline:
        from ..dispatch.cache import manifest_occupancies

        occs |= set(manifest_occupancies(cache_dir, pipeline))
    return CompileKeySpace(
        pad_policy=policy,
        min_pad=min_pad,
        kernels=kernels,
        occupancies=frozenset(occs) if occs else None,
    )
