"""The mrlint rule set (R1-R7). See analysis/__init__ for the catalog.

Each rule is intentionally heuristic — it encodes THIS repo's TPU
invariants, not general Python semantics — and every finding can be
suppressed in place with ``# mrlint: disable=RN(reason)`` (a reason is
mandatory; bare disables are reported as R0 by the framework).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import ModuleInfo, Project, Rule, Violation, register


def _v(module: ModuleInfo, node, rule: str, message: str) -> Violation:
    return Violation(
        path=module.rel,
        line=getattr(node, "lineno", getattr(node, "line", 0)),
        col=getattr(node, "col_offset", getattr(node, "col", 0)),
        rule=rule,
        message=message,
    )


@register
class HostSyncRule(Rule):
    """R1: no implicit host sync on traced values inside jit call graphs.

    ``float()``/``int()``/``bool()``/``.item()``/``np.asarray``/
    ``jax.device_get`` on a value reachable from a non-static parameter
    of a jitted function either crashes at trace time
    (TracerArrayConversionError) or — in op-by-op execution — silently
    serializes dispatch with a device->host round trip per call (~90 ms
    on tunneled-TPU runtimes). The traced-call-graph analysis in
    analysis/traced.py decides what is traced; ``.shape``/``.dtype``
    reads are static and exempt. Laundering is caught too: bound-method
    aliases (``f = x.item; f()``), ``getattr(x, "item")()``, and taint
    carried through nominally-static wrappers (``functools.reduce``/
    ``math.*``/``dataclasses.*`` over a tracer).
    """

    name = "R1"
    slug = "host-sync"
    summary = "implicit host sync on a traced value inside a jit region"

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.traced.events:
            if ev.kind == "host-sync" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class DtypeDriftRule(Rule):
    """R2: no float64 in jax-importing ranking modules.

    The device path is f32/bf16 end to end (PageRankConfig/
    RuntimeConfig.dtype); a ``np.float64`` scalar or ``dtype="float64"``
    leaking into a jnp expression upcasts the whole chain on CPU (and
    silently truncates on TPU), defeating the bf16 MXU path and breaking
    cross-backend score parity. Host-side float64 oracles
    (sparse_oracle, numpy_ref) import numpy only and are out of scope by
    construction.
    """

    name = "R2"
    slug = "dtype-drift"
    summary = "float64 dtype in a jax-importing ranking module"

    _BAD_ATTRS = {"float64", "double", "float_"}

    def check(self, module: ModuleInfo, project: Project):
        if not module.imports_jax:
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute) and node.attr in self._BAD_ATTRS:
                dotted = module.dotted(node)
                if dotted and dotted.split(".")[0] in ("numpy", "jax"):
                    yield _v(
                        module,
                        node,
                        self.name,
                        f"`{dotted}` in a device-path module — the "
                        "ranking pipeline is f32/bf16 (RuntimeConfig."
                        "dtype); a float64 scalar upcasts every jnp "
                        "expression it touches",
                    )
            elif (
                isinstance(node, ast.Constant)
                and node.value == "float64"
            ):
                yield _v(
                    module,
                    node,
                    self.name,
                    '"float64" dtype string in a device-path module — '
                    "the ranking pipeline is f32/bf16",
                )


@register
class RetraceRule(Rule):
    """R3: recompilation hazards.

    (a) ``jax.jit``/``pjit`` built inside a function body creates a new
    cache per call — every invocation retraces and recompiles. Allowed
    only in the module-cache idiom (the enclosing function declares a
    ``global`` it assigns the wrapper to, or is ``functools.lru_cache``/
    ``functools.cache``-decorated).
    (b) a Python ``if``/``while`` on a traced value concretizes the
    tracer (error under jit; a retrace per distinct value with plain
    tracing) — from the same taint analysis as R1.
    (c) a list/dict/set literal passed in a static position of a known
    jit wrapper is unhashable and fails cache lookup.
    """

    name = "R3"
    slug = "retrace"
    summary = "jit recompilation hazard"

    def check(self, module: ModuleInfo, project: Project):
        yield from self._jit_in_body(module, project)
        for ev in project.traced.events:
            if ev.kind == "tracer-branch" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)
        yield from self._unhashable_static(module, project)

    def _jit_in_body(self, module: ModuleInfo, project: Project):
        class _Walker(ast.NodeVisitor):
            def __init__(self):
                self.stack: List[ast.FunctionDef] = []
                self.found = []

            def visit_FunctionDef(self, node):
                self.stack.append(node)
                self.generic_visit(node)
                self.stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Call(self, node):
                if self.stack:
                    dotted = module.dotted(node.func)
                    if dotted in (
                        "jax.jit",
                        "jax.pjit",
                        "jax.experimental.pjit.pjit",
                    ):
                        self.found.append((node, self.stack[-1]))
                self.generic_visit(node)

        w = _Walker()
        w.visit(module.tree)
        for call, fn in w.found:
            if any(
                isinstance(s, ast.Global)
                for s in ast.walk(fn)
            ):
                continue  # module-cache idiom (global singleton)
            if any(
                (module.dotted(d) or "").startswith("functools.")
                and (module.dotted(d) or "").endswith(("cache", "lru_cache"))
                or isinstance(d, ast.Call)
                and (module.dotted(d.func) or "").startswith("functools.")
                for d in fn.decorator_list
            ):
                continue  # cached factory
            yield _v(
                module,
                call,
                self.name,
                f"jax.jit built inside `{fn.name}` without a module "
                "cache — a fresh wrapper per call retraces and "
                "recompiles every invocation; hoist the jit to module "
                "level or cache it behind a `global` singleton",
            )

    def _unhashable_static(self, module: ModuleInfo, project: Project):
        analysis = project.traced
        wrappers = {
            (id(w.module), w.bound_name): w
            for w in analysis.wrappers
            if w.bound_name and (w.static_argnums or w.static_argnames)
        }
        if not wrappers:
            return
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
            ):
                continue
            w = wrappers.get((id(module), node.func.id))
            if w is None:
                continue
            names = ()
            if w.target is not None:
                names = w.target.params
            for i, arg in enumerate(node.args):
                static = i in w.static_argnums or (
                    i < len(names) and names[i] in w.static_argnames
                )
                if static and isinstance(
                    arg, (ast.List, ast.Dict, ast.Set)
                ):
                    yield _v(
                        module,
                        arg,
                        self.name,
                        f"unhashable {type(arg).__name__.lower()} literal "
                        f"in static position {i} of `{node.func.id}` — "
                        "static args are jit cache keys and must be "
                        "hashable; pass a tuple (or mark the arg "
                        "non-static)",
                    )


@register
class DonationRule(Rule):
    """R4: no read of a buffer after it was donated.

    ``donate_argnums`` hands the argument's device buffer to XLA for
    reuse; the Python array object still exists but its buffer is
    deleted once the computation consumes it — a later read raises
    "Array has been deleted" (or, worse, returns stale data on runtimes
    without donation checks). Flags loads of a name after it was passed
    in a donated position of a known jit wrapper in the same function.
    """

    name = "R4"
    slug = "donation"
    summary = "buffer read after donation"

    def check(self, module: ModuleInfo, project: Project):
        analysis = project.traced
        donating = {
            (id(w.module), w.bound_name): w
            for w in analysis.wrappers
            if w.bound_name and w.donate_argnums
        }
        if not donating:
            return
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            donated = {}  # var name -> donation call line
            for stmt in fn.body:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load
                    ):
                        if (
                            node.id in donated
                            and node.lineno > donated[node.id]
                        ):
                            yield _v(
                                module,
                                node,
                                self.name,
                                f"`{node.id}` read after being donated "
                                f"(donate_argnums call at line "
                                f"{donated[node.id]}) — the buffer is "
                                "handed to XLA and deleted; reorder the "
                                "read before the call or drop the "
                                "donation",
                            )
                            donated.pop(node.id)
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name
                    ):
                        w = donating.get((id(module), node.func.id))
                        if w is None:
                            continue
                        for pos in w.donate_argnums:
                            if pos < len(node.args) and isinstance(
                                node.args[pos], ast.Name
                            ):
                                donated[node.args[pos].id] = node.lineno
        return


@register
class ContractRule(Rule):
    """R5: public rank/spectrum entry points declare @contract specs.

    Module-level public functions named ``rank_window*``/
    ``rank_windows*`` (and ``spectrum_scores``) are the seams every
    backend, batch path and test drives — their shape/dtype signatures
    are the repo's data contract and must be machine-readable
    (analysis.contracts.contract), which also arms the trace-time
    checker behind RuntimeConfig.validate_numerics.
    """

    name = "R5"
    slug = "contract"
    summary = "public rank/spectrum entry point without @contract"

    _NAMES = ("rank_window", "rank_windows")

    def check(self, module: ModuleInfo, project: Project):
        for node in module.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name.startswith("_"):
                continue
            if not (
                node.name.startswith(self._NAMES)
                or node.name == "spectrum_scores"
            ):
                continue
            if self._has_contract(module, node):
                continue
            yield _v(
                module,
                node,
                self.name,
                f"public entry point `{node.name}` has no @contract "
                "shape/dtype annotation (analysis.contracts) — the "
                "rank/spectrum seams carry machine-checked signatures",
            )

    @staticmethod
    def _has_contract(module: ModuleInfo, node: ast.FunctionDef) -> bool:
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(target, ast.Name) and target.id == "contract":
                return True
            if (
                isinstance(target, ast.Attribute)
                and target.attr == "contract"
            ):
                return True
        return False


@register
class DevicePutRule(Rule):
    """R6: no ``jax.device_put`` inside traced code.

    Staging belongs at the dispatch boundary (blob.stage_rank_window /
    the per-leaf device_put right before a jitted call). Inside a jit
    call graph the call is not a transfer at all — it traces to a
    placement hint that can silently pin the operand's sharding against
    the surrounding program's layout — and on the op-by-op path it
    serializes dispatch with one blocking RPC per call. Same traced-
    call-graph analysis as R1; host-side staging helpers that are never
    reached from a jit root are exempt by construction.
    """

    name = "R6"
    slug = "device-put-traced"
    summary = "jax.device_put inside a traced region"

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.traced.events:
            if ev.kind == "device-put" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class TelemetryTaintRule(Rule):
    """R7: no traced arrays flowing into the telemetry layer.

    Metric samples and labels (``Counter.inc``/``Gauge.set``/
    ``Histogram.observe`` and the ``obs.metrics.record_*`` helpers),
    journal fields (``RunJournal.emit``) and span attributes
    (``SpanTracer.span``/``record_span``) are HOST values — the sink
    immediately calls ``float()``/``str()``/``json.dumps`` on them. A
    traced value passed there is the same implicit host sync R1 exists
    to catch, just laundered through the telemetry layer (and under
    jit it crashes at trace time). Record after the fetch, outside the
    jit boundary. The jax ``x.at[i].set(v)`` indexed-update idiom is
    exempt despite sharing the ``set`` method name.
    """

    name = "R7"
    slug = "telemetry-taint"
    summary = (
        "traced value in a span attribute, metric sample/label, or "
        "journal field"
    )

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.traced.events:
            if ev.kind == "telemetry-taint" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


def iter_rules() -> Iterable[Rule]:
    from .core import RULES

    return RULES.values()
