"""The mrlint rule set (R1-R16). See analysis/__init__ for the catalog.

Each rule is intentionally heuristic — it encodes THIS repo's TPU
invariants, not general Python semantics — and every finding can be
suppressed in place with ``# mrlint: disable=RN(reason)`` (a reason is
mandatory; bare disables are reported as R0 by the framework).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import ModuleInfo, Project, Rule, Violation, register


def _v(module: ModuleInfo, node, rule: str, message: str) -> Violation:
    return Violation(
        path=module.rel,
        line=getattr(node, "lineno", getattr(node, "line", 0)),
        col=getattr(node, "col_offset", getattr(node, "col", 0)),
        rule=rule,
        message=message,
    )


@register
class HostSyncRule(Rule):
    """R1: no implicit host sync on traced values inside jit call graphs.

    ``float()``/``int()``/``bool()``/``.item()``/``np.asarray``/
    ``jax.device_get`` on a value reachable from a non-static parameter
    of a jitted function either crashes at trace time
    (TracerArrayConversionError) or — in op-by-op execution — silently
    serializes dispatch with a device->host round trip per call (~90 ms
    on tunneled-TPU runtimes). The traced-call-graph analysis in
    analysis/traced.py decides what is traced; ``.shape``/``.dtype``
    reads are static and exempt. Laundering is caught too: bound-method
    aliases (``f = x.item; f()``), ``getattr(x, "item")()``, and taint
    carried through nominally-static wrappers (``functools.reduce``/
    ``math.*``/``dataclasses.*`` over a tracer).
    """

    name = "R1"
    slug = "host-sync"
    summary = "implicit host sync on a traced value inside a jit region"

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.traced.events:
            if ev.kind == "host-sync" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class DtypeDriftRule(Rule):
    """R2: no float64 in jax-importing ranking modules.

    The device path is f32/bf16 end to end (PageRankConfig/
    RuntimeConfig.dtype); a ``np.float64`` scalar or ``dtype="float64"``
    leaking into a jnp expression upcasts the whole chain on CPU (and
    silently truncates on TPU), defeating the bf16 MXU path and breaking
    cross-backend score parity. Host-side float64 oracles
    (sparse_oracle, numpy_ref) import numpy only and are out of scope by
    construction.
    """

    name = "R2"
    slug = "dtype-drift"
    summary = "float64 dtype in a jax-importing ranking module"

    _BAD_ATTRS = {"float64", "double", "float_"}

    def check(self, module: ModuleInfo, project: Project):
        if not module.imports_jax:
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute) and node.attr in self._BAD_ATTRS:
                dotted = module.dotted(node)
                if dotted and dotted.split(".")[0] in ("numpy", "jax"):
                    yield _v(
                        module,
                        node,
                        self.name,
                        f"`{dotted}` in a device-path module — the "
                        "ranking pipeline is f32/bf16 (RuntimeConfig."
                        "dtype); a float64 scalar upcasts every jnp "
                        "expression it touches",
                    )
            elif (
                isinstance(node, ast.Constant)
                and node.value == "float64"
            ):
                yield _v(
                    module,
                    node,
                    self.name,
                    '"float64" dtype string in a device-path module — '
                    "the ranking pipeline is f32/bf16",
                )


@register
class RetraceRule(Rule):
    """R3: recompilation hazards.

    (a) ``jax.jit``/``pjit`` built inside a function body creates a new
    cache per call — every invocation retraces and recompiles. Allowed
    only in the module-cache idiom (the enclosing function declares a
    ``global`` it assigns the wrapper to, or is ``functools.lru_cache``/
    ``functools.cache``-decorated).
    (b) a Python ``if``/``while`` on a traced value concretizes the
    tracer (error under jit; a retrace per distinct value with plain
    tracing) — from the same taint analysis as R1.
    (c) a list/dict/set literal passed in a static position of a known
    jit wrapper is unhashable and fails cache lookup.
    (d) value->shape dataflow: a host measurement (``len()``/``int()``/
    ``float()`` of live data) flowing into a STATIC argument of a known
    jit wrapper, or into the shape of an array the wrapper is called
    with, keys the jit cache on the data itself — under
    ``pad_policy="exact"`` every distinct window retraces. Routing the
    measurement through a bucketing helper (``pad*``/``bucket*``/
    ``round*``/``pow2*``/``align*``) makes it shape-stable and breaks
    the flow.
    """

    name = "R3"
    slug = "retrace"
    summary = "jit recompilation hazard"

    def check(self, module: ModuleInfo, project: Project):
        yield from self._jit_in_body(module, project)
        for ev in project.traced.events:
            if ev.kind == "tracer-branch" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)
        yield from self._unhashable_static(module, project)
        yield from self._value_shape(module, project)

    def _jit_in_body(self, module: ModuleInfo, project: Project):
        class _Walker(ast.NodeVisitor):
            def __init__(self):
                self.stack: List[ast.FunctionDef] = []
                self.found = []

            def visit_FunctionDef(self, node):
                self.stack.append(node)
                self.generic_visit(node)
                self.stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Call(self, node):
                if self.stack:
                    dotted = module.dotted(node.func)
                    if dotted in (
                        "jax.jit",
                        "jax.pjit",
                        "jax.experimental.pjit.pjit",
                    ):
                        self.found.append((node, self.stack[-1]))
                self.generic_visit(node)

        w = _Walker()
        w.visit(module.tree)
        for call, fn in w.found:
            if any(
                isinstance(s, ast.Global)
                for s in ast.walk(fn)
            ):
                continue  # module-cache idiom (global singleton)
            if any(
                (module.dotted(d) or "").startswith("functools.")
                and (module.dotted(d) or "").endswith(("cache", "lru_cache"))
                or isinstance(d, ast.Call)
                and (module.dotted(d.func) or "").startswith("functools.")
                for d in fn.decorator_list
            ):
                continue  # cached factory
            yield _v(
                module,
                call,
                self.name,
                f"jax.jit built inside `{fn.name}` without a module "
                "cache — a fresh wrapper per call retraces and "
                "recompiles every invocation; hoist the jit to module "
                "level or cache it behind a `global` singleton",
            )

    def _unhashable_static(self, module: ModuleInfo, project: Project):
        analysis = project.traced
        wrappers = {
            (id(w.module), w.bound_name): w
            for w in analysis.wrappers
            if w.bound_name and (w.static_argnums or w.static_argnames)
        }
        if not wrappers:
            return
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
            ):
                continue
            w = wrappers.get((id(module), node.func.id))
            if w is None:
                continue
            names = ()
            if w.target is not None:
                names = w.target.params
            for i, arg in enumerate(node.args):
                static = i in w.static_argnums or (
                    i < len(names) and names[i] in w.static_argnames
                )
                if static and isinstance(
                    arg, (ast.List, ast.Dict, ast.Set)
                ):
                    yield _v(
                        module,
                        arg,
                        self.name,
                        f"unhashable {type(arg).__name__.lower()} literal "
                        f"in static position {i} of `{node.func.id}` — "
                        "static args are jit cache keys and must be "
                        "hashable; pass a tuple (or mark the arg "
                        "non-static)",
                    )

    # Value->shape dataflow (the pad_policy="exact" retrace gap): local
    # measurements of live data and the array constructors they shape.
    _MEASURES = {"len", "int", "float"}
    _ARRAY_CTORS = {"zeros", "ones", "empty", "full", "arange"}
    _BUCKET_HINTS = ("pad", "bucket", "pow2", "round", "align", "next_")

    def _value_shape(self, module: ModuleInfo, project: Project):
        analysis = project.traced
        wrappers = {
            (id(w.module), w.bound_name): w
            for w in analysis.wrappers
            if w.bound_name
        }
        if not wrappers:
            return
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            measures: set = set()       # locals holding a raw measurement
            exact_shaped: set = set()   # locals whose SHAPE is a measurement

            def is_measure(expr) -> bool:
                if isinstance(expr, ast.Name):
                    return expr.id in measures
                if isinstance(expr, ast.Call):
                    name = None
                    if isinstance(expr.func, ast.Name):
                        name = expr.func.id
                    elif isinstance(expr.func, ast.Attribute):
                        name = expr.func.attr
                    if name and any(
                        h in name.lower() for h in self._BUCKET_HINTS
                    ):
                        return False  # bucketed -> shape-stable
                    if (
                        name in self._MEASURES
                        and expr.args
                        and not isinstance(expr.args[0], ast.Constant)
                    ):
                        return True
                    return any(is_measure(a) for a in expr.args) or any(
                        is_measure(k.value) for k in expr.keywords
                    )
                if isinstance(expr, ast.BinOp):
                    return is_measure(expr.left) or is_measure(expr.right)
                if isinstance(expr, ast.UnaryOp):
                    return is_measure(expr.operand)
                if isinstance(expr, (ast.Tuple, ast.List)):
                    return any(is_measure(e) for e in expr.elts)
                return False

            for stmt in ast.walk(fn):
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                    tgt = stmt.targets[0]
                    if isinstance(tgt, ast.Name):
                        if is_measure(stmt.value):
                            measures.add(tgt.id)
                        else:
                            measures.discard(tgt.id)
                        shaped = (
                            isinstance(stmt.value, ast.Call)
                            and isinstance(stmt.value.func, ast.Attribute)
                            and stmt.value.func.attr in self._ARRAY_CTORS
                            and (
                                any(
                                    is_measure(a) for a in stmt.value.args
                                )
                                or any(
                                    is_measure(k.value)
                                    for k in stmt.value.keywords
                                )
                            )
                        )
                        if shaped:
                            exact_shaped.add(tgt.id)
                        else:
                            exact_shaped.discard(tgt.id)
            for node in ast.walk(fn):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                ):
                    continue
                w = wrappers.get((id(module), node.func.id))
                if w is None:
                    continue
                params = w.target.params if w.target is not None else ()
                for i, arg in enumerate(node.args):
                    static = i in w.static_argnums or (
                        i < len(params)
                        and params[i] in w.static_argnames
                    )
                    if static and is_measure(arg):
                        yield _v(
                            module,
                            arg,
                            self.name,
                            f"value-derived host scalar in static "
                            f"position {i} of jit wrapper "
                            f"`{node.func.id}` — the jit cache keys on "
                            "the data itself (one retrace per distinct "
                            "window under pad_policy=\"exact\"); bucket "
                            "the measurement (pad_extent/pow2) before "
                            "it reaches a static argument",
                        )
                    elif (
                        isinstance(arg, ast.Name)
                        and arg.id in exact_shaped
                    ):
                        yield _v(
                            module,
                            arg,
                            self.name,
                            f"`{arg.id}` is shaped by a raw host "
                            f"measurement and passed to jit wrapper "
                            f"`{node.func.id}` — its SHAPE keys the jit "
                            "cache, so every distinct window retraces "
                            "(the pad_policy=\"exact\" hazard); pad the "
                            "extent through a bucketing helper "
                            "(pad*/pow2*/round*) before building the "
                            "array",
                        )


@register
class DonationRule(Rule):
    """R4: no read of a buffer after it was donated.

    ``donate_argnums`` hands the argument's device buffer to XLA for
    reuse; the Python array object still exists but its buffer is
    deleted once the computation consumes it — a later read raises
    "Array has been deleted" (or, worse, returns stale data on runtimes
    without donation checks). Flags loads of a name after it was passed
    in a donated position of a known jit wrapper in the same function.
    """

    name = "R4"
    slug = "donation"
    summary = "buffer read after donation"

    def check(self, module: ModuleInfo, project: Project):
        analysis = project.traced
        donating = {
            (id(w.module), w.bound_name): w
            for w in analysis.wrappers
            if w.bound_name and w.donate_argnums
        }
        if not donating:
            return
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            donated = {}  # var name -> donation call line
            for stmt in fn.body:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Load
                    ):
                        if (
                            node.id in donated
                            and node.lineno > donated[node.id]
                        ):
                            yield _v(
                                module,
                                node,
                                self.name,
                                f"`{node.id}` read after being donated "
                                f"(donate_argnums call at line "
                                f"{donated[node.id]}) — the buffer is "
                                "handed to XLA and deleted; reorder the "
                                "read before the call or drop the "
                                "donation",
                            )
                            donated.pop(node.id)
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name
                    ):
                        w = donating.get((id(module), node.func.id))
                        if w is None:
                            continue
                        for pos in w.donate_argnums:
                            if pos < len(node.args) and isinstance(
                                node.args[pos], ast.Name
                            ):
                                donated[node.args[pos].id] = node.lineno
        return


@register
class ContractRule(Rule):
    """R5: public rank/spectrum entry points declare @contract specs.

    Module-level public functions named ``rank_window*``/
    ``rank_windows*`` (and ``spectrum_scores``) are the seams every
    backend, batch path and test drives — their shape/dtype signatures
    are the repo's data contract and must be machine-readable
    (analysis.contracts.contract), which also arms the trace-time
    checker behind RuntimeConfig.validate_numerics.
    """

    name = "R5"
    slug = "contract"
    summary = "public rank/spectrum entry point without @contract"

    _NAMES = ("rank_window", "rank_windows")

    def check(self, module: ModuleInfo, project: Project):
        for node in module.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name.startswith("_"):
                continue
            if not (
                node.name.startswith(self._NAMES)
                or node.name == "spectrum_scores"
            ):
                continue
            if self._has_contract(module, node):
                continue
            yield _v(
                module,
                node,
                self.name,
                f"public entry point `{node.name}` has no @contract "
                "shape/dtype annotation (analysis.contracts) — the "
                "rank/spectrum seams carry machine-checked signatures",
            )

    @staticmethod
    def _has_contract(module: ModuleInfo, node: ast.FunctionDef) -> bool:
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(target, ast.Name) and target.id == "contract":
                return True
            if (
                isinstance(target, ast.Attribute)
                and target.attr == "contract"
            ):
                return True
        return False


@register
class DevicePutRule(Rule):
    """R6: no ``jax.device_put`` inside traced code.

    Staging belongs at the dispatch boundary (blob.stage_rank_window /
    the per-leaf device_put right before a jitted call). Inside a jit
    call graph the call is not a transfer at all — it traces to a
    placement hint that can silently pin the operand's sharding against
    the surrounding program's layout — and on the op-by-op path it
    serializes dispatch with one blocking RPC per call. Same traced-
    call-graph analysis as R1; host-side staging helpers that are never
    reached from a jit root are exempt by construction.
    """

    name = "R6"
    slug = "device-put-traced"
    summary = "jax.device_put inside a traced region"

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.traced.events:
            if ev.kind == "device-put" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class TelemetryTaintRule(Rule):
    """R7: no traced arrays flowing into the telemetry layer.

    Metric samples and labels (``Counter.inc``/``Gauge.set``/
    ``Histogram.observe`` and the ``obs.metrics.record_*`` helpers),
    journal fields (``RunJournal.emit``) and span attributes
    (``SpanTracer.span``/``record_span``) are HOST values — the sink
    immediately calls ``float()``/``str()``/``json.dumps`` on them. A
    traced value passed there is the same implicit host sync R1 exists
    to catch, just laundered through the telemetry layer (and under
    jit it crashes at trace time). Record after the fetch, outside the
    jit boundary. The jax ``x.at[i].set(v)`` indexed-update idiom is
    exempt despite sharing the ``set`` method name.
    """

    name = "R7"
    slug = "telemetry-taint"
    summary = (
        "traced value in a span attribute, metric sample/label, or "
        "journal field"
    )

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.traced.events:
            if ev.kind == "telemetry-taint" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class DeviceOwnershipRule(Rule):
    """R8: device touches stay on the device-owner thread.

    The pipeline is a three-thread system (serve scheduler, build
    worker pool, stream engine) sharing one device; jax dispatch is
    only program-ordered when a single thread issues it. The cross-
    thread analysis (analysis.threads.ThreadAnalysis) classifies every
    function by executing thread — ``threading.Thread`` subclasses and
    targets, ``pool.submit``/``executor.submit`` callables (through
    ``functools.partial`` and bound methods), ``async def`` event-loop
    handlers, incident-sink callbacks — and fires on any jax-touching
    call (jnp/lax/device_put/device_get, a known jit wrapper, or a
    staging seam like ``stage_rank_window``/``stage_sharded``/
    ``rank_batch``) reachable from a non-owner thread class. A thread
    root becomes an owner by calling ``claim_device_owner()``
    (utils.guards — the runtime mrsan twin asserts the same model), and
    an executor's workers by ``initializer=authorize_device_thread``.
    """

    name = "R8"
    slug = "device-ownership"
    summary = "jax touch reachable from a non-owner thread"

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.threads.events:
            if ev.kind == "cross-thread-device" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class CollectiveOrderRule(Rule):
    """R9: uniform collective schedules inside shard_map-traced code.

    Under SPMD every shard must issue the same psum/all_gather/ppermute
    sequence in the same order — a shard that skips one deadlocks the
    mesh (or silently corrupts the combine under single-controller
    emulation). Fires when, inside a ``shard_map``-traced call graph, a
    collective is issued under data-dependent control flow (a Python
    ``if``/``while``/``for`` on a traced value), or a call path only
    reaches a collective-issuing kernel under such a branch (two call
    paths to the same kernel with divergent collective sequences).
    Trace-static predicates (config flags, kernel names) are exempt:
    every shard traces the same branch. The runtime half of this
    contract is mrsan's per-shard collective-schedule recording
    (analysis.mrsan) on the CPU mesh.
    """

    name = "R9"
    slug = "collective-order"
    summary = (
        "data-dependent collective schedule inside shard_map-traced code"
    )

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.threads.events:
            if (
                ev.kind in ("collective-data-dep", "collective-divergent-path")
                and ev.module is module
            ):
                yield _v(module, ev, self.name, ev.message)


@register
class SharedStateRaceRule(Rule):
    """R10: cross-thread shared state carries a common lock.

    The Eraser lockset discipline, statically: an attribute of a
    lock-owning class (or a global of a lock-owning module) written
    outside ``__init__`` and accessed from two distinct thread classes
    must have at least one lock held at EVERY access — the intersection
    of the statically-held locksets must be non-empty. The thread
    classes come from the same interprocedural classifier R8 uses
    (analysis.threads); the locksets from the lock model
    (analysis.locks). Safe seams are recognized, not flagged:
    ``queue.Queue``/``threading.Event``/``deque`` handoff attributes,
    single-assignment-then-publish (all writes in ``__init__``), and
    writes wrapped in ``utils.guards.published(...)`` — the explicit
    intentional-handoff marker that doubles as documentation. The
    runtime twin is mrsan's lockset checker
    (``utils.guards.note_shared_access``) on registered objects.
    """

    name = "R10"
    slug = "shared-state-race"
    summary = (
        "cross-thread shared state accessed with no common lock"
    )

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.locks.events:
            if ev.kind == "shared-state-race" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class LockOrderRule(Rule):
    """R11: the lock-acquisition-order graph stays acyclic.

    Edge A→B whenever B is acquired while A is held — directly
    (``with a: with b:``) or through a resolved callee (``with a:
    self.grab_b()``). Any cycle (including re-acquiring a held
    non-reentrant lock) is a potential deadlock: two threads taking
    the locks in opposite orders block each other forever. The
    DESIGN.md lock catalog assigns every production lock an ordering
    rank; the runtime twin is mrsan's lock-order watchdog
    (utils.guards.TrackedLock), which asserts the OBSERVED acquisition
    DAG on every armed acquire.
    """

    name = "R11"
    slug = "lock-order-cycle"
    summary = "cycle in the static lock-acquisition-order graph"

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.locks.events:
            if ev.kind == "lock-order-cycle" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class BlockingUnderLockRule(Rule):
    """R12: no blocking call while a lock is statically held.

    The generalization of the webhook-hang bug PR 8 fixed once by
    hand: an HTTP/webhook POST, ``time.sleep``, ``fsync``/atomic
    write, subprocess wait, pool ``Future.result()``/``join()``, or a
    device dispatch/fetch seam reached while a lock is held turns
    that lock into a convoy — every thread that contends waits out
    the I/O (heartbeats stall, lease reapers mark live hosts dead,
    the engine thread misses its window deadline). Acquire-via-callee
    counts: a function whose resolved call graph reaches a blocking
    call fires at the call site made under the lock.
    ``Condition.wait`` on the HELD condition is exempt — wait
    releases it by contract. Snapshot state under the lock, release
    it, then block.
    """

    name = "R12"
    slug = "blocking-under-lock"
    summary = "blocking call reached while a lock is held"

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.locks.events:
            if ev.kind == "blocking-under-lock" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class RecompileBombRule(Rule):
    """R13: no ⊤-provenance value in a static argument of a jit wrapper.

    The interprocedural upgrade of R3(d): the shape/dtype provenance
    analysis (analysis.shapes) tracks every value on the finite lattice
    ⊥ < const < bucket < ⊤ through the whole project call graph — a
    host measurement of live data (``len()``/``int()`` of a span table,
    a vocab size) that reaches a static argument of a known jit wrapper
    *through any chain of helper calls* keys the compile cache on the
    data itself: one recompile per distinct value, the recompile bomb.
    Routing the measurement through the bucket registry
    (``graph.structures.pad_to`` or any ``pad*/bucket*/pow2*/round*/
    align*`` helper) lowers it to BUCKET — a finite key family — and
    the rule stays silent. Runtime mirror: the mrsan compile witness
    (analysis.mrsan) observes every dispatched compile key and fails
    on any key outside the predicted bucket space.
    """

    name = "R13"
    slug = "recompile-bomb"
    summary = (
        "⊤-provenance (raw live measurement) reaches a static jit "
        "argument interprocedurally"
    )

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.shapes.events:
            if ev.kind == "recompile-bomb" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class PrecisionLadderRule(Rule):
    """R14: no mixed precision-ladder dtypes at one fused boundary.

    The device path runs a three-level ladder — f32 / bf16 / scaled
    int8 (PageRankConfig.kind_precision) — and a fused program fed two
    different ladder levels without an explicit cast leaves the upcast
    placement to XLA: it lands where the values meet inside the fusion,
    not where the kernel contract says, so accumulation precision
    drifts between call sites that should be bit-identical. The shape/
    dtype analysis joins dtype sets along the same interprocedural flow
    as R13; an argument expression that is itself an ``astype(...)`` /
    ``asarray(dtype=...)`` cast is the sanctioned boundary cast and
    exempts that argument.
    """

    name = "R14"
    slug = "precision-ladder-break"
    summary = (
        "mixed dtype-ladder levels flow into one fused program "
        "boundary without an explicit cast"
    )

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.shapes.events:
            if ev.kind == "ladder-break" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class PadBucketEscapeRule(Rule):
    """R15: arrays reaching DispatchRouter dispatch are bucket-shaped.

    Every array entering a dispatch seam (``DispatchRouter.rank_batch``,
    ``stage_rank_window``/``stage_rank_windows_batched``/
    ``stage_windows_batched``/``stage_sharded``) keys the compile cache
    with its shape. The window-graph builders (``build_window_graph*``/
    ``prepare_window_graph``) draw every extent from the pad-bucket
    registry by construction; an ad-hoc array shaped by a raw host
    measurement (⊤ shape provenance) escapes the bucket family and
    compiles one program per distinct window. Runtime mirror: the
    compile witness checks every OBSERVED extent against
    ``analysis.shapes.is_bucketed_extent``.
    """

    name = "R15"
    slug = "pad-bucket-escape"
    summary = (
        "array whose shape is not drawn from the pad-bucket registry "
        "reaches a dispatch seam"
    )

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.shapes.events:
            if ev.kind == "bucket-escape" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


@register
class WarmupCoverageRule(Rule):
    """R16: production compile keys are warmed before they are served.

    For each jit wrapper whose call sites carry statically enumerable
    static-argument sets (const provenance with small value sets), the
    keys dispatched from production sites must be a subset of the keys
    dispatched from the warmup path (functions reachable from a
    ``warm*`` root — dispatch/warmup.py's seam): a key served before it
    is warmed pays the first-request compile the warmup manifest exists
    to eliminate. Sites whose key sets are unenumerable (⊤ or widened
    const) are out of static scope by design — the runtime compile
    witness (analysis.mrsan) owns them, cross-checking every observed
    key against the static prediction plus the warmup manifest.
    """

    name = "R16"
    slug = "warmup-coverage"
    summary = (
        "statically enumerated compile keys dispatched in production "
        "but absent from the warmup path"
    )

    def check(self, module: ModuleInfo, project: Project):
        for ev in project.shapes.events:
            if ev.kind == "warmup-gap" and ev.module is module:
                yield _v(module, ev, self.name, ev.message)


def iter_rules() -> Iterable[Rule]:
    from .core import RULES

    return RULES.values()
