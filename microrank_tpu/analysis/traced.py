"""Traced-call-graph analysis: which functions run under jax tracing,
and which of their values are traced (tainted) vs trace-static.

Roots are functions wrapped by ``jax.jit``/``pjit`` (as a call, a
decorator, or through ``functools.partial``/``checkify.checkify``).
Parameters in ``static_argnums``/``static_argnames`` positions are
static; everything else entering a root is a traced value. Tracedness
propagates through the project call graph: a callee's parameter becomes
traced when any traced caller passes it a traced-rooted expression
(fixpoint over the module set being linted).

Within a traced function a simple forward taint walk tracks locals:

* attribute reads of ``shape``/``dtype``/``ndim``/``size`` BREAK taint
  (static under tracing — branching or ``int()`` on them is fine);
* ``len``/``isinstance``/``type``/``range``/``min``/``max`` of static
  operands stay static; any expression over a tainted operand is
  tainted — including ``functools``/``math``/``dataclasses`` calls,
  which are static only over static operands (a ``functools.reduce``
  over a tracer must not launder its taint);
* a local bound to a SYNC METHOD of a tainted value (``f = x.item``,
  ``f = getattr(x, "tolist")``) is a sync thunk: calling it anywhere in
  the function is the laundered host sync and fires R1;
* nested ``def``/``lambda`` parameters are treated as tainted when the
  enclosing function is traced (they are the loop/vmap bodies of the
  kernels — their arguments are device values by construction).

The walk emits the events rules R1 (host sync) and R3 (Python branch on
a tracer) report, and records project-internal call edges with per-
parameter taint for the propagation above. R4 uses the jit-wrapper
index (``donate_argnums`` positions) collected during root discovery.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_JIT_NAMES = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}
_UNWRAP_NAMES = {
    "jax.experimental.checkify.checkify",
    "checkify.checkify",
}
# Attribute reads that are static under tracing (break taint).
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding"}
# Builtins whose result is host-static regardless of inputs; calling
# them ON a tainted value is itself the R1 event (flagged separately).
_SCALARIZERS = {"float", "int", "bool", "complex"}
_STATIC_BUILTINS = {"len", "isinstance", "type", "range", "hasattr"}
# Method names that force a host sync on a traced value.
_SYNC_METHODS = {"item", "tolist", "numpy", "block_until_ready"}
_SYNC_EXTERNALS = {"jax.device_get"}
# Telemetry-sink method names (R7): metric samples (Counter.inc /
# Gauge.set|inc|dec / Histogram.observe), journal events (.emit), span
# attributes (.span / .record_span). A traced array flowing into any of
# them is a host sync laundered through the telemetry layer — the
# metric/journal/span code calls float()/json.dumps on it. The jax
# ``x.at[i].set(v)`` indexed-update idiom shares the ``set`` name and
# is explicitly exempted.
_TELEMETRY_METHODS = {
    "observe", "inc", "dec", "set", "emit", "span", "record_span",
}


@dataclass
class FuncDef:
    module: object               # core.ModuleInfo
    node: ast.FunctionDef
    name: str

    @property
    def params(self) -> List[str]:
        a = self.node.args
        return (
            [p.arg for p in a.posonlyargs]
            + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs]
        )


@dataclass
class JitWrapper:
    """One jax.jit wrap site: the wrapped project function (if resolved),
    static/donated positions, and the local name the wrapper is bound to
    (assignment target or decorated function name)."""

    module: object
    bound_name: Optional[str]
    target: Optional[FuncDef]
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    line: int = 0


@dataclass
class Event:
    # "host-sync" | "tracer-branch" | "device-put" | "telemetry-taint"
    kind: str
    module: object
    line: int
    col: int
    message: str


def _int_tuple(node) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _str_tuple(node) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


class TracedAnalysis:
    def __init__(self, project):
        self.project = project
        self.defs: Dict[Tuple[int, str], FuncDef] = {}
        self.wrappers: List[JitWrapper] = []
        self.traced: Dict[int, Set[str]] = {}   # id(FuncDef) -> tainted params
        self._by_id: Dict[int, FuncDef] = {}
        self.events: List[Event] = []
        self._index_defs()
        self._find_roots()
        self._propagate()
        self._collect_events()

    # ---------------------------------------------------------- indexing

    def _index_defs(self) -> None:
        for mod in self.project.modules:
            for node in mod.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fd = FuncDef(module=mod, node=node, name=node.name)
                    self.defs[(id(mod), node.name)] = fd

    def resolve(self, module, name: str) -> Optional[FuncDef]:
        """Resolve a bare name used in ``module`` to a project function:
        a module-level def, or a relative-imported one."""
        fd = self.defs.get((id(module), name))
        if fd is not None:
            return fd
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.level > 0:
                for a in node.names:
                    if (a.asname or a.name) == name:
                        target_mod = self.project.resolve_relative(
                            module, node
                        )
                        if target_mod is not None:
                            return self.defs.get((id(target_mod), a.name))
        return None

    # ------------------------------------------------------------- roots

    def _jit_target(self, module, call: ast.Call):
        """If ``call`` is jax.jit(...)/pjit(...), return the wrapped
        FuncDef (unwrapping checkify) or None-but-jit. Returns
        (is_jit, target)."""
        dotted = module.dotted(call.func)
        if dotted not in _JIT_NAMES:
            return False, None
        if not call.args:
            return True, None
        inner = call.args[0]
        if isinstance(inner, ast.Call):
            inner_dotted = module.dotted(inner.func)
            if (
                inner_dotted in _UNWRAP_NAMES
                or (inner_dotted or "").endswith(".checkify")
            ) and inner.args:
                inner = inner.args[0]
        if isinstance(inner, ast.Name):
            return True, self.resolve(module, inner.id)
        return True, None

    def _wrapper_from_call(
        self, module, call: ast.Call, bound: Optional[str]
    ) -> Optional[JitWrapper]:
        is_jit, target = self._jit_target(module, call)
        if not is_jit:
            return None
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        return JitWrapper(
            module=module,
            bound_name=bound,
            target=target,
            static_argnums=_int_tuple(kw.get("static_argnums")),
            static_argnames=_str_tuple(kw.get("static_argnames")),
            donate_argnums=_int_tuple(kw.get("donate_argnums")),
            line=call.lineno,
        )

    def _find_roots(self) -> None:
        for mod in self.project.modules:
            for node in ast.walk(mod.tree):
                # X = jax.jit(f, ...) anywhere (module level or cached
                # inside a factory function).
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    bound = (
                        node.targets[0].id
                        if len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)
                        else None
                    )
                    w = self._wrapper_from_call(mod, node.value, bound)
                    if w:
                        self.wrappers.append(w)
                # Decorated defs: @jax.jit / @functools.partial(jax.jit,..)
                elif isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    for dec in node.decorator_list:
                        w = self._wrapper_from_decorator(mod, node, dec)
                        if w:
                            self.wrappers.append(w)
        for w in self.wrappers:
            if w.target is None:
                continue
            params = w.target.params
            static = {
                params[i] for i in w.static_argnums if i < len(params)
            } | set(w.static_argnames)
            tainted = {p for p in params if p not in static}
            self._mark(w.target, tainted)

    def _wrapper_from_decorator(
        self, module, fn: ast.FunctionDef, dec
    ) -> Optional[JitWrapper]:
        fd = self.defs.get((id(module), fn.name)) or FuncDef(
            module=module, node=fn, name=fn.name
        )
        dotted = module.dotted(dec)
        if dotted in _JIT_NAMES:
            return JitWrapper(
                module=module, bound_name=fn.name, target=fd, line=fn.lineno
            )
        if isinstance(dec, ast.Call):
            dec_dotted = module.dotted(dec.func)
            kw = {k.arg: k.value for k in dec.keywords if k.arg}
            if dec_dotted in _JIT_NAMES:
                return JitWrapper(
                    module=module,
                    bound_name=fn.name,
                    target=fd,
                    static_argnums=_int_tuple(kw.get("static_argnums")),
                    static_argnames=_str_tuple(kw.get("static_argnames")),
                    donate_argnums=_int_tuple(kw.get("donate_argnums")),
                    line=fn.lineno,
                )
            if dec_dotted == "functools.partial" and dec.args:
                if module.dotted(dec.args[0]) in _JIT_NAMES:
                    return JitWrapper(
                        module=module,
                        bound_name=fn.name,
                        target=fd,
                        static_argnums=_int_tuple(kw.get("static_argnums")),
                        static_argnames=_str_tuple(
                            kw.get("static_argnames")
                        ),
                        donate_argnums=_int_tuple(kw.get("donate_argnums")),
                        line=fn.lineno,
                    )
        return None

    # ------------------------------------------------------- propagation

    def _mark(self, fd: FuncDef, tainted: Set[str]) -> bool:
        self._by_id[id(fd)] = fd
        cur = self.traced.setdefault(id(fd), set())
        before = len(cur)
        cur |= tainted
        return len(cur) != before or before == 0 and not tainted

    def _propagate(self) -> None:
        # Fixpoint: re-walk every traced function until no callee's taint
        # set grows. Monotone, so it terminates.
        changed = True
        while changed:
            changed = False
            for fid, tainted in list(self.traced.items()):
                fd = self._by_id[fid]
                walker = _TaintWalker(self, fd, set(tainted))
                walker.run()
                for callee, callee_tainted in walker.calls:
                    if id(callee) not in self.traced:
                        self._by_id[id(callee)] = callee
                        self.traced[id(callee)] = set()
                        changed = True
                    cur = self.traced[id(callee)]
                    if callee_tainted - cur:
                        cur |= callee_tainted
                        changed = True

    def _collect_events(self) -> None:
        seen = set()
        for fid, tainted in self.traced.items():
            fd = self._by_id[fid]
            walker = _TaintWalker(self, fd, set(tainted), emit=True)
            walker.run()
            for ev in walker.events:
                key = (id(ev.module), ev.line, ev.col, ev.kind, ev.message)
                if key not in seen:
                    seen.add(key)
                    self.events.append(ev)

    def traced_functions(self) -> List[FuncDef]:
        return [self._by_id[fid] for fid in self.traced]


def _identity_test(test) -> bool:
    """``x is None`` / ``x is not None`` (and `and`/`or` chains of them)
    never call ``__bool__`` on a tracer — identity is decided by the
    Python object, so branching on it is trace-safe."""
    if isinstance(test, ast.Compare):
        return all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
    if isinstance(test, ast.BoolOp):
        return all(_identity_test(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _identity_test(test.operand)
    return False


class _TaintWalker:
    """Forward taint walk over one traced function's body."""

    def __init__(self, analysis, fd: FuncDef, tainted: Set[str], emit=False):
        self.analysis = analysis
        self.fd = fd
        self.module = fd.module
        self.tainted = set(tainted)
        self.emit = emit
        self.events: List[Event] = []
        self.calls: List[Tuple[FuncDef, Set[str]]] = []
        # Locals bound to a sync-forcing bound method of a tainted value
        # (``f = x.item`` / ``f = getattr(x, "tolist")``) — calling one
        # later is the SAME host sync, laundered through a name (the
        # method-call R1 gap, round 6).
        self.sync_thunks: Set[str] = set()

    def _sync_thunk_expr(self, node) -> Optional[str]:
        """The sync-method name an expression launders, or None: a bound
        sync method of a tainted receiver (``x.item``) or the getattr
        spelling of one (``getattr(x, "item")``)."""
        if (
            isinstance(node, ast.Attribute)
            and node.attr in _SYNC_METHODS
            and self.is_tainted(node.value)
        ):
            return node.attr
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "getattr"
            and len(node.args) >= 2
            and isinstance(node.args[1], ast.Constant)
            and node.args[1].value in _SYNC_METHODS
            and self.is_tainted(node.args[0])
        ):
            return node.args[1].value
        return None

    def run(self) -> None:
        for stmt in self.fd.node.body:
            self._stmt(stmt)

    # ------------------------------------------------------- taint query

    def is_tainted(self, node) -> bool:
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value) or self.is_tainted(
                node.slice
            )
        if isinstance(node, ast.Call):
            dotted = self.module.dotted(node.func)
            if isinstance(node.func, ast.Name) and node.func.id in (
                _STATIC_BUILTINS | _SCALARIZERS
            ):
                return False
            args_tainted = any(self.is_tainted(a) for a in node.args) or any(
                self.is_tainted(k.value) for k in node.keywords
            )
            if dotted is not None and dotted.split(".")[0] in (
                "math", "dataclasses", "functools"
            ):
                # Static ONLY over static operands: functools.reduce /
                # dataclasses.replace over a tracer launders the taint
                # right past the scalarizer check otherwise (the
                # stop_gradient-style R1 gap, round 6).
                return args_tainted
            # Method on a tainted object (x.astype(...), x.sum()).
            if isinstance(node.func, ast.Attribute) and self.is_tainted(
                node.func.value
            ):
                return True
            if isinstance(node.func, ast.Name) and self.is_tainted(
                node.func
            ):
                return True
            return args_tainted
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.Compare):
            return self.is_tainted(node.left) or any(
                self.is_tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return (
                self.is_tainted(node.body)
                or self.is_tainted(node.orelse)
                or self.is_tainted(node.test)
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.is_tainted(v) for v in node.values if v)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self.is_tainted(node.elt) or any(
                self.is_tainted(g.iter) for g in node.generators
            )
        if isinstance(node, ast.Lambda):
            return False
        if isinstance(node, ast.JoinedStr):
            return False
        if isinstance(node, ast.Slice):
            return any(
                self.is_tainted(p)
                for p in (node.lower, node.upper, node.step)
            )
        return False

    # --------------------------------------------------------- statements

    def _assign_target(self, target, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._assign_target(e, tainted)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, tainted)
        # Attribute/Subscript stores don't create locals.

    def _stmt(self, stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested def: its params receive device values from the
            # enclosing traced context (loop bodies, vmapped lambdas).
            inner = _TaintWalker(
                self.analysis,
                FuncDef(module=self.module, node=stmt, name=stmt.name),
                self.tainted
                | {
                    a.arg
                    for a in (
                        stmt.args.posonlyargs
                        + stmt.args.args
                        + stmt.args.kwonlyargs
                    )
                },
                emit=self.emit,
            )
            inner.run()
            self.events.extend(inner.events)
            self.calls.extend(inner.calls)
            return
        if isinstance(stmt, ast.Assign):
            self._scan_expr(stmt.value)
            t = self.is_tainted(stmt.value)
            thunk = self._sync_thunk_expr(stmt.value)
            for target in stmt.targets:
                self._assign_target(target, t)
                if isinstance(target, ast.Name):
                    if thunk is not None:
                        self.sync_thunks.add(target.id)
                    else:
                        self.sync_thunks.discard(target.id)
            return
        if isinstance(stmt, ast.AugAssign):
            self._scan_expr(stmt.value)
            if self.is_tainted(stmt.value):
                self._assign_target(stmt.target, True)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._scan_expr(stmt.value)
                self._assign_target(stmt.target, self.is_tainted(stmt.value))
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test)
            if (
                self.emit
                and self.is_tainted(stmt.test)
                and not _identity_test(stmt.test)
            ):
                kw = "while" if isinstance(stmt, ast.While) else "if"
                self.events.append(
                    Event(
                        kind="tracer-branch",
                        module=self.module,
                        line=stmt.lineno,
                        col=stmt.col_offset,
                        message=(
                            f"Python `{kw}` on a traced value inside a "
                            "jit region — concretizes the tracer "
                            "(TracerBoolConversionError at best, a "
                            "silent retrace per value at worst); use "
                            "jnp.where/lax.cond or hoist the branch to "
                            "a static argument"
                        ),
                    )
                )
            for s in stmt.body:
                self._stmt(s)
            for s in stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, ast.For):
            self._scan_expr(stmt.iter)
            self._assign_target(stmt.target, self.is_tainted(stmt.iter))
            for s in stmt.body:
                self._stmt(s)
            for s in stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.With,)):
            for item in stmt.items:
                self._scan_expr(item.context_expr)
                if item.optional_vars is not None:
                    self._assign_target(
                        item.optional_vars,
                        self.is_tainted(item.context_expr),
                    )
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body:
                self._stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    self._stmt(s)
            for s in stmt.orelse + stmt.finalbody:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_expr(stmt.value)
            return
        if isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value)
            return
        # Raise/Assert/Import/Pass/Global/...: scan embedded expressions.
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self._scan_expr(node)

    # -------------------------------------------------------- expressions

    def _scan_expr(self, expr) -> None:
        """Walk an expression tree: record project-call edges and (in emit
        mode) host-sync events."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Lambda):
                # Treated like a nested def: params tainted, body scanned
                # by this same walk (ast.walk already descends into it,
                # so just add the params to the taint set first).
                for a in (
                    node.args.posonlyargs
                    + node.args.args
                    + node.args.kwonlyargs
                ):
                    self.tainted.add(a.arg)
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            self._record_call(node)
            if self.emit:
                self._check_call(node)

    def _record_call(self, call: ast.Call) -> None:
        if not isinstance(call.func, ast.Name):
            return
        target = self.analysis.resolve(self.module, call.func.id)
        if target is None:
            return
        params = target.params
        tainted_params: Set[str] = set()
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break  # positions unknown past a splat
            if i < len(params) and self.is_tainted(arg):
                tainted_params.add(params[i])
        for k in call.keywords:
            if k.arg and k.arg in params and self.is_tainted(k.value):
                tainted_params.add(k.arg)
        self.calls.append((target, tainted_params))

    @staticmethod
    def _is_at_set(call: ast.Call) -> bool:
        """``x.at[i].set(v)`` — jax's indexed update, not a telemetry
        sink despite the ``set`` method name."""
        f = call.func
        return (
            isinstance(f, ast.Attribute)
            and f.attr == "set"
            and isinstance(f.value, ast.Subscript)
            and isinstance(f.value.value, ast.Attribute)
            and f.value.value.attr == "at"
        )

    def _check_telemetry(self, call: ast.Call, any_tainted: bool) -> bool:
        """R7 (telemetry taint): a traced value flowing into a metric
        sample, metric label, journal field, or span attribute. Returns
        True when an event was emitted."""
        if not any_tainted:
            return False
        sink = None
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _TELEMETRY_METHODS
            and not self._is_at_set(call)
        ):
            sink = f".{call.func.attr}()"
        elif isinstance(call.func, ast.Name) and call.func.id.startswith(
            "record_"
        ):
            sink = f"{call.func.id}()"  # obs.metrics recording helpers
        if sink is None:
            return False
        self.events.append(
            Event(
                kind="telemetry-taint",
                module=self.module,
                line=call.lineno,
                col=call.col_offset,
                message=(
                    f"traced value flows into telemetry sink `{sink}` "
                    "inside a jit region — metric samples/labels, "
                    "journal fields and span attributes are host "
                    "values (the sink calls float()/str() on them: a "
                    "host sync laundered through the telemetry "
                    "layer); record AFTER the fetch, outside the jit "
                    "boundary"
                ),
            )
        )
        return True

    def _check_call(self, call: ast.Call) -> None:
        args_tainted = any(self.is_tainted(a) for a in call.args)
        kwargs_tainted = any(
            self.is_tainted(k.value) for k in call.keywords
        )
        if self._check_telemetry(call, args_tainted or kwargs_tainted):
            return
        # Laundered sync: calling a local bound to a sync method of a
        # traced value (``f = x.item; f()``), or the inline getattr
        # spelling (``getattr(x, "item")()``).
        if (
            isinstance(call.func, ast.Name)
            and call.func.id in self.sync_thunks
        ):
            self._event_sync(
                call,
                f"`{call.func.id}()` calls a bound sync method of a "
                "traced value (assigned from `.item`/`.tolist`-style "
                "laundering) — the host sync happens here, inside the "
                "jit region",
            )
            return
        laundered = self._sync_thunk_expr(call.func)
        if laundered is not None and not isinstance(
            call.func, ast.Attribute
        ):  # direct x.item() is reported by the branch below
            self._event_sync(
                call,
                f"`getattr(..., '{laundered}')()` on a traced value "
                "forces a host sync inside a jit region — getattr does "
                "not launder the sync away",
            )
            return
        # float(x)/int(x)/bool(x) on a traced value.
        if (
            isinstance(call.func, ast.Name)
            and call.func.id in _SCALARIZERS
            and args_tainted
        ):
            self._event_sync(
                call,
                f"`{call.func.id}()` on a traced value forces a "
                "host sync (blocks dispatch, breaks inside jit); keep "
                "the value on device or fetch it once with "
                "jax.device_get after dispatch",
            )
            return
        # x.item() / x.tolist() / jax.device_get(x) / np.*(x).
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in _SYNC_METHODS and self.is_tainted(
                call.func.value
            ):
                self._event_sync(
                    call,
                    f"`.{call.func.attr}()` on a traced value forces a "
                    "host sync inside a jit region",
                )
                return
        dotted = self.module.dotted(call.func)
        if dotted is None:
            return
        if dotted == "jax.device_put":
            # Tainted or not: staging a host constant from inside a
            # traced region is the same mistake (R6 reports these).
            self.events.append(
                Event(
                    kind="device-put",
                    module=self.module,
                    line=call.lineno,
                    col=call.col_offset,
                    message=(
                        "`jax.device_put` inside a traced region — "
                        "under jit it is no transfer at all (it traces "
                        "to a placement hint that can silently pin the "
                        "operand's sharding), and in op-by-op execution "
                        "it adds a blocking RPC per call; stage inputs "
                        "at the dispatch boundary "
                        "(rank_backends.blob.stage_rank_window) and "
                        "pass them in as arguments"
                    ),
                )
            )
            return
        if dotted in _SYNC_EXTERNALS and args_tainted:
            self._event_sync(
                call,
                "`jax.device_get` inside a traced region — fetch results "
                "after dispatch, outside the jit boundary",
            )
            return
        root = dotted.split(".")[0]
        if root == "numpy" and args_tainted:
            self._event_sync(
                call,
                f"`{dotted.replace('numpy', 'np', 1)}` on a traced value "
                "— numpy concretizes tracers (TracerArrayConversionError "
                "under jit, a silent device->host sync outside); use the "
                "jnp equivalent",
            )
            return
        if root == "math" and args_tainted:
            self._event_sync(
                call,
                f"`{dotted}` on a traced value — the math module calls "
                "float() on its argument (host sync / TracerError under "
                "jit); use the jnp equivalent",
            )

    def _event_sync(self, call: ast.Call, message: str) -> None:
        self.events.append(
            Event(
                kind="host-sync",
                module=self.module,
                line=call.lineno,
                col=call.col_offset,
                message=message,
            )
        )
