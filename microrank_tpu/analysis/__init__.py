"""``mrlint`` — repo-specific TPU-correctness static analysis.

The failure modes that actually ship in JAX/TPU code are invisible to a
value-level test suite until they cost a cliff on real hardware: a
``float()`` on a traced value that forces a host sync inside a jit
region, a stray ``np.float64`` scalar that silently upcasts the bf16
ranking path, a ``jax.jit`` rebuilt per call that recompiles forever, a
donated buffer read after dispatch. ``mrlint`` machine-checks these as
*invariants* of this codebase (they were previously conventions buried
in SURVEY.md §5):

  R1 host-sync     no np.*/float()/int()/bool()/.item() on traced values
                   inside jit/pjit/shard_map call graphs
  R2 dtype-drift   no float64 dtypes in jax-importing ranking modules
                   (the bf16/f32 device path must not silently upcast)
  R3 retrace       no jax.jit built per call without a cache; no Python
                   branch on a traced value; no unhashable static args;
                   no raw host measurement (len()/int() of live data)
                   flowing into a static argument or staged-array shape
                   (the pad_policy="exact" one-trace-per-window hazard)
  R4 donation      no read of a buffer after it was passed in a donated
                   argument position
  R5 contracts     public rank/spectrum entry points carry @contract
                   shape/dtype annotations (analysis.contracts)
  R6 device-put    no jax.device_put inside traced code — staging
                   happens at the dispatch boundary, not under a trace
  R7 telemetry-taint  no traced arrays in span attributes, metric
                   samples/labels, or journal fields — telemetry sinks
                   are host values (a sync laundered through the
                   telemetry layer); record after the fetch
  R8 device-ownership  no jax touch reachable from a non-owner thread
                   class (Thread targets/subclasses, pool.submit
                   workers, async handlers, sink callbacks) — one
                   thread owns the device; roots opt in via
                   claim_device_owner()/authorize_device_thread
  R9 collective-order  inside shard_map-traced code, no psum/
                   all_gather/ppermute under data-dependent control
                   flow, and no call path reaching a collective-
                   issuing kernel only under such a branch — every
                   shard must issue the identical collective schedule
  R10 shared-state-race  cross-thread shared state (attrs of lock-
                   owning classes, globals of lock-owning modules)
                   carries a non-empty COMMON lockset across every
                   access — the Eraser lockset discipline, statically;
                   queue/Event handoffs, __init__-only publishes and
                   utils.guards.published(...) writes are safe seams
  R11 lock-order-cycle  the static lock-acquisition-order graph
                   (edge A->B when B is acquired while A held, incl.
                   acquire-via-callee) stays acyclic — any cycle,
                   including re-acquiring a held non-reentrant lock,
                   is a potential deadlock
  R12 blocking-under-lock  no HTTP/webhook POST, time.sleep, fsync/
                   atomic write, subprocess wait, Future.result()/
                   join(), or device dispatch/fetch seam reached while
                   a lock is statically held — a blocked lock is a
                   convoy (the PR-8 webhook-hang bug, generalized)
  R13 recompile-bomb  interprocedural R3(d): no ⊤-provenance value (a
                   raw host measurement of live data, through ANY
                   chain of helper calls) reaching a static argument
                   of a jit wrapper — the shape/dtype provenance
                   lattice ⊥ < const < bucket < ⊤ (analysis.shapes)
                   tracks the flow project-wide
  R14 precision-ladder-break  no two distinct precision-ladder levels
                   (f32 / bf16 / int8) meeting one fused jit boundary
                   without an explicit cast at the call site — XLA
                   would place the implicit upcast inside the fusion,
                   so accumulation precision drifts between callers
  R15 pad-bucket-escape  no array whose shape carries ⊤ provenance
                   (measured, not pad_to-bucketed or graph-builder
                   produced) reaching a dispatch seam — an unbucketed
                   extent keys the compile cache per distinct window
  R16 warmup-coverage  every statically enumerable compile key a
                   production dispatch can form is covered by a warm*
                   call path — an uncovered key pays its compile on
                   the first live request the warmup existed to absorb

R8-R16 are *static* claims about a concurrent (R8-R12) or compiled
(R13-R16) system; their runtime twin is ``analysis.mrsan`` (armed by
``RuntimeConfig.sanitizers``): ownership asserted at every device
seam, per-shard collective schedules recorded on the mesh and checked
for uniformity, production locks tracked per-thread
(utils.guards.TrackedLock) with an Eraser-style lockset checker on
registered shared objects and a lock-order watchdog asserting the
observed acquisition DAG, and the compile witness — every dispatch
seam reports its (kernel, occupancy, leaf-shapes) compile signature,
first-seen keys journal as ``jit_cache_miss`` events, and a key
outside the statically predicted ``CompileKeySpace``
(analysis.shapes.predict_key_space) is a sanitizer violation. The
``witness`` CLI replays a finished run's journal against the
prediction offline. CI's mrsan-smoke and race-smoke jobs
cross-validate the models.

Run it::

    python -m microrank_tpu.cli lint [paths...]     # exit 1 on findings

or as the pytest-collected suite ``tests/test_mrlint.py`` (tier-1).
Suppress a finding on its line (justification required)::

    x = float(tr)  # mrlint: disable=R1(host scalar needed for logging)

The escape hatch is itself linted: a bare ``disable=R1`` without a
reason is reported as R0.
"""

from .core import RULES, Violation, lint_paths, lint_source  # noqa: F401
from . import rules  # noqa: F401  (imports register the rule set)

__all__ = ["RULES", "Violation", "lint_paths", "lint_source"]
