"""mrlint framework: module parsing, rule registry, disable comments.

A lint run parses every target file once into a :class:`ModuleInfo`
(AST + per-line disable pragmas), wraps the set in a :class:`Project`
(cross-module symbol/import resolution plus the traced-call-graph
analysis in ``analysis.traced``), then asks each registered rule for
violations. Suppression happens centrally: a violation whose line (or
whose immediately preceding comment-only line) carries
``# mrlint: disable=<RULE>(<reason>)`` for its rule is dropped; a
disable pragma without a reason is reported as R0 — the escape hatch
must leave an audit trail.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Tuple


@dataclass(frozen=True, order=True)
class Violation:
    path: str
    line: int
    col: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


class Rule:
    """One lint rule. Subclasses set ``name``/``slug``/``summary`` and
    implement ``check(module, project) -> iterable of Violation``."""

    name: str = ""
    slug: str = ""
    summary: str = ""

    def check(self, module: "ModuleInfo", project: "Project"):
        raise NotImplementedError


RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule (by its ``name``) to the registry."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    RULES[inst.name] = inst
    return cls


# `# mrlint: disable=R1(reason), R2(other reason)` — reasons may hold any
# character but ")," so multiple pragmas on one line stay parseable.
_PRAGMA = re.compile(r"#\s*mrlint:\s*disable=(.*)$")
_ENTRY = re.compile(r"(R\d+)\s*(?:\(([^)]*)\))?")


@dataclass
class ModuleInfo:
    path: Path
    rel: str
    source: str
    tree: ast.Module
    lines: List[str]
    # line -> {rule: reason}; reason "" means a bare (unjustified) pragma.
    disables: Dict[int, Dict[str, str]] = field(default_factory=dict)

    @property
    def stmt_starts(self) -> Dict[int, int]:
        """line -> first physical line of the INNERMOST statement
        spanning it. A pragma on a multi-line statement's first line
        suppresses a violation reported on a continuation line (ast
        anchors some nodes — a wrapped call's argument, a parenthesized
        expression — lines below the statement head the pragma sits
        on)."""
        if not hasattr(self, "_stmt_starts"):
            starts: Dict[int, int] = {}
            for node in ast.walk(self.tree):
                if not isinstance(node, ast.stmt):
                    continue
                end = getattr(node, "end_lineno", None)
                if end is None:
                    continue
                for ln in range(node.lineno, end + 1):
                    # Innermost wins: the deepest statement containing
                    # the line has the largest start line.
                    if starts.get(ln, 0) < node.lineno:
                        starts[ln] = node.lineno
            self._stmt_starts = starts
        return self._stmt_starts

    @property
    def imports_jax(self) -> bool:
        return any(
            m == "jax" or m.startswith("jax.")
            for m in self.import_aliases.values()
        )

    @property
    def import_aliases(self) -> Dict[str, str]:
        """Local name -> absolute dotted module for plain ``import``/
        ``import .. as ..`` statements (external modules; relative
        imports are resolved separately by Project)."""
        if not hasattr(self, "_aliases"):
            aliases: Dict[str, str] = {}
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        aliases[a.asname or a.name.split(".")[0]] = a.name
                elif isinstance(node, ast.ImportFrom) and node.level == 0:
                    for a in node.names:
                        aliases[a.asname or a.name] = (
                            f"{node.module}.{a.name}" if node.module else a.name
                        )
            self._aliases = aliases
        return self._aliases

    def dotted(self, node) -> Optional[str]:
        """Resolve a Name/Attribute chain to an absolute dotted path using
        the module's import aliases (``jnp.float64`` -> ``jax.numpy.
        float64``); None when the root is not an imported name."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        base = self.import_aliases.get(node.id)
        if base is None:
            return None
        return ".".join([base] + list(reversed(parts)))


def _parse_text(source: str, path: Path, rel: str) -> ModuleInfo:
    tree = ast.parse(source, filename=rel)
    lines = source.splitlines()
    info = ModuleInfo(
        path=path, rel=rel, source=source, tree=tree, lines=lines
    )
    for i, text in enumerate(lines, start=1):
        m = _PRAGMA.search(text)
        if not m:
            continue
        entries = {
            rule: (reason or "").strip()
            for rule, reason in _ENTRY.findall(m.group(1))
        }
        if not entries:
            continue
        stripped = text[: m.start()].strip()
        # A comment-only pragma line guards the NEXT line; an end-of-line
        # pragma guards its own.
        info.disables.setdefault(i if stripped else i + 1, {}).update(entries)
    return info


def parse_module(path: Path, rel: Optional[str] = None) -> ModuleInfo:
    return _parse_text(path.read_text(), path, rel or str(path))


class Project:
    """The lint unit: a set of modules linted together, with lazy
    cross-module analyses (symbol table, traced-call-graph taint)."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = modules
        self._traced = None
        self._threads = None
        self._locks = None
        self._shapes = None

    @property
    def traced(self):
        """The traced-call-graph analysis (analysis.traced.TracedAnalysis),
        computed once per project."""
        if self._traced is None:
            from .traced import TracedAnalysis

            self._traced = TracedAnalysis(self)
        return self._traced

    @property
    def threads(self):
        """The cross-thread concurrency analysis
        (analysis.threads.ThreadAnalysis), computed once per project."""
        if self._threads is None:
            from .threads import ThreadAnalysis

            self._threads = ThreadAnalysis(self)
        return self._threads

    @property
    def shapes(self):
        """The interprocedural shape/dtype provenance analysis
        (analysis.shapes.ShapeAnalysis), computed once per project on
        top of the traced-call-graph."""
        if self._shapes is None:
            from .shapes import ShapeAnalysis

            self._shapes = ShapeAnalysis(self)
        return self._shapes

    @property
    def locks(self):
        """The lock model / race analysis (analysis.locks.LockAnalysis),
        computed once per project on top of the thread analysis."""
        if self._locks is None:
            from .locks import LockAnalysis

            self._locks = LockAnalysis(self)
        return self._locks

    def module_for(self, path: Path) -> Optional[ModuleInfo]:
        for m in self.modules:
            if m.path == path:
                return m
        return None

    def resolve_relative(self, module: ModuleInfo, node: ast.ImportFrom):
        """Resolve a relative ``from``-import to a project module path
        (``from ..ops.segment import x`` inside rank_backends/ ->
        .../ops/segment.py). Returns the ModuleInfo or None."""
        if node.level == 0:
            return None
        base = module.path.parent
        for _ in range(node.level - 1):
            base = base.parent
        target = base
        if node.module:
            for part in node.module.split("."):
                target = target / part
        for candidate in (target.with_suffix(".py"), target / "__init__.py"):
            found = self.module_for(candidate)
            if found is not None:
                return found
        return None


def collect_files(paths: Iterable[str]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            files.append(path)
    return files


def lint_paths(
    paths: Iterable[str], rules: Optional[Iterable[str]] = None
) -> List[Violation]:
    """Lint files/directories as ONE project (cross-module call graphs
    resolve within the set). Returns sorted, suppression-filtered
    violations — including R0 for unjustified disables."""
    files = collect_files(paths)
    modules = [parse_module(f, rel=str(f)) for f in files]
    return _run(Project(modules), rules)


def lint_source(
    source: str,
    filename: str = "<snippet>",
    rules: Optional[Iterable[str]] = None,
) -> List[Violation]:
    """Lint one in-memory snippet (the fixture-test entry point)."""
    info = _parse_text(source, Path(filename), filename)
    return _run(Project([info]), rules)


def _run(
    project: Project, rules: Optional[Iterable[str]] = None
) -> List[Violation]:
    active = (
        list(RULES.values())
        if rules is None
        else [RULES[r] for r in rules]
    )
    out: List[Violation] = []
    for module in project.modules:
        found: List[Violation] = []
        for rule in active:
            found.extend(rule.check(module, project))
        for v in found:
            pragma_line = v.line
            pragma = module.disables.get(v.line, {})
            if v.rule not in pragma:
                # Multi-line statements: ast anchors some nodes on
                # continuation lines; the pragma on the statement's
                # FIRST physical line still governs the whole statement.
                start = module.stmt_starts.get(v.line)
                if start is not None and start < v.line:
                    candidate = module.disables.get(start, {})
                    if v.rule in candidate:
                        pragma_line, pragma = start, candidate
            if v.rule in pragma:
                if pragma[v.rule]:
                    continue  # justified suppression
                out.append(
                    Violation(
                        path=v.path,
                        line=pragma_line,
                        col=v.col,
                        rule="R0",
                        message=(
                            f"disable={v.rule} without a justification — "
                            "write # mrlint: disable="
                            f"{v.rule}(why this is safe)"
                        ),
                    )
                )
            else:
                out.append(v)
        # Pragmas that never matched a violation but carry no reason are
        # still unjustified escape hatches.
        for line, entries in module.disables.items():
            for rule_name, reason in entries.items():
                if reason:
                    continue
                already = any(
                    v.rule == "R0" and v.line == line for v in out
                )
                if not already:
                    out.append(
                        Violation(
                            path=module.rel,
                            line=line,
                            col=0,
                            rule="R0",
                            message=(
                                f"disable={rule_name} without a "
                                "justification — write # mrlint: "
                                f"disable={rule_name}(why this is safe)"
                            ),
                        )
                    )
    return sorted(out)
