"""``python -m microrank_tpu.cli lint`` — the mrlint command surface."""

from __future__ import annotations

from typing import List


def add_lint_parser(sub) -> None:
    p = sub.add_parser(
        "lint",
        help="TPU-correctness static analysis (mrlint rules R1-R12)",
        description=(
            "AST lint of the repo's TPU invariants: host syncs inside "
            "jit graphs (R1), float64 drift on the bf16 ranking path "
            "(R2), recompilation hazards incl. value->shape retraces "
            "(R3), donated-buffer reuse (R4), missing shape/dtype "
            "contracts on rank/spectrum entry points (R5), device_put "
            "inside traced code (R6), traced arrays flowing into "
            "telemetry sinks (R7), jax touches reachable from non-"
            "owner threads (R8), data-dependent collective schedules "
            "inside shard_map-traced code (R9), cross-thread shared "
            "state with no common lock (R10, Eraser-style locksets), "
            "lock-acquisition-order cycles (R11), and blocking calls "
            "under a held lock (R12). Suppress a finding in place "
            "with `# mrlint: disable=RN(reason)` — the reason is "
            "mandatory."
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        default=["microrank_tpu"],
        help="files or directories to lint (default: microrank_tpu/)",
    )
    p.add_argument(
        "--rules",
        help="comma-separated subset to run (e.g. R1,R3); default all",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    p.add_argument(
        "--sarif",
        metavar="PATH",
        help=(
            "also write the findings as SARIF 2.1.0 (GitHub code "
            "scanning uploads annotate PRs from it); exit status is "
            "unchanged"
        ),
    )
    p.set_defaults(fn=cmd_lint)


def cmd_lint(args) -> int:
    from . import RULES, lint_paths

    if args.list_rules:
        width = max(len(r.name) for r in RULES.values())
        for rule in sorted(RULES.values(), key=lambda r: r.name):
            print(f"{rule.name:<{width}}  [{rule.slug}] {rule.summary}")
        return 0
    rules: List[str] | None = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}")
            return 2
    violations = lint_paths(args.paths, rules=rules)
    for v in violations:
        print(v.format())
    if args.sarif:
        from .sarif import write_sarif

        out = write_sarif(violations, args.sarif)
        print(f"sarif: {out}")
    n = len(violations)
    if n:
        print(f"mrlint: {n} finding{'s' if n != 1 else ''}")
        return 1
    print("mrlint: clean")
    return 0
