"""``python -m microrank_tpu.cli lint`` / ``witness`` — the mrlint
and compile-witness command surfaces."""

from __future__ import annotations

from typing import List


def add_lint_parser(sub) -> None:
    p = sub.add_parser(
        "lint",
        help="TPU-correctness static analysis (mrlint rules R0-R16)",
        description=(
            "AST lint of the repo's TPU invariants: host syncs inside "
            "jit graphs (R1), float64 drift on the bf16 ranking path "
            "(R2), recompilation hazards incl. value->shape retraces "
            "(R3), donated-buffer reuse (R4), missing shape/dtype "
            "contracts on rank/spectrum entry points (R5), device_put "
            "inside traced code (R6), traced arrays flowing into "
            "telemetry sinks (R7), jax touches reachable from non-"
            "owner threads (R8), data-dependent collective schedules "
            "inside shard_map-traced code (R9), cross-thread shared "
            "state with no common lock (R10, Eraser-style locksets), "
            "lock-acquisition-order cycles (R11), blocking calls "
            "under a held lock (R12), plus the interprocedural "
            "shape/dtype-flow rules: live measurements reaching "
            "static jit arguments (R13, recompile bomb), mixed "
            "precision-ladder dtypes meeting a fused boundary uncast "
            "(R14), measured shapes escaping the pad-bucket registry "
            "into dispatch seams (R15), and statically enumerable "
            "compile keys the warmup path never covers (R16). "
            "Suppress a finding in place with `# mrlint: "
            "disable=RN(reason)` — the reason is mandatory (bare "
            "disables are R0)."
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        default=["microrank_tpu"],
        help="files or directories to lint (default: microrank_tpu/)",
    )
    p.add_argument(
        "--rules",
        help="comma-separated subset to run (e.g. R1,R3); default all",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    p.add_argument(
        "--sarif",
        metavar="PATH",
        help=(
            "also write the findings as SARIF 2.1.0 (GitHub code "
            "scanning uploads annotate PRs from it); exit status is "
            "unchanged"
        ),
    )
    p.set_defaults(fn=cmd_lint)


def cmd_lint(args) -> int:
    from . import RULES, lint_paths

    if args.list_rules:
        width = max(len(r.name) for r in RULES.values())
        for rule in sorted(RULES.values(), key=lambda r: r.name):
            print(f"{rule.name:<{width}}  [{rule.slug}] {rule.summary}")
        return 0
    rules: List[str] | None = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}")
            return 2
    violations = lint_paths(args.paths, rules=rules)
    for v in violations:
        print(v.format())
    if args.sarif:
        from .sarif import write_sarif

        out = write_sarif(violations, args.sarif)
        print(f"sarif: {out}")
    n = len(violations)
    if n:
        print(f"mrlint: {n} finding{'s' if n != 1 else ''}")
        return 1
    print("mrlint: clean")
    return 0


def add_witness_parser(sub) -> None:
    p = sub.add_parser(
        "witness",
        help=(
            "replay a run journal's jit_cache_miss events against the "
            "static compile-key-space prediction (R13-R16's runtime "
            "mirror)"
        ),
        description=(
            "Offline half of the mrsan compile witness: read "
            "journal.jsonl from a finished run, re-check every "
            "jit_cache_miss event against the CompileKeySpace the "
            "shape analysis predicts for the given pad policy, and "
            "exit 1 if any observed compile key falls outside it. A "
            "clean exit is the acceptance criterion that the static "
            "model (analysis.shapes) covers what the run actually "
            "compiled."
        ),
    )
    p.add_argument(
        "journal",
        help="path to a run's journal.jsonl (or its directory)",
    )
    p.add_argument(
        "--pad-policy",
        default=None,
        help=(
            "pad policy to predict with (default: the run_start "
            "event's recorded policy, else pow2q)"
        ),
    )
    p.add_argument(
        "--min-pad", type=int, default=8, help="pad floor (default 8)"
    )
    p.add_argument(
        "--cache-dir",
        default=None,
        help=(
            "compile-cache dir holding a warmup manifest; with "
            "--pipeline, pins the predicted occupancy set to the "
            "manifest's declarations"
        ),
    )
    p.add_argument(
        "--pipeline",
        default=None,
        help="manifest pipeline name (serve | stream | table)",
    )
    p.set_defaults(fn=cmd_witness)


def cmd_witness(args) -> int:
    from pathlib import Path

    from ..obs.journal import JOURNAL_NAME, read_journal
    from .shapes import CompileKeySpace

    path = Path(args.journal)
    if path.is_dir():
        path = path / JOURNAL_NAME
    if not path.exists():
        print(f"witness: no journal at {path}")
        return 2
    events = read_journal(path)
    policy = args.pad_policy
    if policy is None:
        for ev in events:
            if ev.get("event") == "run_start" and ev.get("pad_policy"):
                policy = str(ev["pad_policy"])
                break
    policy = policy or "pow2q"
    occupancies = None
    if args.cache_dir and args.pipeline:
        from ..dispatch.cache import manifest_occupancies

        occs = manifest_occupancies(args.cache_dir, args.pipeline)
        occupancies = frozenset(occs) if occs else None
    space = CompileKeySpace(
        pad_policy=policy, min_pad=args.min_pad, occupancies=occupancies
    )
    misses = [e for e in events if e.get("event") == "jit_cache_miss"]
    escapes = []
    for ev in misses:
        shapes = [tuple(s) for s in (ev.get("key") or [])]
        reason = space.admits(
            str(ev.get("program")),
            ev.get("kernel"),
            ev.get("occupancy"),
            shapes,
        )
        if reason is not None:
            escapes.append((ev, reason))
    print(
        f"witness: {len(misses)} compile key(s) observed "
        f"(pad_policy={policy})"
    )
    for ev, reason in escapes:
        print(f"  ESCAPE {ev.get('program')}: {reason}")
    if escapes:
        print(f"witness: {len(escapes)} key(s) outside the predicted space")
        return 1
    print("witness: all observed keys inside the predicted space")
    return 0
