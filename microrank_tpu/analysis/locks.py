"""mrrace — the lock model behind rules R10/R11/R12.

PRs 3-11 grew a genuinely concurrent host system around the device
pipeline: the serve scheduler thread, the stream engine with its build
worker pool, the fleet coordinator with heartbeat and lease-reaper
threads. Seventeen modules hold ``threading.Lock``s, yet the thread
model (analysis.threads, R8/R9) only checked *device* ownership — the
host-side shared state those threads mutate was unexamined. This module
builds a **lock model** on top of :class:`~.threads.ThreadAnalysis`:

* **Lock identification** — every ``threading.Lock``/``RLock``/
  ``Condition`` (and the mrsan runtime wrapper ``TrackedLock``)
  construction bound to a ``self.<attr>`` or a module global becomes a
  :class:`LockId`. Attr locks are keyed per owning class (instances
  share the key — two instances of one class alias statically, a
  deliberate under-approximation), module locks per module.

* **Held-lockset tracking** — a linear walk over every function body
  threads the statically-held lockset through ``with lock:`` regions
  and paired ``lock.acquire()``/``release()`` calls, and records four
  event streams per function: lock acquisitions (with the set held
  before), resolved project-internal calls (with the set held at the
  call site), known blocking calls, and shared-variable accesses.

* **R10 shared-state race** (Eraser's lockset discipline, statically):
  a variable in the race-checked set — an attribute of a class that
  owns at least one lock, or a global of a module that owns one —
  written outside ``__init__`` and accessed from two distinct thread
  classes whose locksets share no common lock. Safe seams are
  recognized: attributes holding thread-safe handoff types
  (``queue.Queue``/``threading.Event``/``collections.deque``/...),
  single-assignment-then-publish (all writes in ``__init__``), and
  writes wrapped in ``utils.guards.published(...)`` — the explicit
  intentional-handoff marker. Everything else needs a common lock or a
  ``# mrlint: disable=R10(reason)``.

* **R11 lock-order cycle**: the static lock-acquisition-order graph —
  edge A→B whenever B is acquired (directly, or transitively through a
  resolved callee) while A is held — must stay acyclic; any strongly-
  connected component (including a self-edge: re-acquiring a
  non-reentrant lock you hold) is a potential deadlock. The runtime
  twin is the mrsan lock-order watchdog (utils.guards), which asserts
  the *observed* acquisition DAG on every armed acquire.

* **R12 blocking-call-under-lock** — the generalization of the
  webhook-hang bug fixed by hand in PR 8: an HTTP/socket POST,
  ``time.sleep``, ``fsync``/atomic write, subprocess wait, a pool
  ``Future.result()``/thread ``join()``, or a device dispatch/fetch
  seam reached (directly or through resolved callees) while a lock is
  statically held. Every thread that ever contends on that lock then
  waits out the I/O. ``Condition.wait`` on the *held* condition is
  exempt (wait releases it by contract).

Known under-approximations (documented, runtime-compensated): in-place
container mutation (``d[k] = v`` on a shared dict) reads the binding
but never rebinds it, so R10's write detection misses it — the mrsan
lockset checker (``note_shared_access``) covers registered objects at
runtime; calls resolved through dynamic dispatch (``for s in
self.sinks: s.emit(...)``) do not contribute R11/R12 edges.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .threads import FuncInfo, _call_name
from .traced import Event

# Constructors that create a lock object. Condition defaults to an
# RLock, so it is reentrant; TrackedLock (utils.guards — the mrsan
# runtime wrapper) is reentrant only with reentrant=True.
_LOCK_CTORS = {"Lock", "RLock", "Condition", "TrackedLock"}
_REENTRANT_CTORS = {"RLock", "Condition"}
_LOCK_DOTTED_PREFIXES = ("threading.",)

# Attribute types that ARE the sanctioned cross-thread handoff: their
# methods are internally synchronized (or GIL-atomic for deque), so
# accesses through them need no common lock.
_SAFE_HANDOFF_CTORS = {
    "Queue",
    "SimpleQueue",
    "LifoQueue",
    "PriorityQueue",
    "Event",
    "Semaphore",
    "BoundedSemaphore",
    "Barrier",
    "deque",
    "Future",
    "local",           # threading.local: per-thread by construction
    "ContextVar",
}
_PUBLISH_MARKER = "published"

# Thread-class labels that describe a POOL of threads — code running
# only under such a label still races with itself (N workers execute
# the same function concurrently).
_MULTI_INSTANCE_LABELS = {"pool-worker", "authorized-worker"}

# Device dispatch/fetch seams (mirrors threads._DEVICE_SEAMS plus the
# explicit fetch entry points): issuing one while holding a lock parks
# every contending thread behind device latency.
_DEVICE_BLOCKING_NAMES = {
    "stage_rank_window",
    "stage_windows_batched",
    "dispatch_windows_staged",
    "stage_sharded",
    "warm_occupancies",
    "rank_batch",
    "device_get",
    "block_until_ready",
}


@dataclass(frozen=True, order=True)
class LockId:
    """One statically-identified lock object."""

    kind: str      # "attr" | "global"
    owner: str     # owning class name, or module rel path
    name: str      # attribute / global name
    reentrant: bool = field(compare=False, default=False)

    @property
    def label(self) -> str:
        sep = "." if self.kind == "attr" else ":"
        return f"{self.owner}{sep}{self.name}"


@dataclass
class _Access:
    var: Tuple[str, str, str]       # ("attr", cls, name) | ("global", rel, name)
    write: bool
    module: object
    node: ast.AST
    held: FrozenSet[LockId]
    func: FuncInfo


@dataclass
class _FuncSummary:
    acquires: List[Tuple[LockId, FrozenSet[LockId], ast.AST]] = field(
        default_factory=list
    )
    calls: List[Tuple[FuncInfo, FrozenSet[LockId], ast.AST]] = field(
        default_factory=list
    )
    blocking: List[Tuple[str, ast.AST, FrozenSet[LockId]]] = field(
        default_factory=list
    )
    accesses: List[_Access] = field(default_factory=list)


def _is_lock_ctor(module, call: ast.Call) -> Optional[str]:
    """The lock-constructor name when ``call`` builds a lock, else None."""
    name = _call_name(call.func)
    if name not in _LOCK_CTORS:
        return None
    dotted = module.dotted(call.func)
    if dotted is not None and not dotted.startswith(
        _LOCK_DOTTED_PREFIXES
    ) and "." in dotted:
        # Imported from somewhere that is not threading (or the guards
        # TrackedLock, which resolves as a bare/from-import name).
        if not dotted.endswith(("TrackedLock", f"guards.{name}")):
            return None
    return name


def _ctor_reentrant(module, call: ast.Call, ctor: str) -> bool:
    if ctor in _REENTRANT_CTORS:
        return True
    for kw in call.keywords:
        if (
            kw.arg == "reentrant"
            and isinstance(kw.value, ast.Constant)
            and bool(kw.value.value)
        ):
            return True
    return False


class LockAnalysis:
    """Project-wide lock model: locks, per-function held-lockset
    summaries, and the R10/R11/R12 event streams."""

    def __init__(self, project):
        self.project = project
        self.threads = project.threads
        # (class name, attr) -> LockId  /  (id(module), name) -> LockId
        self.attr_locks: Dict[Tuple[str, str], LockId] = {}
        self.module_locks: Dict[Tuple[int, str], LockId] = {}
        self.lock_owning_classes: Set[str] = set()
        self._lock_owning_modules: Set[int] = set()
        self._module_globals: Dict[int, Set[str]] = {}
        self._safe_attrs: Set[Tuple[str, str]] = set()
        self._published_attrs: Set[Tuple[str, str]] = set()
        self._published_globals: Set[Tuple[int, str]] = set()
        self.summaries: Dict[int, _FuncSummary] = {}
        # Bodies of nested defs (callbacks, thunks): they execute LATER
        # on whichever thread invokes them, so their acquires/blocking
        # never join the enclosing function's transitive summary, and
        # no caller-held lockset propagates in.
        self.deferred: List[Tuple[FuncInfo, _FuncSummary]] = []
        # Interprocedural entry locksets: the locks held at EVERY
        # resolved call site of a function (the `_locked`-suffix helper
        # pattern: the caller takes the lock, the helper touches the
        # state). Intersection over call sites; __init__ call sites are
        # pre-publication and excluded.
        self.entry_held: Dict[int, FrozenSet[LockId]] = {}
        self._labels: Dict[int, Set[str]] = {}
        self.events: List[Event] = []
        self._index_locks()
        self._index_shared()
        self._compute_labels()
        self._summarize()
        self._propagate_entry_locksets()
        self._collect_race_events()
        self._collect_order_events()
        self._collect_blocking_events()

    # ------------------------------------------------------------ indexing

    def _index_locks(self) -> None:
        for mod in self.project.modules:
            for node in mod.tree.body:
                if not (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                ctor = _is_lock_ctor(mod, node.value)
                if ctor is None:
                    continue
                name = node.targets[0].id
                self.module_locks[(id(mod), name)] = LockId(
                    kind="global",
                    owner=mod.rel,
                    name=name,
                    reentrant=_ctor_reentrant(mod, node.value, ctor),
                )
                self._lock_owning_modules.add(id(mod))
        for fi in self.threads.funcs:
            if fi.cls is None:
                continue
            for node in ast.walk(fi.node):
                if not (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                ctor = _is_lock_ctor(fi.module, node.value)
                for tgt in node.targets:
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        continue
                    if ctor is not None:
                        self.attr_locks[(fi.cls, tgt.attr)] = LockId(
                            kind="attr",
                            owner=fi.cls,
                            name=tgt.attr,
                            reentrant=_ctor_reentrant(
                                fi.module, node.value, ctor
                            ),
                        )
                        self.lock_owning_classes.add(fi.cls)

    def _index_shared(self) -> None:
        """Race-checked variables, safe-handoff attrs, published marks."""
        for mod in self.project.modules:
            if id(mod) not in self._lock_owning_modules:
                continue
            names: Set[str] = set()
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            names.add(t.id)
                elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name
                ):
                    names.add(node.target.id)
            names -= {
                n for (mid, n) in self.module_locks if mid == id(mod)
            }
            self._module_globals[id(mod)] = names
        for fi in self.threads.funcs:
            if fi.cls is None:
                continue
            for node in ast.walk(fi.node):
                if not (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                ctor = _call_name(node.value.func)
                for tgt in node.targets:
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        continue
                    if ctor in _SAFE_HANDOFF_CTORS:
                        self._safe_attrs.add((fi.cls, tgt.attr))
                    elif ctor == _PUBLISH_MARKER:
                        self._published_attrs.add((fi.cls, tgt.attr))
        for mod in self.project.modules:
            for node in ast.walk(mod.tree):
                if not (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _call_name(node.value.func) == _PUBLISH_MARKER
                ):
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._published_globals.add((id(mod), t.id))

    def _compute_labels(self) -> None:
        for root in self.threads.roots:
            for fi in self.threads.reachable(root.func):
                self._labels.setdefault(id(fi), set()).add(root.label)

    def labels_of(self, fi: FuncInfo) -> Set[str]:
        """Thread classes that can execute ``fi``: the labels of every
        thread root that reaches it, or {"main"} for code no root
        reaches (the caller's own thread)."""
        return self._labels.get(id(fi), {"main"})

    # --------------------------------------------------------- resolution

    def lock_for(self, fi: FuncInfo, expr) -> Optional[LockId]:
        """The LockId an expression denotes, when statically known."""
        if isinstance(expr, ast.Name):
            return self.module_locks.get((id(fi.module), expr.id))
        if isinstance(expr, ast.Attribute):
            if (
                isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and fi.cls is not None
            ):
                return self.attr_locks.get((fi.cls, expr.attr))
            recv = self.threads._receiver_class(fi, expr.value)
            if recv is not None:
                return self.attr_locks.get((recv, expr.attr))
        return None

    def resolve_call(self, fi: FuncInfo, func_node) -> Optional[FuncInfo]:
        """Resolve a call target for the lock model. Unlike the thread
        analysis this does NOT use the unique-method-name fallback: an
        ``f.write(...)`` on an unknown receiver must not resolve to the
        one project class that happens to define ``write`` — a spurious
        edge here invents lock-order cycles and blocking paths."""
        t = self.threads
        if isinstance(func_node, ast.Attribute):
            if (
                isinstance(func_node.value, ast.Name)
                and func_node.value.id == "self"
                and fi.cls is not None
            ):
                table = t._class_methods.get((id(fi.module), fi.cls), {})
                if func_node.attr in table:
                    return table[func_node.attr]
            recv = t._receiver_class(fi, func_node.value)
            if recv is not None:
                for key, table in t._class_methods.items():
                    if key[1] == recv and func_node.attr in table:
                        return table[func_node.attr]
            return None
        return t.resolve_callable(fi, func_node)

    # --------------------------------------------------------- summaries

    def _summarize(self) -> None:
        for fi in self.threads.funcs:
            walker = _LockWalker(self, fi)
            walker.run()
            self.summaries[id(fi)] = walker.summary
            for nested in walker.nested:
                self.deferred.append((fi, nested))

    def _propagate_entry_locksets(self) -> None:
        """Fixpoint over the resolved call graph: a function's entry
        lockset is the intersection, over every resolved call site, of
        the locks statically held there (plus the caller's own entry
        set). Functions with no resolved caller enter with nothing —
        dynamic dispatch is invisible, so the set is a best-effort
        floor, not a proof."""
        incoming_sites: Dict[int, List[Tuple[int, FrozenSet[LockId]]]] = {}
        for fid, s in self.summaries.items():
            fi = self.threads._by_id.get(fid)
            caller_init = fi is not None and fi.name == "__init__"
            for callee, held, _ in s.calls:
                if caller_init or id(callee) not in self.summaries:
                    continue
                incoming_sites.setdefault(id(callee), []).append(
                    (fid, held)
                )
        for fi, s in self.deferred:
            for callee, held, _ in s.calls:
                if id(callee) in self.summaries:
                    # A callback's call executes with unknown ambient
                    # locks: contribute only what it holds itself.
                    incoming_sites.setdefault(id(callee), []).append(
                        (0, held)
                    )
        entry = {fid: frozenset() for fid in self.summaries}
        changed = True
        while changed:
            changed = False
            for fid in self.summaries:
                sites = incoming_sites.get(fid)
                if not sites:
                    continue
                new = frozenset.intersection(
                    *[
                        held | entry.get(caller, frozenset())
                        for caller, held in sites
                    ]
                )
                if new != entry[fid]:
                    entry[fid] = new
                    changed = True
        self.entry_held = entry

    def _iter_summaries(
        self,
    ) -> Iterable[Tuple[FuncInfo, _FuncSummary, FrozenSet[LockId]]]:
        """(function, summary, entry-lockset augmentation) for every
        analyzed body — deferred (nested-def) bodies augment with
        nothing."""
        for fid, s in self.summaries.items():
            fi = self.threads._by_id.get(fid)
            if fi is not None:
                yield fi, s, self.entry_held.get(fid, frozenset())
        for fi, s in self.deferred:
            yield fi, s, frozenset()

    # -------------------------------------------------------- R10 events

    def _race_checked_var(
        self, fi: FuncInfo, node
    ) -> Optional[Tuple[Tuple[str, str, str], bool]]:
        """(var key, is_write) when ``node`` accesses a race-checked
        variable from ``fi``, else None."""
        if isinstance(node, ast.Attribute):
            if not (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and fi.cls is not None
                and fi.cls in self.lock_owning_classes
            ):
                return None
            key = (fi.cls, node.attr)
            if (
                key in self.attr_locks
                or key in self._safe_attrs
                or key in self._published_attrs
            ):
                return None
            return (
                ("attr", fi.cls, node.attr),
                isinstance(node.ctx, (ast.Store, ast.Del)),
            )
        if isinstance(node, ast.Name):
            mod = fi.module
            if node.id not in self._module_globals.get(id(mod), ()):
                return None
            if (id(mod), node.id) in self._published_globals:
                return None
            return (
                ("global", mod.rel, node.id),
                isinstance(node.ctx, (ast.Store, ast.Del)),
            )
        return None

    def _collect_race_events(self) -> None:
        by_var: Dict[Tuple[str, str, str], List[_Access]] = {}
        for fi, s, aug in self._iter_summaries():
            if fi.name == "__init__":
                continue  # publish-before-start: constructor accesses
                # happen before any thread can see the object.
            for acc in s.accesses:
                if aug:
                    acc = _Access(
                        var=acc.var,
                        write=acc.write,
                        module=acc.module,
                        node=acc.node,
                        held=acc.held | aug,
                        func=acc.func,
                    )
                by_var.setdefault(acc.var, []).append(acc)
        for var in sorted(by_var):
            accesses = by_var[var]
            writes = [a for a in accesses if a.write]
            if not writes:
                continue
            labels: Set[str] = set()
            for a in accesses:
                labels |= self.labels_of(a.func)
            if len(labels) < 2 and not (labels & _MULTI_INSTANCE_LABELS):
                continue
            common = frozenset.intersection(
                *[a.held for a in accesses]
            )
            if common:
                continue
            accesses.sort(
                key=lambda a: (a.module.rel, a.node.lineno, a.node.col_offset)
            )
            site = next(
                (a for a in accesses if not a.held),
                next((a for a in accesses if a.write), accesses[0]),
            )
            other = next(
                (
                    a
                    for a in accesses
                    if self.labels_of(a.func) != self.labels_of(site.func)
                ),
                next((a for a in accesses if a is not site), site),
            )
            kind = "attribute" if var[0] == "attr" else "module global"
            vlabel = (
                f"{var[1]}.{var[2]}" if var[0] == "attr" else var[2]
            )
            held_desc = (
                "no lock"
                if not site.held
                else "{" + ", ".join(
                    sorted(l.label for l in site.held)
                ) + "}"
            )
            self.events.append(
                Event(
                    kind="shared-state-race",
                    module=site.module,
                    line=site.node.lineno,
                    col=site.node.col_offset,
                    message=(
                        f"{kind} `{vlabel}` is accessed by thread "
                        f"classes {sorted(labels)} with no common lock "
                        f"(this access in `{site.func.qualname}` holds "
                        f"{held_desc}; see also "
                        f"`{other.func.qualname}` at "
                        f"{other.module.rel}:{other.node.lineno}) — "
                        "guard every access with one shared lock, hand "
                        "the value off through a queue/Event seam, or "
                        "mark an intentional lock-free publish with "
                        "utils.guards.published(...)"
                    ),
                )
            )

    # -------------------------------------------------------- R11 events

    def _transitive_acquires(self) -> Dict[int, Set[LockId]]:
        acq: Dict[int, Set[LockId]] = {}
        callees: Dict[int, Set[int]] = {}
        for fid, s in self.summaries.items():
            acq[fid] = {lock for lock, _, _ in s.acquires}
            callees[fid] = {
                id(callee) for callee, _, _ in s.calls
                if id(callee) in self.summaries
            }
        changed = True
        while changed:
            changed = False
            for fid, outs in callees.items():
                cur = acq[fid]
                before = len(cur)
                for o in outs:
                    cur |= acq.get(o, set())
                if len(cur) != before:
                    changed = True
        return acq

    def _collect_order_events(self) -> None:
        trans = self._transitive_acquires()
        # (a, b) -> (module, node, via description)
        edges: Dict[Tuple[LockId, LockId], Tuple[object, ast.AST, str]] = {}

        def add_edge(a: LockId, b: LockId, module, node, via: str) -> None:
            if a == b and a.reentrant:
                return
            edges.setdefault((a, b), (module, node, via))

        for fi, s, aug in self._iter_summaries():
            for lock, held, node in s.acquires:
                for h in held | aug:
                    add_edge(h, lock, fi.module, node, "")
            for callee, held, node in s.calls:
                eff = held | aug
                if not eff:
                    continue
                for b in trans.get(id(callee), ()):
                    for h in eff:
                        add_edge(
                            h, b, fi.module, node,
                            f" via `{callee.qualname}()`",
                        )
        if not edges:
            return
        graph: Dict[LockId, Set[LockId]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        for scc in _sccs(graph):
            cyclic = len(scc) > 1 or any(
                (a, a) in edges for a in scc
            )
            if not cyclic:
                continue
            members = sorted(scc)
            cycle_edges = sorted(
                (
                    ((a, b), edges[(a, b)])
                    for (a, b) in edges
                    if a in scc and b in scc
                ),
                key=lambda e: (e[1][0].rel, e[1][1].lineno),
            )
            (a0, b0), (mod0, node0, via0) = cycle_edges[0]
            chain = " -> ".join(l.label for l in members + [members[0]])
            sites = "; ".join(
                f"{a.label}->{b.label}{via} at {m.rel}:{n.lineno}"
                for (a, b), (m, n, via) in cycle_edges
            )
            self.events.append(
                Event(
                    kind="lock-order-cycle",
                    module=mod0,
                    line=node0.lineno,
                    col=node0.col_offset,
                    message=(
                        f"lock-acquisition-order cycle {chain} — two "
                        "threads taking these locks in opposite orders "
                        f"deadlock (edges: {sites}); impose one global "
                        "acquisition order (the DESIGN.md lock catalog "
                        "ranks them) or collapse to a single lock"
                    ),
                )
            )

    # -------------------------------------------------------- R12 events

    def _surface(
        self, fid: int, memo: Dict[int, List[str]], visiting: Set[int]
    ) -> List[str]:
        """Blocking descriptions reachable from a function along paths
        that hold NO additional lock (those already reported in place)."""
        if fid in memo:
            return memo[fid]
        if fid in visiting:
            return []
        visiting.add(fid)
        s = self.summaries.get(fid)
        aug = self.entry_held.get(fid, frozenset())
        out: List[str] = []
        if s is not None:
            for desc, _, held in s.blocking:
                if not (held | aug):
                    out.append(desc)
            for callee, held, _ in s.calls:
                if (held | aug) or id(callee) not in self.summaries:
                    continue
                for desc in self._surface(id(callee), memo, visiting):
                    out.append(f"{desc} (via `{callee.qualname}()`)")
        visiting.discard(fid)
        memo[fid] = out[:4]
        return memo[fid]

    def _collect_blocking_events(self) -> None:
        memo: Dict[int, List[str]] = {}
        seen = set()

        def emit(module, node, held, desc):
            key = (id(module), node.lineno, node.col_offset)
            if key in seen:
                return
            seen.add(key)
            locks = ", ".join(sorted(l.label for l in held))
            self.events.append(
                Event(
                    kind="blocking-under-lock",
                    module=module,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"{desc} while holding {{{locks}}} — every "
                        "thread contending on the lock waits out the "
                        "blocking call (the webhook-hang bug class); "
                        "snapshot state under the lock, release it, "
                        "then block"
                    ),
                )
            )

        for fi, s, aug in self._iter_summaries():
            for desc, node, held in s.blocking:
                if held | aug:
                    emit(fi.module, node, held | aug, desc)
            for callee, held, node in s.calls:
                eff = held | aug
                if not eff:
                    continue
                surface = self._surface(id(callee), memo, set())
                if surface:
                    emit(
                        fi.module, node, eff,
                        f"`{callee.qualname}()` reaches {surface[0]}",
                    )


def _sccs(graph: Dict[LockId, Set[LockId]]) -> List[Set[LockId]]:
    """Tarjan strongly-connected components (iterative)."""
    index: Dict[LockId, int] = {}
    low: Dict[LockId, int] = {}
    on_stack: Set[LockId] = set()
    stack: List[LockId] = []
    out: List[Set[LockId]] = []
    counter = [0]

    def strongconnect(root: LockId) -> None:
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])
            if low[v] == index[v]:
                scc: Set[LockId] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == v:
                        break
                out.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return out


class _LockWalker:
    """Held-lockset walk over one function body."""

    def __init__(
        self, analysis: LockAnalysis, fi: FuncInfo, root=None
    ):
        self.la = analysis
        self.fi = fi
        self.module = fi.module
        self.summary = _FuncSummary()
        self.nested: List[_FuncSummary] = []
        self._root = root if root is not None else fi.node
        self._global_decls: Set[str] = set()
        self._shadowed: Set[str] = set()
        for node in ast.walk(self._root):
            if isinstance(node, ast.Global):
                self._global_decls.update(node.names)
        for node in ast.walk(self._root):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store
            ):
                if node.id not in self._global_decls:
                    self._shadowed.add(node.id)

    def run(self) -> None:
        self._walk(self._root.body, frozenset())

    # ------------------------------------------------------------- walk

    def _walk(
        self, stmts: Iterable[ast.stmt], held: FrozenSet[LockId]
    ) -> FrozenSet[LockId]:
        for stmt in stmts:
            held = self._stmt(stmt, held)
        return held

    def _stmt(self, stmt, held: FrozenSet[LockId]) -> FrozenSet[LockId]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs run later (callbacks, thunks): no lexically
            # enclosing lock is held when they execute, and their
            # acquires/blocking must not join THIS function's
            # transitive summary — they get a deferred summary of
            # their own (attributed to the enclosing function for
            # thread-classification purposes).
            inner = _LockWalker(self.la, self.fi, root=stmt)
            inner.run()
            self.nested.append(inner.summary)
            self.nested.extend(inner.nested)
            return held
        if isinstance(stmt, ast.ClassDef):
            return held
        if isinstance(stmt, ast.With):
            inner = held
            for item in stmt.items:
                self._scan(item.context_expr, held)
                lock = self.la.lock_for(self.fi, item.context_expr)
                if lock is not None:
                    self.summary.acquires.append(
                        (lock, inner, item.context_expr)
                    )
                    inner = inner | {lock}
            self._walk(stmt.body, inner)
            return held
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan(stmt.test, held)
            self._walk(stmt.body, held)
            self._walk(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.For):
            self._scan(stmt.iter, held)
            self._scan(stmt.target, held)
            self._walk(stmt.body, held)
            self._walk(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.Try):
            self._walk(stmt.body, held)
            for h in stmt.handlers:
                self._walk(h.body, held)
            self._walk(stmt.orelse, held)
            self._walk(stmt.finalbody, held)
            return held
        # Plain statement: scan expressions, then apply any
        # acquire()/release() effect to the set held AFTERWARDS.
        self._scan(stmt, held)
        for node in self._nodes(stmt):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            if node.func.attr == "acquire":
                lock = self.la.lock_for(self.fi, node.func.value)
                if lock is not None:
                    self.summary.acquires.append((lock, held, node))
                    held = held | {lock}
            elif node.func.attr == "release":
                lock = self.la.lock_for(self.fi, node.func.value)
                if lock is not None:
                    held = held - {lock}
        return held

    @staticmethod
    def _nodes(root):
        """Walk a statement/expression, not descending into nested
        function/class definitions (handled at statement level)."""
        stack = [root]
        while stack:
            n = stack.pop()
            yield n
            for c in ast.iter_child_nodes(n):
                if isinstance(
                    c,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                ):
                    continue
                stack.append(c)

    # ------------------------------------------------------------- scan

    def _scan(self, root, held: FrozenSet[LockId]) -> None:
        for node in self._nodes(root):
            if isinstance(node, ast.Call):
                target = self.la.resolve_call(self.fi, node.func)
                if target is not None and target is not self.fi:
                    self.summary.calls.append((target, held, node))
                desc = self._blocking_desc(node, held)
                if desc is not None:
                    self.summary.blocking.append((desc, node, held))
            found = self.la._race_checked_var(self.fi, node)
            if found is not None:
                var, write = found
                if (
                    var[0] == "global"
                    and var[2] in self._shadowed
                ):
                    continue
                if isinstance(node, ast.Name):
                    if write and node.id not in self._global_decls:
                        continue  # plain local assignment
                self.summary.accesses.append(
                    _Access(
                        var=var,
                        write=write,
                        module=self.module,
                        node=node,
                        held=held,
                        func=self.fi,
                    )
                )

    # -------------------------------------------------- blocking matcher

    def _blocking_desc(
        self, call: ast.Call, held: FrozenSet[LockId]
    ) -> Optional[str]:
        name = _call_name(call.func)
        if name is None:
            return None
        dotted = self.module.dotted(call.func)
        if name == "sleep":
            return "`time.sleep`-style blocking sleep"
        if name in ("urlopen", "getresponse", "create_connection"):
            return f"HTTP/socket I/O (`{name}`)"
        if name == "fsync" or name.startswith("atomic_write"):
            return f"fsync/atomic write (`{name}`)"
        if (dotted or "").startswith("subprocess.") or name in (
            "communicate",
            "check_call",
            "check_output",
        ):
            return f"subprocess wait (`{name}`)"
        if name == "result" and isinstance(call.func, ast.Attribute):
            return "`Future.result()` wait"
        if (
            name == "join"
            and isinstance(call.func, ast.Attribute)
            and not call.args
        ):
            return "`join()` wait"
        if name == "wait" and isinstance(call.func, ast.Attribute):
            recv = self.la.lock_for(self.fi, call.func.value)
            if recv is not None and recv in held:
                return None  # Condition.wait releases the held lock
            return "`wait()` on an event/future"
        if name in _DEVICE_BLOCKING_NAMES:
            return f"device dispatch/fetch seam (`{name}()`)"
        return None
