from .synthetic import (
    SyntheticCase,
    SyntheticConfig,
    SyntheticTimeline,
    Topology,
    generate_case,
    generate_case_with_spans,
    generate_timeline,
    generate_timeline_with_spans,
)

__all__ = [
    "SyntheticCase",
    "SyntheticConfig",
    "SyntheticTimeline",
    "Topology",
    "generate_case",
    "generate_case_with_spans",
    "generate_timeline",
    "generate_timeline_with_spans",
]
