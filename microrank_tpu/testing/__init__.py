from .synthetic import (
    SyntheticCase,
    SyntheticConfig,
    Topology,
    generate_case,
    generate_case_with_spans,
)

__all__ = [
    "SyntheticCase",
    "SyntheticConfig",
    "Topology",
    "generate_case",
    "generate_case_with_spans",
]
