from .synthetic import SyntheticCase, SyntheticConfig, Topology, generate_case

__all__ = ["SyntheticCase", "SyntheticConfig", "Topology", "generate_case"]
