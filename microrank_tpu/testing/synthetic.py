"""Synthetic microservice trace generator with fault injection.

The reference's evaluation data comes from chaos experiments against live
k8s testbeds harvested by collect_data.py; nothing ships with the repo, so
the new framework gets a first-class generator (SURVEY.md §4 item 3, §5
fault-injection row): a random service call tree, a small set of "trace
kinds" (pruned subtrees — real systems exhibit few distinct trace shapes,
which is exactly what the reference's kind-dedup exploits), lognormal
per-operation service times, and *inclusive* span durations (a parent span
covers its children), so the reference's trace-duration-=-max-span rule
(preprocess_data.py:110) picks the root span.

Fault injection adds latency to one (service, pod) operation during the
abnormal window; the inclusive-duration computation propagates it to all
ancestors, giving the detector a real signal. Output DataFrames follow the
canonical span schema (microrank_tpu.io.schema) byte-for-byte.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np
import pandas as pd


@dataclass(frozen=True)
class SyntheticConfig:
    n_operations: int = 40
    n_pods: int = 1            # pods per service (instance-level RCA when >1)
    n_kinds: int = 8           # distinct trace shapes
    child_keep_prob: float = 0.8
    n_traces: int = 200
    mean_own_ms_range: Tuple[float, float] = (1.0, 20.0)
    sigma_log: float = 0.3
    # Expected duration is the sum of *inclusive* per-span SLOs (+k*sigma
    # each), so the detector's margin is large by construction; the injected
    # latency must clear it (see tests/test_detector.py).
    fault_latency_ms: float = 2000.0
    # Simultaneous faults in the abnormal window (paper dataset B uses 2).
    n_faults: int = 1
    # Fault-separation control (multi-fault hardness ablation): target
    # root-path overlap between the injected faults, as the overlap
    # coefficient |P_a ∩ P_b| / min(|P_a|, |P_b|) over root-to-op paths
    # with the root excluded. 0.0 places the faults on disjoint call
    # paths (cleanly separable spectra), 1.0 makes one fault an ancestor
    # of the other (its counters are fully masked by latency
    # propagation). None (default) keeps the historical unconstrained
    # random choice.
    fault_path_overlap: Optional[float] = None
    # Fault family. "latency" adds fault_latency_ms to the faulted
    # (op, pod) own time (the paper's chaos shape). "error" models a
    # status-code fault instead: the faulted span FAILS — its own time
    # collapses to error_duration_factor of the sampled value (fail
    # fast) and a ``statusCode`` column is emitted with the error bit
    # set on the faulted span and propagated to every ancestor span
    # (callers observe the failure) — no latency signal at all, so only
    # a status-aware detector can see it.
    fault_kind: str = "latency"
    error_duration_factor: float = 0.25
    # Cascading downstream propagation (latency faults): every ancestor
    # of a faulted op ALSO gains own-time latency fault_latency_ms *
    # cascade_fraction**depth in ALL traces passing through it — the
    # backpressure shape, where traces that never touch the culprit
    # still slow at shared upstream services (abnormal traces without
    # culprit coverage degrade the spectrum counters; this is the
    # irreducible hardness of the cascade family). 0 disables.
    cascade_fraction: float = 0.0
    # Baseline drift (timelines only): multiplicative own-time growth
    # per window — window i renders at (1 + drift_per_window)**i. A
    # gradual SLO shift the online baseline must absorb (retrain), not
    # alarm on. 0 disables.
    drift_per_window: float = 0.0
    window_minutes: float = 5.0
    seed: int = 0


def _op_id_width(n_operations: int) -> int:
    return max(3, len(str(max(n_operations - 1, 0))))


def _pod_op_name(op: int, pod: int, n_operations: int) -> str:
    """The instance-level (PageRank vocab) name of a (service op, pod)."""
    w = _op_id_width(n_operations)
    return f"svc{op:0{w}d}-{pod}_op{op:0{w}d}"


def _root_path(parent: np.ndarray, op: int) -> frozenset:
    """Ops on the root→op call path, the root itself excluded (every
    path shares the root, so including it would floor the overlap)."""
    out = []
    o = int(op)
    while o > 0:
        out.append(o)
        o = int(parent[o])
    return frozenset(out)


def path_overlap(parent: np.ndarray, a: int, b: int) -> float:
    """Overlap coefficient of two ops' root paths: |Pa ∩ Pb| / min(|Pa|,
    |Pb|). 0 = disjoint paths (share only the root); 1 = one op lies on
    the other's path (ancestor/descendant)."""
    pa, pb = _root_path(parent, a), _root_path(parent, b)
    return len(pa & pb) / max(min(len(pa), len(pb)), 1)


def _pick_faults(
    topo: "Topology",
    rng: np.random.Generator,
    n_pods: int,
    n_faults: int,
    target_overlap: Optional[float] = None,
):
    """Fault candidates: ops covered by >=1 kind, excluding the root (the
    root is trivially always the top anomaly otherwise).

    With ``target_overlap`` set and >=2 faults, ops are chosen so their
    mean pairwise ``path_overlap`` tracks the target: the best pair over
    all candidate pairs seeds the set, then greedy additions minimize the
    deviation. ``None`` keeps the historical unconstrained choice (so
    fixed-seed cases generated before this control exist unchanged).
    """
    covered = np.unique(np.concatenate(topo.kinds))
    candidates = covered[covered != 0]
    if len(candidates) == 0:
        candidates = covered
    n_faults = min(n_faults, len(candidates))
    if target_overlap is None or n_faults < 2:
        fault_ops = rng.choice(candidates, size=n_faults, replace=False)
        return [(int(op), int(rng.integers(0, n_pods))) for op in fault_ops]

    cand = [int(c) for c in candidates]
    # The pair seed below enumerates all O(n^2) candidate pairs; at eval
    # scale (5k ops) that is ~12M tuples and dominates case generation.
    # The greedy selection only needs a good pair, not the global argmin,
    # so bound the pool — 512 candidates is ~131k pairs. Small cases
    # (every fixed-seed case generated before this cap) are unaffected:
    # the rng is only consumed when the cap engages.
    pool_cap = max(512, n_faults)
    if len(cand) > pool_cap:
        cand = sorted(
            int(c) for c in rng.choice(cand, size=pool_cap, replace=False)
        )
    # Root paths once per candidate — the pair loop below is O(n^2) pair
    # set-intersections, not O(n^2 * depth) parent-pointer walks.
    paths = {c: _root_path(topo.parent, c) for c in cand}

    def overlap(a: int, b: int) -> float:
        pa, pb = paths[a], paths[b]
        return len(pa & pb) / max(min(len(pa), len(pb)), 1)

    pairs = [
        (a, b) for i, a in enumerate(cand) for b in cand[i + 1:]
    ]
    dev = np.array(
        [abs(overlap(a, b) - target_overlap) for a, b in pairs]
    )
    best = np.flatnonzero(dev == dev.min())
    chosen = list(pairs[int(rng.choice(best))])
    remaining = [c for c in cand if c not in chosen]
    while len(chosen) < n_faults and remaining:
        devs = np.array(
            [
                abs(
                    float(np.mean([overlap(c, x) for x in chosen]))
                    - target_overlap
                )
                for c in remaining
            ]
        )
        best = np.flatnonzero(devs == devs.min())
        pick = remaining[int(rng.choice(best))]
        chosen.append(pick)
        remaining.remove(pick)
    return [(int(op), int(rng.integers(0, n_pods))) for op in chosen]


def _ancestor_depths(parent: np.ndarray, op: int) -> dict:
    """{ancestor op: depth} walking parent pointers from ``op`` (depth 1
    = direct parent), root included, ``op`` itself excluded."""
    out = {}
    o, d = int(parent[int(op)]), 1
    while o >= 0:
        out[o] = d
        o, d = int(parent[o]), d + 1
    return out


def achieved_overlap(
    parent: np.ndarray, faults: List[Tuple[int, int]]
) -> Optional[float]:
    """Mean pairwise root-path overlap of the injected fault ops
    (None for single-fault cases)."""
    ops = [op for op, _ in faults]
    if len(ops) < 2:
        return None
    vals = [
        path_overlap(parent, a, b)
        for i, a in enumerate(ops)
        for b in ops[i + 1:]
    ]
    return float(np.mean(vals))


@dataclass
class Topology:
    parent: np.ndarray          # int [n_ops], parent[0] = -1
    mean_own_ms: np.ndarray     # float [n_ops]
    kinds: List[np.ndarray]     # each: topo-ordered op ids forming a subtree
    kind_parent_pos: List[np.ndarray]  # position of op's parent within kind


def _make_topology(cfg: SyntheticConfig, rng: np.random.Generator) -> Topology:
    n = cfg.n_operations
    parent = np.full(n, -1, dtype=np.int64)
    for i in range(1, n):
        parent[i] = rng.integers(0, i)
    mean_own = rng.uniform(*cfg.mean_own_ms_range, size=n)

    kinds = []
    kind_parent_pos = []
    for k in range(cfg.n_kinds):
        keep = np.zeros(n, dtype=bool)
        keep[0] = True
        for i in range(1, n):
            keep[i] = keep[parent[i]] and (
                rng.random() < cfg.child_keep_prob
            )
        ops = np.flatnonzero(keep)  # ascending == topological (parent < child)
        pos = {int(o): j for j, o in enumerate(ops)}
        ppos = np.array(
            [pos[int(parent[o])] if parent[o] >= 0 else -1 for o in ops],
            dtype=np.int64,
        )
        kinds.append(ops)
        kind_parent_pos.append(ppos)
    return Topology(parent, mean_own, kinds, kind_parent_pos)


def _render_spans(
    topo: Topology,
    cfg: SyntheticConfig,
    rng: np.random.Generator,
    n_traces: int,
    t0: pd.Timestamp,
    faults: Optional[List[Tuple[int, int]]],  # (op, pod) pairs
    trace_prefix: str,
    scale: float = 1.0,
) -> pd.DataFrame:
    kind_of_trace = rng.integers(0, len(topo.kinds), size=n_traces)
    start_offsets_us = np.sort(
        rng.uniform(0, cfg.window_minutes * 60e6, size=n_traces)
    ).astype(np.int64)

    error_fault = cfg.fault_kind == "error"
    # Ancestor depth maps (one parent-pointer walk per fault) for error
    # propagation and latency cascades; computed outside the kind loop.
    anc_depths = (
        {op: _ancestor_depths(topo.parent, op) for op, _ in faults}
        if faults
        else {}
    )
    blocks = []
    for k, ops in enumerate(topo.kinds):
        t_idx = np.flatnonzero(kind_of_trace == k)
        if len(t_idx) == 0:
            continue
        m = len(ops)
        mu = np.log(topo.mean_own_ms[ops])
        own_ms = rng.lognormal(
            mean=mu[None, :], sigma=cfg.sigma_log, size=(len(t_idx), m)
        )
        if scale != 1.0:
            own_ms *= scale
        # Pod assignment per (trace, op).
        pods = rng.integers(0, cfg.n_pods, size=(len(t_idx), m))
        status = np.zeros((len(t_idx), m), dtype=np.int64)
        if faults:
            pos = {int(o): j for j, o in enumerate(ops)}
            for fault_op, fault_pod in faults:
                j = pos.get(int(fault_op))
                if j is not None:
                    hit = pods[:, j] == fault_pod
                    if error_fault:
                        # Fail-fast: the span errors instead of slowing.
                        own_ms[:, j] = np.where(
                            hit,
                            own_ms[:, j] * cfg.error_duration_factor,
                            own_ms[:, j],
                        )
                        status[:, j] |= hit.astype(np.int64)
                    else:
                        own_ms[:, j] += np.where(
                            hit, cfg.fault_latency_ms, 0.0
                        )
                if not error_fault and cfg.cascade_fraction > 0.0:
                    # Backpressure cascade: ancestors slow in EVERY
                    # trace through them, culprit-covering or not.
                    for anc, depth in anc_depths[fault_op].items():
                        ja = pos.get(anc)
                        if ja is not None:
                            own_ms[:, ja] += (
                                cfg.fault_latency_ms
                                * cfg.cascade_fraction ** depth
                            )
        # Inclusive durations: add each op's total into its parent,
        # deepest-first (ops are topo-ordered). Error status propagates
        # up the same call chain: callers observe the failure.
        dur_ms = own_ms.copy()
        ppos = topo.kind_parent_pos[k]
        for j in range(m - 1, 0, -1):
            dur_ms[:, ppos[j]] += dur_ms[:, j]
            if error_fault:
                status[:, ppos[j]] |= status[:, j]

        nt = len(t_idx)
        trace_rows = np.repeat(t_idx, m)
        op_rows = np.tile(ops, nt)
        pod_rows = pods.reshape(-1)
        dur_rows = (dur_ms.reshape(-1) * 1000.0).astype(np.int64)  # µs
        root_dur_us = np.repeat((dur_ms[:, 0] * 1000.0).astype(np.int64), m)
        parent_rows = np.tile(topo.parent[ops], nt)
        blocks.append(
            (
                trace_rows, op_rows, pod_rows, dur_rows, root_dur_us,
                parent_rows, status.reshape(-1),
            )
        )

    trace_rows = np.concatenate([b[0] for b in blocks])
    op_rows = np.concatenate([b[1] for b in blocks])
    pod_rows = np.concatenate([b[2] for b in blocks])
    dur_rows = np.concatenate([b[3] for b in blocks])
    root_dur_us = np.concatenate([b[4] for b in blocks])
    parent_rows = np.concatenate([b[5] for b in blocks])
    status_rows = np.concatenate([b[6] for b in blocks])

    trace_str = np.char.add(trace_prefix, trace_rows.astype(np.str_))
    op_str = op_rows.astype(np.str_)
    span_id = np.char.add(np.char.add(trace_str, "-s"), op_str)
    has_parent = parent_rows >= 0
    parent_id = np.where(
        has_parent,
        np.char.add(
            np.char.add(trace_str, "-s"),
            np.where(has_parent, parent_rows, 0).astype(np.str_),
        ),
        "",
    )
    # np.char.zfill allocates exactly `width` chars and TRUNCATES longer
    # ids, so the width must cover the largest op id.
    width = _op_id_width(cfg.n_operations)
    svc = np.char.add("svc", np.char.zfill(op_str, width))
    opname = np.char.add("op", np.char.zfill(op_str, width))
    pod = np.char.add(np.char.add(svc, "-"), pod_rows.astype(np.str_))

    start_us = start_offsets_us[trace_rows]
    start_ts = t0 + pd.to_timedelta(start_us, unit="us")
    end_ts = t0 + pd.to_timedelta(start_us + root_dur_us, unit="us")

    columns = {
        "traceID": trace_str,
        "spanID": span_id,
        "ParentSpanId": parent_id,
        "operationName": opname,
        "serviceName": svc,
        "podName": pod,
        "duration": dur_rows,
        "startTime": start_ts,
        "endTime": end_ts,
    }
    if error_fault:
        # Optional status column (0 = OK): only error-fault generators
        # emit it, so every pre-existing fixture/golden CSV is
        # byte-identical and the native lane never sees it.
        columns["statusCode"] = status_rows
    return pd.DataFrame(columns)


@dataclass
class SyntheticCase:
    normal: pd.DataFrame
    abnormal: pd.DataFrame
    fault_service_op: str     # service-level name of the (first) root cause
    fault_pod_op: str         # instance-level (PageRank vocab) name
    fault_op: int
    fault_pod: int
    topology: Topology
    faults: List[Tuple[int, int]] = field(default_factory=list)
    # Mean pairwise root-path overlap of the injected faults (None when
    # single-fault) — the hardness statistic the two-fault ablation
    # conditions on.
    fault_overlap: Optional[float] = None

    @property
    def fault_pod_ops(self) -> List[str]:
        """Instance-level names of every injected root cause."""
        n_ops = int(self.topology.parent.shape[0])
        return [_pod_op_name(op, pod, n_ops) for op, pod in self.faults]


def _traces_for_spans(cfg: SyntheticConfig, target_spans: int) -> int:
    """Trace count whose expected span total is ~``target_spans``: build
    the (deterministic, seed-keyed) topology once to measure the mean
    trace-kind size. The caller's generator rebuilds the same topology
    from the same seed, so the estimate matches what it will render."""
    rng = np.random.default_rng(cfg.seed)
    topo = _make_topology(cfg, rng)
    mean_kind = float(np.mean([len(k) for k in topo.kinds]))
    return max(1, int(round(target_spans / max(mean_kind, 1.0))))


def generate_case_with_spans(
    cfg: SyntheticConfig, target_spans: int
) -> SyntheticCase:
    """Generate a case whose windows hold ~``target_spans`` spans each —
    the knob bench configs are specified in (BASELINE.json: "1M-span /
    5k-operation window")."""
    n_traces = _traces_for_spans(cfg, target_spans)
    return generate_case(
        SyntheticConfig(**{**cfg.__dict__, "n_traces": n_traces})
    )


@dataclass
class SyntheticTimeline:
    """A multi-window replay: one normal baseline window plus
    ``n_windows`` consecutive windows, a subset of which carry the fault —
    the shape of the paper's anomaly-detection experiment (Fig. 9:
    per-window precision/recall/F1)."""

    normal: pd.DataFrame
    timeline: pd.DataFrame
    window_faulted: List[bool]
    window_minutes: float
    start: pd.Timestamp          # first timeline window's start
    fault_pod_op: str
    # Full injected culprit SET (instance-level names) — multi-fault
    # timelines need every culprit for well-defined scoring;
    # fault_pod_op stays the first for back compat.
    fault_pod_ops: List[str] = field(default_factory=list)


def generate_timeline(
    cfg: SyntheticConfig,
    n_windows: int,
    faulted: List[int],
) -> SyntheticTimeline:
    """Generate a continuous ``n_windows``-window trace stream where the
    windows listed in ``faulted`` carry the injected fault(s) —
    ``cfg.n_faults`` simultaneous culprits of ``cfg.fault_kind`` — and
    the rest are clean. ``cfg.n_traces`` applies per window. With
    ``cfg.drift_per_window`` set, window i renders all own times scaled
    by ``(1 + drift)**i`` (gradual SLO shift, no fault needed)."""
    rng = np.random.default_rng(cfg.seed)
    topo = _make_topology(cfg, rng)
    faults = _pick_faults(
        topo, rng, cfg.n_pods, cfg.n_faults, cfg.fault_path_overlap
    )
    fault_op, fault_pod = faults[0]

    t0 = pd.Timestamp("2025-02-14 12:00:00")
    t1 = t0 + pd.Timedelta(minutes=cfg.window_minutes)
    normal = _render_spans(topo, cfg, rng, cfg.n_traces, t0, None, "n")
    fault_set = set(faulted)
    frames = []
    flags = []
    for i in range(n_windows):
        ti = t1 + pd.Timedelta(minutes=i * cfg.window_minutes)
        is_faulted = i in fault_set
        frames.append(
            _render_spans(
                topo, cfg, rng, cfg.n_traces, ti,
                faults if is_faulted else None, f"w{i}x",
                scale=(1.0 + cfg.drift_per_window) ** i,
            )
        )
        flags.append(is_faulted)
    return SyntheticTimeline(
        normal=normal,
        timeline=pd.concat(frames, ignore_index=True),
        window_faulted=flags,
        window_minutes=cfg.window_minutes,
        start=t1,
        fault_pod_op=_pod_op_name(fault_op, fault_pod, cfg.n_operations),
        fault_pod_ops=[
            _pod_op_name(op, pod, cfg.n_operations) for op, pod in faults
        ],
    )


def generate_timeline_with_spans(
    cfg: SyntheticConfig,
    target_spans_per_window: int,
    n_windows: int,
    faulted: List[int],
) -> SyntheticTimeline:
    """generate_timeline with the per-window trace count derived from a
    spans target (same estimation as generate_case_with_spans)."""
    n_traces = _traces_for_spans(cfg, target_spans_per_window)
    return generate_timeline(
        SyntheticConfig(**{**cfg.__dict__, "n_traces": n_traces}),
        n_windows,
        faulted,
    )


def generate_case(cfg: SyntheticConfig) -> SyntheticCase:
    """One chaos case: a normal window and an abnormal window with one
    injected latency fault (the collect_data.py normal/abnormal dump pair)."""
    rng = np.random.default_rng(cfg.seed)
    topo = _make_topology(cfg, rng)
    faults = _pick_faults(
        topo, rng, cfg.n_pods, cfg.n_faults, cfg.fault_path_overlap
    )

    t0 = pd.Timestamp("2025-02-14 12:00:00")
    t1 = t0 + pd.Timedelta(minutes=cfg.window_minutes)
    normal = _render_spans(topo, cfg, rng, cfg.n_traces, t0, None, "n")
    abnormal = _render_spans(topo, cfg, rng, cfg.n_traces, t1, faults, "a")
    fault_op, fault_pod = faults[0]
    w = _op_id_width(cfg.n_operations)
    return SyntheticCase(
        normal=normal,
        abnormal=abnormal,
        fault_service_op=f"svc{fault_op:0{w}d}_op{fault_op:0{w}d}",
        fault_pod_op=_pod_op_name(fault_op, fault_pod, cfg.n_operations),
        fault_op=fault_op,
        fault_pod=fault_pod,
        topology=topo,
        faults=faults,
        fault_overlap=achieved_overlap(topo.parent, faults),
    )
