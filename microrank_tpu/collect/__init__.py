"""Optional ClickHouse chaos-case collector (gated dependency)."""
