"""Chaos-case trace collector (reference component C17, collect_data.py).

Exports OTel trace windows around chaos-injection events from ClickHouse
into the ``{case}/normal/traces.csv`` + ``{case}/abnormal/traces.csv``
layout the pipeline consumes, with a TOML manifest of the collected cases.
Optional: requires ``clickhouse_connect`` (not a core dependency); the
import is gated so the rest of the framework never needs it. Credentials
come from CLICKHOUSE_USER / CLICKHOUSE_PASSWORD env vars, as in the
reference (collect_data.py:12-13).
"""

from __future__ import annotations

import asyncio
import os
from dataclasses import dataclass
from datetime import datetime, timedelta
from pathlib import Path
from typing import List, Optional

from ..utils.logging import get_logger

log = get_logger("microrank_tpu.collect")

# Query shape mirrors the reference's projection (collect_data.py:18-55):
# span rows joined with per-trace start/end bounds, filtered by namespace.
TRACE_QUERY = """
WITH
    trace_times AS (
        SELECT TraceId, MIN(Start) AS TraceStart, MAX(End) AS TraceEnd
        FROM otel_traces_trace_id_ts
        GROUP BY TraceId
    )
SELECT
    ot.`Timestamp`, ot.TraceId, ot.SpanId, ot.ParentSpanId, ot.SpanName,
    ot.ServiceName, ResourceAttributes['pod.name'] AS PodName,
    ot.Duration, ot.SpanKind, trace_times.TraceStart, trace_times.TraceEnd
FROM otel_traces ot
LEFT JOIN trace_times ON ot.TraceId = trace_times.TraceId
WHERE ot.`Timestamp` BETWEEN '{start}' AND '{end}'
  AND ot.ResourceAttributes['service.namespace'] = '{namespace}'
"""


@dataclass
class ChaosEvent:
    timestamp: str           # "YYYY-MM-DD HH:MM:SS" injection time
    namespace: str
    chaos_type: str = ""
    service: str = ""

    @property
    def case_name(self) -> str:
        dt = datetime.strptime(self.timestamp, "%Y-%m-%d %H:%M:%S")
        return f"{self.service}-{dt.month:02d}{dt.day:02d}-{dt.hour:02d}{dt.minute:02d}"


def load_events_toml(path) -> List[ChaosEvent]:
    try:
        import tomllib  # stdlib (3.11+) — no third-party toml needed
    except ModuleNotFoundError:  # 3.10: same API under the backport name
        import tomli as tomllib

    with open(path, "rb") as f:
        data = tomllib.load(f)
    events = []
    for event in data.get("chaos_events", []):
        ts = event.get("timestamp", "")
        try:
            datetime.strptime(ts, "%Y-%m-%d %H:%M:%S")
        except ValueError:
            log.warning("invalid timestamp %r; skipping event", ts)
            continue
        events.append(
            ChaosEvent(
                timestamp=ts,
                namespace=event.get("namespace", ""),
                chaos_type=event.get("chaos_type", ""),
                service=event.get("service", ""),
            )
        )
    return events


def interactive_events(
    input_fn=input, print_fn=print
) -> List[ChaosEvent]:
    """Prompt an operator for chaos events (reference
    collect_data.py:145-172 behavior): loop until an empty timestamp;
    invalid timestamps re-prompt; each event then asks for namespace,
    chaos type, and service. ``input_fn``/``print_fn`` are injectable
    for tests."""
    events: List[ChaosEvent] = []
    try:
        while True:
            ts = input_fn(
                "Enter the timestamp for anomaly injection "
                "(YYYY-MM-DD HH:MM:SS, or press Enter to stop): "
            ).strip()
            if not ts:
                print_fn("No valid timestamp provided. Stopping input.")
                break
            try:
                datetime.strptime(ts, "%Y-%m-%d %H:%M:%S")
            except ValueError:
                print_fn("Invalid timestamp format. Please try again.")
                continue
            events.append(
                ChaosEvent(
                    timestamp=ts,
                    namespace=input_fn("Enter namespace: ").strip(),
                    chaos_type=input_fn("Enter the chaos type: ").strip(),
                    service=input_fn("Enter the service name: ").strip(),
                )
            )
    except EOFError:
        # Closed stdin mid-prompt (piped/headless use): keep whatever
        # complete events were entered instead of crashing.
        print_fn("Input closed. Stopping input.")
    return events


async def _fetch_csv(client, query: str, filepath: Path, semaphore, retries=3):
    async with semaphore:
        for attempt in range(retries):
            try:
                result = await client.raw_query(query=query, fmt="CSVWithNames")
                filepath.write_bytes(result)
                log.info("wrote %s", filepath)
                return True
            except Exception as exc:  # noqa: BLE001 — retried I/O
                log.warning(
                    "fetch failed (%d/%d): %s", attempt + 1, retries, exc
                )
        log.error("giving up on %s", filepath)
        return False


async def collect_cases(
    events: List[ChaosEvent],
    host: str,
    out_dir,
    window_minutes: int = 10,
    concurrency: int = 2,
):
    try:
        import clickhouse_connect
    except ImportError as exc:
        raise RuntimeError(
            "the collect command needs the optional clickhouse_connect "
            "dependency; install it or export traces.csv dumps another way"
        ) from exc

    client = await clickhouse_connect.create_async_client(
        host=host,
        username=os.getenv("CLICKHOUSE_USER", "default"),
        password=os.getenv("CLICKHOUSE_PASSWORD", ""),
    )
    semaphore = asyncio.Semaphore(concurrency)
    out = Path(out_dir)
    tasks = []
    for ev in events:
        t = datetime.strptime(ev.timestamp, "%Y-%m-%d %H:%M:%S")
        windows = {
            "abnormal": (t, t + timedelta(minutes=window_minutes)),
            "normal": (t - timedelta(minutes=window_minutes), t),
        }
        for kind, (w0, w1) in windows.items():
            folder = out / ev.case_name / kind
            folder.mkdir(parents=True, exist_ok=True)
            query = TRACE_QUERY.format(
                start=w0, end=w1, namespace=ev.namespace
            )
            tasks.append(
                _fetch_csv(client, query, folder / "traces.csv", semaphore)
            )
    ok = await asyncio.gather(*tasks)
    (out / "manifest.toml").write_text(manifest_toml(events))
    return all(ok)


def manifest_toml(events: List[ChaosEvent]) -> str:
    """Serialize the collected-cases manifest (all-string fields — the
    stdlib has no TOML writer, and pulling in the third-party ``toml``
    package for this shape is not worth the dependency)."""

    def esc(s: str) -> str:
        out = []
        for ch in s:
            if ch == "\\":
                out.append("\\\\")
            elif ch == '"':
                out.append('\\"')
            elif ch == "\n":
                out.append("\\n")
            elif ch == "\r":
                out.append("\\r")
            elif ch == "\t":
                out.append("\\t")
            elif ord(ch) < 0x20 or ch == "\x7f":
                out.append(f"\\u{ord(ch):04X}")
            else:
                out.append(ch)
        return "".join(out)

    lines = []
    for ev in events:
        lines.append("[[chaos_injection]]")
        for k, v in (
            ("case", ev.case_name),
            ("timestamp", ev.timestamp),
            ("namespace", ev.namespace),
            ("chaos_type", ev.chaos_type),
            ("service", ev.service),
        ):
            lines.append(f'{k} = "{esc(v)}"')
        lines.append("")
    return "\n".join(lines)


def run_collect(args) -> int:
    if args.config_toml:
        events = load_events_toml(args.config_toml)
    else:
        # The reference's fallback when no TOML exists
        # (collect_data.py:185-187): prompt the operator for events —
        # but only on a real terminal; headless invocations keep the
        # old clean error instead of hanging on (or crashing over) a
        # non-interactive stdin.
        import sys

        if not sys.stdin.isatty():
            log.error(
                "--config-toml is required when stdin is not a terminal"
            )
            return 2
        log.info("no --config-toml given; switching to interactive input")
        events = interactive_events()
    if not events:
        log.error("no chaos events to collect")
        return 2
    ok = asyncio.run(
        collect_cases(
            events,
            args.host,
            args.output,
            window_minutes=args.window_minutes,
            concurrency=args.concurrency,
        )
    )
    return 0 if ok else 1
