"""Declarative scenario specs: every fault family the matrix covers.

The paper's evaluation (and the repo's replay bench) exercises ONE
fault family — a large latency fault on a single op — and the 8/8
fault-top-1 headline reflects exactly that. A ``ScenarioSpec`` names a
*family* (what kind of failure), an *intensity* (how hard it hits), a
*topology* (how big/deep the service graph is) and a *timing* (which
windows carry it), and compiles — via the seeded synthetic generator —
into a reproducible span workload with ground-truth culprit labels.

Families (``FAMILIES``):

* ``latency``    — the paper's shape: one op's own time jumps.
* ``error``      — status-code fault: the op FAILS FAST (no latency
  signal at all; only the error-status detector path can see it).
* ``multi``      — 2+ simultaneous culprits on separated call paths;
  scoring is against the full culprit SET.
* ``cascade``    — latency fault plus backpressure: ancestors slow in
  EVERY trace, so abnormal traces exist that never touch the culprit.
* ``cold_start`` — the fault is already burning while the stream
  engine's online baseline is still warming up (no --normal seed).
* ``drift``      — no fault: a gradual SLO shift the baseline must
  absorb (retrain) without opening an incident.
* ``hostile``    — a latency fault UNDER DIRTY DATA: the compiled
  timeline is corrupted with the ``hostile_classes`` mix
  (ingest.hostile — unparseable rows, duplicate spans, orphans, clock
  skew, a cardinality bomb); the admission ladder must contain the
  corruption and the fault window must still rank the true culprit on
  the clean subset. This is the family the policy engine scores
  formulas under dirty data with.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import List, Optional, Tuple

FAMILIES = (
    "latency", "error", "multi", "cascade", "cold_start", "drift",
    "hostile",
)


@dataclass(frozen=True)
class ScenarioSpec:
    """One reproducible scenario: family + intensity + topology + timing.

    Pure data — :func:`scenarios.generate.generate_scenario` compiles it
    into span frames; the same spec (same seed) always yields a
    byte-identical span stream.
    """

    name: str
    family: str
    seed: int = 0
    # Timing: timeline length and which windows carry the fault(s).
    n_windows: int = 8
    faulted: Tuple[int, ...] = (3, 4)
    # Topology.
    n_operations: int = 24
    n_pods: int = 1
    n_kinds: int = 16
    n_traces: int = 200
    child_keep_prob: float = 0.8
    window_minutes: float = 5.0
    # Intensity / family knobs.
    fault_latency_ms: float = 2000.0
    n_faults: int = 1
    fault_kind: str = "latency"          # "latency" | "error"
    fault_path_overlap: Optional[float] = None
    cascade_fraction: float = 0.0
    error_duration_factor: float = 0.25
    drift_per_window: float = 0.0
    # Hostile family: corruption classes applied to the compiled
    # timeline (ingest.hostile.CORRUPTION_KINDS subset; the normal
    # baseline window stays clean), the corrupted row fraction per
    # class, and the cardinality bomb's unique-op count.
    hostile_classes: Tuple[str, ...] = ()
    hostile_fraction: float = 0.05
    hostile_bomb_ops: int = 64
    # Stream-lane shape: seed the online baseline from the generator's
    # normal window (False = the cold-start family — the engine warms
    # up from the live stream while the fault may already be burning).
    seed_baseline: bool = True

    def __post_init__(self):
        if self.family not in FAMILIES:
            raise ValueError(
                f"unknown scenario family {self.family!r}; "
                f"expected one of {FAMILIES}"
            )

    def synth_config(self):
        """The seeded SyntheticConfig this spec compiles through."""
        from ..testing import SyntheticConfig

        return SyntheticConfig(
            n_operations=self.n_operations,
            n_pods=self.n_pods,
            n_kinds=self.n_kinds,
            child_keep_prob=self.child_keep_prob,
            n_traces=self.n_traces,
            fault_latency_ms=self.fault_latency_ms,
            n_faults=self.n_faults,
            fault_kind=self.fault_kind,
            fault_path_overlap=self.fault_path_overlap,
            cascade_fraction=self.cascade_fraction,
            error_duration_factor=self.error_duration_factor,
            drift_per_window=self.drift_per_window,
            window_minutes=self.window_minutes,
            seed=self.seed,
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def default_matrix(seed: int = 0, full: bool = False) -> List[ScenarioSpec]:
    """The standard scenario matrix: one spec per family (the CI smoke
    shape), plus a harder variant per family with ``full=True``. Every
    spec's seed derives from the ONE matrix seed, so the whole matrix is
    reproducible from a single integer."""

    def s(i: int) -> int:
        return seed * 1009 + i

    specs = [
        ScenarioSpec(
            name="latency-basic", family="latency", seed=s(1),
        ),
        ScenarioSpec(
            name="error-failfast", family="error", seed=s(2),
            fault_kind="error",
        ),
        ScenarioSpec(
            name="multi-disjoint", family="multi", seed=s(3),
            n_faults=2, fault_path_overlap=0.0, n_operations=30,
        ),
        ScenarioSpec(
            name="cascade-backpressure", family="cascade", seed=s(4),
            cascade_fraction=0.5, n_operations=30,
        ),
        ScenarioSpec(
            name="coldstart-early-fault", family="cold_start", seed=s(5),
            faulted=(2, 3), seed_baseline=False,
        ),
        ScenarioSpec(
            name="drift-slo-shift", family="drift", seed=s(6),
            faulted=(), drift_per_window=0.05,
        ),
        ScenarioSpec(
            name="hostile-mixed", family="hostile", seed=s(13),
            hostile_classes=(
                "corrupt_row", "dup_span", "orphan", "clock_skew",
                "cardinality_bomb",
            ),
        ),
    ]
    if full:
        specs += [
            ScenarioSpec(
                name="latency-subtle", family="latency", seed=s(7),
                fault_latency_ms=600.0, n_operations=40, n_kinds=24,
            ),
            ScenarioSpec(
                name="error-multi-pod", family="error", seed=s(8),
                fault_kind="error", n_pods=2, n_traces=300,
            ),
            ScenarioSpec(
                name="multi-nested", family="multi", seed=s(9),
                n_faults=2, fault_path_overlap=1.0, n_operations=30,
            ),
            ScenarioSpec(
                name="cascade-strong", family="cascade", seed=s(10),
                cascade_fraction=0.8, n_operations=40, n_kinds=24,
            ),
            ScenarioSpec(
                name="coldstart-immediate", family="cold_start",
                seed=s(11), faulted=(1, 2, 3), seed_baseline=False,
            ),
            ScenarioSpec(
                name="drift-fast", family="drift", seed=s(12),
                faulted=(), drift_per_window=0.10,
            ),
            ScenarioSpec(
                name="hostile-heavy", family="hostile", seed=s(14),
                hostile_classes=(
                    "corrupt_row", "dup_span", "orphan", "clock_skew",
                    "cardinality_bomb",
                ),
                hostile_fraction=0.15, hostile_bomb_ops=128,
                n_operations=30,
            ),
        ]
    return specs
