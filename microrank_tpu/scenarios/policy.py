"""The self-tuning policy engine: matrix results -> persisted policy.

RankMap's framing (PAPERS.md, arxiv 1503.08169): platform- and
workload-aware tuning belongs in a *persisted policy*, not in hardcoded
defaults. The scenario matrix measures which spectrum formula wins on
which workload (and, optionally, which kernel/pad-policy is fastest
there); :func:`select_policy` distills that into ``policy.json`` —
written atomically next to the warmup manifest in the compile-cache
directory, so a restarted serve/stream/table process inherits the
tuned policy the same way it inherits its compiled programs.

Resolution is ONE seam (:func:`apply_tuned_policy`) all three lanes
call, with strict precedence:

    explicit config  >  persisted policy  >  built-in default

"Explicit" means the field differs from its built-in default — the
operator asked for something; the policy never overrides an operator.
(To pin the built-in default itself against a persisted policy, disable
consultation: ``RuntimeConfig.tuned_policy="off"`` / CLI
``--no-tuned-policy``.)

Staleness: a ``policy.json`` whose schema version or profile-bucket
schema differs from this build's — or which has no entry for the run's
workload profile — is rejected WHOLE (the checkpoint whole-rejection
rule from the chaos subsystem): the run cold-starts on built-in
defaults and ``microrank_policy_events_total{outcome="rejected"}``
counts it. A half-applied stale policy is worse than none.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from ..config import MicroRankConfig, RuntimeConfig, SpectrumConfig
from ..utils.logging import get_logger

log = get_logger("microrank_tpu.scenarios.policy")

POLICY_NAME = "policy.json"
POLICY_VERSION = 1

# Workload-profile bucket edges. Part of the policy file's identity:
# a policy tuned under different edges is stale by definition.
PROFILE_SCHEMA: Dict[str, object] = {
    "version": 1,
    # Spans per detection window.
    "span_volume": [50_000, 2_000_000],        # small | medium | large
    # Distinct (service, op) names.
    "op_cardinality": [256, 4096],             # small | medium | large
    # Trace-kind dedup factor (traces per distinct trace shape).
    "dedup_factor": [8.0],                     # low | high
}

_SIZE_NAMES = ("small", "medium", "large")

#: The tuned fields and their built-in defaults (the "explicit config"
#: test compares against these).
TUNED_DEFAULTS: Dict[str, str] = {
    "method": SpectrumConfig().method,
    "kernel": RuntimeConfig().kernel,
    "pad_policy": RuntimeConfig().pad_policy,
}


def _bucket(value: float, edges) -> str:
    for name, edge in zip(_SIZE_NAMES, edges):
        if value < edge:
            return name
    return _SIZE_NAMES[len(edges)]


@dataclass(frozen=True)
class WorkloadProfile:
    """A run's workload, bucketed — the policy lookup key."""

    span_volume: str
    op_cardinality: str
    dedup: str

    def key(self) -> str:
        return (
            f"spans={self.span_volume}|ops={self.op_cardinality}"
            f"|dedup={self.dedup}"
        )


def profile_from_counts(
    n_spans: int,
    n_ops: int,
    dedup_factor: Optional[float] = None,
) -> WorkloadProfile:
    """Profile from raw counts. ``dedup_factor=None`` (lanes that cannot
    cheaply measure trace kinds, e.g. the native table lane) buckets as
    "low" — the conservative bucket: no dedup assumed."""
    return WorkloadProfile(
        span_volume=_bucket(n_spans, PROFILE_SCHEMA["span_volume"]),
        op_cardinality=_bucket(n_ops, PROFILE_SCHEMA["op_cardinality"]),
        dedup=(
            "high"
            if dedup_factor is not None
            and dedup_factor >= PROFILE_SCHEMA["dedup_factor"][0]
            else "low"
        ),
    )


def dedup_factor_from_frame(span_df, sample_traces: int = 2000) -> float:
    """Traces per distinct trace shape (byte-signature kind grouping),
    measured on a bounded trace sample — the same equivalence the
    kind-collapse build exploits."""
    ids = span_df["traceID"]
    unique = ids.unique()
    if len(unique) == 0:
        return 1.0
    if len(unique) > sample_traces:
        sub = span_df[ids.isin(unique[:sample_traces])]
    else:
        sub = span_df
    names = (
        sub["serviceName"].astype(str)
        + "_"
        + sub["operationName"].astype(str)
    )
    sig = names.groupby(sub["traceID"].to_numpy()).apply(
        lambda s: hash(tuple(sorted(s)))
    )
    return float(len(sig) / max(sig.nunique(), 1))


def profile_from_frame(span_df) -> Optional[WorkloadProfile]:
    """Profile one representative span frame (a normal-period window);
    None for an empty/absent frame (no lookup key — defaults apply)."""
    if span_df is None or len(span_df) == 0:
        return None
    n_ops = int(
        (
            span_df["serviceName"].astype(str)
            + "_"
            + span_df["operationName"].astype(str)
        ).nunique()
    )
    return profile_from_counts(
        n_spans=len(span_df),
        n_ops=n_ops,
        dedup_factor=dedup_factor_from_frame(span_df),
    )


# ------------------------------------------------------------- persistence


def resolve_policy_dir(runtime=None) -> str:
    """Directory holding ``policy.json``: ``MICRORANK_POLICY_DIR`` env
    (hermetic tests / split deployments) over the compile-cache dir
    (the default — the policy lives next to the warmup manifest, so a
    restart inherits both through one mount)."""
    import os

    env = os.environ.get("MICRORANK_POLICY_DIR")
    if env:
        return env
    from ..dispatch import resolve_cache_dir

    return resolve_cache_dir(runtime)


def policy_path(cache_dir) -> Path:
    return Path(cache_dir) / POLICY_NAME


def save_policy(cache_dir, data: dict) -> Path:
    """Atomic + durable write next to the warmup manifest."""
    from ..utils.atomic import atomic_write_json

    return atomic_write_json(policy_path(cache_dir), data)


def load_policy(
    cache_dir,
) -> Tuple[Optional[dict], Optional[str]]:
    """(data, reject_reason): (None, None) when absent; (None, reason)
    when present but stale/corrupt — rejected WHOLE; (data, None) when
    valid for this build."""
    path = policy_path(cache_dir) if cache_dir else None
    if path is None or not path.exists():
        return None, None
    import json

    try:
        data = json.loads(path.read_text())
    except (OSError, ValueError) as exc:
        return None, f"unreadable ({exc})"
    if not isinstance(data, dict):
        return None, "not a JSON object"
    if data.get("version") != POLICY_VERSION:
        return None, (
            f"schema version {data.get('version')!r} != "
            f"{POLICY_VERSION}"
        )
    if data.get("profile_schema") != PROFILE_SCHEMA:
        return None, "profile-bucket schema mismatch"
    profiles = data.get("profiles")
    if not isinstance(profiles, dict):
        return None, "missing profiles table"
    return data, None


# -------------------------------------------------------------- resolution


@dataclass
class PolicyResolution:
    """What one lane's policy consultation decided (journal evidence)."""

    lane: str
    outcome: str                       # applied|override|default|rejected|disabled
    profile: Optional[str] = None
    reason: Optional[str] = None
    policy_file: Optional[str] = None
    # field -> {"value": ..., "source": "config"|"policy"|"default"}
    fields: Dict[str, Dict[str, str]] = field(default_factory=dict)

    def journal(self) -> dict:
        return {
            "lane": self.lane,
            "outcome": self.outcome,
            "profile": self.profile,
            "reason": self.reason,
            "policy_file": self.policy_file,
            **{
                f"{name}": d["value"]
                for name, d in self.fields.items()
            },
            **{
                f"{name}_source": d["source"]
                for name, d in self.fields.items()
            },
        }


def _apply_fields(
    config: MicroRankConfig, values: Dict[str, str]
) -> MicroRankConfig:
    return config.replace(
        spectrum=dataclasses.replace(
            config.spectrum, method=values["method"]
        ),
        runtime=dataclasses.replace(
            config.runtime,
            kernel=values["kernel"],
            pad_policy=values["pad_policy"],
        ),
    )


def resolve_policy(
    config: MicroRankConfig,
    profile: Optional[WorkloadProfile],
    lane: str,
    cache_dir: Optional[str] = None,
) -> Tuple[MicroRankConfig, PolicyResolution]:
    """The ONE resolver seam: serve, stream, and the table lane call
    this (via :func:`apply_tuned_policy`) before their first dispatch.
    Returns the (possibly-updated) config plus the resolution record;
    every call lands one ``microrank_policy_events_total`` sample."""
    from ..obs.metrics import record_policy_event

    current = {
        "method": config.spectrum.method,
        "kernel": config.runtime.kernel,
        "pad_policy": config.runtime.pad_policy,
    }
    explicit = {
        name: current[name] != default
        for name, default in TUNED_DEFAULTS.items()
    }
    res = PolicyResolution(
        lane=lane,
        outcome="default",
        profile=profile.key() if profile is not None else None,
        fields={
            name: {
                "value": current[name],
                "source": "config" if explicit[name] else "default",
            }
            for name in TUNED_DEFAULTS
        },
    )
    if getattr(config.runtime, "tuned_policy", "auto") == "off":
        res.outcome = "disabled"
        record_policy_event("disabled", lane)
        return config, res

    if cache_dir is None:
        cache_dir = resolve_policy_dir(config.runtime)
    data, reject = load_policy(cache_dir)
    if data is None and reject is None:
        record_policy_event("default", lane)
        return config, res
    res.policy_file = str(policy_path(cache_dir))
    if reject is None:
        entry = (
            data["profiles"].get(profile.key())
            if profile is not None
            else None
        )
        if entry is None:
            reject = (
                f"no tuned entry for workload profile "
                f"{profile.key() if profile else None!r}"
            )
    if reject is not None:
        # Whole rejection (the checkpoint rule): stale or mismatched
        # policy applies NOTHING — built-in defaults, counted.
        res.outcome = "rejected"
        res.reason = reject
        record_policy_event("rejected", lane)
        log.warning(
            "%s lane: policy.json rejected (%s); built-in defaults",
            lane, reject,
        )
        return config, res

    values = dict(current)
    applied = []
    for name in TUNED_DEFAULTS:
        tuned = entry.get(name)
        if tuned is None or explicit[name]:
            continue  # operator's explicit choice (or untuned field) wins
        values[name] = str(tuned)
        res.fields[name] = {"value": values[name], "source": "policy"}
        applied.append(name)
    res.outcome = "applied" if applied else "override"
    record_policy_event(res.outcome, lane)
    log.info(
        "%s lane: tuned policy %s for profile %s (%s)",
        lane,
        res.outcome,
        res.profile,
        ", ".join(
            f"{n}={d['value']}({d['source']})"
            for n, d in res.fields.items()
        ),
    )
    return _apply_fields(config, values), res


def apply_tuned_policy(
    config: MicroRankConfig,
    lane: str,
    profile_frame=None,
    counts: Optional[Tuple[int, int, Optional[float]]] = None,
    cache_dir: Optional[str] = None,
) -> Tuple[MicroRankConfig, PolicyResolution]:
    """Lane entry point: compute the workload profile from a
    representative frame (pandas lanes) or raw ``(n_spans, n_ops,
    dedup_factor)`` counts (the native table lane), then resolve."""
    if profile_frame is not None:
        profile = profile_from_frame(profile_frame)
    elif counts is not None:
        profile = profile_from_counts(*counts)
    else:
        profile = None
    return resolve_policy(config, profile, lane, cache_dir=cache_dir)


# --------------------------------------------------------------- selection


def select_policy(
    scenario_records: List[dict],
    timings: Optional[Dict[str, dict]] = None,
    matrix_seed: Optional[int] = None,
) -> dict:
    """Distill matrix results into the persisted policy document.

    Per workload profile observed in the matrix: the formula with the
    best mean MAP across that profile's scenarios wins (ties break by
    top-1 exact rate, then mean MRR, then name — deterministic);
    kernel/pad-policy come from the harness's timing sweep for that
    profile when one ran, else stay at the built-in defaults.
    """
    by_profile: Dict[str, List[dict]] = {}
    for rec in scenario_records:
        prof = rec.get("profile")
        formulas = rec.get("formulas") or {}
        if prof and formulas:
            by_profile.setdefault(prof, []).append(formulas)

    profiles: Dict[str, dict] = {}
    for prof, recs in sorted(by_profile.items()):
        methods = sorted({m for r in recs for m in r})
        scored = []
        for m in methods:
            rows = [r[m] for r in recs if m in r]
            mean = lambda key: (  # noqa: E731
                sum(float(r.get(key) or 0.0) for r in rows)
                / max(len(rows), 1)
            )
            scored.append(
                (-mean("map"), -mean("top1_rate"), -mean("mrr"), m)
            )
        scored.sort()
        best = scored[0]
        entry = {
            "method": best[3],
            "kernel": TUNED_DEFAULTS["kernel"],
            "pad_policy": TUNED_DEFAULTS["pad_policy"],
            "evidence": {
                "scenarios": len(recs),
                "map": round(-best[0], 4),
                "top1_rate": round(-best[1], 4),
                "mrr": round(-best[2], 4),
            },
        }
        timing = (timings or {}).get(prof)
        if timing:
            entry["kernel"] = timing["kernel"]
            entry["pad_policy"] = timing["pad_policy"]
            entry["evidence"]["rank_ms"] = timing.get("rank_ms")
            entry["evidence"]["timed_candidates"] = timing.get(
                "candidates"
            )
        profiles[prof] = entry

    return {
        "version": POLICY_VERSION,
        "profile_schema": PROFILE_SCHEMA,
        "matrix_seed": matrix_seed,
        "profiles": profiles,
    }
