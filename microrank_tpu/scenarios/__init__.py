"""Scenario matrix + self-tuning policy engine (ISSUE 13).

Three cooperating layers:

* **spec/generate** — declarative ``ScenarioSpec`` (fault family,
  intensity, topology, timing) compiled into byte-reproducible span
  workloads through the seeded synthetic path; six families cover
  latency, error/status-code, multi-culprit, cascading backpressure,
  fault-during-cold-start, and baseline drift.
* **harness** — every scenario runs the real batch + streaming
  pipelines; all 13 spectrum formulas score per scenario with
  tie-aware MAP/MRR/top-k exactness, joined with the explain
  subsystem's attribution terms; the matrix artifact lands as
  ``scenario_matrix.json`` (``cli scenarios`` renders the table).
* **policy** — matrix results auto-select formula/kernel/pad-policy
  per workload profile, persisted atomically as ``policy.json`` next
  to the warmup manifest; serve, stream and the table lane consult it
  through ONE resolver seam with explicit config overrides winning
  and stale policies rejected whole.
"""

from .generate import (
    ScenarioWorkload,
    generate_scenario,
    workload_digest,
)
from .harness import (
    MATRIX_NAME,
    render_table,
    run_matrix,
    run_scenario,
    time_policy_candidates,
)
from .policy import (
    POLICY_NAME,
    PolicyResolution,
    WorkloadProfile,
    apply_tuned_policy,
    load_policy,
    profile_from_counts,
    profile_from_frame,
    resolve_policy,
    resolve_policy_dir,
    save_policy,
    select_policy,
)
from .spec import FAMILIES, ScenarioSpec, default_matrix

__all__ = [
    "FAMILIES",
    "MATRIX_NAME",
    "POLICY_NAME",
    "PolicyResolution",
    "ScenarioSpec",
    "ScenarioWorkload",
    "WorkloadProfile",
    "apply_tuned_policy",
    "default_matrix",
    "generate_scenario",
    "load_policy",
    "profile_from_counts",
    "profile_from_frame",
    "render_table",
    "resolve_policy",
    "resolve_policy_dir",
    "run_matrix",
    "run_scenario",
    "save_policy",
    "select_policy",
    "time_policy_candidates",
    "workload_digest",
]
