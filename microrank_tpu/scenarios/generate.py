"""Scenario compilation: spec -> reproducible span workload.

One function of one spec: the seeded synthetic path
(``testing.synthetic.generate_timeline``) renders the timeline, so the
same spec always yields a byte-identical span stream — the determinism
the regression net needs (and a test pins via :func:`workload_digest`).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List

import pandas as pd

from .spec import ScenarioSpec


@dataclass
class ScenarioWorkload:
    """A compiled scenario: span frames + ground truth."""

    spec: ScenarioSpec
    normal: pd.DataFrame              # baseline-seed window
    timeline: pd.DataFrame            # n_windows consecutive windows
    window_faulted: List[bool]
    start: pd.Timestamp
    # Ground truth: the FULL culprit set (instance-level vocab names);
    # empty for the drift family (success there is NOT alarming).
    truth: List[str] = field(default_factory=list)

    @property
    def n_spans(self) -> int:
        return len(self.timeline)

    def window_frame(self, i: int) -> pd.DataFrame:
        """Window i's spans, by the pipeline's own window predicate.

        Hostile timelines carry rows whose timestamps will not coerce;
        window placement is undefined for those, so the predicate runs
        on the COERCED key — NaT rows fall out here exactly as they do
        at the stream engine's pre-windowing admission gate (the batch
        lane counts them once up front, see harness.run_scenario)."""
        w0 = self.start + pd.Timedelta(
            minutes=i * self.spec.window_minutes
        )
        w1 = w0 + pd.Timedelta(minutes=self.spec.window_minutes)
        df = self.timeline
        start = df["startTime"]
        end = df["endTime"]
        if not pd.api.types.is_datetime64_any_dtype(start):
            start = pd.to_datetime(
                start, format="mixed", errors="coerce"
            )
        if not pd.api.types.is_datetime64_any_dtype(end):
            end = pd.to_datetime(end, format="mixed", errors="coerce")
        mask = (start >= w0) & (end <= w1)
        return df[mask.fillna(False)]


def generate_scenario(spec: ScenarioSpec) -> ScenarioWorkload:
    """Compile one spec into its workload (pure function of the spec)."""
    from ..testing.synthetic import generate_timeline

    tl = generate_timeline(
        spec.synth_config(), spec.n_windows, list(spec.faulted)
    )
    truth = list(tl.fault_pod_ops) if spec.faulted else []
    timeline = tl.timeline
    if getattr(spec, "hostile_classes", ()):
        # The hostile family: corrupt the compiled timeline (NOT the
        # normal baseline window) with the spec's class mix — the
        # corruption is a pure function of the spec seed, so the
        # workload digest stays a determinism witness.
        from ..ingest.hostile import corrupt_timeline

        timeline = corrupt_timeline(
            timeline,
            spec.hostile_classes,
            seed=spec.seed,
            fraction=spec.hostile_fraction,
            bomb_ops=spec.hostile_bomb_ops,
        )
    return ScenarioWorkload(
        spec=spec,
        normal=tl.normal,
        timeline=timeline,
        window_faulted=tl.window_faulted,
        start=tl.start,
        truth=truth,
    )


def workload_digest(workload: ScenarioWorkload) -> str:
    """sha256 over the canonical CSV bytes of normal + timeline — the
    determinism witness (same seed => same digest, byte for byte)."""
    h = hashlib.sha256()
    h.update(workload.normal.to_csv(index=False).encode())
    h.update(workload.timeline.to_csv(index=False).encode())
    return h.hexdigest()
