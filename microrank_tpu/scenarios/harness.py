"""The scenario evaluation harness: every family x all 13 formulas.

Each scenario runs through the REAL pipeline twice:

* **batch lane** — the ``cli run`` seam: SLO baseline from the normal
  window (``detect.compute_slo``), the shared detect+partition seam on
  every timeline window, and ONE all-formulas device dispatch per
  abnormal window (``JaxBackend.rank_window_all_methods`` — power
  iterations are method-independent, so 13 rankings cost one program).
  Every faulted window scores every formula with the shared tie-aware
  metrics (``evaluation.ranking_metrics``: MAP/MRR/top-k exactness/
  rank-of-true-culprit against the full culprit SET).

* **stream lane** — the ``cli stream`` engine end to end: event-time
  windower, ONLINE baselines (seeded or cold-starting, per the spec),
  anomaly-gated dispatch and the incident lifecycle. This is where the
  cold-start and drift families actually mean something: a fault
  burning before the baseline armed, and a gradual SLO shift that must
  retrain rather than alarm.

The per-scenario records join the explain subsystem's attribution
terms (ef/nf/ep/np counters, PPR mass split, per-formula term values
for each true culprit — one explained dispatch on the first ranked
faulted window) as diagnostic features, land in the matrix artifact
(``scenario_matrix.json``), and feed :func:`scenarios.policy.
select_policy`.
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from ..config import MicroRankConfig, SpectrumConfig
from ..utils.logging import get_logger
from .generate import ScenarioWorkload, generate_scenario, workload_digest
from .policy import (
    profile_from_frame,
    select_policy,
)
from .spec import FAMILIES, ScenarioSpec, default_matrix

log = get_logger("microrank_tpu.scenarios")

MATRIX_NAME = "scenario_matrix.json"
MATRIX_SCHEMA = 1

#: (kernel, pad_policy) candidates the optional tuning sweep times.
#: "kind" joined in PR 14 — the kind-compressed reduced-precision
#: kernel competes for the persisted per-workload policy like any
#: other (its parity vs packed is gated by the scenario-matrix test).
DEFAULT_TUNE_CANDIDATES: Tuple[Tuple[str, str], ...] = (
    ("packed", "pow2q"),
    ("kind", "pow2q"),
    ("pcsr", "pow2q"),
)


def _widen(config: MicroRankConfig, spec: ScenarioSpec) -> MicroRankConfig:
    """Full-depth rankings so rank-of-culprit is exact (the evaluation
    harness's own convention)."""
    return config.replace(
        spectrum=SpectrumConfig(
            method=config.spectrum.method,
            top_max=spec.n_operations * max(1, spec.n_pods),
            extra_rows=config.spectrum.extra_rows,
            eps=config.spectrum.eps,
        )
    )


def _rank_all_methods(config, backend, frame, nrm, abn):
    """{method: (names, scores)} — one fused dispatch on the jax
    backend, a per-method loop on the oracle."""
    if hasattr(backend, "rank_window_all_methods"):
        return backend.rank_window_all_methods(frame, nrm, abn)
    from ..rank_backends import get_backend
    from ..spectrum.formulas import METHODS

    out = {}
    for m in METHODS:
        mcfg = config.replace(
            spectrum=dataclasses.replace(config.spectrum, method=m)
        )
        out[m] = get_backend(mcfg).rank_window(frame, nrm, abn)
    return out


def _attribution_features(
    config: MicroRankConfig, frame, nrm, abn, truth: Sequence[str]
) -> Optional[dict]:
    """One explained dispatch; returns {culprit: {counters, mass,
    terms, rank}} for every true culprit the explain epilogue surfaced
    — PR 8's per-formula attribution joined as diagnostic features."""
    import jax

    from ..config import ExplainConfig
    from ..explain import build_bundle
    from ..rank_backends.blob import stage_rank_window
    from ..rank_backends.jax_tpu import prepare_window_graph_explained

    ex = ExplainConfig(enabled=True, top_traces=3)
    graph, op_names, kernel, ectx = prepare_window_graph_explained(
        frame, nrm, abn, config
    )
    outs = jax.device_get(
        stage_rank_window(
            graph,
            config.pagerank,
            config.spectrum,
            kernel,
            config.runtime.blob_staging,
            explain=ex,
        )
    )
    bundle = build_bundle(
        outs, op_names, ectx,
        method=config.spectrum.method, kernel=kernel,
        trigger="scenario",
    )
    features = {}
    for s in bundle.suspects:
        if s["op"] in truth:
            features[s["op"]] = {
                "rank": s["rank"],
                "score": s["score"],
                "counters": s["counters"],
                "mass": s["mass"],
                "terms": s["terms"],
            }
    return features or None


def _stream_lane(
    config: MicroRankConfig,
    wl: ScenarioWorkload,
    out_dir: Optional[Path],
    ks: Sequence[int],
) -> dict:
    """Run the workload through the real streaming engine."""
    import numpy as np

    from ..evaluation import topk_exact
    from ..stream import ReplaySource, StreamEngine

    spec = wl.spec
    scfg = dataclasses.replace(
        config.stream,
        window_minutes=spec.window_minutes,
        slide_minutes=None,
        allowed_lateness_seconds=5.0,
        checkpoint=False,
        max_windows=0,
    )
    # The harness measures the config under test; a previously persisted
    # policy must not contaminate the matrix that will REPLACE it.
    rcfg = dataclasses.replace(config.runtime, tuned_policy="off")
    cfg = config.replace(stream=scfg, runtime=rcfg)
    source = ReplaySource(wl.timeline, chunk_spans=4000)
    engine = StreamEngine(
        cfg,
        source,
        out_dir=str(out_dir) if out_dir is not None else None,
        normal_df=wl.normal if spec.seed_baseline else None,
    )
    seeded_mean = None
    if engine.baseline.seeded:
        _, slo0 = engine.baseline.snapshot()
        seeded_mean = float(np.mean(slo0.mean_ms)) if len(
            slo0.mean_ms
        ) else None
    summary = engine.run()
    # Baseline-retrain evidence (the drift family's success metric):
    # how far the online SLO center moved over the run.
    baseline_shift = None
    if engine.baseline.ready:
        _, slo1 = engine.baseline.snapshot()
        if seeded_mean and len(slo1.mean_ms):
            baseline_shift = round(
                float(np.mean(slo1.mean_ms)) / seeded_mean, 4
            )
    hits = 0
    ranked_faulted = 0
    for i, r in enumerate(summary.results):
        if not r.ranking:
            continue
        # Event-time window index relative to the timeline start.
        widx = None
        try:
            import pandas as pd

            widx = int(
                (pd.Timestamp(r.start) - wl.start).total_seconds()
                // (spec.window_minutes * 60)
            )
        except (ValueError, TypeError):
            pass
        if (
            widx is not None
            and 0 <= widx < len(wl.window_faulted)
            and wl.window_faulted[widx]
            and wl.truth
        ):
            ranked_faulted += 1
            names = [n for n, _ in r.ranking]
            scores = [s for _, s in r.ranking]
            hits += topk_exact(
                names, scores, wl.truth, k=max(1, len(wl.truth))
            )
    return {
        "windows": summary.windows,
        "ranked": summary.ranked,
        "dispatches": summary.dispatches,
        "warmup": summary.warmup,
        "incidents_opened": summary.incidents_opened,
        "incidents_resolved": summary.incidents_resolved,
        "ranked_faulted": ranked_faulted,
        "topc_hits": int(hits),
        "baseline_shift": baseline_shift,
        "seeded": bool(spec.seed_baseline),
    }


def run_scenario(
    config: MicroRankConfig,
    spec: ScenarioSpec,
    out_dir=None,
    stream_lane: bool = True,
    ks: Sequence[int] = (1, 3, 5),
) -> dict:
    """Run + score one scenario; returns the matrix record."""
    from ..detect import compute_slo, detect_partition
    from ..evaluation import ranking_metrics
    from ..rank_backends import get_backend
    from ..spectrum.formulas import METHODS

    t0 = time.monotonic()
    wl = generate_scenario(spec)
    cfg = _widen(config, spec)
    backend = get_backend(cfg)
    vocab, slo = compute_slo(wl.normal)

    detection = {"tp": 0, "fp": 0, "fn": 0, "tn": 0}
    per_method: Dict[str, List[dict]] = {m: [] for m in METHODS}
    attribution = None
    ingest_rejected = 0
    if cfg.ingest.enabled and getattr(spec, "hostile_classes", ()):
        # Hostile family, batch lane: run the SAME pre-windowing gate
        # the stream engine runs — rows without a placeable event time
        # reject (and are counted here, since no window frame would
        # ever see them), and trace-relative clock skew repairs
        # against the first-seen registry BEFORE window slicing, so a
        # displaced root span cannot turn into a spurious anomaly in
        # somebody else's window.
        from ..ingest import TraceClock, pre_admit_frame

        repaired, rej = pre_admit_frame(
            wl.timeline, cfg.ingest, source=f"scenario:{spec.name}",
            trace_clock=TraceClock(),
        )
        ingest_rejected += sum(rej.values())
        wl.timeline = repaired
    first_ranked = None  # (frame, nrm, abn) of the first faulted rank
    for i in range(spec.n_windows):
        frame = wl.window_frame(i)
        truth_window = wl.window_faulted[i]
        if len(frame) > 0 and cfg.ingest.enabled:
            # The shared admission seam: the clean subset detects and
            # ranks; the scenario record carries the rejection total.
            from ..ingest import admit_frame

            adm = admit_frame(
                frame, cfg.ingest, source=f"scenario:{spec.name}",
                known_ops=frozenset(vocab.names),
            )
            frame = adm.frame
            ingest_rejected += adm.n_rejected
        if len(frame) == 0:
            detection["fn" if truth_window else "tn"] += 1
            continue
        flag, nrm, abn = detect_partition(cfg, vocab, slo, frame)
        if flag and truth_window:
            detection["tp"] += 1
        elif flag:
            detection["fp"] += 1
        elif truth_window:
            detection["fn"] += 1
        else:
            detection["tn"] += 1
        if not (flag and nrm and abn and truth_window and wl.truth):
            continue
        ranked = _rank_all_methods(cfg, backend, frame, nrm, abn)
        for m in METHODS:
            names, scores = ranked[m]
            per_method[m].append(
                ranking_metrics(names, scores, wl.truth, ks=tuple(ks))
            )
        if first_ranked is None:
            first_ranked = (frame, nrm, abn)

    if first_ranked is not None:
        try:
            attribution = _attribution_features(
                cfg, *first_ranked, truth=wl.truth
            )
        except Exception as exc:  # noqa: BLE001 - diagnostics only
            log.warning(
                "scenario %s: attribution join failed (%s)",
                spec.name, exc,
            )

    formulas: Dict[str, dict] = {}
    for m, rows in per_method.items():
        if not rows:
            continue
        n = len(rows)
        mean = lambda vals: sum(vals) / n  # noqa: E731
        topk_rate = {
            int(k): mean(
                [float(r["topk_exact"][int(k)]) for r in rows]
            )
            for k in ks
        }
        found = [
            r2
            for r in rows
            for r2 in r["ranks"].values()
            if r2 is not None
        ]
        formulas[m] = {
            "map": round(mean([r["ap"] for r in rows]), 4),
            "mrr": round(mean([r["rr"] for r in rows]), 4),
            "top1_rate": round(topk_rate.get(1, 0.0), 4),
            "topc_rate": round(
                mean(
                    [
                        float(
                            all(
                                r3 is not None
                                and r3 <= max(1, len(wl.truth))
                                for r3 in r["ranks"].values()
                            )
                        )
                        for r in rows
                    ]
                ),
                4,
            ),
            "topk_rate": topk_rate,
            "mean_rank": (
                round(sum(found) / len(found), 2) if found else None
            ),
            "unranked": sum(
                1
                for r in rows
                for r2 in r["ranks"].values()
                if r2 is None
            ),
            "windows": n,
        }

    record = {
        "scenario": spec.name,
        "family": spec.family,
        "seed": spec.seed,
        "spec": spec.to_dict(),
        "digest": workload_digest(wl),
        "profile": (
            profile_from_frame(wl.normal).key()
            if len(wl.normal)
            else None
        ),
        "spans": int(wl.n_spans),
        "truth": list(wl.truth),
        "detection": detection,
        "formulas": formulas,
        "attribution": attribution,
        "ingest_rejected": int(ingest_rejected),
        "elapsed_s": round(time.monotonic() - t0, 2),
    }
    if stream_lane:
        sdir = (
            Path(out_dir) / "scenarios" / spec.name / "stream"
            if out_dir is not None
            else None
        )
        record["stream"] = _stream_lane(config, wl, sdir, ks)
    return record


# ------------------------------------------------------------ tuning sweep


def time_policy_candidates(
    config: MicroRankConfig,
    wl: ScenarioWorkload,
    candidates: Tuple[Tuple[str, str], ...] = DEFAULT_TUNE_CANDIDATES,
) -> Optional[dict]:
    """Time each (kernel, pad_policy) candidate on this workload's
    first abnormal window (one warm + one timed dispatch each); the
    fastest candidate whose ranking stays tie-aware-identical to the
    first candidate's wins. Returns the timing record select_policy
    persists, or None when no window partitions."""
    import jax

    from ..detect import compute_slo, detect_partition
    from ..rank_backends.blob import stage_rank_window
    from ..rank_backends.jax_tpu import prepare_window_graph
    from ..utils.ranking_compare import tie_aware_topk_agreement

    spec = wl.spec
    vocab, slo = compute_slo(wl.normal)
    picked = None
    for i in range(spec.n_windows):
        if not wl.window_faulted[i]:
            continue
        frame = wl.window_frame(i)
        if len(frame) > 0 and config.ingest.enabled:
            from ..ingest import admit_frame

            frame = admit_frame(
                frame, config.ingest, source=f"tune:{spec.name}"
            ).frame
        if len(frame) == 0:
            continue
        flag, nrm, abn = detect_partition(config, vocab, slo, frame)
        if flag and nrm and abn:
            picked = (frame, nrm, abn)
            break
    if picked is None:
        return None
    frame, nrm, abn = picked
    results = {}
    reference = None
    for kernel, pad in candidates:
        cfg = config.replace(
            runtime=dataclasses.replace(
                config.runtime, kernel=kernel, pad_policy=pad,
                tuned_policy="off",
            )
        )
        try:
            graph, op_names, resolved = prepare_window_graph(
                frame, nrm, abn, cfg
            )

            def _once():
                return jax.device_get(
                    stage_rank_window(
                        graph,
                        cfg.pagerank,
                        cfg.spectrum,
                        resolved,
                        cfg.runtime.blob_staging,
                    )
                )

            _once()  # warm (compile) pass
            t0 = time.monotonic()
            out = _once()
            ms = (time.monotonic() - t0) * 1e3
            ti, ts, nv = out[:3]
            n = int(nv)
            names = [op_names[int(j)] for j in ti[:n]]
            scores = [float(s) for s in ts[:n]]
            parity = True
            if reference is None:
                reference = (names, scores)
            else:
                parity, _ = tie_aware_topk_agreement(
                    names, scores, reference[0], reference[1],
                    k=min(5, len(names), len(reference[0])),
                    rtol=1e-3, exempt_last=True,
                )
            results[f"{kernel}/{pad}"] = {
                "kernel": kernel,
                "pad_policy": pad,
                "resolved_kernel": resolved,
                "rank_ms": round(ms, 2),
                "parity": bool(parity),
            }
        except Exception as exc:  # noqa: BLE001 - a candidate that
            # cannot build/dispatch at this shape simply loses the sweep.
            log.warning(
                "tune candidate %s/%s failed (%s)", kernel, pad, exc
            )
    viable = [r for r in results.values() if r["parity"]]
    if not viable:
        return None
    best = min(viable, key=lambda r: r["rank_ms"])
    return {
        "kernel": best["kernel"],
        "pad_policy": best["pad_policy"],
        "rank_ms": best["rank_ms"],
        "candidates": results,
    }


# --------------------------------------------------------------- the matrix


def run_matrix(
    config: MicroRankConfig,
    specs: Optional[List[ScenarioSpec]] = None,
    out_dir=None,
    seed: int = 0,
    full: bool = False,
    stream_lane: bool = True,
    tune: bool = True,
    persist_policy: bool = True,
    cache_dir: Optional[str] = None,
) -> dict:
    """Run every scenario, score every formula, select + persist the
    tuned policy. Returns the matrix artifact (also written to
    ``out_dir/scenario_matrix.json``)."""
    if specs is None:
        specs = default_matrix(seed, full=full)
    records = []
    for spec in specs:
        log.info("scenario %s (%s family)...", spec.name, spec.family)
        records.append(
            run_scenario(
                config, spec, out_dir=out_dir, stream_lane=stream_lane
            )
        )

    timings: Dict[str, dict] = {}
    if tune:
        for spec, rec in zip(specs, records):
            prof = rec.get("profile")
            if not prof or prof in timings or not rec.get("formulas"):
                continue
            timing = time_policy_candidates(
                config, generate_scenario(spec)
            )
            if timing is not None:
                timings[prof] = timing

    policy = select_policy(records, timings, matrix_seed=seed)
    artifact = {
        "schema": MATRIX_SCHEMA,
        "seed": seed,
        "families": sorted({s.family for s in specs}),
        "n_scenarios": len(records),
        "scenarios": records,
        "policy": policy,
    }
    if persist_policy and policy["profiles"]:
        from .policy import resolve_policy_dir, save_policy

        if cache_dir is None:
            cache_dir = resolve_policy_dir(config.runtime)
        ppath = save_policy(cache_dir, policy)
        log.info("tuned policy persisted: %s", ppath)
        artifact["policy_path"] = str(ppath)
    if out_dir is not None:
        from ..utils.atomic import atomic_write_json

        path = Path(out_dir) / MATRIX_NAME
        atomic_write_json(path, artifact)
        log.info("matrix artifact: %s", path)
    return artifact


def render_table(artifact: dict) -> str:
    """Human-readable matrix summary (the ``cli scenarios`` output)."""
    lines = []
    lines.append(
        f"scenario matrix (seed {artifact.get('seed')}): "
        f"{artifact.get('n_scenarios')} scenarios, "
        f"{len(artifact.get('families', []))} families"
    )
    header = (
        f"{'scenario':<24} {'family':<11} {'profile':<36} "
        f"{'det tp/fp':<10} {'best formula':<14} {'MAP':>6} "
        f"{'top-1':>6} {'stream':<14}"
    )
    lines.append(header)
    lines.append("-" * len(header))
    for rec in artifact.get("scenarios", []):
        det = rec.get("detection", {})
        formulas = rec.get("formulas") or {}
        if formulas:
            best_m = max(
                sorted(formulas),
                key=lambda m: (
                    formulas[m]["map"],
                    formulas[m]["top1_rate"],
                ),
            )
            best = (
                f"{best_m:<14} {formulas[best_m]['map']:>6.2f} "
                f"{formulas[best_m]['top1_rate']:>6.2f}"
            )
        else:
            best = f"{'-':<14} {'-':>6} {'-':>6}"
        stream = rec.get("stream") or {}
        stream_s = (
            f"inc {stream.get('incidents_opened', '-')}"
            f"/{stream.get('incidents_resolved', '-')}"
            + (
                f" hit {stream.get('topc_hits')}"
                f"/{stream.get('ranked_faulted')}"
                if stream.get("ranked_faulted")
                else ""
            )
            if stream
            else "-"
        )
        lines.append(
            f"{rec['scenario']:<24} {rec['family']:<11} "
            f"{(rec.get('profile') or '-'):<36} "
            f"{det.get('tp', 0)}/{det.get('fp', 0):<8} "
            f"{best} {stream_s:<14}"
        )
    prof = (artifact.get("policy") or {}).get("profiles", {})
    if prof:
        lines.append("")
        lines.append("tuned policy (persisted as policy.json):")
        for key, entry in sorted(prof.items()):
            ev = entry.get("evidence", {})
            lines.append(
                f"  {key}: method={entry['method']} "
                f"kernel={entry['kernel']} "
                f"pad={entry['pad_policy']} "
                f"(MAP {ev.get('map')}, {ev.get('scenarios')} scenarios"
                + (
                    f", {ev.get('rank_ms')} ms/rank"
                    if ev.get("rank_ms") is not None
                    else ""
                )
                + ")"
            )
    return "\n".join(lines) + "\n"


__all__ = [
    "DEFAULT_TUNE_CANDIDATES",
    "FAMILIES",
    "MATRIX_NAME",
    "render_table",
    "run_matrix",
    "run_scenario",
    "time_policy_candidates",
]
