"""Command-line entry point (reference component C16, rebuilt as a real CLI).

The reference's CLI is ``python online_rca.py`` with hard-coded dataset
paths and constants edited in source (online_rca.py:219-255; README.md
instructs editing the file). Here:

    python -m microrank_tpu.cli run    --normal N.csv --abnormal A.csv -o out/
    python -m microrank_tpu.cli serve  --normal N.csv --port 8377 -o out/
    python -m microrank_tpu.cli stream --source tail --input live.csv -o out/
    python -m microrank_tpu.cli synth  -o data/ --operations 100 --traces 500
    python -m microrank_tpu.cli eval   --cases 40 [--faults 2] [--detection]
    python -m microrank_tpu.cli stats  out/       (telemetry exposition)
    python -m microrank_tpu.cli stats  --diff before/ after/   (deltas)
    python -m microrank_tpu.cli stats  --merge host0/ host1/   (fleet view)
    python -m microrank_tpu.cli collect ...       (optional ClickHouse export)

(The benchmark lives at the repo root — ``python bench.py`` — because it
drives repo-local cached datasets, not the installed package.)
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def _add_config_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument("--backend", default="jax", choices=["jax", "numpy_ref"])
    p.add_argument("--spectrum-method", default="dstar2")
    p.add_argument("--top-max", type=int, default=5)
    p.add_argument("--iterations", type=int, default=25)
    p.add_argument("--damping", type=float, default=0.85)
    p.add_argument("--call-weight", type=float, default=0.01)
    p.add_argument(
        "--preference", default="reference", choices=["reference", "paper"]
    )
    p.add_argument("--k-sigma", type=float, default=3.0)
    p.add_argument("--slack-ms", type=float, default=0.0)
    p.add_argument(
        "--slo-stat",
        default="mean",
        help='SLO central statistic: "mean" or a percentile like "p90"',
    )
    p.add_argument("--detect-minutes", type=float, default=5.0)
    p.add_argument("--skip-minutes", type=float, default=4.0)
    p.add_argument(
        "--reference-compat",
        action="store_true",
        help="reproduce the reference code exactly, documented quirks "
        "included (partition swap, overwritten result.csv)",
    )
    p.add_argument(
        "--compile-cache-dir", default=None,
        help="persistent XLA compilation cache directory (compiled rank "
        "programs reload across process restarts instead of re-paying "
        "the ~1.7s first-call compile; default ~/.cache/microrank_tpu/"
        "jit, MICRORANK_JIT_CACHE env overrides)",
    )
    p.add_argument(
        "--sharded-threshold-mb", type=float, default=None,
        help="dispatch router size threshold: batches whose staged "
        "device footprint reaches this many MB route to the sharded "
        "mesh path (needs --mesh; default 64)",
    )
    p.add_argument(
        "--coalesce-windows", type=_positive_int, default=None,
        help="dispatch router burst coalescing: same-pad-bucket stream "
        "windows queued behind an in-flight dispatch coalesce into one "
        "vmapped program, up to this many (1 disables; default 8)",
    )
    p.add_argument(
        "--no-span-trace", action="store_true",
        help="disable the self-tracing span ring (obs.spans; on by "
        "default — every pipeline stage emits a parent-linked span "
        "the flight recorder can dump)",
    )
    p.add_argument(
        "--span-ring", type=_positive_int, default=None,
        help="span ring capacity (spans; default 8192 — oldest spans "
        "fall off, the flight manifest counts drops)",
    )
    p.add_argument(
        "--profile-every-n", type=_positive_int, default=None,
        help="wrap every N-th router dispatch in a jax.profiler.trace "
        "session (sampled device profiling; sessions land under the "
        "out dir's profiles/; default off)",
    )
    p.add_argument(
        "--inject-stage-sleep-ms", type=float, default=None,
        help="chaos/test knob: sleep this long inside every matching "
        "--inject-stage span (drives the flight-recorder dogfood "
        "path: slow one pipeline stage, dump, self-rank)",
    )
    p.add_argument(
        "--inject-stage", default=None,
        help='stage name --inject-stage-sleep-ms slows (default "build")',
    )
    p.add_argument(
        "--sanitizers", action="store_true",
        help="arm the mrsan runtime sanitizers (debug mode — mrlint "
        "R8/R9's runtime twin): device-ownership asserted at every "
        "staging/dispatch/fetch seam, per-shard collective schedules "
        "recorded and checked for uniformity; forces a retrace of "
        "collective-bearing programs on arm",
    )
    p.add_argument(
        "--explain", action="store_true",
        help="arm the rank-provenance subsystem (explain/): stream "
        "builds an explain bundle automatically when an incident "
        "opens (written next to the flight dump, served at "
        "/explainz); off by default — the hot path pays nothing",
    )
    p.add_argument(
        "--explain-top-traces", type=_positive_int, default=None,
        help="contributing coverage columns (traces) kept per suspect "
        "in explain bundles (default 5)",
    )
    p.add_argument(
        "--chaos", default=None, metavar="PLAN.json",
        help="arm the unified fault-injection harness (chaos/): a "
        'seeded JSON fault plan ({"seed": N, "faults": [{"seam": ..., '
        '"kind": ..., ...}]}) injected deterministically at every '
        "instrumented seam — dispatch/build/source/webhook/checkpoint/"
        "fetch; injections land in "
        "microrank_fault_injections_total and the journal",
    )
    p.add_argument(
        "--no-tuned-policy", action="store_true",
        help="do not consult the persisted tuned policy (policy.json "
        "written by `cli scenarios` next to the warmup manifest); "
        "pins the built-in spectrum/kernel/pad defaults. Explicit "
        "flags always beat the policy even without this",
    )
    p.add_argument(
        "--chaos-seed", type=int, default=None,
        help="RNG seed for probabilistic chaos fault specs (default: "
        "the plan file's seed, else 0)",
    )
    p.add_argument(
        "--quarantine-dir", default=None,
        help="directory for the span-admission dead-letter store "
        "(quarantine.jsonl — every rejected row with its reason; "
        "default: the run's output directory)",
    )
    p.add_argument(
        "--orphan-policy", default=None, choices=["stitch", "drop"],
        help="orphan spans (parent id absent from the trace): stitch "
        "clears the link (span becomes a root, kept + counted) or "
        "drop rejects the row to quarantine (default stitch)",
    )
    p.add_argument(
        "--max-skew-seconds", type=float, default=None,
        help="clock-skew clamp bound: spans outside the window by up "
        "to this many seconds normalize to the bound; far beyond it "
        "(skew_reject_seconds) they quarantine (default 300)",
    )
    p.add_argument(
        "--max-ops-per-window", type=int, default=None,
        help="op-vocab budget per window: distinct operations past "
        "this keep the highest-span-count ops and quarantine the thin "
        "tail — the cardinality-bomb guard (default 20000, 0 off)",
    )
    p.add_argument(
        "--max-spans-per-trace", type=int, default=None,
        help="trace-length budget: spans of one trace past this "
        "quarantine (reason trace_too_long) so a mega-trace cannot "
        "escalate the pad buckets (default 4096, 0 off)",
    )
    p.add_argument(
        "--min-admission-ratio", type=float, default=None,
        help="refuse a window WHOLE when fewer than this fraction of "
        "its spans survive admission: no baseline update, no incident "
        "transition (default 0.5)",
    )
    p.add_argument(
        "--no-ingest-guard", action="store_true",
        help="disable span admission + quarantine entirely (frames "
        "pass through unvalidated — one malformed row can abort a "
        "frame; debugging only)",
    )
    p.add_argument("--config-json", help="load a full MicroRankConfig dict")


def _positive_int(value: str) -> int:
    n = int(value)
    if n < 1:
        raise argparse.ArgumentTypeError(f"must be >= 1, got {n}")
    return n


def _parse_mesh(spec):
    """'8' -> (8,); '2x4' -> (2, 4); None/'' -> None (single device)."""
    if not spec:
        return None
    try:
        shape = tuple(int(p) for p in str(spec).lower().split("x"))
    except ValueError:
        raise SystemExit(f'invalid --mesh {spec!r}; use "8" or "2x4"')
    if not shape or any(n < 1 for n in shape) or len(shape) > 2:
        raise SystemExit(f'invalid --mesh {spec!r}; use "8" or "2x4"')
    return shape


def _config_from_args(args) -> "MicroRankConfig":
    from ..config import (
        CompatConfig,
        DetectorConfig,
        DispatchConfig,
        ExplainConfig,
        MicroRankConfig,
        ObsConfig,
        PageRankConfig,
        RuntimeConfig,
        SpectrumConfig,
        WindowConfig,
    )

    if args.config_json:
        with open(args.config_json) as f:
            return MicroRankConfig.from_dict(json.load(f))
    obs_overrides = {
        k: v
        for k, v in {
            "spans": (
                False if getattr(args, "no_span_trace", False) else None
            ),
            "span_ring": getattr(args, "span_ring", None),
            "profile_every_n": getattr(args, "profile_every_n", None),
            "profile_dir": (
                str(Path(args.output) / "profiles")
                if getattr(args, "profile_every_n", None)
                and getattr(args, "output", None)
                else None
            ),
            "inject_stage_sleep_ms": getattr(
                args, "inject_stage_sleep_ms", None
            ),
            "inject_stage": getattr(args, "inject_stage", None),
        }.items()
        if v is not None
    }
    explain_overrides = {
        k: v
        for k, v in {
            "enabled": (
                True if getattr(args, "explain", False) else None
            ),
            "top_traces": getattr(args, "explain_top_traces", None),
        }.items()
        if v is not None
    }
    dispatch_overrides = {
        k: v
        for k, v in {
            "sharded_bytes_threshold": (
                int(args.sharded_threshold_mb * (1 << 20))
                if getattr(args, "sharded_threshold_mb", None) is not None
                else None
            ),
            "coalesce_windows": getattr(args, "coalesce_windows", None),
        }.items()
        if v is not None
    }
    from ..config import ChaosConfig, IngestConfig

    ingest_overrides = {
        k: v
        for k, v in {
            "enabled": (
                False
                if getattr(args, "no_ingest_guard", False)
                else None
            ),
            "quarantine_dir": getattr(args, "quarantine_dir", None),
            "orphan_policy": getattr(args, "orphan_policy", None),
            "max_skew_seconds": getattr(args, "max_skew_seconds", None),
            "max_ops_per_window": getattr(
                args, "max_ops_per_window", None
            ),
            "max_spans_per_trace": getattr(
                args, "max_spans_per_trace", None
            ),
            "min_admission_ratio": getattr(
                args, "min_admission_ratio", None
            ),
        }.items()
        if v is not None
    }
    chaos_overrides = {
        k: v
        for k, v in {
            "enabled": (
                True if getattr(args, "chaos", None) else None
            ),
            "plan_path": getattr(args, "chaos", None),
            "seed": getattr(args, "chaos_seed", None),
        }.items()
        if v is not None
    }
    cfg = MicroRankConfig(
        obs=ObsConfig(**obs_overrides),
        explain=ExplainConfig(**explain_overrides),
        dispatch=DispatchConfig(**dispatch_overrides),
        chaos=ChaosConfig(**chaos_overrides),
        ingest=IngestConfig(**ingest_overrides),
        detector=DetectorConfig(
            k_sigma=args.k_sigma,
            slack_ms=args.slack_ms,
            slo_stat=args.slo_stat,
        ),
        pagerank=PageRankConfig(
            iterations=args.iterations,
            damping=args.damping,
            call_weight=args.call_weight,
            preference=args.preference,
            **{
                k: v
                for k, v in {
                    "kind_precision": getattr(
                        args, "kind_precision", None
                    ),
                }.items()
                if v is not None
            },
        ),
        spectrum=SpectrumConfig(
            method=args.spectrum_method, top_max=args.top_max
        ),
        window=WindowConfig(
            detect_minutes=args.detect_minutes, skip_minutes=args.skip_minutes
        ),
        runtime=RuntimeConfig(
            backend=args.backend,
            mesh_shape=_parse_mesh(getattr(args, "mesh", None)),
            kernel=getattr(args, "kernel", "auto"),
            # Flags only the `run` parser defines: absent/None attrs fall
            # back to RuntimeConfig's own defaults (single source of
            # truth — `eval` shares this builder without these flags).
            **{
                k: v
                for k, v in {
                    # store_true flags: only override when actually set.
                    "async_dispatch": (
                        False if getattr(args, "sync_dispatch", False) else None
                    ),
                    "blob_staging": (
                        False
                        if getattr(args, "no_blob_staging", False)
                        else None
                    ),
                    "device_checks": (
                        True if getattr(args, "device_checks", False) else None
                    ),
                    "sanitizers": (
                        True if getattr(args, "sanitizers", False) else None
                    ),
                    "tuned_policy": (
                        "off"
                        if getattr(args, "no_tuned_policy", False)
                        else None
                    ),
                    "pipeline_depth": getattr(args, "pipeline_depth", None),
                    "fetch_mode": getattr(args, "fetch_mode", None),
                    "bulk_fetch_windows": getattr(
                        args, "bulk_fetch_windows", None
                    ),
                    "dispatch_batch_windows": getattr(
                        args, "dispatch_batch_windows", None
                    ),
                    "compile_cache_dir": getattr(
                        args, "compile_cache_dir", None
                    ),
                    "kind_dedup_threshold": getattr(
                        args, "kind_dedup_threshold", None
                    ),
                    "delta_build": (
                        True if getattr(args, "delta_build", False) else None
                    ),
                    "fused_pair": (
                        True if getattr(args, "fused_pair", False) else None
                    ),
                }.items()
                if v is not None
            },
        ),
    )
    if args.reference_compat:
        cfg = cfg.replace(
            compat=CompatConfig(partition_swap=True, overwrite_results=True)
        )
    return cfg


def _load_snapshot(target: Path):
    """Resolve a stats target (run dir or metrics.json path) to its
    parsed snapshot dict, or None with a message on stderr."""
    snap_path = target / "metrics.json" if target.is_dir() else target
    if not snap_path.exists():
        print(
            f"no metrics snapshot at {snap_path} (run `cli run -o "
            f"{target}` first, or point at a metrics.json)",
            file=sys.stderr,
        )
        return None
    return json.loads(snap_path.read_text())


def _merge_targets(paths):
    """Resolve ``--merge`` targets to ONE federated registry. Each
    target is a run dir / metrics.json path, or a fleet output dir —
    a dir with ``host*/metrics.json`` children expands to those
    per-host ledgers (its own top-level metrics.json is already the
    merged fleet view; re-merging it with its children would double
    count). The host label on gauges is the snapshot's directory
    name. Returns None (with a stderr message) on a missing target."""
    from ..obs import registry_from_json
    from ..obs.registry import merge_registries

    sources = []
    for t in paths:
        tp = Path(t)
        children = (
            sorted(tp.glob("host*/metrics.json")) if tp.is_dir() else []
        )
        for p in children or [tp]:
            data = _load_snapshot(Path(p))
            if data is None:
                return None
            p = Path(p)
            label = (p if p.is_dir() else p.parent).name
            sources.append((label, registry_from_json(data)))
    return merge_registries(sources)


def cmd_stats(args) -> int:
    """Offline metrics exposition: re-emit a finished run's snapshot
    (``metrics.json`` written at run end) as Prometheus text or JSON,
    and summarize the run journal when present. ``--diff`` takes TWO
    targets and emits after-minus-before deltas (counters/histograms
    subtract; gauges keep the after reading) — compare two runs, or a
    snapshot taken before and after a traffic window. ``--merge``
    federates N per-host snapshots (counters/histogram buckets sum,
    gauges gain a ``host`` label) — the same law the fleet coordinator
    applies live — and composes with ``--diff``: two fleet dirs, each
    merged, then diffed."""
    import os

    from ..obs import read_journal, registry_from_json
    from ..obs.journal import JOURNAL_NAME

    if args.merge:
        if args.diff and len(args.target) != 2:
            print(
                "--merge --diff takes exactly two targets (each a "
                "fleet dir / snapshot list member): "
                "`cli stats --merge --diff before_fleet/ after_fleet/`",
                file=sys.stderr,
            )
            return 2
        if args.diff:
            from ..obs import diff_registries

            regs = [_merge_targets([t]) for t in args.target]
            if any(r is None for r in regs):
                return 2
            out = diff_registries(regs[0], regs[1])
        else:
            out = _merge_targets(args.target)
            if out is None:
                return 2
        if args.format == "json":
            print(json.dumps(out.to_json(), indent=2))
        else:
            print(out.to_prometheus(), end="")
        return 0
    if args.diff:
        if len(args.target) != 2:
            print(
                "--diff takes exactly two targets: "
                "`cli stats --diff before/ after/`",
                file=sys.stderr,
            )
            return 2
        from ..obs import diff_registries

        snaps = [_load_snapshot(Path(t)) for t in args.target]
        if any(s is None for s in snaps):
            return 2
        delta = diff_registries(
            registry_from_json(snaps[0]), registry_from_json(snaps[1])
        )
        if args.format == "json":
            print(json.dumps(delta.to_json(), indent=2))
        else:
            print(delta.to_prometheus(), end="")
        return 0
    if len(args.target) != 1:
        print("stats takes one target (or two with --diff)", file=sys.stderr)
        return 2
    target = Path(args.target[0])
    data = _load_snapshot(target)
    if data is None:
        return 2
    if args.format == "json":
        print(json.dumps(data, indent=2))
    else:
        # Round-trip through the registry so the text form is generated
        # by the same exposition code the live endpoint uses.
        print(registry_from_json(data).to_prometheus(), end="")
    if args.journal:
        jpath = (
            target / JOURNAL_NAME
            if target.is_dir()
            else target.parent / JOURNAL_NAME
        )
        events = read_journal(jpath)
        if events:
            from ..obs import journal_parts

            windows = [e for e in events if e["event"] == "window"]
            ranked = [w for w in windows if w.get("outcome") == "ranked"]
            contended = sum(
                1
                for w in windows
                if (w.get("host") or {}).get("contended")
            )
            # Size spans the rotated parts too (journal_max_bytes):
            # rotation must not make a run look smaller than it was.
            parts = journal_parts(jpath)
            nbytes = sum(
                os.path.getsize(p) for p in [*parts, jpath]
                if os.path.exists(p)
            )
            rotated = (
                f" across {len(parts) + 1} parts" if parts else ""
            )
            print(
                f"# journal: {len(windows)} windows ({len(ranked)} "
                f"ranked), {contended} contended samples, "
                f"{nbytes} bytes{rotated}",
                file=sys.stderr,
            )
    return 0


def cmd_run(args) -> int:
    from ..utils.logging import get_logger

    log = get_logger("microrank_tpu.cli")

    primary = True
    if args.distributed or args.coordinator:
        # Must precede every other jax touch (config building is safe).
        from ..parallel.distributed import (
            coordinator_configured,
            initialize_distributed,
            is_primary,
        )

        active = initialize_distributed(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
        if not active and args.distributed:
            if coordinator_configured(args.coordinator):
                log.warning(
                    "--distributed: runtime initialized but the world has "
                    "a single process; running single-process"
                )
            else:
                log.warning(
                    "--distributed set but no coordinator configured "
                    "(flag or MICRORANK_COORDINATOR); running "
                    "single-process"
                )
        primary = is_primary()
        if active:
            import jax

            log.info(
                "distributed runtime: process %d/%d, %d global devices",
                jax.process_index(), jax.process_count(),
                len(jax.devices()),
            )

    cfg = _config_from_args(args)
    if cfg.runtime.compile_cache_dir:
        _enable_jit_cache(cfg.runtime)  # re-point at the configured dir
    if getattr(args, "metrics_port", None) is not None and primary:
        from ..obs.server import start_metrics_server

        server = start_metrics_server(
            args.metrics_port,
            profile_dir=(
                str(Path(args.output) / "profiles") if args.output else None
            ),
        )
        log.info(
            "metrics endpoint: http://127.0.0.1:%d/metrics (+ "
            "/metrics.json, /healthz, /profilez)",
            server.port,
        )

    def _write_metrics(dest) -> None:
        """Persist the metrics snapshot next to the results so
        `cli stats <out_dir>` works after the process exits."""
        if dest is None or not cfg.runtime.telemetry:
            return
        from ..obs import get_registry
        from ..obs.metrics import ensure_catalog

        ensure_catalog()
        get_registry().write_snapshot(dest)

    if (
        getattr(args, "bulk_fetch_windows", None) is not None
        and cfg.runtime.fetch_mode != "bulk"
    ):
        log.warning(
            "--bulk-fetch-windows has no effect without "
            "--fetch-mode bulk (streaming fetches are per-window)"
        )

    engine = args.engine
    if engine == "auto":
        from ..native import native_available

        engine = "native" if native_available() else "pandas"
    log.info("ingest engine: %s", engine)

    # The process count matters for any path that runs collectives — a
    # TPU-pod runtime can be multi-process WITHOUT an explicit
    # --distributed (native multi-host discovery). Ask jax whenever the
    # chosen path will initialize a backend anyway; only the pure-host
    # combination (pandas engine + numpy_ref backend) skips the query,
    # because asking would initialize a device backend (a tunnel round
    # trip) for a run that never touches one.
    multiprocess = False
    if (
        args.distributed
        or args.coordinator
        or engine == "native"
        or args.backend == "jax"
    ):
        import jax

        multiprocess = jax.process_count() > 1
    from ..utils.profiling import trace_context

    # A mesh only exists on the native engine's sharded path; reject the
    # combination up front so a multi-process pandas run cannot fall
    # through and silently drop a configured --mesh.
    if cfg.runtime.mesh_shape is not None and engine != "native":
        log.error(
            "--mesh needs the native engine (the pandas pipeline has no "
            "sharded path); rerun with --engine native"
        )
        return 2
    if getattr(args, "follow", False) and engine != "native":
        log.error(
            "--follow needs the native engine (the poll loop tails via "
            "the C++ ingest); rerun with --engine native"
        )
        return 2

    # In a multi-process run every process executes the same pipeline —
    # the sharded TableRCA programs are collective; only rank 0 writes
    # results (and caches: concurrent ranks must not race shared files).
    out_dir = args.output if primary else None
    profile_dir = args.profile_dir if primary else None
    if engine == "native":
        from ..native import load_span_table
        from ..pipeline import TableRCA

        # A windows axis > 1 only makes sense with batch-mode ranking
        # (all anomalous windows in one sharded dispatch) — enable it
        # automatically so "--mesh 2x4" works as advertised.
        mesh_shape = cfg.runtime.mesh_shape
        batch_windows = bool(
            mesh_shape is not None
            and len(mesh_shape) == 2
            and mesh_shape[0] > 1
        )
        if batch_windows:
            log.info(
                "mesh windows axis > 1: ranking in batch mode (one "
                "sharded dispatch over all anomalous windows)"
            )
        resume = args.resume
        if resume and multiprocess:
            # Only rank 0 has a cursor (out_dir); resuming it alone
            # would desynchronize the ranks' collective window loops.
            log.warning(
                "--resume is disabled in multi-process runs (all ranks "
                "must execute the same window sequence); starting over"
            )
            resume = False
        rca = TableRCA(cfg)
        rca.fit_baseline(load_span_table(args.normal, cache=primary))
        if getattr(args, "follow", False):
            # Online mode: tail the growing abnormal CSV, ranking
            # windows as they close (pipeline.follow). The window
            # cursor in out_dir makes polls and restarts incremental.
            if multiprocess:
                log.error(
                    "--follow is single-process (the poll loop cannot "
                    "synchronize collective window sequences)"
                )
                return 2
            if out_dir is None:
                log.error("--follow needs -o/--output (window cursor)")
                return 2
            from ..pipeline.follow import run_follow

            def _print_batch(batch):
                for r in batch:
                    if r.ranking:
                        print(f"window {r.start}:")
                        for rank, (name, score) in enumerate(
                            r.ranking, 1
                        ):
                            print(
                                f"  {rank:2d}. {name:<50s} {score:.8f}"
                            )

            with trace_context(profile_dir):
                n = run_follow(
                    rca,
                    args.abnormal,
                    out_dir,
                    poll_seconds=args.poll_seconds,
                    grace_us=int(args.follow_grace_seconds * 1e6),
                    idle_exit=args.follow_idle_exit or 0,
                    on_results=_print_batch,
                )
            log.info("follow: %d windows ranked; results in %s", n, out_dir)
            _write_metrics(out_dir)
            return 0
        with trace_context(profile_dir):
            results = rca.run(
                load_span_table(args.abnormal, cache=primary),
                out_dir=out_dir,
                batch_windows=batch_windows,
                resume=resume,
            )
    elif multiprocess and not primary:
        # The pandas pipeline has no collectives — duplicating it on
        # every rank buys nothing and non-primary ranks would drop
        # --resume (no cursor without an out_dir). Idle here.
        log.info("pandas engine is single-process; rank idle")
        return 0
    else:
        if multiprocess:
            log.warning(
                "pandas engine does not shard; running on the primary "
                "rank only (use --engine native with a mesh to "
                "distribute)"
            )
        from ..io import load_traces_csv
        from ..pipeline import OnlineRCA

        normal = load_traces_csv(args.normal)
        abnormal = load_traces_csv(args.abnormal)
        log.info(
            "loaded %d normal spans, %d abnormal spans",
            len(normal),
            len(abnormal),
        )
        rca = OnlineRCA(cfg)
        # Non-primary ranks must not race rank 0 on the shared cache file.
        rca.fit_baseline(
            normal, cache_path=args.slo_cache if primary else None
        )
        with trace_context(profile_dir):
            results = rca.run(abnormal, out_dir=out_dir, resume=args.resume)
    n_anom = sum(r.anomaly for r in results)
    _write_metrics(out_dir)
    log.info(
        "processed %d windows, %d anomalous; results in %s",
        len(results),
        n_anom,
        args.output,
    )
    for r in results:
        if r.ranking:
            print(f"window {r.start}:")
            for rank, (name, score) in enumerate(r.ranking, 1):
                print(f"  {rank:2d}. {name:<50s} {score:.8f}")
    return 0


def _parse_tenant_floats(specs, flag: str):
    """Repeatable ``NAME=FLOAT`` flags -> the SchedConfig pair tuple."""
    out = []
    for spec in specs or ():
        name, sep, val = spec.partition("=")
        if not name or not sep:
            raise SystemExit(f"{flag} takes NAME=FLOAT, got {spec!r}")
        try:
            out.append((name, float(val)))
        except ValueError:
            raise SystemExit(
                f"{flag}: {val!r} is not a number (in {spec!r})"
            ) from None
    return tuple(out)


def cmd_serve(args) -> int:
    """Online RCA service: accept windows over HTTP, coalesce concurrent
    requests into padded micro-batches, rank on device, degrade to the
    numpy_ref oracle on dispatch failure (serve/ subsystem).

    Co-deploy (``--stream-input`` / ``--backfill``): serve, the stream
    engine, and warehouse replay backfill share ONE device through the
    unified scheduler (sched/) — every lane parks prepared windows into
    the shared store; the scheduler thread dequeues by priority lane
    (open-incident > interactive serve > backfill) under per-tenant
    weighted fair share (``--tenant-weight``) and soft token-bucket
    quotas (``--tenant-rate``)."""
    import dataclasses
    import threading

    from ..config import ServeConfig
    from ..io import load_traces_csv
    from ..serve import ServeService, run_serve
    from ..utils.logging import get_logger

    log = get_logger("microrank_tpu.cli")
    cfg = _config_from_args(args)
    overrides = {
        k: v
        for k, v in {
            "host": args.host,
            "port": args.port,
            "max_queue_depth": args.max_queue_depth,
            "retry_after_seconds": args.retry_after,
            "max_batch_windows": args.max_batch_windows,
            "max_wait_ms": args.max_wait_ms,
            "request_timeout_seconds": args.request_timeout,
            "drain_seconds": args.drain_seconds,
            "warmup_occupancies": (
                tuple(
                    int(x)
                    for x in args.warmup_occupancies.split(",")
                    if x.strip()
                )
                if args.warmup_occupancies
                else None
            ),
            "build_workers": args.build_workers,
            "warmup": False if args.no_warmup else None,
            "fallback": False if args.no_fallback else None,
            "inject_dispatch_failures": args.inject_dispatch_failures,
        }.items()
        if v is not None
    }
    cfg = cfg.replace(serve=dataclasses.replace(cfg.serve, **overrides))
    sched_overrides = {}
    if getattr(args, "tenant_weight", None):
        sched_overrides["tenant_weights"] = _parse_tenant_floats(
            args.tenant_weight, "--tenant-weight"
        )
    if getattr(args, "tenant_rate", None):
        sched_overrides["tenant_rates"] = _parse_tenant_floats(
            args.tenant_rate, "--tenant-rate"
        )
    if sched_overrides:
        cfg = cfg.replace(
            sched=dataclasses.replace(cfg.sched, **sched_overrides)
        )

    codeploy = bool(
        getattr(args, "stream_input", None)
        or getattr(args, "backfill", None)
    )
    sched = None
    if codeploy:
        from ..sched import DeviceScheduler, ParkedWindowStore

        store = ParkedWindowStore(cfg.sched, serve_cfg=cfg.serve)
        sched = DeviceScheduler(store)
        sched.start()
        log.info(
            "co-deploy: unified device scheduler up (lanes: "
            "incident > serve > backfill)"
        )

    normal_df = load_traces_csv(args.normal)
    service = ServeService(cfg, out_dir=args.output, sched=sched)
    service.fit_baseline(normal_df)
    for spec in args.dataset or ():
        name, _, path = spec.partition("=")
        if not name or not path:
            log.error("--dataset takes NAME=CSV_PATH, got %r", spec)
            return 2
        service.add_dataset(name, load_traces_csv(path))

    side_threads = []
    engine = None
    if getattr(args, "stream_input", None):
        from ..stream import FileTailSource, StreamEngine

        stream_out = (
            str(Path(args.output) / "stream") if args.output else None
        )
        engine = StreamEngine(
            cfg,
            FileTailSource(
                args.stream_input,
                parse_retry_max=cfg.ingest.parse_retry_max,
            ),
            out_dir=stream_out,
            normal_df=normal_df,
            sched=sched,
        )
        t = threading.Thread(
            target=engine.run, name="co-stream", daemon=True
        )
        t.start()
        side_threads.append(t)
        log.info(
            "co-deploy: stream engine tailing %s (incident lane "
            "preempts serve)", args.stream_input,
        )

    if getattr(args, "backfill", None):
        from ..warehouse import parse_time_range, replay_range

        t0_us, t1_us = parse_time_range(
            getattr(args, "backfill_range", None) or "all"
        )

        def _backfill():
            report = replay_range(
                args.backfill, t0_us, t1_us, config=cfg, sched=sched
            )
            log.info(
                "co-deploy backfill done: verdict=%s ranked=%d "
                "matched=%d",
                report["verdict"], report["ranked"], report["matched"],
            )

        t = threading.Thread(
            target=_backfill, name="co-backfill", daemon=True
        )
        t.start()
        side_threads.append(t)
        log.info(
            "co-deploy: warehouse backfill of %s on the backfill lane",
            args.backfill,
        )

    service.start()
    rc = run_serve(service, cfg.serve.host, cfg.serve.port)
    if engine is not None:
        engine.request_stop()
    for t in side_threads:
        t.join(timeout=30)
    if sched is not None:
        sched.stop(drain=True, timeout=30)
    return rc


def cmd_stream(args) -> int:
    """Continuous RCA engine (stream/): an unbounded span source feeds
    an event-time windower with watermarks; online SLO baselines arm the
    detector on every closed window; only abnormal windows pay for graph
    build + device rank; ranked windows dedup into incidents with an
    open/update/resolve lifecycle."""
    import dataclasses

    from ..stream import (
        FileTailSource,
        ReplaySource,
        StdoutIncidentSink,
        StreamEngine,
        SyntheticSource,
    )
    from ..utils.logging import get_logger

    log = get_logger("microrank_tpu.cli")
    cfg = _config_from_args(args)
    overrides = {
        k: v
        for k, v in {
            # Stream windows share the detector's window width flag.
            "window_minutes": args.detect_minutes,
            "slide_minutes": args.slide_minutes,
            "allowed_lateness_seconds": args.lateness_seconds,
            "baseline_decay": args.baseline_decay,
            "min_healthy_windows": args.min_healthy_windows,
            "resolve_after_windows": args.resolve_after,
            "cooldown_windows": args.cooldown,
            "fingerprint_top_k": args.fingerprint_top_k,
            "build_workers": args.build_workers,
            "pipeline_windows": args.pipeline_windows,
            "webhook_url": args.webhook,
            "max_windows": args.max_windows,
        }.items()
        if v is not None
    }
    cfg = cfg.replace(stream=dataclasses.replace(cfg.stream, **overrides))
    if getattr(args, "warehouse", False) or getattr(
        args, "warehouse_dir", None
    ):
        cfg = cfg.replace(
            warehouse=dataclasses.replace(
                cfg.warehouse,
                enabled=True,
                dir=getattr(args, "warehouse_dir", None),
            )
        )
    if getattr(args, "journal_max_bytes", None) is not None:
        cfg = cfg.replace(
            obs=dataclasses.replace(
                cfg.obs, journal_max_bytes=args.journal_max_bytes
            )
        )
    fleet_overrides = {
        k: v
        for k, v in {
            "partitions": getattr(args, "fleet_partitions", None),
            "partition_by": getattr(args, "partition_by", None),
            "heartbeat_seconds": getattr(args, "heartbeat_seconds", None),
            "lease_seconds": getattr(args, "lease_seconds", None),
            "port": getattr(args, "fleet_port", None),
            "restart_delay_seconds": getattr(
                args, "fleet_restart_delay", None
            ),
            "restart_dead_workers": (
                False
                if getattr(args, "fleet_no_restart", False)
                else None
            ),
        }.items()
        if v is not None
    }
    if fleet_overrides:
        cfg = cfg.replace(
            fleet=dataclasses.replace(cfg.fleet, **fleet_overrides)
        )

    if getattr(args, "fleet", None):
        # Fleet launcher: this process becomes the coordinator; workers
        # are subprocesses re-invoking this command with --fleet-role
        # worker. Source flags forward verbatim; everything else rides
        # a config-json snapshot of the merged config.
        from ..fleet.launcher import run_local_fleet

        return run_local_fleet(cfg, args)

    if args.source == "synthetic":
        from ..testing import SyntheticConfig

        faulted = [
            int(x)
            for x in (args.fault_windows or "").split(",")
            if x.strip()
        ]
        source = SyntheticSource(
            n_windows=args.windows,
            faulted=faulted,
            synth_config=SyntheticConfig(
                n_operations=args.operations,
                n_pods=args.pods,
                n_kinds=args.kinds,
                n_traces=args.traces,
                fault_latency_ms=args.fault_ms,
                fault_kind=args.fault_kind,
                n_faults=args.fault_count,
                drift_per_window=args.drift,
                window_minutes=args.detect_minutes,
                seed=args.seed,
            ),
            pace_seconds=args.pace_seconds,
        )
        log.info(
            "synthetic source: %d windows, fault windows %s, "
            "injected %s fault(s) %s",
            args.windows, faulted or "none", args.fault_kind,
            source.fault_pod_ops,
        )
    elif args.input is None:
        log.error("--source %s needs --input TRACES_CSV", args.source)
        return 2
    elif args.source == "replay":
        source = ReplaySource(
            args.input,
            chunk_spans=args.chunk_spans,
            pace_seconds=args.pace_seconds,
            rate=args.rate,
        )
    else:  # tail
        source = FileTailSource(
            args.input,
            poll_seconds=args.poll_seconds,
            idle_exit=args.idle_exit or 0,
            parse_retry_max=cfg.ingest.parse_retry_max,
        )

    normal_df = None
    if args.normal:
        from ..io import load_traces_csv

        normal_df = load_traces_csv(args.normal)
    if getattr(args, "metrics_port", None) is not None:
        from ..obs.server import start_metrics_server

        server = start_metrics_server(
            args.metrics_port,
            profile_dir=(
                str(Path(args.output) / "profiles") if args.output else None
            ),
        )
        log.info(
            "metrics endpoint: http://127.0.0.1:%d/metrics (+ /profilez)",
            server.port,
        )
    # Crash-only shutdown: SIGTERM asks the engine to drain at the next
    # batch boundary and write a final checkpoint — the process can be
    # restarted with --resume and continue the SAME run.
    import signal as _signal

    def _install_sigterm(engine):
        def _on_sigterm(_signo, _frame):
            log.info(
                "SIGTERM: draining stream engine (checkpoint on exit)"
            )
            engine.request_stop()

        try:
            _signal.signal(_signal.SIGTERM, _on_sigterm)
        except ValueError:  # pragma: no cover - not on the main thread
            pass

    if getattr(args, "fleet_role", None) == "worker":
        if not args.coordinator_url or not args.host_id:
            log.error(
                "--fleet-role worker needs --coordinator-url and "
                "--host-id"
            )
            return 2
        from ..fleet.worker import run_fleet_worker

        s, _engine = run_fleet_worker(
            cfg,
            source,
            out_dir=args.output,
            host_id=args.host_id,
            coordinator_url=args.coordinator_url,
            normal_df=normal_df,
            resume=bool(getattr(args, "resume", False)),
            on_engine=_install_sigterm,
        )
        log.info(
            "fleet worker %s done: %d windows (%d ranked), %d spans; "
            "results in %s",
            args.host_id, s.windows, s.ranked, s.spans, args.output,
        )
        return 0

    engine = StreamEngine(
        cfg,
        source,
        out_dir=args.output,
        normal_df=normal_df,
        incident_sinks=[StdoutIncidentSink()],
        resume=bool(getattr(args, "resume", False)),
    )
    _install_sigterm(engine)
    s = engine.run()
    for r in s.results:
        if r.ranking:
            print(f"window {r.start}:")
            for rank, (name, score) in enumerate(r.ranking, 1):
                print(f"  {rank:2d}. {name:<50s} {score:.8f}")
    log.info(
        "stream done: %d windows (%d ranked, %d clean, %d empty, "
        "%d skipped, %d warmup), %d gated dispatches, %d late spans "
        "dropped, incidents %d opened / %d resolved; results in %s",
        s.windows, s.ranked, s.clean, s.empty, s.skipped, s.warmup,
        s.dispatches, s.late_spans, s.incidents_opened,
        s.incidents_resolved, args.output,
    )
    return 0


def _find_bundles(target: Path):
    """Resolve an explain target (bundle .json, run output dir, flight
    dump dir, or journal.jsonl) to a list of bundle dicts, searching:
    the file itself -> explain_bundle.json -> explain/*/ bundle dirs ->
    journal/events ``explain`` records (compact journal mirrors)."""
    from ..explain.bundle import BUNDLE_JSON, ExplainBundle

    bundles = []
    if target.is_file():
        if target.name.endswith(".jsonl"):
            from ..obs import read_journal

            for e in read_journal(target):
                if e.get("event") == "explain":
                    bpath = e.get("bundle")
                    if bpath and Path(bpath).exists():
                        bundles.append(
                            ExplainBundle.load(bpath).data
                        )
                    else:
                        bundles.append({"journal_record": e})
            return bundles
        return [ExplainBundle.load(target).data]
    if (target / BUNDLE_JSON).exists():
        return [ExplainBundle.load(target / BUNDLE_JSON).data]
    exp_dir = target / "explain"
    if exp_dir.is_dir():
        for sub in sorted(exp_dir.iterdir()):
            if (sub / BUNDLE_JSON).exists():
                bundles.append(ExplainBundle.load(sub / BUNDLE_JSON).data)
        if bundles:
            return bundles
    for journal_name in ("journal.jsonl", "events.jsonl"):
        if (target / journal_name).exists():
            bundles.extend(_find_bundles(target / journal_name))
            if bundles:
                return bundles
    return bundles


def cmd_explain(args) -> int:
    """Render rank provenance from run artifacts: explain bundles
    written by the stream engine (incident opens), journal ``explain``
    events, or a flight dump's cross-linked bundle — the offline twin
    of ``GET /explainz``."""
    from ..explain.bundle import ExplainBundle

    target = Path(args.target)
    if not target.exists():
        print(f"no such explain target: {target}", file=sys.stderr)
        return 2
    bundles = _find_bundles(target)
    if not bundles:
        print(
            f"no explain bundles under {target} (run with --explain, "
            "or ask serve for explain:true)",
            file=sys.stderr,
        )
        return 2
    if args.window is not None:
        bundles = [
            b
            for b in bundles
            if str(
                (b.get("window") or {}).get("start")
                or (b.get("journal_record") or {}).get("start")
            )
            == str(args.window)
        ]
        if not bundles:
            print(
                f"no bundle for window {args.window!r}", file=sys.stderr
            )
            return 2
    data = bundles[-1]
    if "journal_record" in data:
        # Compact journal mirror only (bundle file gone): show it raw.
        print(json.dumps(data["journal_record"], indent=2))
        return 0
    if args.json:
        Path(args.json).write_text(json.dumps(data, indent=2))
    if args.format == "json":
        print(json.dumps(data, indent=2))
    else:
        print(ExplainBundle(data).to_table(), end="")
    return 0


def cmd_scenarios(args) -> int:
    """Scenario matrix + self-tuning policy engine (scenarios/): run
    every fault family through the real batch + streaming pipelines,
    score all 13 spectrum formulas per scenario (tie-aware MAP/MRR/
    top-k), emit the matrix artifact, and persist the auto-selected
    formula/kernel/pad policy as policy.json next to the warmup
    manifest — restarted serve/stream/table/run processes inherit it."""
    from ..scenarios import FAMILIES, default_matrix, render_table, run_matrix
    from ..utils.logging import get_logger

    log = get_logger("microrank_tpu.cli")
    cfg = _config_from_args(args)
    if getattr(args, "from_warehouse", None):
        # Retroactive lane: score a STORED run's incidents (all 13
        # formulas over the sealed blobs + recorded truth) and feed the
        # winner back through the same policy engine.
        from ..warehouse import render_retro_table, run_retro

        result = run_retro(
            args.from_warehouse,
            config=cfg,
            seed=args.seed,
            persist_policy=not args.no_persist_policy,
        )
        print(render_retro_table(result))
        if args.json:
            Path(args.json).write_text(json.dumps(result, indent=2))
        if not result["record"]["formulas"]:
            log.error(
                "warehouse %s: no stored ranked windows to score",
                args.from_warehouse,
            )
            return 1
        return 0
    specs = default_matrix(args.seed, full=args.full)
    if args.families:
        wanted = {f.strip() for f in args.families.split(",") if f.strip()}
        unknown = wanted - set(FAMILIES)
        if unknown:
            log.error(
                "unknown families %s; available: %s",
                sorted(unknown), ", ".join(FAMILIES),
            )
            return 2
        specs = [s for s in specs if s.family in wanted]
    if not specs:
        log.error("no scenarios selected")
        return 2
    log.info(
        "scenario matrix: %d scenarios over %d families (seed %d)",
        len(specs), len({s.family for s in specs}), args.seed,
    )
    artifact = run_matrix(
        cfg,
        specs=specs,
        out_dir=args.output,
        seed=args.seed,
        stream_lane=not args.no_stream_lane,
        tune=not args.no_tune,
        persist_policy=not args.no_persist_policy,
    )
    print(render_table(artifact), end="")
    if args.json:
        Path(args.json).write_text(json.dumps(artifact, indent=2))
    errors = [
        r["scenario"]
        for r in artifact["scenarios"]
        if r["truth"] and not r["formulas"]
    ]
    if errors:
        log.error(
            "scenarios with injected faults but no scored windows: %s",
            errors,
        )
        return 1
    return 0


def cmd_replay(args) -> int:
    """Time-travel RCA (warehouse/): re-rank stored windows for a time
    range through the live DispatchRouter (blob load + dispatch, no CSV
    parse) and verify each fresh ranking against the stored verdict
    with the tie-aware comparator. Exits nonzero on any mismatch — the
    warehouse-smoke CI job gates on this."""
    from ..utils.logging import get_logger
    from ..warehouse import parse_time_range, replay_range

    log = get_logger("microrank_tpu.cli")
    cfg = _config_from_args(args)
    try:
        t0_us, t1_us = parse_time_range(args.at)
    except (ValueError, TypeError) as exc:
        log.error("bad --at range %r: %s", args.at, exc)
        return 2
    report = replay_range(
        args.target, t0_us, t1_us, config=cfg, k=args.top
    )
    rng = args.at if args.at not in ("", "*") else "all"
    print(
        f"replay --at {rng}: {report['ranked']}/{report['windows']} "
        f"windows re-ranked, {report['matched']} matched, "
        f"{len(report['mismatched'])} mismatched "
        f"({report['spans']} spans in {report['elapsed_s']}s"
        + (
            f", {report['spans_per_sec']} spans/s"
            if report["spans_per_sec"] is not None else ""
        )
        + f") -> {report['verdict']}"
    )
    for mm in report["mismatched"]:
        print(
            f"  MISMATCH {mm['start']}..{mm['end']}: {mm['reason']}"
        )
        print(f"    stored:   {mm['stored_top']}")
        print(f"    replayed: {mm['replayed_top']}")
    if report["skipped_no_blob"]:
        log.warning(
            "%d ranked window(s) stored without rank blobs were "
            "skipped (run with warehouse.store_blobs=true to make "
            "history replayable)",
            report["skipped_no_blob"],
        )
    if args.json:
        Path(args.json).write_text(json.dumps(report, indent=2))
    return 0 if report["verdict"] == "match" else 1


def cmd_synth(args) -> int:
    from ..testing import SyntheticConfig, generate_case

    cfg = SyntheticConfig(
        n_operations=args.operations,
        n_pods=args.pods,
        n_kinds=args.kinds,
        n_traces=args.traces,
        fault_latency_ms=args.fault_ms,
        seed=args.seed,
    )
    case = generate_case(cfg)
    out = Path(args.output)
    (out / "normal").mkdir(parents=True, exist_ok=True)
    (out / "abnormal").mkdir(parents=True, exist_ok=True)
    case.normal.to_csv(out / "normal" / "traces.csv", index=False)
    case.abnormal.to_csv(out / "abnormal" / "traces.csv", index=False)
    truth = {
        "fault_service_op": case.fault_service_op,
        "fault_pod_op": case.fault_pod_op,
        "fault_op": case.fault_op,
        "fault_pod": case.fault_pod,
        "config": {
            "n_operations": cfg.n_operations,
            "n_traces": cfg.n_traces,
            "seed": cfg.seed,
        },
    }
    (out / "ground_truth.json").write_text(json.dumps(truth, indent=2))
    print(
        f"wrote {len(case.normal)} normal + {len(case.abnormal)} abnormal "
        f"spans to {out} (fault: {case.fault_pod_op})"
    )
    return 0


def cmd_collect(args) -> int:
    from ..collect.clickhouse import run_collect

    return run_collect(args)


def _report_dict(rep) -> dict:
    """The JSON shape shared by every eval report writer."""
    return {
        "recall_at": rep.recall_at,
        "exam_score": rep.exam_score,
        # The paper's unnormalized Exam form (Tables 4-6 comparability).
        "exam_score_paper": rep.exam_score_paper,
        "detection_rate": rep.detection_rate,
    }


def cmd_eval(args) -> int:
    from ..evaluation import (
        EvalConfig,
        evaluate,
        evaluate_all_methods,
        evaluate_detection,
    )

    cfg = _config_from_args(args)
    eval_cfg = EvalConfig(
        n_cases=args.cases,
        n_operations=args.operations,
        n_traces=args.traces,
        n_pods=args.pods,
        n_kinds=args.kinds,
        child_keep_prob=args.keep_prob,
        n_faults=args.faults,
        fault_latency_ms=args.fault_ms,
        fault_path_overlap=args.fault_overlap,
        seed0=args.seed,
    )
    if args.overlap_ablation:
        from ..evaluation import evaluate_overlap_ablation

        reports = evaluate_overlap_ablation(cfg, eval_cfg)
        for ov, rep in reports.items():
            print(f"overlap={ov:.2f}  {rep.summary()}")
        if args.json:
            out = {str(ov): _report_dict(rep) for ov, rep in reports.items()}
            Path(args.json).write_text(json.dumps(out, indent=2))
        return 0
    if args.detection:
        report = evaluate_detection(cfg, eval_cfg, n_windows=args.windows)
        print(report.summary())
        if args.json:
            Path(args.json).write_text(
                json.dumps(
                    {
                        "precision": report.precision,
                        "recall": report.recall,
                        "f1": report.f1,
                        "tp": report.tp, "fp": report.fp,
                        "fn": report.fn, "tn": report.tn,
                    },
                    indent=2,
                )
            )
        return 0
    if args.all_methods:
        reports = evaluate_all_methods(cfg, eval_cfg)
        width = max(len(m) for m in reports)
        for m, rep in reports.items():
            print(f"{m:<{width}}  {rep.summary()}")
        if args.json:
            out = {m: _report_dict(rep) for m, rep in reports.items()}
            Path(args.json).write_text(json.dumps(out, indent=2))
        return 0
    report = evaluate(cfg, eval_cfg)
    print(report.summary())
    if args.json:
        out = {
            **_report_dict(report),
            "cases": [
                {"seed": c.seed, "faults": c.faults, "ranks": c.ranks}
                for c in report.cases
            ],
        }
        Path(args.json).write_text(json.dumps(out, indent=2))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="microrank_tpu",
        description="TPU-native trace-based root cause analysis",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_run = sub.add_parser("run", help="online RCA over trace dumps")
    p_run.add_argument("--normal", required=True, help="normal-period traces.csv")
    p_run.add_argument("--abnormal", required=True, help="traces.csv to analyze")
    p_run.add_argument("-o", "--output", default="rca_out")
    p_run.add_argument("--slo-cache", help="npz path to cache the SLO baseline")
    p_run.add_argument(
        "--resume", action="store_true", help="resume from the window cursor"
    )
    p_run.add_argument(
        "--engine",
        default="auto",
        choices=["auto", "native", "pandas"],
        help="ingest engine: the C++ span loader or the pandas path",
    )
    p_run.add_argument(
        "--mesh",
        help='device mesh for sharded ranking: "8" (graph-parallel '
        'over 8 devices) or "2x4" (2-way window batch x 4-way graph '
        "shard; the windows axis >1 needs batch mode)",
    )
    p_run.add_argument(
        "--kernel",
        default="auto",
        choices=[
            "auto", "kind", "packed", "packed_bf16", "packed_blocked",
            "pcsr", "csr", "coo", "dense", "dense_bf16", "pallas",
        ],
        help="power-iteration kernel ('kind' = kind-compressed "
        "reduced-precision iteration over the collapsed trace-kind "
        "axis; 'auto' selects it when the measured dedup factor "
        "clears --kind-dedup-threshold)",
    )
    p_run.add_argument(
        "--kind-precision",
        default=None,
        choices=["int8", "bf16", "f32"],
        help="kernel='kind' coverage matvec precision: f32 (default — "
        "bit-identical to packed f32) / bf16 operands with f32 "
        "accumulation, or scaled-int8 operands with exact int32 "
        "accumulation",
    )
    p_run.add_argument(
        "--kind-dedup-threshold",
        type=float,
        default=None,
        help="measured window dedup factor (true traces / distinct "
        "kinds) past which kernel='auto' selects the kind-compressed "
        "kernel (default 4.0; microrank_kind_dedup_ratio records the "
        "measured factor)",
    )
    p_run.add_argument(
        "--profile-dir",
        help="wrap the window loop in a jax.profiler trace and write the "
        "Perfetto dump here (rank 0 only in distributed runs)",
    )
    p_run.add_argument(
        "--sync-dispatch", action="store_true",
        help="disable the async stage/fetch worker threads (default on: "
        "staging and fetch RPC latency overlap the next window's host "
        "work)",
    )
    p_run.add_argument(
        "--pipeline-depth", type=_positive_int, default=None,
        help="device rank programs allowed in flight (1 = synchronous)",
    )
    p_run.add_argument(
        "--no-blob-staging", action="store_true",
        help="stage graphs as per-leaf transfers instead of one packed "
        "uint32 buffer",
    )
    p_run.add_argument(
        "--device-checks", action="store_true",
        help="assert the finite-score invariant INSIDE the compiled "
        "program (checkify; forces synchronous dispatch)",
    )
    p_run.add_argument(
        "--fetch-mode", choices=["stream", "bulk"], default=None,
        help="result fetches: per-window ('stream', lowest sink "
        "latency) or batched over --bulk-fetch-windows windows "
        "('bulk', highest replay throughput on high-latency links; "
        "supersedes --pipeline-depth as the in-flight bound)",
    )
    p_run.add_argument(
        "--bulk-fetch-windows", type=_positive_int, default=None,
        help="windows joined per batched fetch in --fetch-mode bulk",
    )
    p_run.add_argument(
        "--dispatch-batch-windows", type=_positive_int, default=None,
        help="group this many anomalous windows into one stacked "
        "stage+dispatch (one staging transfer per group — the replay "
        "throughput knob on high-latency links; 1 = lowest per-window "
        "latency)",
    )
    p_run.add_argument(
        "--follow", action="store_true",
        help="online mode: tail the (growing) --abnormal CSV and rank "
        "windows as they close; the window cursor in -o makes polls "
        "and restarts incremental (native engine, single process)",
    )
    p_run.add_argument(
        "--poll-seconds", type=float, default=5.0,
        help="--follow: seconds between file polls",
    )
    p_run.add_argument(
        "--follow-grace-seconds", type=float, default=0.0,
        help="--follow: hold a window open this long past its end for "
        "straggler spans before ranking it",
    )
    p_run.add_argument(
        "--follow-idle-exit", type=_positive_int, default=None,
        help="--follow: exit after this many consecutive polls without "
        "file growth (default: follow forever)",
    )
    p_run.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve live telemetry over HTTP on this port (127.0.0.1): "
        "/metrics (Prometheus text), /metrics.json, /healthz; 0 picks "
        "a free port. The snapshot is also written to -o at run end "
        "for offline `stats`",
    )
    p_run.add_argument(
        "--distributed", action="store_true",
        help="join a multi-host jax.distributed runtime before any "
        "device work (coordinator from --coordinator or "
        "MICRORANK_COORDINATOR; only process 0 writes results)",
    )
    p_run.add_argument(
        "--coordinator", help='process 0 address, "host:port"'
    )
    p_run.add_argument("--num-processes", type=int)
    p_run.add_argument("--process-id", type=int)
    _add_config_flags(p_run)
    p_run.set_defaults(fn=cmd_run)

    p_srv = sub.add_parser(
        "serve",
        help="online RCA service: HTTP requests coalesced into "
        "micro-batched device dispatches, with admission control and "
        "numpy_ref graceful degradation",
    )
    p_srv.add_argument(
        "--normal", required=True,
        help="normal-period traces.csv (SLO baseline fitted at startup)",
    )
    p_srv.add_argument(
        "--dataset", action="append", metavar="NAME=CSV",
        help="pre-stage an abnormal dump; requests may then send "
        '{"dataset": NAME, "start": ..., "end": ...} instead of inline '
        "spans (repeatable)",
    )
    p_srv.add_argument("--host", default=None, help="bind address")
    p_srv.add_argument(
        "--port", type=int, default=None,
        help="listen port (0 picks a free port; default 8377)",
    )
    p_srv.add_argument(
        "-o", "--output", default=None,
        help="service output directory: journal.jsonl per batch/window "
        "+ metrics snapshot written at drain",
    )
    p_srv.add_argument(
        "--max-queue-depth", type=_positive_int, default=None,
        help="admission bound: requests admitted at once before the "
        "service answers 429 + Retry-After",
    )
    p_srv.add_argument(
        "--retry-after", type=float, default=None,
        help="Retry-After seconds on 429/503 responses",
    )
    p_srv.add_argument(
        "--max-batch-windows", type=_positive_int, default=None,
        help="micro-batch ceiling: a shape bucket dispatches as soon "
        "as it holds this many requests",
    )
    p_srv.add_argument(
        "--max-wait-ms", type=float, default=None,
        help="micro-batch latency bound: a bucket dispatches once its "
        "oldest request waited this long (the latency/occupancy knob)",
    )
    p_srv.add_argument(
        "--request-timeout", type=float, default=None,
        help="seconds an HTTP caller waits before 504",
    )
    p_srv.add_argument(
        "--drain-seconds", type=float, default=None,
        help="SIGTERM drain bound for in-flight requests",
    )
    p_srv.add_argument(
        "--no-warmup", action="store_true",
        help="skip the startup jit warmup (first requests pay compile)",
    )
    p_srv.add_argument(
        "--warmup-occupancies", default=None, metavar="N,N,...",
        help="batch occupancies the startup warmup compiles (default "
        '"1,2"); every entry must be <= --max-batch-windows',
    )
    p_srv.add_argument(
        "--build-workers", type=int, default=None,
        help="build-pool threads running host graph builds off the "
        "scheduler thread (0 = serial builds on the scheduler thread)",
    )
    p_srv.add_argument(
        "--no-fallback", action="store_true",
        help="disable numpy_ref degradation: failed batches answer 500",
    )
    p_srv.add_argument(
        "--mesh",
        help='device mesh for the dispatch router\'s sharded route: "8" '
        'or "2x4" — batches past --sharded-threshold-mb (or filling the '
        "windows axis) rank via shard_map instead of the single-device "
        "vmapped program",
    )
    p_srv.add_argument(
        "--inject-dispatch-failures", type=int, default=None,
        help="chaos/test knob: fail this many device dispatches with "
        "an injected error (drives the degradation path)",
    )
    p_srv.add_argument(
        "--stream-input", default=None, metavar="TRACES_CSV",
        help="co-deploy: tail this growing trace file through a stream "
        "engine sharing the device via the unified scheduler — "
        "open-incident work preempts interactive serve requests",
    )
    p_srv.add_argument(
        "--backfill", default=None, metavar="WAREHOUSE_DIR",
        help="co-deploy: replay this trace warehouse on the lowest-"
        "priority backfill lane of the unified scheduler (never "
        "jumps ahead of serve or incident work)",
    )
    p_srv.add_argument(
        "--backfill-range", default=None, metavar="START..END",
        help='time range for --backfill (epoch-us ints or pandas-'
        'parsable timestamps; default "all")',
    )
    p_srv.add_argument(
        "--tenant-weight", action="append", metavar="NAME=W",
        help="weighted fair share: tenant NAME gets W times the "
        "device turns of a weight-1 tenant (repeatable)",
    )
    p_srv.add_argument(
        "--tenant-rate", action="append", metavar="NAME=R",
        help="soft token-bucket quota: tenant NAME refills R windows/s "
        "(0 = background class: runs only when in-quota tenants are "
        "idle; unlisted tenants are unlimited) (repeatable)",
    )
    _add_config_flags(p_srv)
    p_srv.set_defaults(fn=cmd_serve)

    p_stream = sub.add_parser(
        "stream",
        help="continuous RCA: event-time windows closed at the "
        "watermark, online SLO baselines, anomaly-gated device "
        "ranking, incident lifecycle",
    )
    p_stream.add_argument(
        "--source",
        default="synthetic",
        choices=["synthetic", "tail", "replay"],
        help="span source: paced synthetic timeline, growing-CSV tail, "
        "or staged-CSV replay with pacing",
    )
    p_stream.add_argument(
        "--input",
        help="traces CSV for --source tail (growing) / replay (staged)",
    )
    p_stream.add_argument(
        "--normal",
        help="normal-period traces.csv seeding the online SLO baseline "
        "(else the baseline cold-starts from the first "
        "--min-healthy-windows windows; the synthetic source seeds "
        "from its own normal window)",
    )
    p_stream.add_argument("-o", "--output", default="stream_out")
    p_stream.add_argument(
        "--resume", action="store_true",
        help="restore the engine's durable state checkpoint "
        "(out_dir/state.ckpt: online SLO baselines, incident tracker, "
        "windower watermark + buffered windows, source cursor) and "
        "continue the crashed/stopped run — zero duplicate incidents, "
        "no cold start, no re-ranked windows",
    )
    p_stream.add_argument(
        "--slide-minutes", type=float, default=None,
        help="sliding-window step (default: tumbling windows of "
        "--detect-minutes)",
    )
    p_stream.add_argument(
        "--lateness-seconds", type=float, default=None,
        help="allowed out-of-order lateness before the watermark "
        "closes a window (later spans are dropped and counted)",
    )
    p_stream.add_argument(
        "--baseline-decay", type=float, default=None,
        help="exponential-decay weight one healthy window contributes "
        "to the online SLO baseline",
    )
    p_stream.add_argument(
        "--min-healthy-windows", type=_positive_int, default=None,
        help="cold-start windows absorbed before detection arms "
        "(ignored when the baseline is seeded)",
    )
    p_stream.add_argument(
        "--resolve-after", type=_positive_int, default=None,
        help="consecutive healthy windows that resolve an incident",
    )
    p_stream.add_argument(
        "--cooldown", type=int, default=None,
        help="windows a resolved fingerprint is suppressed instead of "
        "reopened (flap damping)",
    )
    p_stream.add_argument(
        "--fingerprint-top-k", type=_positive_int, default=None,
        help="tie-aware top-k suspect set size fingerprinting each "
        "ranked window",
    )
    p_stream.add_argument(
        "--build-workers", type=int, default=None,
        help="build-pool threads overlapping host graph builds with "
        "device ranking",
    )
    p_stream.add_argument(
        "--webhook", help="POST every incident transition here (JSON)"
    )
    p_stream.add_argument(
        "--pipeline-windows", type=_positive_int, default=None,
        help="abnormal windows in flight (build submitted, rank "
        "pending) before the engine ranks the head — also the burst "
        "depth available to the router's coalescing",
    )
    p_stream.add_argument(
        "--mesh",
        help='device mesh for the dispatch router\'s sharded route: "8" '
        'or "2x4" — windows past --sharded-threshold-mb rank via '
        "shard_map instead of the single-device program",
    )
    p_stream.add_argument(
        "--max-windows", type=int, default=None,
        help="stop after this many closed windows (CI/smoke bound; "
        "default: run until the source ends)",
    )
    p_stream.add_argument(
        "--pace-seconds", type=float, default=0.0,
        help="synthetic/replay: sleep between emitted span chunks",
    )
    p_stream.add_argument(
        "--chunk-spans", type=_positive_int, default=5000,
        help="replay: spans per emitted chunk",
    )
    p_stream.add_argument(
        "--rate", type=float, default=None,
        help="replay: event-time faithful pacing at RATE x real time "
        "(overrides --pace-seconds)",
    )
    p_stream.add_argument(
        "--poll-seconds", type=float, default=2.0,
        help="tail: seconds between file polls",
    )
    p_stream.add_argument(
        "--idle-exit", type=_positive_int, default=None,
        help="tail: exit after this many consecutive polls without "
        "progress (default: tail forever)",
    )
    p_stream.add_argument(
        "--windows", type=_positive_int, default=8,
        help="synthetic: timeline length in windows",
    )
    p_stream.add_argument(
        "--fault-windows", default="3",
        help='synthetic: comma list of faulted window indices ("" = '
        "none)",
    )
    p_stream.add_argument("--operations", type=int, default=30)
    p_stream.add_argument("--pods", type=int, default=1)
    p_stream.add_argument("--kinds", type=int, default=24)
    p_stream.add_argument("--traces", type=int, default=300)
    p_stream.add_argument("--fault-ms", type=float, default=2000.0)
    p_stream.add_argument(
        "--fault-kind", choices=["latency", "error"], default="latency",
        help="synthetic: injected fault family — latency (own time "
        "jumps) or error (status-code fault, fail-fast; only the "
        "error-status detector path sees it)",
    )
    p_stream.add_argument(
        "--fault-count", type=_positive_int, default=1,
        help="synthetic: simultaneous culprits per faulted window "
        "(ground truth carries the full set)",
    )
    p_stream.add_argument(
        "--drift", type=float, default=0.0,
        help="synthetic: per-window multiplicative own-time growth "
        "(gradual SLO drift the online baseline must absorb)",
    )
    p_stream.add_argument("--seed", type=int, default=0)
    p_stream.add_argument(
        "--metrics-port", type=int, default=None,
        help="serve live telemetry over HTTP on this port; the "
        "snapshot also lands in -o at exit",
    )
    p_stream.add_argument(
        "--fleet", type=_positive_int, default=None, metavar="N",
        help="fleet mode: run the global incident coordinator in this "
        "process and spawn N worker subprocesses, each streaming its "
        "partition of the span source under -o/host<i>/ with its own "
        "checkpoint; heartbeat leases + partition reassignment make "
        "the fleet survive losing a worker, and dead workers restart "
        "with --resume (crash-only supervision)",
    )
    p_stream.add_argument(
        "--fleet-role", choices=["worker"], default=None,
        help="join an existing fleet as a worker (needs "
        "--coordinator-url and --host-id; `--fleet N` spawns these "
        "for you locally — use this directly to place workers on "
        "their own hosts)",
    )
    p_stream.add_argument(
        "--coordinator-url", default=None,
        help="fleet coordinator base URL (worker role)",
    )
    p_stream.add_argument(
        "--host-id", default=None,
        help="this worker's stable fleet identity (worker role; also "
        "the id host-scoped chaos specs match)",
    )
    p_stream.add_argument(
        "--fleet-partitions", type=_positive_int, default=None,
        help="source partitions split across the fleet (default: one "
        "per worker)",
    )
    p_stream.add_argument(
        "--partition-by", choices=["trace", "service"], default=None,
        help="partition key: crc32 of traceID (even spread; default) "
        "or of serviceName (service locality)",
    )
    p_stream.add_argument(
        "--heartbeat-seconds", type=float, default=None,
        help="worker heartbeat cadence (renews the coordinator lease)",
    )
    p_stream.add_argument(
        "--lease-seconds", type=float, default=None,
        help="lease a silent worker holds before it is marked dead "
        "and its partitions reassign to survivors",
    )
    p_stream.add_argument(
        "--fleet-port", type=int, default=None,
        help="coordinator bind port for --fleet (default: a free port)",
    )
    p_stream.add_argument(
        "--fleet-restart-delay", type=float, default=None,
        help="--fleet supervision: seconds before a dead worker "
        "restarts with --resume",
    )
    p_stream.add_argument(
        "--fleet-no-restart", action="store_true",
        help="--fleet supervision: leave dead workers dead",
    )
    p_stream.add_argument(
        "--warehouse", action="store_true",
        help="archive every sealed window into the tiered span "
        "warehouse under the output dir (hot -> warm segment blobs at "
        "seal, cold compaction after warehouse.compact_after windows); "
        "enables `replay --at` and `scenarios --from-warehouse`",
    )
    p_stream.add_argument(
        "--warehouse-dir", default=None, metavar="DIR",
        help="warehouse directory (default: <output>/warehouse; "
        "implies --warehouse)",
    )
    p_stream.add_argument(
        "--delta-build", action="store_true",
        help="incremental sliding-window graph builds: thread each "
        "window's per-trace build caches into the next overlapping "
        "window so only boundary traces pay string/factorize work "
        "(exact — integrity-checked per window with automatic cold "
        "fallback; see microrank_build_route_total)",
    )
    p_stream.add_argument(
        "--fused-pair", action="store_true",
        help="fused pair program: both PageRank solves + the spectrum "
        "epilogue in ONE jitted dispatch per abnormal window, "
        "exporting converged state to warm-start the next window "
        "while an incident is open",
    )
    p_stream.add_argument(
        "--journal-max-bytes", type=int, default=None, metavar="N",
        help="rotate journal.jsonl once it exceeds N bytes (fsync-"
        "before-rename into journal.jsonl.<n> parts; 0 = never, the "
        "default)",
    )
    _add_config_flags(p_stream)
    p_stream.set_defaults(fn=cmd_stream)

    p_exp = sub.add_parser(
        "explain",
        help="render rank provenance from run artifacts (explain "
        "bundles, journal explain events, flight-dump bundles)",
    )
    p_exp.add_argument(
        "target",
        help="an explain bundle .json, a run output dir (reads "
        "explain/*/ bundles or journal.jsonl), a flight dump dir "
        "(reads its cross-linked bundle), or a journal.jsonl path",
    )
    p_exp.add_argument(
        "--window", default=None,
        help="select the bundle for this window start (default: the "
        "latest bundle found)",
    )
    p_exp.add_argument(
        "--format", choices=["table", "json"], default="table",
        help="human-readable table (default) or the raw bundle JSON",
    )
    p_exp.add_argument(
        "--json", default=None,
        help="also write the selected bundle JSON to this path",
    )
    p_exp.set_defaults(fn=cmd_explain)

    p_scn = sub.add_parser(
        "scenarios",
        help="run the scenario matrix (every fault family x all 13 "
        "spectrum formulas) through the real pipelines, emit the "
        "per-scenario MAP/top-k artifact, and persist the auto-"
        "selected formula/kernel/pad policy for restarts to inherit",
    )
    p_scn.add_argument(
        "-o", "--output", default="scenario_out",
        help="artifact directory: scenario_matrix.json + per-scenario "
        "stream-lane run dirs (journal, incidents)",
    )
    p_scn.add_argument(
        "--seed", type=int, default=0,
        help="ONE seed reproduces the whole matrix byte-for-byte",
    )
    p_scn.add_argument(
        "--full", action="store_true",
        help="two specs per family (harder variants) instead of one",
    )
    p_scn.add_argument(
        "--families", default=None, metavar="F1,F2,...",
        help="restrict to these families (latency, error, multi, "
        "cascade, cold_start, drift)",
    )
    p_scn.add_argument(
        "--no-stream-lane", action="store_true",
        help="skip the streaming-engine lane (batch scoring only; "
        "cold-start and drift evidence comes from the stream lane)",
    )
    p_scn.add_argument(
        "--no-tune", action="store_true",
        help="skip the kernel/pad-policy timing sweep (the persisted "
        "policy keeps built-in kernel/pad defaults)",
    )
    p_scn.add_argument(
        "--no-persist-policy", action="store_true",
        help="emit the matrix artifact but do not write policy.json",
    )
    p_scn.add_argument(
        "--json", default=None,
        help="also write the full matrix artifact JSON here",
    )
    p_scn.add_argument(
        "--from-warehouse", default=None, metavar="DIR",
        help="retroactive lane: instead of synthetic scenarios, score "
        "a STORED run's warehouse incidents across all 13 formulas "
        "(tie-aware MAP/MRR/top-k vs the recorded ground truth) and "
        "persist the winning policy — the policy engine tunes on real "
        "incident outcomes",
    )
    _add_config_flags(p_scn)
    p_scn.set_defaults(fn=cmd_scenarios)

    p_replay = sub.add_parser(
        "replay",
        help="time-travel RCA: re-rank stored warehouse windows for a "
        "time range through the live dispatch lane (blob load, no CSV "
        "parse) and verify bit-tie-aware agreement with the stored "
        "verdicts; exits nonzero on mismatch",
    )
    p_replay.add_argument(
        "target",
        help="a stream run output dir (reads its warehouse/) or a "
        "warehouse directory itself",
    )
    p_replay.add_argument(
        "--at", required=True, metavar="RANGE",
        help="time range to replay: 'all', 'START..END' (each side an "
        "epoch-microsecond integer or any parsable timestamp, either "
        "side empty = open), or a single instant selecting the "
        "window(s) containing it",
    )
    p_replay.add_argument(
        "-k", "--top", type=int, default=5,
        help="verify agreement over the top-k of each stored verdict "
        "(default 5)",
    )
    p_replay.add_argument(
        "--json", default=None,
        help="also write the full replay report JSON to this path",
    )
    _add_config_flags(p_replay)
    p_replay.set_defaults(fn=cmd_replay)

    p_synth = sub.add_parser("synth", help="generate a synthetic chaos case")
    p_synth.add_argument("-o", "--output", required=True)
    p_synth.add_argument("--operations", type=int, default=40)
    p_synth.add_argument("--pods", type=int, default=1)
    p_synth.add_argument("--kinds", type=int, default=24)
    p_synth.add_argument("--traces", type=int, default=500)
    p_synth.add_argument("--fault-ms", type=float, default=2000.0)
    p_synth.add_argument("--seed", type=int, default=0)
    p_synth.set_defaults(fn=cmd_synth)

    p_eval = sub.add_parser(
        "eval",
        help="R@k / Exam-Score accuracy experiment over synthetic chaos "
        "cases (the paper's Tables 4-6 methodology, reproducible)",
    )
    p_eval.add_argument("--cases", type=int, default=20)
    p_eval.add_argument("--operations", type=int, default=30)
    p_eval.add_argument("--traces", type=int, default=400)
    p_eval.add_argument("--pods", type=int, default=1)
    p_eval.add_argument("--kinds", type=int, default=48)
    p_eval.add_argument("--faults", type=int, default=1)
    p_eval.add_argument("--fault-ms", type=float, default=2000.0)
    p_eval.add_argument(
        "--keep-prob", type=float, default=0.15,
        help="per-kind subtree keep probability: trace-kind breadth "
        "(lower = narrower, more request-like traces)",
    )
    p_eval.add_argument(
        "--fault-overlap", type=float, default=None,
        help="target root-path overlap between injected faults "
        "(multi-fault hardness control, 0=disjoint paths, 1=nested)",
    )
    p_eval.add_argument(
        "--overlap-ablation", action="store_true",
        help="sweep --fault-overlap over 0, 0.25, 0.5, 0.75, 1 "
        "(two-fault hardness ablation)",
    )
    p_eval.add_argument("--seed", type=int, default=1000)
    p_eval.add_argument(
        "--all-methods",
        action="store_true",
        help="score every spectrum formula (one device dispatch per case)",
    )
    p_eval.add_argument(
        "--detection",
        action="store_true",
        help="window-level detection precision/recall/F1 over timelines "
        "(the paper's Fig. 9 experiment)",
    )
    p_eval.add_argument(
        "--windows", type=int, default=10,
        help="timeline length for --detection (half the windows faulted)",
    )
    p_eval.add_argument("--json", help="write the detailed report here")
    _add_config_flags(p_eval)
    p_eval.set_defaults(fn=cmd_eval)

    p_col = sub.add_parser(
        "collect", help="export chaos-case traces from ClickHouse (optional)"
    )
    p_col.add_argument("--host", default="localhost")
    p_col.add_argument("--namespace", required=False)
    p_col.add_argument("--config-toml", help="chaos events TOML manifest")
    p_col.add_argument("-o", "--output", default=".")
    p_col.add_argument(
        "--window-minutes", type=_positive_int, default=10,
        help="normal/abnormal export window around each event "
        "(reference: 10 minutes)",
    )
    p_col.add_argument(
        "--concurrency", type=_positive_int, default=2,
        help="concurrent ClickHouse queries (reference: Semaphore(2))",
    )
    p_col.set_defaults(fn=cmd_collect)

    p_stats = sub.add_parser(
        "stats",
        help="re-emit a finished run's metrics snapshot (Prometheus "
        "text or JSON) and summarize its journal",
    )
    p_stats.add_argument(
        "target",
        nargs="+",
        help="a run output directory (reads metrics.json there) or a "
        "metrics.json path; with --diff, exactly two of these "
        "(before after)",
    )
    p_stats.add_argument(
        "--diff", action="store_true",
        help="emit after-minus-before metric deltas between TWO "
        "targets (counters/histograms subtract, gauges keep the "
        "after reading)",
    )
    p_stats.add_argument(
        "--merge", action="store_true",
        help="federate N per-host snapshots into one fleet view "
        "(counters and histogram buckets sum, gauges gain a host "
        "label); a fleet output dir expands to its host*/metrics.json "
        "children; composes with --diff (two targets, each merged)",
    )
    p_stats.add_argument(
        "--format", choices=["prom", "json"], default="prom",
        help="exposition format (default: Prometheus text)",
    )
    p_stats.add_argument(
        "--journal", action="store_true",
        help="also print a one-line journal summary to stderr",
    )
    p_stats.set_defaults(fn=cmd_stats)

    from ..analysis.cli import add_lint_parser, add_witness_parser

    add_lint_parser(sub)
    add_witness_parser(sub)

    args = parser.parse_args(argv)
    if args.fn in (
        cmd_run, cmd_eval, cmd_serve, cmd_stream, cmd_scenarios,
        cmd_replay,
    ):  # jax-touching only
        _enable_jit_cache()
    return args.fn(args)


def _enable_jit_cache(runtime=None) -> None:
    """Persist compiled XLA programs across CLI invocations (first TPU
    compile is seconds; cached reloads are near-instant — a second
    process on the same config reports compile_ms ~ 0, see
    tests/test_pipeline.py::test_persistent_compile_cache_across_processes).
    One wiring point since PR 5: dispatch.cache.configure_compile_cache
    (MICRORANK_JIT_CACHE env > RuntimeConfig.compile_cache_dir /
    --compile-cache-dir > the user-cache default; min-compile-time and
    min-entry-size gates zeroed so windows-shaped programs and CPU runs
    persist too)."""
    from ..dispatch import configure_compile_cache

    configure_compile_cache(runtime)


if __name__ == "__main__":
    sys.exit(main())
