from .main import main

__all__ = ["main"]
