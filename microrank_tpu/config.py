"""Typed configuration for the whole framework.

The reference hard-codes every constant at its call site (see SURVEY.md §2.2
item 8; /root/reference/pagerank.py:116-117, online_rca.py:158-159,197-201).
Here every knob lives in one frozen dataclass tree, with two presets:

* ``MicroRankConfig()``             — paper semantics (the default).
* ``MicroRankConfig.reference_compat()`` — bit-faithful reproduction of the
  reference code's behavior, including its documented quirks (partition swap
  at the orchestrator boundary, code-form anomalous preference vector).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


@dataclass(frozen=True)
class DetectorConfig:
    """SLO-deviation anomaly detector (reference: anormaly_detector.py:44-84).

    The reference has two detection paths with different thresholds:
    the main path uses ``k_sigma=3`` and no slack
    (anormaly_detector.py:64-65), the alternate/dead path uses ``k_sigma=1``
    plus a 50 ms slack (anormaly_detector.py:107-110). The paper's Eq (1)
    uses n=1.5. One configurable detector covers all three.
    """

    k_sigma: float = 3.0
    slack_ms: float = 0.0
    # A window is flagged anomalous iff >= min_abnormal_traces traces exceed
    # their expected duration (reference: ``if anormaly_trace:`` i.e. >= 1).
    min_abnormal_traces: int = 1
    # Central statistic of the SLO baseline: "mean" (reference behavior) or
    # any percentile "pNN" — e.g. "p90" (the alternative the reference left
    # commented out at preprocess_data.py:72), "p99", "p99.9".
    slo_stat: str = "mean"
    # Error/status-code faults: a trace carrying a span with
    # ``statusCode > 0`` (when the optional column is present) is
    # classified abnormal regardless of latency — error faults fail
    # FAST, so the latency deviation check alone is blind to them. The
    # error bit feeds the same partition the spectrum ranks over; span
    # frames without the column behave exactly as before.
    error_status_abnormal: bool = True

    @classmethod
    def single_trace_variant(cls) -> "DetectorConfig":
        """The reference's alternate path (anormaly_detector.py:101-113)."""
        return cls(k_sigma=1.0, slack_ms=50.0)


@dataclass(frozen=True)
class PageRankConfig:
    """Personalized PageRank scorer (reference: pagerank.py:116-130)."""

    iterations: int = 25
    damping: float = 0.85          # d in the paper
    call_weight: float = 0.01      # alpha / the paper's omega
    # "reference": the code's anomalous preference vector (pagerank.py:75-85);
    # "paper": Eq (7) — phi-weighted sum of normalized 1/n_t and 1/kind_t.
    preference: str = "reference"
    phi: float = 0.5               # only used by preference="paper"
    # Max-normalize both ranking vectors every iteration
    # (pagerank.py:126-127 — not in the paper, but load-bearing for parity).
    max_normalize_each_iter: bool = True
    # Optional convergence tolerance: stop early once the L-inf change of
    # every ranking vector falls below tol (checked jointly for both
    # partitions), still capped at ``iterations``. None (default)
    # reproduces the reference exactly — a fixed 25 iterations with no
    # check, which its own README flags as potentially insufficient for
    # large systems (reference README.md:34-38); set tol AND a higher
    # iterations cap to rank such systems to convergence.
    tol: Optional[float] = None
    # kernel="packed_blocked": ceiling on the unpacked f32 block each
    # scan step materializes (the trace/op column axis splits into the
    # fewest power-of-two blocks that fit). Static under jit (part of
    # the config cache key), so changing it recompiles correctly.
    packed_block_bytes: int = 128 << 20
    # kernel="kind" compute precision of the kind-compressed coverage
    # matvec pair (STORAGE is the int8 pattern in every mode — that is
    # the reduced-precision representation; the call-graph row-sum
    # stays f32 either way): "f32" (default — f32 operands and
    # accumulation, bit-identical scores to the f32 packed kernel, so
    # auto-selected kind preserves every tight-parity guarantee),
    # "bf16" (bf16 operands, f32 accumulation — the measured-parity
    # trade packed_bf16 established), or "int8" (scaled fixed-point per
    # arxiv 2009.10443: the 0/1 pattern streams as int8, the operand
    # vector quantizes per iteration with a symmetric max/127 scale,
    # and the int32 accumulation is exact — operand quantization is the
    # only rounding; rank parity is tie-aware-tested, score tolerance
    # widens). Static under jit (config cache key).
    kind_precision: str = "f32"
    # Entry-sharded (coo/csr/pallas) cross-shard combine: True replaces
    # the plain psum of the dense SpMV partials with a compensated
    # all-gather TwoSum fold (ops.segment.compensated_psum). Evaluated
    # for the ROADMAP compensated-scan item (PR 5) and left OFF: unlike
    # the csr prefix scan — where a plain cumsum rounded value-identical
    # rows differently WITHIN one program and deterministically flipped
    # exact ties — the sharded combine's reassociation is dominated by
    # the per-shard partials' own f32 rounding, which no combine-order
    # fix can recover (measured on the 4-window CPU-mesh batch: worst
    # relative score drift 1.7e-6 plain vs 1.66e-6 compensated, both
    # well inside the tie-aware tolerance the cross-shard parity
    # regression test pins). Kept as an opt-in for shard-count-
    # invariance experiments; costs S x the collective bytes.
    compensated_psum: bool = False
    # Entry-sharded cross-shard combine, sparse prototype (arxiv
    # 1312.3020): True replaces the dense psum of the [V]/[T] SpMV
    # partials with a top-cap (index, value) exchange —
    # ops.segment.sparse_psum: each shard contributes its
    # ``sparse_allreduce_cap`` largest-|value| entries, one all_gather
    # moves the pairs, and a local scatter-add rebuilds the dense
    # vector. Exact whenever every shard's partial has at most ``cap``
    # nonzeros (cap 0 = the full axis, always exact). Evaluated for the
    # ISSUE-11 fleet-scaling item and left OFF — see DESIGN.md "Sparse
    # allreduce evaluation" for the CPU-mesh measurement and verdict.
    sparse_allreduce: bool = False
    # Per-shard entry budget of the sparse exchange; 0 = the full axis
    # length (exact, but then the exchange moves MORE bytes than the
    # dense psum — useful only for parity tests and measurement).
    sparse_allreduce_cap: int = 0


@dataclass(frozen=True)
class SpectrumConfig:
    """Weighted spectrum ranker (reference: online_rca.py:33-152)."""

    method: str = "dstar2"
    top_max: int = 5
    # The reference emits ``top_max + 6`` rows (online_rca.py:148).
    extra_rows: int = 6
    # Missing-side spectrum value. Code uses 1e-7 (online_rca.py:57-58);
    # the paper says 1e-4. Code wins by default.
    eps: float = 1e-7
    # Order of EXACTLY tied scores: "name" (ascending op name — the
    # deterministic default; the device path realizes it as ascending
    # vocab index over the name-sorted window vocab) or "insertion"
    # (the reference's accidental dict-insertion order under a stable
    # sort, online_rca.py:144-152 — oracle backend only).
    tiebreak: str = "name"

    @property
    def n_rows(self) -> int:
        return self.top_max + self.extra_rows


@dataclass(frozen=True)
class WindowConfig:
    """Sliding-window orchestration (reference: online_rca.py:155-216)."""

    detect_minutes: float = 5.0    # online_rca.py:158
    skip_minutes: float = 4.0      # extra advance after an anomaly (:215)


@dataclass(frozen=True)
class CompatConfig:
    """Flags reproducing documented reference quirks (SURVEY.md §2.2)."""

    # Quirk #1: the orchestrator unpacks (flag, abnormal, normal) as
    # (flag, normal, abnormal) (online_rca.py:167), inverting the roles of
    # the two partitions downstream. False = paper semantics.
    partition_swap: bool = False
    # Quirk #5: result.csv opened 'w' per anomaly — only the last survives.
    # False = append per-window records (the sane behavior).
    overwrite_results: bool = False


@dataclass(frozen=True)
class RuntimeConfig:
    """Backend/device execution knobs (no reference equivalent — C18/C19)."""

    backend: str = "jax"           # "jax" | "numpy_ref"
    # Pad dynamic op/trace/nnz extents up to the next bucket to avoid jit
    # recompilation storms (SURVEY.md §7 "Ragged → dense"). Default
    # "pow2q" (round 4): quarter-pow2 buckets — max 25% padding waste
    # (vs pow2's 100%) for at most 4x the compile-cache entries; cuts
    # staged bytes and per-iteration HBM traffic ~35% at the bench shape.
    pad_policy: str = "pow2q"      # "pow2q" | "pow2" | "exact"
    min_pad: int = 8
    # Mesh axis sizes for the sharded path; None = single device.
    mesh_shape: Optional[Tuple[int, ...]] = None
    mesh_axes: Tuple[str, ...] = ("shard",)
    # Compute dtype for the iteration. float32 preserves ranking parity;
    # bfloat16 trades precision for MXU throughput (rank-parity tested).
    dtype: str = "float32"
    # Power-iteration kernel:
    #   "packed" / "packed_bf16" — bitmap-expanded dense MXU matvecs, no
    #       scatter (fastest on TPU when the matrices fit);
    #   "packed_blocked" — the same matvecs with the bitmap's column axis
    #       streamed in blocks through a lax.scan (pagerank.
    #       packed_block_bytes caps the unpacked f32 intermediate) — the
    #       at-scale path past the dense budget;
    #   "pcsr" — partition-centric SpMV (Partition-Centric PageRank,
    #       arxiv 1709.07122): the build bins entries into
    #       source-trace partitions so each SpMV streams contiguous
    #       trace-vector slices + small-range segment sums instead of
    #       T-range random gathers — the memory-bounded at-scale
    #       fallback (entry-linear memory, no bitmap ever exists);
    #   "csr" — cumsum-difference SpMV, scatter-free and entry-linear in
    #       memory (the legacy fallback pcsr replaces; kept for forced
    #       runs and cross-kernel parity);
    #   "dense" / "dense_bf16" — scatter densify + MXU matvecs;
    #   "coo" — segment-sum SpMV (entry-shardable under shard_map, like
    #       csr; packed shards the trace axis instead — see parallel/);
    #   "pallas" — one-hot MXU segment sums (measured on v5e: beats the
    #       coo scatter at 1M entries, ~7x slower than packed — see
    #       DESIGN.md's kernel table; never chosen by "auto");
    #   "kind" — kind-compressed reduced-precision iteration: the
    #       coverage pattern materialized as int8 over the COLLAPSED
    #       kind column axis (multiplicity weights folded — exactly
    #       equivalent PageRank over unique kinds) streamed without the
    #       packed kernel's per-iteration bit-unpack, the call-graph
    #       term an O(C) scatter-free row-sum instead of a [V, V]
    #       matvec, and pagerank.kind_precision selecting
    #       int8/bf16/f32 operands with f32 (int8: exact int32)
    #       accumulation;
    #   "auto" — kind when the build kind-collapsed the window AND the
    #       measured dedup factor cleared kind_dedup_threshold, else
    #       packed when both partitions' unpacked matrices fit
    #       dense_budget_bytes, packed_blocked when only the bitmaps fit
    #       a quarter of it (graph build constructs the matching
    #       auxiliary view), else pcsr.
    kernel: str = "auto"
    # kernel="auto": window dedup factor (true traces / distinct kind
    # columns, both partitions) at which a collapsed build constructs
    # the kind-compressed views and auto-selects kernel="kind". The
    # microrank_kind_dedup_ratio gauge + per-window journal field
    # record the measured factor so this is tunable from real profiles.
    kind_dedup_threshold: float = 4.0
    # Budget for the packed kernel's unpacked f32 matrices, summed over
    # both partitions (graph.build.resolve_aux applies it at build time).
    dense_budget_bytes: int = 2 << 30
    # Kind-collapse the trace axis at graph build
    # (graph.build.collapse_window_graph): identical p_sr columns — the
    # reference's own trace-kind equivalence (pagerank.py:54-66) — merge
    # into one column carrying its multiplicity, shrinking staged bytes,
    # HBM traffic and matvec width by T/kinds with exact ranking
    # semantics (full-window float64-oracle parity is checked by the
    # bench against an uncollapsed build every run). "auto" (default)
    # collapses only when the axis actually shrinks; "on" always; "off"
    # never (the pre-round-5 layout).
    collapse_kinds: str = "auto"   # "auto" | "on" | "off"
    # kernel="auto" resolves the in-budget bitmap path to "packed_bf16"
    # (bf16 operands, f32 accumulation — measured 1.55x faster per
    # iteration than f32 "packed" with rank parity tested) instead of
    # f32 "packed". Scores move within bf16 rounding; set False for
    # bit-level f32 score reproduction.
    prefer_bf16: bool = True
    # Validate fetched ranking scores for NaN/inf (nearly free: results are
    # already on host when checked).
    validate_numerics: bool = True
    # Carry the per-partition power-iteration residual trace and the
    # iterations-to-tolerance count out of the jitted rank program
    # (rank_window_traced_core) inside the existing result fetch — no
    # extra host sync or RPC; the per-step cost is an O(V+T) delta next
    # to the matvecs (<1% measured). Off: the plain 3-output program.
    convergence_trace: bool = True
    # Pipeline-level telemetry: per-run JSONL journal (out_dir/
    # journal.jsonl — one event per window with timings, convergence,
    # queue depth and a host-contention sample) plus the metrics
    # snapshot (metrics.json/.prom) written at run end for `cli stats`.
    # The metrics registry itself (obs.registry) always records; this
    # gates the file outputs.
    telemetry: bool = True
    # Additionally assert the finite-score invariant INSIDE the compiled
    # program via jax.experimental.checkify (rank_window_checked) —
    # catches NaN/inf at the device boundary with the failing check
    # named, at the cost of an error-state thread through the program.
    # Off by default; the host-side check above stays on regardless.
    device_checks: bool = False
    # mrsan runtime sanitizers (debug mode — the runtime twin of mrlint
    # R8/R9): every device-touching seam asserts it runs on the claimed
    # device-owner thread (utils.guards.assert_device_owner raises
    # DeviceOwnershipError on a cross-thread dispatch), and the mesh
    # collectives are interposed so the per-shard psum/all_gather
    # schedule is recorded and checked for uniformity after each
    # sharded dispatch (analysis.mrsan). Off by default: arming forces
    # a retrace of collective-bearing programs (the recording callback
    # is baked into the trace) and adds a host callback per collective
    # per shard — CI's mrsan-smoke runs with it on; production keeps it
    # for debugging sessions.
    sanitizers: bool = False
    # Window-loop pipelining (table lane): number of device rank programs
    # allowed in flight before the host blocks. 2 overlaps window N's
    # device execution with window N+1's host graph build (jax async
    # dispatch); 1 restores fully synchronous per-window execution.
    pipeline_depth: int = 2
    # Run device staging (device_put + program dispatch) and result
    # fetches on worker threads so their RPC latency — ~90 ms apiece on
    # tunneled-TPU runtimes — overlaps the main thread's detect/build
    # work instead of serializing with it. The main thread still does all
    # host compute; the workers only hold latency-bound PJRT calls.
    # Single-process only (a multi-process mesh needs every rank to issue
    # collectives in program order, which per-process worker threads
    # cannot guarantee against the fetch allgathers); ignored with a
    # warning there. Default ON since round 4: the r3 drain bug is fixed
    # and sync/async equivalence is tested
    # (test_table_lane_async_dispatch_matches_sync).
    async_dispatch: bool = True
    # Result fetch strategy for the window loop. "stream" (default)
    # fetches each window's top-k as soon as its turn comes — lowest
    # latency to the sink, one fetch RPC per window. "bulk" defers and
    # joins up to ``bulk_fetch_windows`` windows' results in ONE batched
    # device_get — on tunneled runtimes each fetch costs a full ~80-110
    # ms round trip; measured ~1.15x replay throughput at 4 windows
    # (all but one fetch RPC eliminated, so the gain grows with the
    # replay length). Results reach the sink in bursts and the resume
    # cursor advances later (a crash re-runs more windows).
    # Single-process only; outputs are tiny (top-k), so deferral holds
    # no significant device memory (program INPUTS free as each program
    # executes, so the flush cadence does not pin staged graphs).
    # NOTE: in bulk mode ``bulk_fetch_windows`` SUPERSEDES
    # pipeline_depth as the in-flight bound — the flush is the
    # backpressure; a strict low-depth requirement needs
    # fetch_mode="stream".
    fetch_mode: str = "stream"     # "stream" | "bulk"
    bulk_fetch_windows: int = 32
    # Micro-batched dispatch: accumulate up to this many anomalous
    # windows' graphs and stage+rank them as ONE stacked vmapped device
    # program (one staging transfer + one dispatch per group instead of
    # one per window). On tunneled runtimes per-dispatch RPC overheads
    # serialize on the staging worker; grouping 4 windows took the
    # 8x1M-span replay from ~64 to ~49 ms/window (20M spans/s
    # aggregate). Results still emit per window, in order. Trade-off:
    # the first window of a group waits for its group-mates before
    # ranking, so keep 1 (default) for lowest per-window latency.
    # Single-process, single-device (no mesh), unchecked dispatch only —
    # forced back to 1 with a warning otherwise.
    dispatch_batch_windows: int = 1
    # Stage single-device window graphs as ONE packed uint32 buffer
    # (rank_backends.blob) instead of ~50 per-leaf transfers — each leaf
    # transfer pays a full RPC round trip on tunneled-TPU runtimes
    # (round 3: 5 MB staged in 1,675 ms of pure latency). The sharded
    # path ignores this (shards need per-device placement).
    blob_staging: bool = True
    # Warm-start seam (down payment on ROADMAP item 2): the stream
    # engine threads each open incident's previous window's converged
    # rv/score vectors into the next overlapping window's iteration
    # (mapped across the window delta by op name and the kind retention
    # map — rank_backends.warm). Pays off with a convergence tol set
    # (pagerank.tol: iteration counts drop, residual-trace-proven);
    # without one the fixed 25 iterations run either way and only the
    # final-residual telemetry improves. Warm windows dispatch
    # single-window (no coalescing/sharding), so keep this off for
    # burst-heavy streams where micro-batching wins.
    warm_start: bool = False
    # Incremental sliding-window build (ROADMAP item 1, closed by the
    # delta-build lane): thread each window's per-trace build caches
    # (graph.build.DeltaBuildState) into the next overlapping window so
    # only the boundary traces pay string/factorize work. Exact by
    # construction — every delta window passes a row-count + span-time
    # checksum integrity gate and falls back to the cold build (counted
    # in microrank_build_route_total{route="cold"}) on churn past
    # delta_max_changed, unseen op names, or a pad-bucket shift.
    delta_build: bool = False
    # Changed-trace fraction past which a delta window rebuilds cold.
    delta_max_changed: float = 0.5
    # Fused pair program: rank each abnormal window through the warm
    # program (both PageRank solves + the spectrum epilogue in ONE
    # jitted dispatch, exporting converged state for the next window's
    # warm seed). Implies the warm-start threading; like warm_start,
    # fused windows dispatch single-window (no coalescing/sharding).
    fused_pair: bool = False
    # Tuned-policy consultation (scenarios/ subsystem): "auto" (default)
    # resolves spectrum method / kernel / pad_policy from the persisted
    # policy.json (written by `cli scenarios` next to the warmup
    # manifest) for any of those fields still at its built-in default —
    # explicit config always wins; "off" never consults (pins the
    # built-in defaults even when a policy file exists). Stale policies
    # (schema/profile mismatch) are rejected whole and counted in
    # microrank_policy_events_total{outcome="rejected"}.
    tuned_policy: str = "auto"     # "auto" | "off"
    # Persistent XLA compilation cache directory (jax_compilation_cache_dir).
    # None resolves MICRORANK_JIT_CACHE, else ~/.cache/microrank_tpu/jit —
    # the CLI default since PR 5. First-call compile of the fused rank
    # program costs ~1.7 s per process cold (BENCH_r05); a warm restart
    # reloads it in milliseconds. dispatch.cache.configure_compile_cache
    # is the one wiring point (CLI, serve, stream, bench all call it).
    compile_cache_dir: Optional[str] = None


@dataclass(frozen=True)
class DispatchConfig:
    """Adaptive dispatch router knobs (``dispatch/`` subsystem).

    Serve's scheduler and stream's engine both hand prepared window
    graphs to one shared DispatchRouter, which (a) routes by size —
    batches whose staged device footprint crosses
    ``sharded_bytes_threshold`` (or whose occupancy fills the mesh's
    windows axis) go to ``parallel.rank_windows_sharded``, small ones
    keep the vmapped single-device program; (b) coalesces same-bucket
    stream windows queued behind an in-flight dispatch into one vmapped
    program; (c) double-buffers staging so the next batch's H2D
    transfer overlaps the current batch's device execution.
    """

    # Route a batch to the sharded mesh path once its post-device_subset
    # staged footprint reaches this many bytes (and a mesh is
    # configured + the kernel is shard-capable). 0 shards everything a
    # mesh can take; a huge value keeps everything vmapped.
    sharded_bytes_threshold: int = 64 << 20
    # Occupancy trigger: a batch holding at least the mesh windows-axis
    # size of windows also routes sharded (the windows axis is full, so
    # the mesh is busy even if each graph is small). Only fires when the
    # mesh's windows axis is > 1.
    shard_on_full_occupancy: bool = True
    # Stream burst coalescing: same-pad-bucket windows pending behind
    # the current dispatch coalesce into one vmapped program, up to this
    # many (1 disables — every abnormal window dispatches alone).
    coalesce_windows: int = 8
    # Double-buffered staging: stage the NEXT ready batch (host blob
    # pack + H2D transfer) after dispatching the current program and
    # before fetching its results, so staging overlaps device execution
    # and leaves the critical path.
    double_buffer: bool = True
    # Donate the staged blob buffer to the rank program (the program
    # never aliases its input, so XLA may reuse the memory for outputs
    # — halves peak staging HBM under double-buffering). Skipped on
    # backends without donation support (CPU).
    donate_staging: bool = True
    # Record warmed program shapes (kernel + occupancies) into a
    # manifest next to the persistent compile cache and replay it at
    # startup, so a restarted serve/stream process re-traces every
    # program it will need while the on-disk cache makes each compile a
    # reload instead of the ~1.7 s cold build.
    warmup_manifest: bool = True


@dataclass(frozen=True)
class ObsConfig:
    """Self-tracing, flight recorder and device-profiler knobs
    (``obs/spans.py`` / ``obs/flight.py`` / ``obs/profiler.py``).

    The pipeline applies MicroRank's own premise to itself: every stage
    at the journal's choke points (ingest/parse, detect, graph build on
    the worker pool, staging, device dispatch, result fetch, incident
    lifecycle) emits a parent-linked span under a per-window /
    per-request trace id, recorded into a bounded in-memory ring. On
    incident open, degraded dispatch, or SIGTERM drain, the flight
    recorder dumps the ring to ``out_dir/flight/`` as Perfetto/Chrome
    trace-event JSON AND MicroRank's own span CSV schema — so
    ``cli run`` over a flight dump ranks the pipeline's own slowest
    stage (the dogfood path).
    """

    # Span tracer on/off. The per-span cost is a contextvar read plus a
    # locked deque append (~2 us) at millisecond-scale stages; bench.py
    # measures the pipelined-replay overhead as the ``trace_overhead``
    # artifact field (acceptance: within 5% of spans-disabled).
    spans: bool = True
    # Bounded span ring capacity (spans, not bytes — a Span is ~300 B of
    # host memory, so the default holds ~2.5 MB and many minutes of
    # window traffic). Oldest spans fall off; the flight manifest
    # records how many were dropped.
    span_ring: int = 8192
    # Flight recorder: dump the ring (+ correlated journal events + a
    # metrics snapshot) to out_dir/flight/<stamp>-<reason>/ on incident
    # open, degraded dispatch, or SIGTERM drain. Dumps within
    # ``flight_min_interval_seconds`` of the previous one are suppressed
    # (counted) so an incident storm cannot fill the disk.
    flight: bool = True
    flight_min_interval_seconds: float = 30.0
    # Device profiler: wrap every N-th router dispatch in a
    # ``jax.profiler.trace`` session written under ``profile_dir``
    # (0 disables). The obs HTTP server additionally exposes
    # ``GET /profilez?seconds=S`` for on-demand sessions.
    profile_every_n: int = 0
    profile_dir: Optional[str] = None
    # Size-based journal rotation: when journal.jsonl would exceed this
    # many bytes, the live file is fsynced, renamed to
    # ``journal.jsonl.<n>`` and a ``journal_rotated`` event opens the
    # fresh file (0 = never rotate). ``cli stats``/``cli witness`` read
    # rotated parts in order, so a long stream run's journal stays
    # bounded per part without losing history.
    journal_max_bytes: int = 0
    # Chaos/test knobs: sleep this long inside every ``inject_every``-th
    # span named ``inject_stage`` (the dogfood test slows the build pool
    # and asserts the self-rank blames it; 0 disables).
    inject_stage: str = "build"
    inject_stage_sleep_ms: float = 0.0
    inject_every: int = 1


@dataclass(frozen=True)
class ExplainConfig:
    """Rank provenance / explainability knobs (``explain/`` subsystem).

    Every ranked score decomposes into the four spectrum counters
    (ef/nf/ep/np), the per-formula term values, the normal-vs-abnormal
    PPR mass split, and the coverage columns (traces) that fed the
    suspect's PageRank mass. The explain twins of the rank programs
    carry those attribution tensors out of the jitted program in the
    SAME result fetch (mirroring the convergence traces), and the host
    materializes them as an ``ExplainBundle`` (JSON + human table).

    Off by default: with ``enabled=False`` the normal rank programs
    dispatch unchanged and the hot path pays nothing (bench.py's
    ``explain_overhead`` artifact field pins the on-cost; the spans-off
    headline is measured explain-off).
    """

    # Master switch: arm the explain twins on the pipelines (stream
    # builds bundles on incident open; serve honors explain:true
    # requests even when this is off — the request flag is the opt-in).
    enabled: bool = False
    # J: contributing coverage columns (traces) kept per suspect, per
    # partition — recovered on device from the kernel's own coverage
    # representation (bitmap rows / COO entries / CSR rows / ELL slab).
    top_traces: int = 5
    # Suspects explained per window: 0 = every returned rank row
    # (spectrum top_max + extra_rows), else min(this, rank rows).
    top_suspects: int = 0
    # Stream engine: build + persist a bundle automatically when a NEW
    # incident opens (written next to the flight dump and cross-linked
    # in its manifest; the incident_open event carries the path).
    on_incident: bool = True
    # Recent bundles kept in the in-process store the obs server's
    # ``GET /explainz?window=...`` endpoint serves from.
    store_windows: int = 32
    # Mirror a compact explain record into the run journal (the CI
    # smoke cross-checks bundle top-1/ef against it).
    journal: bool = True


@dataclass(frozen=True)
class IngestConfig:
    """Span admission + quarantine knobs (``ingest/`` subsystem).

    Every lane passes span frames through the admission ladder
    (``ingest.admission.admit_frame``) before detect/build: per-row
    schema+value validation with rejected rows routed to a bounded
    dead-letter store (``quarantine.jsonl``) under a fixed reason
    taxonomy, plus resource-budget guards that keep adversarial
    high-cardinality traffic from growing the op vocab, the pad
    buckets, and the staged-bytes footprint without bound.
    """

    # Master switch. Off: frames pass through untouched (the pre-PR-15
    # behavior — one malformed row can abort a frame; keep on).
    enabled: bool = True
    # Orphan spans (parent id absent from the trace): "stitch" clears
    # the link — the span becomes a trace root, its coverage still
    # counts (kept + counted in microrank_ingest_clamped_total) —
    # "drop" rejects the row to quarantine instead.
    orphan_policy: str = "stitch"      # "stitch" | "drop"
    # Cross-host clock-skew normalization: a span whose start sits
    # outside the window by up to max_skew_seconds CLAMPS to the
    # window-relative bound (kept); beyond skew_reject_seconds it is
    # hopeless and rejects (reason clock_skew). The clamp bound must
    # exceed half the window width or healthy edge rows would clamp.
    max_skew_seconds: float = 300.0
    skew_reject_seconds: float = 3600.0
    # FORWARD skew bound at the pre-windowing gate: rows claiming a
    # time ahead of the batch's robust spread clamp to this much —
    # tighter than max_skew_seconds because a future-claiming row
    # advances the event-time WATERMARK, and every second of advance
    # closes innocent windows that much earlier (their real spans then
    # drop as late). Backward skew cannot close windows, so it keeps
    # the loose bound.
    forward_skew_seconds: float = 30.0
    # Duration overflow bound (microseconds): anything longer than an
    # hour is a corrupt export, not a span (reason duration_overflow).
    max_duration_us: int = 3_600_000_000
    # Resource budgets (the cardinality-bomb guards): spans per trace
    # past the cap reject (reason trace_too_long) so one mega-trace
    # cannot escalate the pad buckets; distinct ops per window past the
    # cap keep the highest-span-count ops and reject the thin tail
    # (reason vocab_budget) so the op vocab and the staged footprint
    # stay bounded. 0 disables either budget.
    max_spans_per_trace: int = 4096
    max_ops_per_window: int = 20_000
    # Op-vocab GROWTH cap: when the caller supplies the baseline's
    # known operation set, a window introducing more than this many
    # never-seen operations is under cardinality attack — ALL its
    # never-seen-op spans quarantine (reason vocab_budget), so a bomb
    # of novel op names can neither open a spurious incident (the
    # detector never sees them) nor poison the online baseline nor
    # grow the pad buckets. Gradual real deployments stay under the
    # cap and admit normally. 0 disables.
    max_new_ops_per_window: int = 32
    # Baseline anti-poisoning: a window whose admitted fraction falls
    # below this neither updates the online baseline nor advances the
    # incident lifecycle — a corruption burst cannot retrain the SLO
    # floor or fire a spurious incident (the window journals as
    # skipped, reason low_admission).
    min_admission_ratio: float = 0.5
    # Dead-letter store: directory for quarantine.jsonl (None = the
    # run's out_dir) and its byte cap (records past it drop + count).
    quarantine_dir: Optional[str] = None
    quarantine_max_bytes: int = 16 << 20
    # Tail source: consecutive failed parses of the SAME byte range
    # before the offending line is dead-lettered (with its byte offset)
    # and the cursor advances past it — a permanently unparseable line
    # must not retry forever.
    parse_retry_max: int = 3


@dataclass(frozen=True)
class ChaosConfig:
    """Unified fault-injection harness (``chaos/`` subsystem).

    One seeded, deterministic ``FaultPlan`` drives every injection seam
    the span tracer already instruments — dispatch failure/latency,
    build-pool exception, source stall/rotation/torn-line, webhook
    hang/5xx, checkpoint-write crash (kill between tmp and rename),
    device-fetch NaN poison — instead of per-subsystem knobs. The
    legacy knobs (``ServeConfig.inject_dispatch_failures``,
    ``ObsConfig.inject_stage_sleep_ms``) keep working and are recorded
    through the same surface
    (``microrank_fault_injections_total{seam,kind}`` + journal
    ``fault_injected`` events).
    """

    # Master switch (also set by ``--chaos PLAN.json``). Off: every
    # maybe_inject() call is a None-check and the hot path pays nothing.
    enabled: bool = False
    # RNG seed for probabilistic specs (prob < 1); counting specs
    # (after/count/every) are deterministic regardless.
    seed: int = 0
    # Path of a JSON fault plan: {"seed": N, "faults": [{spec}, ...]}.
    plan_path: Optional[str] = None
    # Inline fault specs (dicts with seam/kind/after/count/every/value/
    # prob), merged before the plan file's.
    faults: Tuple[Dict[str, Any], ...] = ()


@dataclass(frozen=True)
class FleetConfig:
    """Fleet-scale streaming knobs (``fleet/`` subsystem).

    Span sources partition across N worker processes (hash-of-trace-id
    or per-service), each running its own windower + online baselines +
    per-host ``state.ckpt``; a global coordinator merges per-host
    watermarks into the fleet watermark, merges ranked verdicts with
    the tie-aware comparator, and owns the SINGLE incident lifecycle —
    N hosts seeing the same fault open exactly one incident. Workers
    carry heartbeat leases; a missed lease marks the host dead and
    reassigns its partitions to survivors; a rejoining worker restores
    from its own checkpoint (``--resume``) without duplicate or lost
    windows.
    """

    # Source partitions across the fleet; 0 = one per expected worker.
    partitions: int = 0
    # Partition key: "trace" (crc32 of traceID — even spread, every
    # host sees every service) or "service" (crc32 of serviceName —
    # RankMap-style locality: one service's spans land on one host).
    partition_by: str = "trace"
    # Heartbeat cadence (worker -> coordinator) and the lease it renews;
    # a worker silent past ``lease_seconds`` is marked dead and its
    # partitions reassign to survivors.
    heartbeat_seconds: float = 1.0
    lease_seconds: float = 5.0
    # Coordinator bind address for `cli stream --fleet N` (port 0 picks
    # a free one; workers get the resolved URL on their command line).
    host: str = "127.0.0.1"
    port: int = 0
    # Worker -> coordinator HTTP timeout, and the bounded buffer reports
    # park in while the coordinator is unreachable (drained in order on
    # the next successful send; overflow drops oldest, counted).
    report_timeout_seconds: float = 2.0
    report_queue: int = 256
    # Local launcher supervision: restart a dead worker with --resume
    # (the rejoin path), after this delay, at most this many times.
    restart_dead_workers: bool = True
    restart_delay_seconds: float = 0.0
    max_restarts: int = 1
    # Fleet telemetry plane: workers piggyback a versioned metrics
    # delta (vs the last coordinator-acked baseline) on each heartbeat;
    # the coordinator folds them into one federated registry served at
    # GET /fleetz/metrics and snapshotted as the launcher's fleet
    # metrics.{prom,json}.
    metrics_in_heartbeat: bool = True
    # Delta payload byte bound: an oversize delta drops whole metrics
    # (largest first, counted as status="truncated") until it fits —
    # the dropped increments ride the NEXT delta because the acked
    # baseline only advances by what was actually sent.
    delta_max_bytes: int = 262144
    # Cardinality cap on host-labeled series in the fleet registry:
    # deltas from more than expected_hosts + this many distinct hosts
    # are refused whole and counted
    # (microrank_fleet_series_dropped_total) instead of growing the
    # registry without bound — the vocab-budget rationale applied to
    # our own telemetry.
    host_series_grace: int = 2
    # Clamp on the heartbeat-RTT-estimated per-host clock offset used
    # to order the merged fleet journal / fleet trace (the ingest
    # skew-repair bound applied to our own telemetry).
    max_clock_skew_seconds: float = 5.0


@dataclass(frozen=True)
class WatchdogConfig:
    """SLO self-watchdog knobs (``obs/watchdog.py``).

    The fleet coordinator evaluates the system's OWN golden signals
    from the federated registry — per-stage latency budgets, error/
    degraded rate, watermark lag, queue depth — as multi-window burn
    rates (fast + slow window, both must burn past the threshold), and
    a breach opens a SELF-incident through the unmodified
    IncidentTracker machinery: suspect = the breaching stage/host,
    fingerprint-deduped, resolved after sustained recovery, journaled /
    webhooked / flight-dumped like any fault. This is the sensor layer
    ROADMAP item 5's adaptive shedding actuates on.
    """

    enabled: bool = True
    # Evaluation cadence (seconds between burn-rate samples; the
    # coordinator's reaper drives it, extra calls are rate-limited).
    eval_seconds: float = 1.0
    # Multi-window burn rates: both the fast and the slow window must
    # exceed burn_threshold for a breach (fast = reactive, slow =
    # flap-damping; windows are counts of eval samples).
    fast_windows: int = 5
    slow_windows: int = 60
    burn_threshold: float = 1.0
    # Per-stage latency SLO: fraction of stage_seconds observations
    # allowed above the budget (the error budget); burn = observed
    # over-budget fraction / stage_error_budget. The budget snaps to
    # the first histogram bucket bound >= the configured value.
    stage_budget_ms: float = 500.0
    # Per-stage overrides as (stage, budget_ms) pairs.
    stage_budgets: Tuple[Tuple[str, float], ...] = ()
    stage_error_budget: float = 0.1
    # Error/degraded-rate SLO over windows processed: skipped stream
    # windows + degraded serves, as a fraction of all windows.
    error_budget: float = 0.1
    # Gauge SLOs: burn = reading / budget (averaged over the window).
    watermark_lag_budget_seconds: float = 600.0
    queue_depth_budget: float = 8.0
    # Ratio signals need at least this many new observations across
    # the fast window before they can breach (cold-start guard).
    min_samples: int = 3
    # Self-incident lifecycle (the tracker's own knobs): consecutive
    # healthy evals that resolve, and the reopen cooldown.
    resolve_after_evals: int = 3
    cooldown_evals: int = 5
    # A single host whose recent per-stage cost exceeds the runner-up
    # by this factor gets named in the suspect ("stage:<s>@<host>").
    host_attribution_factor: float = 2.0


@dataclass(frozen=True)
class ServeConfig:
    """Online RCA service knobs (``cli serve`` — serve/ subsystem).

    The service coalesces concurrent requests into padded micro-batches
    (one vmapped device dispatch ranks many tenants' windows), bounds its
    queue with admission control, and degrades to the numpy_ref oracle
    when the device path fails.
    """

    host: str = "127.0.0.1"
    port: int = 8377
    # Admission control: requests admitted (queued or in flight through
    # the batcher) at once. Past it the service answers 429 with a
    # Retry-After header instead of letting the queue grow unboundedly.
    max_queue_depth: int = 64
    retry_after_seconds: float = 1.0
    # Micro-batching: a shape bucket dispatches as soon as it holds
    # max_batch_windows requests, or when its oldest request has waited
    # max_wait_ms — the latency/occupancy knob (0 disables coalescing
    # waits entirely: every request dispatches alone).
    max_batch_windows: int = 8
    max_wait_ms: float = 25.0
    # Per-request ceiling an HTTP caller waits before 504 (the request
    # itself is NOT cancelled — its batch completes and is journaled).
    request_timeout_seconds: float = 60.0
    # Compile the batched rank program at startup (occupancies 1 and 2)
    # so the first real requests don't pay the trace+compile stall.
    warmup: bool = True
    # Graceful degradation: after a failed device dispatch (one retry),
    # rank each batch member on the numpy_ref oracle and mark the
    # response ``degraded``. Off: the batch's requests fail with 500.
    fallback: bool = True
    # SIGTERM drain bound: seconds to wait for in-flight requests before
    # the process force-exits.
    drain_seconds: float = 10.0
    # Chaos/test knob: fail this many device dispatches (including
    # retries) with an injected error before behaving normally — drives
    # the degradation path end to end without a real device fault.
    inject_dispatch_failures: int = 0
    # Startup warmup compiles the batched rank program at these
    # occupancies (the jit cache key includes the batch size, so a full
    # batch at an uncompiled occupancy pays a first-hit compile under
    # traffic). Every entry must be >= 1 and <= max_batch_windows —
    # validated at service start.
    warmup_occupancies: Tuple[int, ...] = (1, 2)
    # Host graph builds (parse -> detect -> partition -> padded graph)
    # run on this many build-pool worker threads so they overlap the
    # scheduler thread's device dispatches; 0 builds on the scheduler
    # thread (the pre-pool serial behavior).
    build_workers: int = 2


@dataclass(frozen=True)
class StreamConfig:
    """Continuous RCA engine knobs (``cli stream`` — stream/ subsystem).

    The engine consumes an unbounded span stream, closes event-time
    windows at the watermark, detects every window against ONLINE SLO
    baselines, and gates the expensive graph-build + device-rank path on
    the detector — the paper's always-on monitor shape, vs the batch
    replay of ``cli run`` and the request/response path of ``cli serve``.
    """

    # Event-time windowing: tumbling windows of ``window_minutes`` when
    # slide_minutes is None, sliding (overlapping) windows otherwise.
    window_minutes: float = 5.0
    slide_minutes: Optional[float] = None
    # Watermark lag: a window [s, s+w) closes only once the max span
    # start time seen passes s+w+lateness — out-of-order spans within
    # the bound still land in their window; spans older than the
    # watermark are DROPPED and counted (stream_late_spans metric).
    allowed_lateness_seconds: float = 30.0
    # Online SLO baseline: exponential-decay weight one healthy window
    # contributes to the per-operation mean/std and P^2 quantile state.
    baseline_decay: float = 0.1
    # Cold start (no --normal seed dump): treat this many initial
    # windows as healthy baseline-feeding warmup before detection arms.
    min_healthy_windows: int = 1
    # Incident lifecycle: consecutive healthy windows that resolve an
    # open incident, and the post-resolve window count during which the
    # same fingerprint is suppressed instead of reopened (flap damping).
    resolve_after_windows: int = 2
    cooldown_windows: int = 2
    # Fingerprint: the tie-aware top-k suspect set of a ranked window
    # (exact score ties at the k-th rank expand the set). Consecutive
    # abnormal windows whose fingerprints match exactly or overlap by
    # >= fingerprint_jaccard dedup into one incident.
    fingerprint_top_k: int = 5
    fingerprint_jaccard: float = 0.5
    # Drift-aware dedup: a window that dedups into an open incident
    # (same/overlapping top-k SET) but whose suspect SCORE vector moved
    # by more than this relative L-inf distance since the incident's
    # last update emits ``incident_update`` with ``drifted: true`` —
    # the fault is evolving even though the suspects look the same.
    # <= 0 disables drift flagging.
    fingerprint_score_drift: float = 0.25
    # Build worker pool: threads running host graph builds so window
    # N+1's build overlaps window N's device rank; pipeline_windows
    # bounds abnormal windows in flight (build submitted, rank pending).
    build_workers: int = 2
    pipeline_windows: int = 2
    # Optional incident webhook: every lifecycle transition POSTs its
    # JSON event here (best-effort, failures counted). The POST is
    # bounded by an EXPLICIT timeout — the sink runs on the engine
    # thread, so a hung endpoint must never stall windowing/ranking
    # longer than this.
    webhook_url: Optional[str] = None
    webhook_timeout_seconds: float = 2.0
    # Webhook delivery: a failed POST no longer silently loses the
    # incident notification — it parks in a bounded retry queue and
    # re-sends with backoff on later lifecycle traffic. Events past
    # webhook_retry_max attempts (or evicted by a full queue) are
    # dropped AND counted (microrank_webhook_dropped_total).
    webhook_retry_max: int = 4
    webhook_queue: int = 64
    # Crash-only durability: checkpoint the engine's host state
    # (baseline moments + P^2 markers, incident tracker, windower
    # watermark + buffered open windows, source cursor) to
    # out_dir/state.ckpt at every pipeline-drained window boundary, so
    # `cli stream --resume` continues the run instead of cold-starting.
    checkpoint: bool = True
    # Stop after this many CLOSED windows (0 = run until the source
    # ends) — the CI/smoke bound.
    max_windows: int = 0


@dataclass(frozen=True)
class SchedConfig:
    """Unified multi-tenant device scheduler (``sched/`` subsystem).

    Serve's bucket batcher, stream's gated dispatch and warehouse/replay
    backfill all park prepared window graphs into ONE shared
    parked-window store (keyed by the dispatch router's (kernel,
    padded-leaf-shapes) bucket key); a single scheduler thread dequeues
    by priority lane (open-incident hot path > interactive serve >
    backfill), weighted fair share across tenants (stride scheduling)
    and per-tenant token-bucket quotas. Quotas are SOFT: a tenant out
    of tokens is deprioritized behind in-quota tenants but still served
    when the device would otherwise idle — the scheduler is
    work-conserving, so a zero-rate (background) tenant can never
    starve others and is never starved outright itself.
    """

    # Weighted fair share: (tenant, weight) pairs; a tenant's long-run
    # share of dispatched windows under contention converges to
    # weight / sum(weights of backlogged tenants). Unlisted tenants get
    # default_weight.
    tenant_weights: Tuple[Tuple[str, float], ...] = ()
    default_weight: float = 1.0
    # Soft token-bucket quotas: (tenant, windows/second) refill rates.
    # Unlisted tenants are unthrottled; rate 0 marks a pure background
    # tenant (dispatched only when no in-quota work is ready).
    tenant_rates: Tuple[Tuple[str, float], ...] = ()
    # Token bucket capacity (windows) — the burst a quota'd tenant may
    # spend at once after idling.
    burst: float = 8.0
    # Tenant names the non-serve lanes charge their dispatches to.
    stream_tenant: str = "stream"
    backfill_tenant: str = "backfill"
    # Shape-faithful warmup: replay the warmup manifest's recorded
    # production pad-bucket shapes (kernel, occupancy, leaf shapes) at
    # startup so the first real window's jit lookup is a cache hit.
    shape_warmup: bool = True
    # Manifest cap: at most this many recorded shape signatures per
    # (pipeline, kernel) — bounds both the manifest file and the
    # startup replay time.
    max_shapes: int = 8


@dataclass(frozen=True)
class WarehouseConfig:
    """Trace warehouse knobs (``warehouse/`` subsystem).

    A tiered columnar span store fed by the stream engine at window-seal
    time: hot tier = in-memory sealed windows, warm tier = per-window
    dictionary-compressed ``.npz`` segments (spans + the staged rank
    blob), cold tier = compacted multi-window segments. Every window
    record carries its OWN detection context (op vocab + SLO baseline
    snapshot + admission counters), so any stored range re-ranks with
    byte-faithful context (``cli replay --at``, ``cli scenarios
    --from-warehouse``).
    """

    # Master switch: the stream engine seals segments only when on AND
    # the run has an output dir.
    enabled: bool = False
    # Segment root; None = <out_dir>/warehouse.
    dir: Optional[str] = None
    # Store the admitted span frame columns (dictionary-encoded) per
    # window. Off: only detection context + rank blobs persist (replay
    # still works; warehouse-source re-streaming does not).
    store_spans: bool = True
    # Store the packed rank blob (+ layout + op names) for ranked
    # windows — replay is a blob load + dispatch, not a parse/build.
    store_blobs: bool = True
    # Compact the oldest warm segments into one cold multi-window
    # segment once this many warm segments exist (0 disables).
    compact_after: int = 16
    # Drop the oldest COLD segments beyond this count (0 = unbounded).
    retention_segments: int = 0


@dataclass(frozen=True)
class MicroRankConfig:
    detector: DetectorConfig = field(default_factory=DetectorConfig)
    pagerank: PageRankConfig = field(default_factory=PageRankConfig)
    spectrum: SpectrumConfig = field(default_factory=SpectrumConfig)
    window: WindowConfig = field(default_factory=WindowConfig)
    compat: CompatConfig = field(default_factory=CompatConfig)
    runtime: RuntimeConfig = field(default_factory=RuntimeConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    stream: StreamConfig = field(default_factory=StreamConfig)
    dispatch: DispatchConfig = field(default_factory=DispatchConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    explain: ExplainConfig = field(default_factory=ExplainConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    ingest: IngestConfig = field(default_factory=IngestConfig)
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)
    warehouse: WarehouseConfig = field(default_factory=WarehouseConfig)
    sched: SchedConfig = field(default_factory=SchedConfig)

    @classmethod
    def reference_compat(cls) -> "MicroRankConfig":
        """Preset that reproduces the reference code exactly, quirks and all."""
        return cls(
            compat=CompatConfig(partition_swap=True, overwrite_results=True),
            pagerank=PageRankConfig(preference="reference"),
            spectrum=SpectrumConfig(tiebreak="insertion"),
        )

    def replace(self, **kwargs: Any) -> "MicroRankConfig":
        return dataclasses.replace(self, **kwargs)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "MicroRankConfig":
        def _mk(typ, sub):
            flt = {k: v for k, v in sub.items() if k in {f.name for f in dataclasses.fields(typ)}}
            if typ is RuntimeConfig and flt.get("mesh_shape") is not None:
                flt["mesh_shape"] = tuple(flt["mesh_shape"])
            if typ is RuntimeConfig and flt.get("mesh_axes") is not None:
                flt["mesh_axes"] = tuple(flt["mesh_axes"])
            if typ is ServeConfig and flt.get("warmup_occupancies") is not None:
                flt["warmup_occupancies"] = tuple(flt["warmup_occupancies"])
            if typ is ChaosConfig and flt.get("faults") is not None:
                flt["faults"] = tuple(dict(f) for f in flt["faults"])
            if typ is WatchdogConfig and flt.get("stage_budgets") is not None:
                flt["stage_budgets"] = tuple(
                    (str(s), float(b)) for s, b in flt["stage_budgets"]
                )
            if typ is SchedConfig:
                for key in ("tenant_weights", "tenant_rates"):
                    if flt.get(key) is not None:
                        flt[key] = tuple(
                            (str(t), float(v)) for t, v in flt[key]
                        )
            return typ(**flt)

        return cls(
            detector=_mk(DetectorConfig, d.get("detector", {})),
            pagerank=_mk(PageRankConfig, d.get("pagerank", {})),
            spectrum=_mk(SpectrumConfig, d.get("spectrum", {})),
            window=_mk(WindowConfig, d.get("window", {})),
            compat=_mk(CompatConfig, d.get("compat", {})),
            runtime=_mk(RuntimeConfig, d.get("runtime", {})),
            serve=_mk(ServeConfig, d.get("serve", {})),
            stream=_mk(StreamConfig, d.get("stream", {})),
            dispatch=_mk(DispatchConfig, d.get("dispatch", {})),
            obs=_mk(ObsConfig, d.get("obs", {})),
            explain=_mk(ExplainConfig, d.get("explain", {})),
            chaos=_mk(ChaosConfig, d.get("chaos", {})),
            fleet=_mk(FleetConfig, d.get("fleet", {})),
            ingest=_mk(IngestConfig, d.get("ingest", {})),
            watchdog=_mk(WatchdogConfig, d.get("watchdog", {})),
            warehouse=_mk(WarehouseConfig, d.get("warehouse", {})),
            sched=_mk(SchedConfig, d.get("sched", {})),
        )
