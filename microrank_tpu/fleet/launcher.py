"""Local fleet launcher: ``cli stream --fleet N`` on one machine.

Runs the coordinator IN this process (HTTP on a loopback port) and
spawns N worker subprocesses, each a full ``cli stream --fleet-role
worker`` invocation writing under ``out_dir/host<i>/`` — the
one-command shape of the N-host deployment (real fleets start workers
on their own hosts pointing ``--coordinator-url`` at this process, and
optionally join a cross-host device mesh via ``--distributed`` /
``initialize_distributed`` exactly like ``cli run``).

Supervision is the crash-only story at fleet scope: a worker that dies
(nonzero exit — e.g. the ``host_kill`` chaos seam's ``os._exit(137)``)
restarts with ``--resume`` after ``restart_delay_seconds``, up to
``max_restarts`` times; its lease meanwhile expires, the survivors
absorb its partitions, and the rejoin rebalances them back. The
coordinator's incidents.jsonl, journal and metrics snapshot land in
``out_dir`` — the per-host artifacts under ``out_dir/host<i>/``.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path
from typing import List, Optional

from ..utils.logging import get_logger

log = get_logger("microrank_tpu.fleet.launcher")

FLEET_CONFIG_NAME = "fleet_config.json"

# Source / stream flags forwarded verbatim to worker command lines
# (argparse dest -> flag). Everything else rides --config-json.
_FORWARDED_FLAGS = {
    "source": "--source",
    "input": "--input",
    "normal": "--normal",
    "detect_minutes": "--detect-minutes",
    "slide_minutes": "--slide-minutes",
    "lateness_seconds": "--lateness-seconds",
    "max_windows": "--max-windows",
    "pace_seconds": "--pace-seconds",
    "chunk_spans": "--chunk-spans",
    "rate": "--rate",
    "poll_seconds": "--poll-seconds",
    "idle_exit": "--idle-exit",
    "windows": "--windows",
    "fault_windows": "--fault-windows",
    "operations": "--operations",
    "pods": "--pods",
    "kinds": "--kinds",
    "traces": "--traces",
    "fault_ms": "--fault-ms",
    "seed": "--seed",
    "chaos": "--chaos",
    "chaos_seed": "--chaos-seed",
}


def worker_command(
    args,
    config_json: Path,
    url: str,
    host_id: str,
    host_out: Path,
    resume: bool = False,
) -> List[str]:
    """The `cli stream --fleet-role worker` command line for one host."""
    cmd = [
        sys.executable, "-m", "microrank_tpu.cli", "stream",
        "--fleet-role", "worker",
        "--coordinator-url", url,
        "--host-id", host_id,
        "--config-json", str(config_json),
        "-o", str(host_out),
    ]
    for dest, flag in _FORWARDED_FLAGS.items():
        val = getattr(args, dest, None)
        if val is not None:
            cmd += [flag, str(val)]
    if resume:
        cmd.append("--resume")
    return cmd


class _Worker:
    def __init__(
        self,
        host_id: str,
        cmd: List[str],
        resume_cmd: List[str],
        out_dir: Path,
    ):
        self.host_id = host_id
        self.cmd = cmd
        self.resume_cmd = resume_cmd
        self.out_dir = out_dir
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.exit_code: Optional[int] = None

    def spawn(self, resume: bool = False) -> None:
        cmd = list(self.resume_cmd if resume else self.cmd)
        self.out_dir.mkdir(parents=True, exist_ok=True)
        log_path = self.out_dir / "worker.log"
        with open(log_path, "ab") as logf:
            self.proc = subprocess.Popen(
                cmd, stdout=logf, stderr=subprocess.STDOUT
            )
        log.info(
            "spawned %s (pid %d%s); log: %s",
            self.host_id, self.proc.pid,
            ", resume" if resume else "", log_path,
        )


def run_local_fleet(config, args) -> int:
    """Coordinator + N local worker subprocesses; returns exit code."""
    from ..obs.metrics import ensure_catalog
    from ..stream.incidents import JsonlIncidentSink, StdoutIncidentSink

    fc = config.fleet
    n_workers = int(args.fleet)
    out_dir = Path(args.output)
    out_dir.mkdir(parents=True, exist_ok=True)
    ensure_catalog()
    # The coordinator records its own spans (report/seal/merge/incident,
    # parent-linked into the workers' window traces) — arm the process
    # tracer exactly like the worker entry points do.
    from ..obs.spans import configure_tracer

    configure_tracer(config.obs)

    journal = None
    sinks = [StdoutIncidentSink()]
    from ..stream.engine import INCIDENT_LOG_NAME, _JournalIncidentSink

    sinks.append(JsonlIncidentSink(out_dir / INCIDENT_LOG_NAME))
    if config.runtime.telemetry:
        from ..obs import JOURNAL_NAME, RunJournal, set_current_journal

        journal = RunJournal(out_dir / JOURNAL_NAME)
        set_current_journal(journal)
        sinks.append(_JournalIncidentSink(journal))

    from .coordinator import FleetCoordinator, FleetServer

    coordinator = FleetCoordinator(
        config,
        out_dir=out_dir,
        sinks=sinks,
        journal=journal,
        expected_workers=n_workers,
    )
    server = FleetServer(coordinator, host=fc.host, port=fc.port).start()
    if journal is not None:
        journal.run_start(
            pipeline="fleet",
            workers=n_workers,
            partitions=coordinator.n_partitions,
            partition_by=coordinator.partition_by,
            lease_seconds=coordinator.lease_seconds,
        )

    config_json = out_dir / FLEET_CONFIG_NAME
    config_json.write_text(json.dumps(config.to_dict(), indent=2))
    # Restart incarnations run chaos-CLEAN: a plan's event counters are
    # per-process, so re-arming it on the rejoin would replay the same
    # deterministic kill and defeat supervision.
    from ..config import ChaosConfig

    clean_json = out_dir / ("clean_" + FLEET_CONFIG_NAME)
    clean_json.write_text(
        json.dumps(config.replace(chaos=ChaosConfig()).to_dict(), indent=2)
    )
    workers = []
    for i in range(n_workers):
        host_id = f"host{i}"
        host_out = out_dir / host_id
        cmd = worker_command(
            args, config_json, server.url, host_id, host_out,
            resume=bool(getattr(args, "resume", False)),
        )
        resume_cmd = worker_command(
            args, clean_json, server.url, host_id, host_out, resume=True
        )
        # Drop the forwarded chaos flags from the restart line too (the
        # clean config already disarms them; this keeps the logged
        # command honest).
        for flag in ("--chaos", "--chaos-seed"):
            while flag in resume_cmd:
                i_f = resume_cmd.index(flag)
                del resume_cmd[i_f : i_f + 2]
        w = _Worker(host_id, cmd, resume_cmd, host_out)
        w.spawn()
        workers.append(w)

    try:
        running = list(workers)
        while running:
            time.sleep(0.2)
            for w in list(running):
                rc = w.proc.poll()
                if rc is None:
                    continue
                w.exit_code = rc
                if (
                    rc != 0
                    and fc.restart_dead_workers
                    and w.restarts < fc.max_restarts
                ):
                    # The rejoin path: the worker's own checkpoint is
                    # the lossless half, the lease/reassignment dance
                    # covered the gap.
                    log.warning(
                        "%s exited %d; restarting with --resume "
                        "(%d/%d)", w.host_id, rc, w.restarts + 1,
                        fc.max_restarts,
                    )
                    w.restarts += 1
                    if fc.restart_delay_seconds > 0:
                        time.sleep(fc.restart_delay_seconds)
                    w.spawn(resume=True)
                    continue
                running.remove(w)
                if rc != 0:
                    log.error("%s exited %d (no restart)", w.host_id, rc)
    finally:
        status = coordinator.finalize()
        if journal is not None:
            journal.run_end(
                sealed=status["sealed"],
                incidents_opened=status["incidents_opened"],
                incidents_resolved=status["incidents_resolved"],
                duplicate_reports=status["duplicate_reports"],
                late_reports=status["late_reports"],
                reassignments=status["reassignments"],
            )
            journal.sync()
        if config.runtime.telemetry:
            # The fleet view replaces the old coordinator-only
            # snapshot: every worker's ledger is on disk by now (the
            # supervision loop only exits once the processes are
            # reaped), so the merged metrics.{prom,json}, the
            # offset-corrected fleet journal and the cross-host
            # Perfetto trace all reconcile against durable state.
            coordinator.write_fleet_artifacts()
        server.shutdown()

    failed = [w for w in workers if w.exit_code != 0]
    log.info(
        "fleet done: %d sealed windows, incidents %d opened / %d "
        "resolved, %d duplicate + %d late reports, %d reassignments, "
        "%d worker restart(s); results in %s",
        status["sealed"], status["incidents_opened"],
        status["incidents_resolved"], status["duplicate_reports"],
        status["late_reports"], status["reassignments"],
        sum(w.restarts for w in workers), out_dir,
    )
    return 1 if failed else 0
