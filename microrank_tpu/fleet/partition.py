"""Span-source partitioning for the fleet (``fleet/`` subsystem).

"Millions of users" means many services' spans arriving on many hosts;
the fleet splits one logical span stream into ``n_partitions`` disjoint
sub-streams and assigns whole partitions to worker processes. Two keys
(RankMap's platform-aware framing, arxiv 1503.08169 — map the workload
onto the platform by what the platform is good at):

* ``partition_by="trace"`` — crc32(traceID) mod N: spans of one trace
  always land on one host (a window graph needs whole traces), load
  spreads evenly, and every host sees every service — per-host
  baselines converge on the global SLO.
* ``partition_by="service"`` — crc32(serviceName) mod N: one service's
  spans land on one host (collector-locality: the host nearest the
  service tails its files), at the price of skewed load. NOTE: a trace
  crossing services splits across hosts under this key; each host
  ranks the sub-trace it saw and the coordinator's merge re-joins the
  verdicts — the per-host graphs are smaller but partial.

crc32 (not Python ``hash``) because the assignment must agree across
processes and restarts — ``PYTHONHASHSEED`` randomizes ``hash``.

``PartitionedSource`` wraps any engine source (replay / synthetic /
tail) and filters each yielded chunk down to the partitions currently
assigned; the assignment is a mutable thread-safe set the heartbeat
thread updates when the coordinator reassigns a dead host's partitions
to survivors. Reassignment covers spans not yet consumed from the
source — historical spans of a moved partition are not replayed (the
dead host's own checkpoint + ``--resume`` is the lossless path for its
already-windowed data).

Durability: the checkpoint cursor is the inner source's cursor plus
the partition-filter identity (key, partition count, assigned set).
Restore validates ALL of it and raises ``ValueError`` on any mismatch
— a checkpoint written under a different partition assignment would
silently re-window a different sub-stream, so the engine rejects the
WHOLE checkpoint (cold start) instead.
"""

from __future__ import annotations

import zlib
from typing import Iterable, Iterator, List, Optional, Set

import pandas as pd

from ..utils.guards import TrackedLock, note_shared_access, register_shared
from ..utils.logging import get_logger

log = get_logger("microrank_tpu.fleet")

PARTITION_COLUMNS = {"trace": "traceID", "service": "serviceName"}


def partition_of(key: str, n_partitions: int) -> int:
    """Stable cross-process partition of one key (crc32 mod N)."""
    return zlib.crc32(str(key).encode("utf-8")) % max(1, int(n_partitions))


def partition_ids(
    keys: Iterable[str], n_partitions: int
) -> "pd.Series":
    """Vectorized :func:`partition_of` over a pandas Series of keys."""
    n = max(1, int(n_partitions))
    return pd.Series(list(keys)).map(
        lambda k: zlib.crc32(str(k).encode("utf-8")) % n
    )


def split_partitions(
    n_partitions: int, worker_ids: List[str]
) -> dict:
    """Deterministic round-robin assignment of partitions to workers
    (sorted worker order — every process computes the same map)."""
    workers = sorted(worker_ids)
    out = {w: [] for w in workers}
    if not workers:
        return out
    for p in range(max(1, int(n_partitions))):
        out[workers[p % len(workers)]].append(p)
    return out


class PartitionSet:
    """The worker's current partition assignment: a thread-safe set the
    heartbeat thread overwrites on coordinator reassignment and the
    engine thread reads per source chunk."""

    def __init__(self, partitions: Iterable[int] = ()):
        # The heartbeat thread swaps the assignment, the engine thread
        # reads it per source chunk — a registered mrsan shared object
        # (mrlint R10's runtime twin lockset-checks every access).
        self._lock = TrackedLock("fleet_partitions")
        register_shared("fleet_partitions", {"fleet_partitions"})
        self._parts: Set[int] = {int(p) for p in partitions}
        self.changes = 0

    def get(self) -> Set[int]:
        with self._lock:
            note_shared_access("fleet_partitions")
            return set(self._parts)

    def set(self, partitions: Iterable[int]) -> bool:
        """Overwrite the assignment; returns True when it changed."""
        new = {int(p) for p in partitions}
        with self._lock:
            note_shared_access("fleet_partitions")
            if new == self._parts:
                return False
            log.info(
                "partition assignment changed: %s -> %s",
                sorted(self._parts), sorted(new),
            )
            self._parts = new
            self.changes += 1
            return True


class PartitionedSource:
    """Filter an inner span source down to the assigned partitions.

    Iterating yields the inner source's chunks restricted to spans
    whose partition (``partition_of`` over the key column) is currently
    assigned; chunks left empty by the filter are skipped (the
    windower's watermark is driven by the spans this host owns).
    """

    def __init__(
        self,
        inner,
        assignment: PartitionSet,
        n_partitions: int,
        partition_by: str = "trace",
    ):
        if partition_by not in PARTITION_COLUMNS:
            raise ValueError(
                f"partition_by must be one of "
                f"{sorted(PARTITION_COLUMNS)}, got {partition_by!r}"
            )
        self.inner = inner
        self.assignment = assignment
        self.n_partitions = max(1, int(n_partitions))
        self.partition_by = partition_by
        self.column = PARTITION_COLUMNS[partition_by]
        self.spans_seen = 0
        self.spans_kept = 0

    # The synthetic source exposes these for baseline seeding / ground
    # truth; pass them through so fleet workers seed like single ones.
    @property
    def normal(self):
        return getattr(self.inner, "normal", None)

    @property
    def fault_pod_op(self):
        return getattr(self.inner, "fault_pod_op", None)

    def _filter(self, frame: pd.DataFrame) -> pd.DataFrame:
        parts = self.assignment.get()
        self.spans_seen += len(frame)
        if len(parts) >= self.n_partitions:
            self.spans_kept += len(frame)
            return frame
        n = self.n_partitions
        pids = frame[self.column].map(
            lambda k: zlib.crc32(str(k).encode("utf-8")) % n
        )
        sub = frame[pids.isin(list(parts))]
        self.spans_kept += len(sub)
        return sub

    def __iter__(self) -> Iterator[pd.DataFrame]:
        for chunk in self.inner:
            sub = self._filter(chunk)
            if len(sub):
                yield sub.reset_index(drop=True)

    # ------------------------------------------------------- durability
    def checkpoint_state(self) -> Optional[dict]:
        inner_state = None
        ckpt = getattr(self.inner, "checkpoint_state", None)
        if callable(ckpt):
            inner_state = ckpt()
        return {
            "type": "partitioned",
            "partition_by": self.partition_by,
            "n_partitions": self.n_partitions,
            "partitions": sorted(self.assignment.get()),
            "inner": inner_state,
        }

    def restore_state(self, state: dict) -> None:
        """Validate-then-commit: EVERY identity field must match the
        live configuration before the inner cursor is touched — a
        cursor taken under a different partition filter describes a
        different sub-stream, and restoring just the matching half
        would silently lose or duplicate spans (the ISSUE-11 bugfix:
        reject whole, cold start)."""
        if state.get("type") != "partitioned":
            raise ValueError(f"not a partitioned cursor: {state}")
        if state.get("partition_by") != self.partition_by:
            raise ValueError(
                f"checkpoint partition key {state.get('partition_by')!r}"
                f" != configured {self.partition_by!r}"
            )
        if int(state.get("n_partitions", -1)) != self.n_partitions:
            raise ValueError(
                f"checkpoint partition count "
                f"{state.get('n_partitions')} != configured "
                f"{self.n_partitions}"
            )
        ckpt_parts = sorted(int(p) for p in state.get("partitions", []))
        live_parts = sorted(self.assignment.get())
        if ckpt_parts != live_parts:
            raise ValueError(
                f"checkpoint partition assignment {ckpt_parts} != "
                f"assigned {live_parts} (reassigned since the "
                "checkpoint; the cursor covers a different sub-stream)"
            )
        inner_state = state.get("inner")
        restore = getattr(self.inner, "restore_state", None)
        if inner_state is not None:
            if not callable(restore):
                raise ValueError(
                    "checkpoint carries an inner cursor but the live "
                    "source is not resumable"
                )
            restore(inner_state)

    def reset_cursor(self) -> None:
        reset = getattr(self.inner, "reset_cursor", None)
        if callable(reset):
            reset()
