"""Fleet-scale crash-tolerant streaming (ISSUE 11, ROADMAP item 3).

One logical span stream partitions across N worker processes
(``partition``: crc32-of-trace-id or per-service assignment); each
worker runs the full single-host streaming stack — windower, online
baselines, gated device rank, per-host ``state.ckpt`` — and reports
every finalized window to a global coordinator (``coordinator``) that
merges per-host watermarks into the fleet watermark, merges ranked
verdicts with the tie-aware comparator (``merge``), and owns the ONE
incident lifecycle: N hosts seeing the same fault open exactly one
incident. Heartbeat leases make host loss a first-class event — missed
beats mark the host dead and reassign its partitions to survivors; the
dead host rejoins with ``--resume`` and its re-reports dedup at the
coordinator (``worker``). ``launcher`` is the one-command local shape
(``cli stream --fleet N``) with crash-only supervision.
"""

from .coordinator import (
    FleetCoordinator,
    FleetServer,
    WorkerState,
)
from .merge import fleet_watermark, merge_rankings
from .partition import (
    PartitionSet,
    PartitionedSource,
    partition_of,
    split_partitions,
)
from .worker import (
    CoordinatorClient,
    FleetTracker,
    run_fleet_worker,
)

__all__ = [
    "CoordinatorClient",
    "FleetCoordinator",
    "FleetServer",
    "FleetTracker",
    "PartitionSet",
    "PartitionedSource",
    "WorkerState",
    "fleet_watermark",
    "merge_rankings",
    "partition_of",
    "run_fleet_worker",
    "split_partitions",
]
