"""The fleet's global coordinator: one incident lifecycle for N hosts.

Workers register, heartbeat, and report every finalized window; the
coordinator owns everything that must be GLOBAL so that N hosts seeing
the same fault open exactly ONE incident:

* **membership + leases** — every heartbeat (and report) renews a
  worker's lease; a worker silent past ``lease_seconds`` is marked
  dead, its source partitions reassign to the survivors (round-robin
  over the live set), and sealing stops waiting for it. A rejoining
  worker re-registers, the partitions rebalance back, and its
  ``--resume``-restored stream re-reports from its checkpoint — those
  already-sealed windows are dropped as ``late``/``duplicate``
  (counted, never re-merged), which is the exactly-once guarantee
  across a host loss.

* **watermark sealing** — per-window report slots keyed by the
  event-time window start; the fleet watermark is the MIN over live
  workers' last-reported window, and every pending window at or below
  it SEALS in start order, exactly once (the seal cursor is
  monotonic). Workers window the same epoch-aligned geometry over the
  same event time, so the same fault produces the same window keys on
  every host.

* **verdict merge + incident lifecycle** — a sealed window with any
  ranked report merges the per-host rankings (``merge.merge_rankings``
  — summed scores, tie-aware name order) and feeds the ONE
  ``IncidentTracker``; otherwise it advances the healthy streak. The
  tracker, its sinks (incidents.jsonl / stdout / webhook) and the
  open/update/resolve dedup are exactly the single-process machinery —
  lifted up one level.

The HTTP surface (``FleetServer``) is the same stdlib shape as the
serve/ and obs/ servers: POST /register, /heartbeat, /report,
/goodbye; GET /fleetz for status. A reaper thread ticks leases so a
dead host is noticed even while no traffic flows.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..utils.logging import get_logger
from .merge import fleet_watermark, merge_rankings
from .partition import split_partitions

log = get_logger("microrank_tpu.fleet.coordinator")

FLEET_INCIDENT_LOG = "incidents.jsonl"
HOST_LEDGER_NAME = "metrics.json"


class _JournalSink:
    """Incident sink -> run journal bridge (the stream engine has its
    own copy next to its jax-heavy imports; the coordinator re-declares
    these ten lines rather than paying that import)."""

    def __init__(self, journal):
        self._journal = journal

    def emit(self, event: dict) -> None:
        rest = {k: v for k, v in event.items() if k != "event"}
        try:
            self._journal.emit(event["event"], **rest)
        except Exception:  # noqa: BLE001 - telemetry stays best-effort
            pass


@dataclass
class WorkerState:
    host_id: str
    partitions: List[int] = field(default_factory=list)
    lease_deadline: float = 0.0
    state: str = "alive"            # pending | alive | dead | done
    spans: int = 0
    windows: int = 0
    uptime_s: float = 0.0
    last_start_us: Optional[int] = None
    registrations: int = 0

    @property
    def spans_per_second(self) -> float:
        return self.spans / self.uptime_s if self.uptime_s > 0 else 0.0


class FleetCoordinator:
    """Global fleet state machine (lock-per-call; HTTP handler threads
    and the reaper all funnel through one lock)."""

    def __init__(
        self,
        config,
        out_dir=None,
        sinks: Optional[List] = None,
        journal=None,
        expected_workers: int = 0,
        clock=time.monotonic,
    ):
        from ..stream.incidents import IncidentTracker

        self.config = config
        fc = config.fleet
        sc = config.stream
        self.clock = clock
        self.lease_seconds = float(fc.lease_seconds)
        self.heartbeat_seconds = float(fc.heartbeat_seconds)
        self.partition_by = fc.partition_by
        self.n_partitions = int(fc.partitions) or max(
            1, int(expected_workers)
        )
        self.journal = journal
        self.tracker = IncidentTracker(
            top_k=sc.fingerprint_top_k,
            resolve_after=sc.resolve_after_windows,
            cooldown_windows=sc.cooldown_windows,
            jaccard=sc.fingerprint_jaccard,
            score_drift=sc.fingerprint_score_drift,
            sinks=list(sinks or []),
        )
        self.out_dir = out_dir
        # ------------------------------------------------ telemetry plane
        from ..obs.fleetplane import FleetPlane

        self.plane = FleetPlane(
            expected_hosts=int(expected_workers) or int(fc.partitions),
            grace=fc.host_series_grace,
            max_skew_seconds=fc.max_clock_skew_seconds,
        )
        # The coordinator's own flight recorder: on incident open,
        # self-incident, or worker death it dumps the coordinator ring
        # and asks alive workers for theirs (piggybacked on heartbeat
        # responses), cross-linked in the dump manifest.
        self.flight = None
        if out_dir is not None:
            from ..obs.flight import FlightRecorder

            self.flight = FlightRecorder(
                out_dir, config.obs, journal=journal
            )
        self._flight_pending: Optional[str] = None
        self._dump_requests: Dict[str, str] = {}
        self._last_dump_req: Optional[float] = None
        # The SLO self-watchdog: golden signals from the fleet view,
        # breaches through an UNMODIFIED IncidentTracker of its own
        # (self_incidents.jsonl, journal, webhook — like any fault).
        self.watchdog = None
        wc = getattr(config, "watchdog", None)
        if wc is not None and wc.enabled:
            from ..obs.watchdog import SELF_INCIDENT_LOG, SLOWatchdog

            wd_sinks: List = []
            if out_dir is not None:
                from pathlib import Path as _Path

                from ..stream.incidents import JsonlIncidentSink

                wd_sinks.append(
                    JsonlIncidentSink(_Path(out_dir) / SELF_INCIDENT_LOG)
                )
            if journal is not None:
                wd_sinks.append(_JournalSink(journal))
            if sc.webhook_url:
                from ..stream.incidents import WebhookIncidentSink

                wd_sinks.append(
                    WebhookIncidentSink(
                        sc.webhook_url,
                        timeout=sc.webhook_timeout_seconds,
                        retry_max=sc.webhook_retry_max,
                        max_queue=sc.webhook_queue,
                    )
                )
            self.watchdog = SLOWatchdog(
                wc,
                tracker=IncidentTracker(
                    top_k=sc.fingerprint_top_k,
                    resolve_after=wc.resolve_after_evals,
                    cooldown_windows=wc.cooldown_evals,
                    jaccard=sc.fingerprint_jaccard,
                    score_drift=sc.fingerprint_score_drift,
                    sinks=wd_sinks,
                ),
                view=self._fleet_view,
            )
        from ..utils.guards import TrackedLock, register_shared

        self.workers: Dict[str, WorkerState] = {}
        self._slots: Dict[int, Dict[str, dict]] = {}  # start_us -> host
        # HTTP handler threads (register/heartbeat/report) and the
        # lease reaper funnel through one lock: the fleet state machine
        # is a registered mrsan shared object.
        self._lock = TrackedLock("fleet_coordinator")
        register_shared("fleet_coordinator", {"fleet_coordinator"})
        self._seal_cursor: Optional[int] = None  # last sealed start_us
        self.sealed: List[dict] = []  # {start, start_us, outcome, hosts}
        self.duplicate_reports = 0
        self.late_reports = 0
        self.reassignments = 0
        # Expected-host pre-registration: the launcher knows its worker
        # ids up front, so partitions are assigned stably BEFORE anyone
        # registers (no first-comer-takes-all startup race) and a host
        # that is merely slow to boot (jax import) blocks sealing
        # through a startup grace instead of being sealed past.
        with self._lock:
            for i in range(max(0, int(expected_workers))):
                host_id = f"host{i}"
                self.workers[host_id] = WorkerState(
                    host_id=host_id,
                    state="pending",
                    lease_deadline=self.clock()
                    + 3.0 * self.lease_seconds,
                )
            if self.workers:
                self._rebalance_locked("expect")
                self._workers_gauge_locked()

    # -------------------------------------------------------- lifecycle
    def _journal(self, event: str, **fields) -> None:
        if self.journal is not None:
            try:
                self.journal.emit(event, **fields)
            except Exception:  # noqa: BLE001 - telemetry stays best-effort
                pass

    def _status_locked(self, ws: Optional[WorkerState]) -> dict:
        return {
            "ok": True,
            "partitions": sorted(ws.partitions) if ws else [],
            "n_partitions": self.n_partitions,
            "partition_by": self.partition_by,
            "lease_seconds": self.lease_seconds,
            "heartbeat_seconds": self.heartbeat_seconds,
            "incident_open": self.tracker.has_open,
            "opened": self.tracker.opened,
            "resolved": self.tracker.resolved,
            "sealed": len(self.sealed),
        }

    def _workers_gauge_locked(self) -> None:
        from ..obs.metrics import record_fleet_workers

        counts = {"alive": 0, "dead": 0, "done": 0}
        for ws in self.workers.values():
            counts[ws.state] = counts.get(ws.state, 0) + 1
        record_fleet_workers(**counts)

    def _rebalance_locked(self, why: str) -> None:
        """Redistribute every partition round-robin across the live
        workers (deterministic sorted-host order); journal + count each
        host whose set changed."""
        from ..obs.metrics import record_fleet_reassignment

        # "done" workers keep their seats: a clean end-of-stream exit
        # only happens on finite sources (nothing left to own), and
        # keeping the map STABLE across it means a host that rejoins a
        # winding-down fleet gets its own partitions back — which is
        # exactly what lets its checkpointed source cursor restore
        # (the partition-assignment validation would reject a cursor
        # taken under a different set). Only death strips partitions.
        live = [
            w for w in self.workers.values() if w.state != "dead"
        ]
        if not live:
            return
        target = split_partitions(
            self.n_partitions, [w.host_id for w in live]
        )
        for ws in live:
            new = target[ws.host_id]
            if new != ws.partitions:
                if ws.partitions or why not in ("register", "expect"):
                    # First-ever assignment is not a "reassignment";
                    # every later move is.
                    self.reassignments += 1
                    record_fleet_reassignment()
                    self._journal(
                        "partition_reassigned",
                        host=ws.host_id,
                        partitions=new,
                        previous=ws.partitions,
                        why=why,
                    )
                ws.partitions = new

    # -------------------------------------------------------------- API
    def register(self, host_id: str, resume: bool = False) -> dict:
        from ..utils.guards import note_shared_access

        with self._lock:
            note_shared_access("fleet_coordinator")
            ws = self.workers.get(host_id)
            rejoin = ws is not None and ws.registrations > 0
            if ws is None:
                ws = self.workers[host_id] = WorkerState(host_id=host_id)
            ws.state = "alive"
            ws.registrations += 1
            ws.lease_deadline = self.clock() + self.lease_seconds
            self._rebalance_locked("rejoin" if rejoin else "register")
            self._workers_gauge_locked()
            self._journal(
                "worker_registered",
                host=host_id,
                rejoin=rejoin,
                resume=bool(resume),
                partitions=sorted(ws.partitions),
            )
            log.info(
                "worker %s %s (partitions %s)",
                host_id,
                "rejoined" if rejoin else "registered",
                sorted(ws.partitions),
            )
            return self._status_locked(ws)

    def heartbeat(
        self,
        host_id: str,
        spans: int = 0,
        windows: int = 0,
        uptime_s: float = 0.0,
        queue_depth: int = 0,
        wall: Optional[float] = None,
        rtt: Optional[float] = None,
        metrics: Optional[dict] = None,
    ) -> dict:
        from ..obs.metrics import (
            record_fleet_heartbeat,
            record_fleet_host_rate,
        )

        from ..utils.guards import note_shared_access

        recv_wall = time.time()
        with self._lock:
            note_shared_access("fleet_coordinator")
            ws = self.workers.get(host_id)
            if ws is None:
                return {"ok": False, "error": f"unknown host {host_id!r}"}
            ws.lease_deadline = self.clock() + self.lease_seconds
            if ws.state == "dead":
                # A heartbeat from a "dead" host: it was only silent —
                # bring it back and rebalance (the lease system's
                # false-positive recovery path).
                ws.state = "alive"
                self._rebalance_locked("lease_recovered")
                self._workers_gauge_locked()
            ws.spans = int(spans)
            ws.windows = int(windows)
            ws.uptime_s = float(uptime_s)
            record_fleet_heartbeat(host_id)
            record_fleet_host_rate(host_id, ws.spans_per_second)
            self._host_telemetry_locked(ws, queue_depth)
            self._reap_locked()
            self._seal_locked()
            resp = self._status_locked(ws)
            dump = self._dump_requests.pop(host_id, None)
            if dump:
                resp["dump"] = dump
        # Plane work happens OUTSIDE the fleet lock: the plane has its
        # own registered lock, and the delta fold walks metric samples
        # — not something to hold the state machine through.
        if wall is not None and rtt is not None:
            try:
                self.plane.note_clock(
                    host_id, float(wall), float(rtt), recv_wall
                )
            except (TypeError, ValueError):
                pass
        if metrics is not None:
            resp["metrics_ack"] = self.plane.ingest(host_id, metrics)
        return resp

    def _host_telemetry_locked(
        self, ws: WorkerState, queue_depth: int
    ) -> None:
        """Per-host golden-signal gauges from one heartbeat: the
        reporting host's engine queue depth, and every host's watermark
        lag behind the fleet's FURTHEST front (event-time seconds — the
        straggler signal the watchdog's lag budget watches)."""
        from ..obs.metrics import (
            record_fleet_host_lag,
            record_fleet_host_queue,
        )

        record_fleet_host_queue(ws.host_id, int(queue_depth))
        fronts = [
            w.last_start_us
            for w in self.workers.values()
            if w.state in ("alive", "pending")
            and w.last_start_us is not None
        ]
        if not fronts:
            return
        head = max(fronts)
        for w in self.workers.values():
            if w.last_start_us is not None and w.state != "done":
                record_fleet_host_lag(
                    w.host_id, (head - w.last_start_us) / 1e6
                )

    @staticmethod
    def _window_ctx(window: object):
        """The worker-side root span context a report carries (its
        ``trace`` field) -> a SpanContext to parent-link coordinator
        spans against, or None. Same window => same ``win-<start>``
        trace id on every host, which is what makes the merged Perfetto
        dump one causal chain across processes."""
        from ..obs.spans import SpanContext

        if not isinstance(window, dict):
            return None
        tr = window.get("trace")
        if (
            isinstance(tr, dict)
            and tr.get("trace_id")
            and tr.get("span_id")
        ):
            return SpanContext(str(tr["trace_id"]), str(tr["span_id"]))
        return None

    def report(
        self,
        host_id: str,
        window: dict,
        traceparent: Optional[Tuple[str, str]] = None,
    ) -> dict:
        """One finalized window from one host. Idempotent per
        (host, window): re-reports after a resume dedup here, and
        reports for already-sealed windows drop as ``late`` — both
        counted, neither ever reaches the tracker twice."""
        from ..obs.metrics import record_fleet_report
        from ..obs.spans import get_tracer

        from ..utils.guards import note_shared_access

        attrs = {"host": host_id}
        if traceparent:
            # The W3C header the worker sent — recorded so the span is
            # joinable from standards-speaking tooling too.
            attrs["w3c_trace"] = traceparent[0]
        with get_tracer().span(
            "report",
            service="fleet",
            ctx=self._window_ctx(window),
            **attrs,
        ), self._lock:
            note_shared_access("fleet_coordinator")
            ws = self.workers.get(host_id)
            if ws is None:
                return {"ok": False, "error": f"unknown host {host_id!r}"}
            ws.lease_deadline = self.clock() + self.lease_seconds
            if ws.state != "alive":
                ws.state = "alive"
                self._rebalance_locked("lease_recovered")
                self._workers_gauge_locked()
            start_us = int(window["start_us"])
            ws.last_start_us = start_us
            if (
                self._seal_cursor is not None
                and start_us <= self._seal_cursor
            ):
                self.late_reports += 1
                status = "late"
            elif host_id in self._slots.get(start_us, {}):
                self.duplicate_reports += 1
                status = "duplicate"
            else:
                self._slots.setdefault(start_us, {})[host_id] = dict(
                    window
                )
                status = "accepted"
            record_fleet_report(status)
            self._reap_locked()
            self._seal_locked()
            resp = self._status_locked(ws)
            resp["report"] = status
            return resp

    def goodbye(
        self, host_id: str, metrics: Optional[dict] = None
    ) -> dict:
        """Clean worker exit (finite source drained): the host stops
        blocking the fleet watermark without the lease having to age
        out; when the LAST worker leaves, everything pending seals.
        A final metrics delta rides the goodbye so the last beat's
        increments land before the host goes silent (finalize still
        reconciles against the on-disk ledger — this just narrows the
        window a crash could lose)."""
        if metrics is not None:
            # Outside the fleet lock, like the heartbeat path.
            self.plane.ingest(host_id, metrics)
        with self._lock:
            ws = self.workers.get(host_id)
            if ws is None:
                return {"ok": False, "error": f"unknown host {host_id!r}"}
            ws.state = "done"
            self._workers_gauge_locked()
            self._journal(
                "worker_done", host=host_id, windows=ws.windows,
                spans=ws.spans,
            )
            if all(
                w.state not in ("alive", "pending")
                for w in self.workers.values()
            ):
                self._seal_locked(flush=True)
            else:
                self._seal_locked()
            return self._status_locked(ws)

    def tick(self) -> None:
        """Reaper entry: age leases, then try to seal (a death can
        unblock the watermark)."""
        from ..utils.guards import note_shared_access

        with self._lock:
            note_shared_access("fleet_coordinator")
            self._reap_locked()
            self._seal_locked()

    # ------------------------------------------------------------ leases
    def _reap_locked(self) -> None:
        now = self.clock()
        newly_dead = [
            ws
            for ws in self.workers.values()
            if ws.state in ("alive", "pending")
            and ws.lease_deadline < now
        ]
        if not newly_dead:
            return
        for ws in newly_dead:
            ws.state = "dead"
            log.warning(
                "worker %s lease expired (%.1fs silent); marking dead "
                "and reassigning partitions %s",
                ws.host_id, self.lease_seconds, sorted(ws.partitions),
            )
            self._journal(
                "worker_dead",
                host=ws.host_id,
                partitions=sorted(ws.partitions),
                last_start_us=ws.last_start_us,
            )
            ws.partitions = []
        self._rebalance_locked("lease_expired")
        self._workers_gauge_locked()
        # A host death is a flight-recorder moment: capture the
        # coordinator ring and ask the SURVIVORS for theirs (the dead
        # host can't answer; its last on-disk dump still merges into
        # the fleet trace at finalize). The dump itself happens in
        # service_flight, outside this lock.
        if self.flight is not None:
            self._flight_pending = self._flight_pending or "worker-dead"
        self._request_dumps_locked("worker-dead")

    # ----------------------------------------------------------- sealing
    def _seal_locked(self, flush: bool = False) -> None:
        from ..obs.metrics import record_fleet_sealed
        from ..obs.spans import get_tracer

        tracer = get_tracer()
        while self._slots:
            start_us = min(self._slots)
            if not flush:
                wm = fleet_watermark(
                    ws.last_start_us
                    for ws in self.workers.values()
                    if ws.state in ("alive", "pending")
                )
                if wm is None or start_us > wm:
                    return
            reports = self._slots.pop(start_us)
            self._seal_cursor = start_us
            ranked = [
                r for r in reports.values() if r.get("outcome") == "ranked"
            ]
            start = next(iter(reports.values())).get("start") or str(
                start_us
            )
            # Seal under the window's OWN trace: any report's carried
            # worker-root context (ranked first — an incident's chain
            # should hang off a ranked host) parents the coordinator's
            # seal -> merge -> incident spans into the same
            # ``win-<start>`` trace the workers recorded into.
            ctx = next(
                filter(None, (self._window_ctx(r) for r in ranked)),
                None,
            ) or next(
                filter(
                    None,
                    (self._window_ctx(r) for r in reports.values()),
                ),
                None,
            )
            opened_before = self.tracker.opened
            with tracer.span(
                "seal",
                service="fleet",
                ctx=ctx,
                start=start,
                hosts=len(reports),
            ):
                if ranked:
                    with tracer.span(
                        "merge", service="fleet", ranked_hosts=len(ranked)
                    ):
                        merged = merge_rankings(
                            r.get("ranking") for r in ranked
                        )
                    outcome = "ranked"
                    with tracer.span("incident", service="fleet"):
                        self.tracker.observe_ranked(start, merged)
                else:
                    merged = []
                    outcome = "healthy"
                    with tracer.span("incident", service="fleet"):
                        self.tracker.observe_healthy(start)
            if self.tracker.opened > opened_before:
                # A fleet incident just opened: dump the coordinator
                # ring and ask every live worker for its ring — the
                # cross-linked dumps are what finalize merges into one
                # cross-host trace of the faulted window.
                if self.flight is not None:
                    self._flight_pending = (
                        self._flight_pending or "incident"
                    )
                self._request_dumps_locked("incident")
            record_fleet_sealed(outcome)
            self.sealed.append(
                {
                    "start": start,
                    "start_us": start_us,
                    "outcome": outcome,
                    "hosts": sorted(reports),
                    "n_spans": sum(
                        int(r.get("n_spans", 0)) for r in reports.values()
                    ),
                }
            )
            self._journal(
                "fleet_window",
                start=start,
                outcome=outcome,
                hosts=sorted(reports),
                ranked_hosts=len(ranked),
                top=[[n, float(s)] for n, s in merged[:5]],
            )

    # ------------------------------------------------- telemetry plane
    def _fleet_view(self):
        """The federated registry: the coordinator's own process
        registry (fleet_* counters, per-host breakdown gauges) merged
        with every host's folded cum."""
        from ..obs.registry import get_registry

        return self.plane.fleet_view([("coordinator", get_registry())])

    def fleet_metrics_text(self) -> str:
        """GET /fleetz/metrics: the fleet view in Prometheus text
        exposition."""
        return self._fleet_view().to_prometheus()

    def _request_dumps_locked(self, reason: str) -> None:
        """Flag every live worker for a flight dump on its next
        heartbeat response. Rate-limited by the flight min-interval so
        an incident flap cannot stampede N hosts into disk writes (each
        worker's own recorder rate-limits again on its side)."""
        now = self.clock()
        min_gap = max(
            0.0, float(self.config.obs.flight_min_interval_seconds)
        )
        if (
            self._last_dump_req is not None
            and now - self._last_dump_req < min_gap
        ):
            return
        self._last_dump_req = now
        for ws in self.workers.values():
            if ws.state == "alive":
                self._dump_requests[ws.host_id] = reason

    def service_flight(self) -> None:
        """Perform any pending coordinator flight dump OUTSIDE the
        fleet lock (a dump writes trace/journal/metrics files — never
        under the state machine's lock). Driven by the server's reaper
        thread and by finalize; the manifest's ``fleet`` key
        cross-links the worker rings the coordinator asked for."""
        if self.flight is None:
            return
        with self._lock:
            reason, self._flight_pending = self._flight_pending, None
            if not reason:
                return
            hosts = {h: ws.state for h, ws in self.workers.items()}
            requested = dict(self._dump_requests)
        try:
            self.flight.dump(
                f"fleet-{reason}",
                extra={
                    "reason": reason,
                    "hosts": hosts,
                    "worker_dumps_requested": requested,
                    "clock_offsets_s": self.plane.offsets(),
                },
            )
        except Exception:  # noqa: BLE001 - telemetry stays best-effort
            log.exception("fleet flight dump failed")

    def watchdog_tick(self, force: bool = False) -> None:
        """One SLO self-watchdog evaluation (reaper thread, OUTSIDE the
        fleet lock — the watchdog reads the plane's merged view under
        the plane's own lock). A newly opened self-incident is a flight
        moment exactly like a fleet incident."""
        if self.watchdog is None:
            return
        opened_before = self.watchdog.tracker.opened
        try:
            self.watchdog.evaluate(force=force)
        except Exception:  # noqa: BLE001 - the watchdog must not kill
            log.exception("SLO watchdog evaluation failed")
            return
        if self.watchdog.tracker.opened > opened_before:
            with self._lock:
                if self.flight is not None:
                    self._flight_pending = (
                        self._flight_pending or "slo-breach"
                    )
                self._request_dumps_locked("slo-breach")

    def _reconcile_ledgers(self) -> None:
        """Durable state wins: replace each host's folded heartbeat
        deltas with its on-disk ``metrics.json`` ledger, so the fleet
        totals equal the per-host ledger sums EXACTLY (an in-flight
        delta that raced the worker's exit cannot leave them apart)."""
        if self.out_dir is None:
            return
        from pathlib import Path

        base = Path(self.out_dir)
        for host in set(self.plane.host_names()) | set(self.workers):
            ledger = base / host / HOST_LEDGER_NAME
            try:
                doc = json.loads(ledger.read_text())
            except (OSError, ValueError):
                continue
            self.plane.reconcile(host, doc)

    def write_fleet_artifacts(self) -> Dict[str, str]:
        """End-of-run fleet telemetry: the ledger-reconciled fleet
        metrics snapshot (``metrics.{prom,json}`` at the fleet root),
        the clock-offset-corrected merged ``fleet_journal.jsonl``, and
        the cross-host ``fleet_trace.json``. Returns artifact paths."""
        if self.out_dir is None:
            return {}
        from pathlib import Path

        from ..obs.fleetplane import (
            write_fleet_journal,
            write_fleet_trace,
        )
        from ..obs.spans import get_tracer

        out = Path(self.out_dir)
        self._reconcile_ledgers()
        paths: Dict[str, str] = {}
        try:
            self._fleet_view().write_snapshot(out)
            paths["metrics"] = str(out / "metrics.prom")
        except OSError:
            log.exception("fleet metrics snapshot failed")
        offsets = self.plane.offsets()
        host_dirs = {
            h: out / h
            for h in set(self.plane.host_names()) | set(self.workers)
            if (out / h).is_dir()
        }
        try:
            p = write_fleet_journal(out, host_dirs, offsets)
            if p is not None:
                paths["journal"] = str(p)
        except OSError:
            log.exception("fleet journal merge failed")
        try:
            p = write_fleet_trace(
                out, get_tracer().snapshot(), host_dirs, offsets
            )
            if p is not None:
                paths["trace"] = str(p)
        except OSError:
            log.exception("fleet trace merge failed")
        if paths:
            self._journal("fleet_artifacts", **paths)
        return paths

    # ------------------------------------------------------------ status
    def status(self) -> dict:
        with self._lock:
            return {
                "workers": {
                    w.host_id: {
                        "state": w.state,
                        "partitions": sorted(w.partitions),
                        "spans": w.spans,
                        "windows": w.windows,
                        "spans_per_second": round(w.spans_per_second, 2),
                        "last_start_us": w.last_start_us,
                    }
                    for w in self.workers.values()
                },
                "n_partitions": self.n_partitions,
                "sealed": len(self.sealed),
                "pending": len(self._slots),
                "duplicate_reports": self.duplicate_reports,
                "late_reports": self.late_reports,
                "reassignments": self.reassignments,
                "incidents_opened": self.tracker.opened,
                "incidents_resolved": self.tracker.resolved,
                "incident_open": self.tracker.has_open,
            }

    def finalize(self) -> dict:
        """End of run: seal everything pending, journal per-host rates
        and the run summary, drain any pending flight dump. Returns the
        final status dict. (The launcher calls write_fleet_artifacts
        separately, AFTER the worker processes are reaped — their
        ledgers and last flight dumps must be on disk first.)"""
        with self._lock:
            self._seal_locked(flush=True)
            for ws in self.workers.values():
                self._journal(
                    "fleet_host_stats",
                    host=ws.host_id,
                    state=ws.state,
                    spans=ws.spans,
                    windows=ws.windows,
                    spans_per_second=round(ws.spans_per_second, 2),
                )
        self.watchdog_tick(force=True)
        self.service_flight()
        return self.status()


class FleetServer:
    """stdlib HTTP front of a FleetCoordinator + the lease reaper."""

    def __init__(self, coordinator: FleetCoordinator,
                 host: str = "127.0.0.1", port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        coord = coordinator

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, status: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib API name)
                path = self.path.partition("?")[0]
                if path == "/fleetz":
                    self._reply(200, coord.status())
                elif path == "/fleetz/metrics":
                    body = coord.fleet_metrics_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802 (stdlib API name)
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    doc = json.loads(self.rfile.read(n) or b"{}")
                except (ValueError, OSError):
                    self._reply(400, {"ok": False, "error": "bad JSON"})
                    return
                host_id = str(doc.get("host", ""))
                route = self.path.partition("?")[0]
                if route == "/register":
                    resp = coord.register(
                        host_id, resume=bool(doc.get("resume"))
                    )
                elif route == "/heartbeat":
                    resp = coord.heartbeat(
                        host_id,
                        spans=int(doc.get("spans", 0)),
                        windows=int(doc.get("windows", 0)),
                        uptime_s=float(doc.get("uptime_s", 0.0)),
                        queue_depth=int(doc.get("queue_depth", 0)),
                        wall=doc.get("wall"),
                        rtt=doc.get("rtt"),
                        metrics=doc.get("metrics"),
                    )
                elif route == "/report":
                    from ..serve.protocol import parse_traceparent

                    resp = coord.report(
                        host_id,
                        doc.get("window") or {},
                        traceparent=parse_traceparent(
                            self.headers.get("traceparent")
                        ),
                    )
                elif route == "/goodbye":
                    resp = coord.goodbye(
                        host_id, metrics=doc.get("metrics")
                    )
                else:
                    self.send_error(404)
                    return
                self._reply(200 if resp.get("ok") else 404, resp)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self.coordinator = coordinator
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self.url = f"http://{host}:{self.port}"
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(
                target=self._httpd.serve_forever,
                name="mr-fleet-http",
                daemon=True,
            ),
            threading.Thread(
                target=self._reap_loop, name="mr-fleet-reaper", daemon=True
            ),
        ]

    def _reap_loop(self) -> None:
        tick = max(0.05, min(self.coordinator.lease_seconds / 4.0, 1.0))
        while not self._stop.wait(tick):
            try:
                self.coordinator.tick()
                # Reaper doubles as the telemetry heartbeat: SLO
                # watchdog evals (rate-limited internally) and any
                # pending flight dump, both outside the fleet lock.
                self.coordinator.watchdog_tick()
                self.coordinator.service_flight()
            except Exception:  # noqa: BLE001 - the reaper must survive
                log.exception("fleet reaper tick failed")

    def start(self) -> "FleetServer":
        for t in self._threads:
            t.start()
        log.info("fleet coordinator listening on %s", self.url)
        return self

    def shutdown(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
