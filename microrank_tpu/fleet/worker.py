"""Fleet worker: one stream engine + the coordinator protocol client.

A worker is a whole single-host streaming stack — partitioned source,
windower, online baselines, build pool, device dispatch, per-host
``state.ckpt`` — with the incident lifecycle REPLACED by a proxy: every
finalized window becomes a report to the coordinator, which owns the
one global tracker. Three moving parts:

* ``CoordinatorClient`` — the HTTP client (stdlib urllib, explicit
  timeouts). Every send consults the ``coordinator_unreachable`` chaos
  seam; sends go through the unified retry policy
  (``FLEET_REPORT_POLICY`` — short backoff, per-seam breaker), and a
  report that still fails PARKS in a bounded FIFO, re-sent IN ORDER
  before the next report — an unreachable coordinator costs the fleet
  verdict latency, never a window (the coordinator's per-(host,window)
  dedup makes the re-sends idempotent).

* ``FleetTracker`` — the engine-facing IncidentTracker stand-in:
  ``observe_ranked``/``observe_healthy`` build reports;
  ``has_open``/``opened``/``resolved`` mirror the coordinator's
  response so the baseline anti-poisoning freeze and the incident
  flight dump keep working per host. Its checkpoint state carries the
  parked report buffer, so a SIGKILL loses no buffered report either.
  The ``host_kill`` chaos seam fires here, once per observed window —
  ``kind: "kill"`` is ``os._exit``, the modeled host loss.

* ``_HeartbeatLoop`` — a daemon thread renewing the lease every
  ``heartbeat_seconds`` with per-host throughput stats, applying any
  partition reassignment the coordinator returns to the live
  ``PartitionSet`` (the ``heartbeat_drop`` seam skips sends so lease
  expiry is drivable without killing anything). Heartbeats touch no
  jax — the engine thread stays the sole device owner.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from collections import deque
from typing import List, Optional

import pandas as pd

from ..chaos.retry import RetryPolicy, retry_call
from ..utils.logging import get_logger
from .partition import PartitionSet, PartitionedSource

log = get_logger("microrank_tpu.fleet.worker")

# Report sends fail fast and lean on the buffer (the engine thread is
# calling); registration retries patiently — a worker that cannot join
# the fleet has nothing else to do.
FLEET_REPORT_POLICY = RetryPolicy(
    max_attempts=2, base_delay_s=0.05, max_delay_s=0.5,
    breaker_threshold=4, breaker_reset_s=2.0,
)
FLEET_REGISTER_POLICY = RetryPolicy(
    max_attempts=10, base_delay_s=0.2, max_delay_s=2.0,
    breaker_threshold=100,
)


class CoordinatorClient:
    """Worker -> coordinator HTTP with buffering + backoff + breaker."""

    def __init__(
        self,
        url: str,
        host_id: str,
        timeout: float = 2.0,
        max_queue: int = 256,
    ):
        self.url = url.rstrip("/")
        self.host_id = host_id
        self.timeout = max(0.1, float(timeout))
        self.max_queue = max(1, int(max_queue))
        from ..utils.guards import TrackedLock, register_shared

        self._buffer = deque()        # parked report payloads, in order
        # Engine thread parks/drains, heartbeat thread updates stats,
        # checkpoints snapshot — a registered mrsan shared object.
        self._lock = TrackedLock("fleet_report_buffer")
        register_shared("fleet_report_buffer", {"fleet_report_buffer"})
        self._draining = False        # one drainer at a time (in-order)
        self.sent = 0
        self.buffered = 0
        self.dropped = 0
        self.last_status: dict = {}

    # ------------------------------------------------------------- wire
    def _post(self, route: str, payload: dict) -> dict:
        from ..chaos.faults import InjectedFault, maybe_inject

        action = maybe_inject("coordinator_unreachable")
        if action is not None:
            # Non-raising kinds (e.g. "drop") simulate the same loss.
            raise InjectedFault("coordinator_unreachable", action["kind"])
        headers = {"Content-Type": "application/json"}
        # W3C trace propagation: the ambient span context (the window's
        # ``win-<start>`` trace during a report) rides the wire, so the
        # coordinator's spans join the SAME trace the worker's stages
        # recorded under.
        from ..obs.spans import SpanTracer

        ctx = SpanTracer.current_context()
        if ctx is not None:
            from ..serve.protocol import format_traceparent

            headers["traceparent"] = format_traceparent(
                ctx.trace_id, ctx.span_id
            )
        req = urllib.request.Request(
            f"{self.url}{route}",
            data=json.dumps({"host": self.host_id, **payload}).encode(),
            headers=headers,
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            doc = json.loads(resp.read() or b"{}")
        if not doc.get("ok"):
            raise RuntimeError(
                f"coordinator rejected {route}: {doc.get('error')}"
            )
        # The engine thread (reports) and the heartbeat thread both
        # land here; the stats share the buffer's lock. The wire call
        # above is NEVER made under it (mrlint R12).
        with self._lock:
            self.sent += 1
            self.last_status = doc
        return doc

    # -------------------------------------------------------------- API
    def register(self, resume: bool = False) -> dict:
        return retry_call(
            "fleet_register",
            lambda: self._post("/register", {"resume": bool(resume)}),
            policy=FLEET_REGISTER_POLICY,
        )

    def heartbeat(
        self,
        spans: int,
        windows: int,
        uptime_s: float,
        extra: Optional[dict] = None,
    ) -> Optional[dict]:
        """Best-effort lease renewal; a failure is counted by the
        caller, never raised (the next beat retries naturally).
        ``extra`` piggybacks the telemetry-plane fields (metrics
        delta, wall clock, rtt, queue depth)."""
        try:
            return self._post(
                "/heartbeat",
                {
                    "spans": int(spans),
                    "windows": int(windows),
                    "uptime_s": float(uptime_s),
                    **(extra or {}),
                },
            )
        except Exception as e:  # noqa: BLE001 - heartbeats are lossy
            log.warning("heartbeat failed: %s", e)
            return None

    def report(self, window: dict) -> Optional[dict]:
        """Deliver one finalized window, draining parked reports first
        (order preserved). On failure the window parks; a full buffer
        evicts the OLDEST entry (counted) — the coordinator will seal
        that window from the other hosts' reports."""
        with self._lock:
            self._buffer.append(window)
            if len(self._buffer) > self.max_queue:
                from ..obs.metrics import record_fleet_report

                self._buffer.popleft()
                self.dropped += 1
                record_fleet_report("dropped")
        return self._drain()

    def flush(self) -> Optional[dict]:
        """Drain parked reports (engine drain / final checkpoint)."""
        return self._drain()

    def _drain(self) -> Optional[dict]:
        """Send parked reports head-first, the WIRE CALL outside the
        buffer lock (mrlint R12: a hung coordinator — 2 s timeout x
        retry attempts — must not convoy the heartbeat thread and the
        checkpoint snapshot behind ``_lock``). Order is preserved by a
        single-drainer flag plus pop-after-ack: the head stays in the
        buffer until its send succeeds, so a crash mid-send checkpoints
        the unacknowledged report and ``--resume`` re-sends it (the
        coordinator dedups)."""
        from ..chaos.retry import BreakerOpen
        from ..obs.metrics import record_fleet_report

        from ..utils.guards import note_shared_access

        resp = None
        with self._lock:
            note_shared_access("fleet_report_buffer")
            if self._draining:
                return None  # the active drainer owns the in-order send
            self._draining = True
        try:
            while True:
                with self._lock:
                    if not self._buffer:
                        self.buffered = 0
                        return resp
                    head = self._buffer[0]
                try:
                    resp = retry_call(
                        "fleet_report",
                        lambda: self._post("/report", {"window": head}),
                        policy=FLEET_REPORT_POLICY,
                    )
                except BreakerOpen:
                    # Coordinator definitively down right now: park
                    # silently, the breaker's half-open probe gates the
                    # next attempt.
                    with self._lock:
                        self.buffered = len(self._buffer)
                    record_fleet_report("buffered")
                    return resp
                except Exception as e:  # noqa: BLE001 - park, move on
                    with self._lock:
                        parked = len(self._buffer)
                        self.buffered = parked
                    log.warning(
                        "report for window %s parked (%s); %d buffered",
                        head.get("start"), e, parked,
                    )
                    record_fleet_report("buffered")
                    return resp
                with self._lock:
                    if self._buffer and self._buffer[0] is head:
                        self._buffer.popleft()
        finally:
            with self._lock:
                self._draining = False

    def goodbye(self, extra: Optional[dict] = None) -> None:
        try:
            self.flush()
            self._post("/goodbye", dict(extra or {}))
        except Exception as e:  # noqa: BLE001 - exit is best-effort
            log.warning("goodbye failed: %s", e)

    def pending(self) -> int:
        with self._lock:
            return len(self._buffer)

    # ------------------------------------------------------- durability
    def buffered_state(self) -> List[dict]:
        from ..utils.guards import note_shared_access

        with self._lock:
            note_shared_access("fleet_report_buffer")
            return [dict(w) for w in self._buffer]

    def restore_buffer(self, windows: List[dict]) -> None:
        with self._lock:
            self._buffer = deque(dict(w) for w in windows)

    def reset_buffer(self) -> None:
        with self._lock:
            self._buffer.clear()


def _start_us(window_start: str) -> int:
    return int(pd.Timestamp(window_start).value // 1000)


class FleetTracker:
    """IncidentTracker-shaped proxy: windows out, lifecycle state in.

    The engine drives it exactly like the local tracker; every observed
    window becomes a coordinator report, and the lifecycle counters
    (``has_open``/``opened``/``resolved``) mirror the coordinator's
    last response — so the worker's baseline freeze and
    incident-open flight dump follow the FLEET lifecycle, not a local
    one. ``on_open`` hooks (the explain bundle) are ignored: provenance
    for a fleet incident is the coordinator's concern.
    """

    def __init__(self, client: CoordinatorClient, host_id: str):
        self.client = client
        self.host_id = host_id
        self.sinks: List = []     # engine flushes tracker sinks at drain
        self.opened = 0
        self.resolved = 0
        self.suppressed = 0
        self._open = False
        self._window_no = 0

    # ------------------------------------------------------------- state
    @property
    def has_open(self) -> bool:
        return self._open

    def open_incidents(self) -> List:
        return []

    def apply_status(self, resp: Optional[dict]) -> None:
        if not resp:
            return
        self.opened = int(resp.get("opened", self.opened))
        self.resolved = int(resp.get("resolved", self.resolved))
        self._open = bool(resp.get("incident_open", self._open))

    # ------------------------------------------------------------ intake
    def _observe(self, window: dict):
        from ..chaos.faults import maybe_inject

        action = maybe_inject("host_kill")
        if action is not None and action["kind"] in ("kill", "fail"):
            # The modeled host loss: no drain, no final checkpoint, no
            # goodbye — the coordinator finds out via the lease.
            log.warning("chaos host_kill: exiting hard (os._exit 137)")
            os._exit(137)
        self._window_no += 1
        # The engine calls us inside its per-window "incident" span, so
        # the ambient context carries this window's ``win-<start>``
        # trace — ship it with the report and the coordinator's
        # seal/merge/incident spans parent-link into the SAME trace.
        from ..obs.spans import SpanTracer

        ctx = SpanTracer.current_context()
        if ctx is not None:
            window = {
                **window,
                "trace": {
                    "trace_id": ctx.trace_id, "span_id": ctx.span_id,
                },
            }
        self.apply_status(self.client.report(window))

    def observe_ranked(self, window_start: str, ranking, on_open=None):
        self._observe(
            {
                "start": str(window_start),
                "start_us": _start_us(window_start),
                "outcome": "ranked",
                "ranking": [[str(n), float(s)] for n, s in ranking],
            }
        )
        return None

    def observe_healthy(self, window_start: str) -> List:
        self._observe(
            {
                "start": str(window_start),
                "start_us": _start_us(window_start),
                "outcome": "healthy",
                "ranking": [],
            }
        )
        return []

    # ------------------------------------------------------- durability
    def to_state(self) -> dict:
        return {
            "type": "fleet",
            "window_no": self._window_no,
            "buffered": self.client.buffered_state(),
        }

    def restore(self, state: dict) -> None:
        if state.get("type") != "fleet":
            raise ValueError(
                "checkpoint tracker state is not a fleet proxy state "
                "(single-process and fleet checkpoints do not mix)"
            )
        buffered = [dict(w) for w in state.get("buffered", [])]
        self._window_no = int(state.get("window_no", 0))
        self.client.restore_buffer(buffered)

    def reset(self) -> None:
        self._window_no = 0
        self.client.reset_buffer()


class _HeartbeatLoop(threading.Thread):
    def __init__(self, client: CoordinatorClient, engine,
                 assignment: PartitionSet, tracker: FleetTracker,
                 interval: float, metrics_sender=None):
        super().__init__(name="mr-fleet-heartbeat", daemon=True)
        self.client = client
        self.engine = engine
        self.assignment = assignment
        self.tracker = tracker
        self.interval = max(0.05, float(interval))
        # Telemetry-plane piggyback: the delta sender lives on THIS
        # thread only (build -> send -> ack, single-threaded protocol
        # state; the registry it reads is itself thread-safe).
        self.metrics_sender = metrics_sender
        self.beats = 0
        self.drops = 0
        self.last_rtt = 0.0
        self._t0 = time.monotonic()
        # NB: not ``_stop`` — threading.Thread has a private method of
        # that name and shadowing it breaks join().
        self._halt = threading.Event()

    def _telemetry(self) -> dict:
        """The heartbeat's telemetry-plane fields: wall clock + the
        previous beat's RTT (the coordinator's clock-offset estimator),
        pipeline queue depth, and the metrics delta when armed."""
        from ..obs import get_registry

        extra = {
            "wall": time.time(),
            "rtt": round(self.last_rtt, 6),
            "queue_depth": int(getattr(self.engine, "queue_depth", 0)),
        }
        if self.metrics_sender is not None:
            try:
                extra["metrics"] = self.metrics_sender.payload(
                    get_registry()
                )
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                log.exception("metrics delta build failed; beat sent bare")
        return extra

    def _apply(self, resp: dict) -> None:
        self.tracker.apply_status(resp)
        self.assignment.set(resp.get("partitions", []))
        if self.metrics_sender is not None:
            self.metrics_sender.handle_ack(resp.get("metrics_ack"))
        reason = resp.get("dump")
        if reason and getattr(self.engine, "flight", None) is not None:
            # Coordinator asked for this host's ring (incident open or
            # a peer died): best-effort, the recorder's own rate limit
            # caps a storm of requests.
            safe = "".join(
                c if c.isalnum() or c in "-_" else "-" for c in str(reason)
            )[:48]
            try:
                self.engine.flight.dump(f"fleet-{safe}")
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                log.exception("requested flight dump failed")

    def run(self) -> None:
        from ..chaos.faults import maybe_inject

        while not self._halt.wait(self.interval):
            if maybe_inject("heartbeat_drop") is not None:
                self.drops += 1
                continue
            summary = self.engine.summary
            t0 = time.monotonic()
            resp = self.client.heartbeat(
                spans=getattr(summary, "spans", 0),
                windows=summary.windows,
                uptime_s=time.monotonic() - self._t0,
                extra=self._telemetry(),
            )
            if resp is not None:
                self.last_rtt = time.monotonic() - t0
                self.beats += 1
                self._apply(resp)

    def stop(self) -> None:
        self._halt.set()


def run_fleet_worker(
    config,
    source,
    out_dir,
    host_id: str,
    coordinator_url: str,
    normal_df=None,
    resume: bool = False,
    on_engine=None,
):
    """Join the fleet and stream until the source drains.

    Registration blocks (with patient retry) until the coordinator
    answers with this host's partition assignment; the engine then runs
    the ordinary crash-only loop with the partitioned source and the
    tracker proxy. Exit flushes parked reports and says goodbye so the
    fleet watermark stops waiting on this host without a lease timeout.
    """
    from ..chaos import set_chaos_host
    from ..stream.engine import StreamEngine

    fc = config.fleet
    set_chaos_host(host_id)
    client = CoordinatorClient(
        coordinator_url,
        host_id,
        timeout=fc.report_timeout_seconds,
        max_queue=fc.report_queue,
    )
    hello = client.register(resume=resume)
    assignment = PartitionSet(hello.get("partitions", []))
    psource = PartitionedSource(
        source,
        assignment,
        n_partitions=int(hello.get("n_partitions", 1)),
        partition_by=hello.get("partition_by", fc.partition_by),
    )
    tracker = FleetTracker(client, host_id)
    tracker.apply_status(hello)
    engine = StreamEngine(
        config,
        psource,
        out_dir=out_dir,
        normal_df=normal_df,
        tracker=tracker,
        resume=resume,
    )
    if on_engine is not None:
        on_engine(engine)   # e.g. the CLI's SIGTERM drain hook
    sender = None
    if fc.metrics_in_heartbeat:
        from ..obs.fleetplane import MetricsDeltaSender

        sender = MetricsDeltaSender(host_id, max_bytes=fc.delta_max_bytes)
    heartbeat = _HeartbeatLoop(
        client, engine, assignment, tracker,
        interval=float(hello.get("heartbeat_seconds", fc.heartbeat_seconds)),
        metrics_sender=sender,
    )
    heartbeat.start()
    try:
        summary = engine.run()
    finally:
        heartbeat.stop()
        # The sender's protocol state is single-threaded (heartbeat
        # thread only), so wait for the loop to exit before the final
        # delta; a beat wedged in a slow send just forfeits it.
        heartbeat.join(timeout=2.0 * fc.report_timeout_seconds + 2.0)
        extra = {}
        if sender is not None and not heartbeat.is_alive():
            # Final delta rides the goodbye (the engine already wrote
            # the per-host ledger; this keeps the LIVE view current).
            from ..obs import get_registry

            try:
                extra["metrics"] = sender.payload(get_registry())
                extra["wall"] = time.time()
                extra["rtt"] = round(heartbeat.last_rtt, 6)
            except Exception:  # noqa: BLE001 - exit is best-effort
                pass
        client.goodbye(extra)
    log.info(
        "fleet worker %s done: %d windows (%d ranked), %d spans, "
        "%d reports sent, %d still buffered",
        host_id, summary.windows, summary.ranked,
        getattr(summary, "spans", 0), client.sent, client.pending(),
    )
    return summary, engine
