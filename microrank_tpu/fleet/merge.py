"""Cross-host verdict + watermark merging (``fleet/`` subsystem).

Each worker ranks the sub-window its partitions produced; the
coordinator re-joins the per-host verdicts into ONE fleet verdict per
window:

* scores SUM per suspect name — the spectrum counters underlying a
  score are counts over the host's (disjoint) trace subset, so the sum
  is the natural pooled evidence: a suspect two hosts both blame
  outranks one only a single host saw;
* the merged list sorts with the SAME tie-aware two-key comparator the
  device path realizes (descending score, ascending name on an exact
  tie — SpectrumConfig.tiebreak="name") so a legally permuted tie on
  two hosts cannot produce two different fleet verdicts.

The fleet watermark is the MIN over live workers' last-finalized
window: a window seals only once every live host's stream has moved
past it, which is what makes the coordinator's incident lifecycle
observe windows exactly once and strictly in order.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

Ranking = Sequence[Tuple[str, float]]


def merge_rankings(rankings: Iterable[Ranking]) -> List[Tuple[str, float]]:
    """Pool per-host ranked verdicts into one fleet ranking."""
    totals: Dict[str, float] = {}
    for ranking in rankings:
        for name, score in ranking or ():
            totals[str(name)] = totals.get(str(name), 0.0) + float(score)
    return sorted(totals.items(), key=lambda kv: (-kv[1], kv[0]))


def fleet_watermark(
    worker_watermarks: Iterable[Optional[int]],
) -> Optional[int]:
    """MIN over live workers' last-finalized window start (µs); None —
    a live worker that has not finalized a window yet — blocks sealing
    entirely (the fleet cannot know that worker's stream position)."""
    marks = list(worker_watermarks)
    if not marks or any(m is None for m in marks):
        return None
    return min(marks)
