"""CSV ingest honoring the reference's load contract
(/root/reference/online_rca.py:219-248): read the ClickHouse export, rename
columns to the canonical schema, and parse trace-level start/end datetimes.
"""

from __future__ import annotations

from pathlib import Path
from typing import Union

import pandas as pd

from .schema import CLICKHOUSE_RENAME, REQUIRED_COLUMNS, validate_columns


def load_traces_csv(path: Union[str, Path]) -> pd.DataFrame:
    """Load one ``traces.csv`` dump into the canonical span DataFrame."""
    df = pd.read_csv(path)
    # Renaming is a no-op for already-canonical columns, so both raw
    # ClickHouse exports and canonical CSVs load through the same path.
    df = df.rename(columns=CLICKHOUSE_RENAME)
    validate_columns(df.columns)
    df["startTime"] = pd.to_datetime(df["startTime"], format="mixed")
    df["endTime"] = pd.to_datetime(df["endTime"], format="mixed")
    return df


def window_spans(df: pd.DataFrame, start=None, end=None) -> pd.DataFrame:
    """Filter spans to a window (reference: get_span, preprocess_data.py:10-14).

    Keeps rows with ``startTime >= start`` and ``endTime <= end``. Like the
    reference, a missing bound disables filtering entirely.
    """
    if start is not None and end is not None:
        return df[(df["startTime"] >= start) & (df["endTime"] <= end)]
    return df
