"""CSV ingest honoring the reference's load contract
(/root/reference/online_rca.py:219-248): read the ClickHouse export, rename
columns to the canonical schema, and parse trace-level start/end datetimes.

Hostile-data hardening (ingest/ subsystem): one malformed timestamp no
longer aborts the whole frame — ``pd.to_datetime(errors="coerce")``
turns it into NaT, the poisoned rows route to the dead-letter store
(reason ``bad_timestamp``) and are counted in
``microrank_ingest_rejected_total``, and the 9,999 good rows of a
10,000-row dump load normally.
"""

from __future__ import annotations

from pathlib import Path
from typing import Union

import pandas as pd

from ..utils.logging import get_logger
from .schema import CLICKHOUSE_RENAME, REQUIRED_COLUMNS, validate_columns

log = get_logger("microrank_tpu.io")


def load_traces_csv(
    path: Union[str, Path], quarantine=None, source: str = "csv"
) -> pd.DataFrame:
    """Load one ``traces.csv`` dump into the canonical span DataFrame.

    Rows whose timestamps will not coerce are dropped to the
    dead-letter store (``quarantine`` or the process store) instead of
    raising — a single poisoned row must not abort the frame.
    """
    df = pd.read_csv(path)
    # Renaming is a no-op for already-canonical columns, so both raw
    # ClickHouse exports and canonical CSVs load through the same path.
    df = df.rename(columns=CLICKHOUSE_RENAME)
    validate_columns(df.columns)
    start = parse_span_times(df["startTime"])
    end = parse_span_times(df["endTime"])
    bad = (start.isna() | end.isna()).to_numpy()
    df["startTime"] = start
    df["endTime"] = end
    if bad.all() and len(df) > 0:
        # NOTHING coerced: this is not a dump with some bad rows, it
        # is a mis-parse (e.g. pandas index-inference on an over-long
        # first data row silently shifts every column). Raise like a
        # parse failure so retry/salvage machinery — not wholesale
        # quarantine — handles it.
        raise ValueError(
            f"{path}: no row had a coercible timestamp "
            f"({len(df)} rows) — mis-parsed or wholly corrupt input"
        )
    if bad.any():
        from ..ingest.quarantine import get_quarantine
        from ..obs.metrics import record_ingest_rejected

        n_bad = int(bad.sum())
        record_ingest_rejected("bad_timestamp", n_bad)
        store = quarantine if quarantine is not None else get_quarantine()
        store.put_frame(
            df, {"bad_timestamp": bad}, source=f"{source}:{path}"
        )
        log.warning(
            "%s: %d/%d rows had uncoercible timestamps; quarantined "
            "(reason bad_timestamp), loading the clean remainder",
            path, n_bad, len(df),
        )
        df = df.loc[~bad].reset_index(drop=True)
    return df


def parse_span_times(raw: pd.Series) -> pd.Series:
    """Vectorized timestamp parse with legacy-parity fallback.

    ``to_datetime(format="mixed")`` — the legacy request-path parse —
    infers the format PER ELEMENT: ~75 us/row of dateutil for any
    non-ISO format, so the two timestamp columns of a 100k-span POST
    cost ~15 s of pure Python. The ladder here stays in C:

    1. the vectorized ISO8601 parser (canonical ClickHouse exports);
    2. else guess the format from the first non-null value and parse
       the whole column with that one format (C strptime loop) — the
       same guesser ``mixed`` applies per element, so rows it parses
       agree with the legacy result;
    3. any row both reject (plus non-string columns — epoch numbers
       parse vectorized there anyway) falls back to the whole-column
       legacy ``mixed`` parse, keeping bit-identical values AND dtype.
    """
    notna = raw.notna()

    def _covers(parsed) -> bool:
        return parsed is not None and not (parsed.isna() & notna).any()

    try:
        parsed = pd.to_datetime(raw, format="ISO8601", errors="coerce")
    except (ValueError, TypeError):
        parsed = None
    if _covers(parsed):
        return parsed
    fmt = None
    nonnull = raw[notna]
    if len(nonnull) and isinstance(nonnull.iloc[0], str):
        try:
            from pandas.tseries.api import guess_datetime_format

            fmt = guess_datetime_format(nonnull.iloc[0])
        except (ImportError, ValueError, TypeError):
            fmt = None
    if fmt:
        try:
            parsed = pd.to_datetime(raw, format=fmt, errors="coerce")
        except (ValueError, TypeError):
            parsed = None
        if _covers(parsed):
            return parsed
    return pd.to_datetime(raw, format="mixed", errors="coerce")


def frame_from_records(spans) -> "pd.DataFrame | None":
    """Inline span records -> canonical frame, on the fast parse path
    (serve POST /rank): same rename + NaT semantics as the legacy
    row-wise parse, with timestamps through :func:`parse_span_times`'s
    vectorized ladder instead of the per-element ``mixed`` parser.

    Returns ``None`` for payload shapes the legacy path owns (empty /
    non-list) so the caller keeps its error semantics.
    """
    if not isinstance(spans, list) or not spans:
        return None
    df = pd.DataFrame(spans).rename(columns=CLICKHOUSE_RENAME)
    for col in ("startTime", "endTime"):
        if col in df.columns:
            df[col] = parse_span_times(df[col])
    return df


def window_spans(
    df: pd.DataFrame, start=None, end=None
) -> pd.DataFrame:
    """Filter spans to a window (reference: get_span, preprocess_data.py:10-14).

    Keeps rows with ``startTime >= start`` and ``endTime <= end``. Like the
    reference, a missing bound disables filtering entirely.
    """
    if start is not None and end is not None:
        return df[(df["startTime"] >= start) & (df["endTime"] <= end)]
    return df
