"""CSV ingest honoring the reference's load contract
(/root/reference/online_rca.py:219-248): read the ClickHouse export, rename
columns to the canonical schema, and parse trace-level start/end datetimes.

Hostile-data hardening (ingest/ subsystem): one malformed timestamp no
longer aborts the whole frame — ``pd.to_datetime(errors="coerce")``
turns it into NaT, the poisoned rows route to the dead-letter store
(reason ``bad_timestamp``) and are counted in
``microrank_ingest_rejected_total``, and the 9,999 good rows of a
10,000-row dump load normally.
"""

from __future__ import annotations

from pathlib import Path
from typing import Union

import pandas as pd

from ..utils.logging import get_logger
from .schema import CLICKHOUSE_RENAME, REQUIRED_COLUMNS, validate_columns

log = get_logger("microrank_tpu.io")


def load_traces_csv(
    path: Union[str, Path], quarantine=None, source: str = "csv"
) -> pd.DataFrame:
    """Load one ``traces.csv`` dump into the canonical span DataFrame.

    Rows whose timestamps will not coerce are dropped to the
    dead-letter store (``quarantine`` or the process store) instead of
    raising — a single poisoned row must not abort the frame.
    """
    df = pd.read_csv(path)
    # Renaming is a no-op for already-canonical columns, so both raw
    # ClickHouse exports and canonical CSVs load through the same path.
    df = df.rename(columns=CLICKHOUSE_RENAME)
    validate_columns(df.columns)
    start = pd.to_datetime(df["startTime"], format="mixed", errors="coerce")
    end = pd.to_datetime(df["endTime"], format="mixed", errors="coerce")
    bad = (start.isna() | end.isna()).to_numpy()
    df["startTime"] = start
    df["endTime"] = end
    if bad.all() and len(df) > 0:
        # NOTHING coerced: this is not a dump with some bad rows, it
        # is a mis-parse (e.g. pandas index-inference on an over-long
        # first data row silently shifts every column). Raise like a
        # parse failure so retry/salvage machinery — not wholesale
        # quarantine — handles it.
        raise ValueError(
            f"{path}: no row had a coercible timestamp "
            f"({len(df)} rows) — mis-parsed or wholly corrupt input"
        )
    if bad.any():
        from ..ingest.quarantine import get_quarantine
        from ..obs.metrics import record_ingest_rejected

        n_bad = int(bad.sum())
        record_ingest_rejected("bad_timestamp", n_bad)
        store = quarantine if quarantine is not None else get_quarantine()
        store.put_frame(
            df, {"bad_timestamp": bad}, source=f"{source}:{path}"
        )
        log.warning(
            "%s: %d/%d rows had uncoercible timestamps; quarantined "
            "(reason bad_timestamp), loading the clean remainder",
            path, n_bad, len(df),
        )
        df = df.loc[~bad].reset_index(drop=True)
    return df


def window_spans(
    df: pd.DataFrame, start=None, end=None
) -> pd.DataFrame:
    """Filter spans to a window (reference: get_span, preprocess_data.py:10-14).

    Keeps rows with ``startTime >= start`` and ``endTime <= end``. Like the
    reference, a missing bound disables filtering entirely.
    """
    if start is not None and end is not None:
        return df[(df["startTime"] >= start) & (df["endTime"] <= end)]
    return df
