"""Canonical operation naming (reference component C2).

The reference repeats one ``np.where`` idiom four times
(preprocess_data.py:27-31, :53-57, :100-104, :151-155): the canonical
operation id is ``<prefix>_<operationName>``, where for services in the
strip set (hard-coded 'ts-ui-dashboard' upstream) the last URL path segment
of the operation name is dropped, collapsing parameterized endpoints.

Two naming levels exist:
* service-level (``serviceName`` prefix) — used by the SLO baseline and the
  anomaly detector (preprocess_data.py:26-33, :100-104);
* instance-level (``podName`` prefix)  — used by the PageRank graph
  (preprocess_data.py:151-155). The strip rule still keys on serviceName.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable

import numpy as np
import pandas as pd

from .schema import DEFAULT_STRIP_LAST_SEGMENT_SERVICES


def _stripped_op_name(op_names: pd.Series) -> pd.Series:
    # 'a/b/c' -> 'a/b' ; 'a' -> 'a' (pandas rsplit keeps the whole string
    # when there is no separator — matches the reference's .str[0]).
    return op_names.str.rsplit("/", n=1).str[0]


def operation_names(
    span_df: pd.DataFrame,
    level: str = "service",
    strip_services: FrozenSet[str] = DEFAULT_STRIP_LAST_SEGMENT_SERVICES,
) -> pd.Series:
    """Vectorized canonical operation name per span row.

    ``level`` is "service" (detector/SLO vocab) or "pod" (PageRank vocab).
    Unlike the reference, the input DataFrame is never mutated
    (preprocess_data.py:100-104 renames a caller's column in place —
    SURVEY.md §2.2 quirk #6).
    """
    if level == "service":
        prefix = span_df["serviceName"].astype(str)
    elif level == "pod":
        prefix = span_df["podName"].astype(str)
    else:
        raise ValueError(f"unknown naming level {level!r}")
    op = span_df["operationName"].astype(str)
    in_strip = span_df["serviceName"].isin(strip_services)
    if bool(in_strip.any()):
        name = pd.Series(
            np.where(in_strip.to_numpy(), (prefix + "_" + _stripped_op_name(op)).to_numpy(),
                     (prefix + "_" + op).to_numpy()),
            index=span_df.index,
        )
    else:
        name = prefix + "_" + op
    return name


def service_operation_list(span_df: pd.DataFrame, strip_services=DEFAULT_STRIP_LAST_SEGMENT_SERVICES) -> list:
    """All distinct service-level operations, first-seen order
    (reference: get_service_operation_list, preprocess_data.py:26-33)."""
    return operation_names(span_df, "service", strip_services).drop_duplicates().tolist()
