"""The input span-data contract (reference: SURVEY.md §2.1).

The reference's ground-truth input is a CSV dump of OTel traces exported from
ClickHouse with columns ``Timestamp, TraceId, SpanId, ParentSpanId, SpanName,
ServiceName, PodName, Duration, SpanKind, TraceStart, TraceEnd``
(/root/reference/collect_data.py:36-46), renamed at load time
(/root/reference/online_rca.py:222-232). ``Duration`` is in microseconds and
is compared in milliseconds downstream (preprocess_data.py:71,73).
"""

from __future__ import annotations

from typing import Dict, List

# ClickHouse export column -> canonical column (online_rca.py:222-232).
CLICKHOUSE_RENAME: Dict[str, str] = {
    "TraceId": "traceID",
    "SpanId": "spanID",
    "ServiceName": "serviceName",
    "SpanName": "operationName",
    "PodName": "podName",
    "Duration": "duration",
    "TraceStart": "startTime",
    "TraceEnd": "endTime",
}

# Canonical columns the pipeline requires after rename.
REQUIRED_COLUMNS: List[str] = [
    "traceID",
    "spanID",
    "ParentSpanId",
    "operationName",
    "serviceName",
    "podName",
    "duration",   # microseconds
    "startTime",  # trace-level start (datetime)
    "endTime",    # trace-level end (datetime)
]

# Services whose operation names get their last URL path segment stripped,
# collapsing parameterized endpoints (preprocess_data.py:27-31 hard-codes
# 'ts-ui-dashboard'; here it is a configurable set).
DEFAULT_STRIP_LAST_SEGMENT_SERVICES = frozenset({"ts-ui-dashboard"})

US_PER_MS = 1000.0


def validate_columns(columns) -> None:
    missing = [c for c in REQUIRED_COLUMNS if c not in set(columns)]
    if missing:
        raise ValueError(
            f"span DataFrame is missing required columns {missing}; "
            f"expected the contract {REQUIRED_COLUMNS} "
            "(rename ClickHouse exports via microrank_tpu.io.load_traces_csv)"
        )
