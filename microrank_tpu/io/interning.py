"""String interning: the device never sees strings (SURVEY.md C2 plan).

Operations and trace ids are interned to dense int32 ids host-side; all
device arrays carry ids only. A ``Vocab`` is append-only and stable, so ids
are valid across windows of a run and can be checkpointed.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np
import pandas as pd


class Vocab:
    """Append-only string <-> int32 interner."""

    __slots__ = ("_index", "_names")

    def __init__(self, names: Optional[Iterable[str]] = None):
        self._index: Dict[str, int] = {}
        self._names: List[str] = []
        if names is not None:
            for n in names:
                self.add(n)

    def add(self, name: str) -> int:
        idx = self._index.get(name)
        if idx is None:
            idx = len(self._names)
            self._index[name] = idx
            self._names.append(name)
        return idx

    def update(self, names: Iterable[str]) -> None:
        for n in names:
            self.add(n)

    def encode(self, names: Sequence[str], missing: int = -1) -> np.ndarray:
        """int32 ids; unseen names map to ``missing`` (no mutation)."""
        return np.asarray(
            [self._index.get(n, missing) for n in names], dtype=np.int32
        )

    def encode_series(self, names: pd.Series, missing: int = -1) -> np.ndarray:
        return names.map(self._index).fillna(missing).to_numpy(dtype=np.int32)

    def grow_encode(self, names: pd.Series) -> np.ndarray:
        """Intern every name (adding unseen ones) and return ids."""
        uniques = pd.unique(names)
        for n in uniques:
            self.add(n)
        return self.encode_series(names)

    def decode(self, ids: Iterable[int]) -> List[str]:
        return [self._names[int(i)] for i in ids]

    def name(self, idx: int) -> str:
        return self._names[int(idx)]

    @property
    def names(self) -> List[str]:
        return list(self._names)

    def __len__(self) -> int:
        return len(self._names)

    def __contains__(self, name: str) -> bool:
        return name in self._index


def factorize_local(names: pd.Series) -> tuple:
    """Window-local interning: ids in first-seen order plus the vocab list.

    Backed by ``pd.factorize`` — O(n), no Python loop.
    """
    codes, uniques = pd.factorize(names, use_na_sentinel=False)
    return codes.astype(np.int32), list(uniques)
