from .interning import Vocab, factorize_local
from .loader import (
    frame_from_records,
    load_traces_csv,
    parse_span_times,
    window_spans,
)
from .naming import operation_names, service_operation_list
from .schema import (
    CLICKHOUSE_RENAME,
    DEFAULT_STRIP_LAST_SEGMENT_SERVICES,
    REQUIRED_COLUMNS,
    US_PER_MS,
    validate_columns,
)

__all__ = [
    "Vocab",
    "factorize_local",
    "frame_from_records",
    "parse_span_times",
    "load_traces_csv",
    "window_spans",
    "operation_names",
    "service_operation_list",
    "CLICKHOUSE_RENAME",
    "DEFAULT_STRIP_LAST_SEGMENT_SERVICES",
    "REQUIRED_COLUMNS",
    "US_PER_MS",
    "validate_columns",
]
