"""Telemetry subsystem (microrank_tpu.obs): registry semantics,
exposition formats, journal schema, convergence-trace parity between the
numpy oracle and the jitted kernels, contention sentinel, follow-mode
counters, and the DetectBatch/blob-codec contracts.
"""

import json
import re
import threading

import numpy as np
import pandas as pd
import pytest

from microrank_tpu.config import (
    MicroRankConfig,
    PageRankConfig,
    RuntimeConfig,
    WindowConfig,
)
from microrank_tpu.obs import (
    MetricsRegistry,
    get_registry,
    read_journal,
    registry_from_json,
    set_registry,
)
from microrank_tpu.obs.journal import RunJournal
from microrank_tpu.testing import SyntheticConfig, generate_case


@pytest.fixture
def registry():
    """Install a fresh process registry; restore the old one after."""
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


# ---------------------------------------------------------------- registry


def test_counter_concurrent_increments_are_exact(registry):
    c = registry.counter("t_total", "test", labelnames=("k",))
    n_threads, per_thread = 8, 2000

    def work():
        for _ in range(per_thread):
            c.inc(k="a")

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    [t.start() for t in threads]
    [t.join() for t in threads]
    assert c.value(k="a") == n_threads * per_thread


def test_gauge_set_inc_dec(registry):
    g = registry.gauge("t_gauge", "test")
    g.set(5)
    g.inc(2)
    g.dec()
    assert g.value() == 6


def test_histogram_buckets_cumulative_and_sum(registry):
    h = registry.histogram("t_hist", "test", buckets=(1.0, 5.0, 10.0))
    for v in (0.5, 0.7, 3.0, 7.0, 100.0):
        h.observe(v)
    s = h.snapshot()
    assert s["counts"] == [2, 1, 1, 1]  # (..1], (1..5], (5..10], (10..inf)
    assert s["count"] == 5
    assert s["sum"] == pytest.approx(111.2)


def test_prometheus_exposition_format(registry):
    c = registry.counter("t_reqs_total", "requests", labelnames=("path",))
    c.inc(3, path='a"b\\c')
    h = registry.histogram("t_lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(2.0)
    text = registry.to_prometheus()
    lines = text.splitlines()
    assert "# TYPE t_reqs_total counter" in lines
    assert "# TYPE t_lat_seconds histogram" in lines
    # Label escaping: quote and backslash escaped in the value.
    assert 't_reqs_total{path="a\\"b\\\\c"} 3' in lines
    # Histogram: cumulative buckets ending at +Inf == count.
    bucket_lines = [l for l in lines if l.startswith("t_lat_seconds_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in bucket_lines]
    assert counts == sorted(counts)
    assert bucket_lines[-1].startswith('t_lat_seconds_bucket{le="+Inf"}')
    assert counts[-1] == 2
    assert "t_lat_seconds_count 2" in lines
    # Every sample line: name{labels} value — no stray whitespace.
    for l in lines:
        if l.startswith("#") or not l:
            continue
        assert re.match(r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? \S+$", l), l


def test_registry_json_roundtrip(registry):
    registry.counter("t_c_total", "c", labelnames=("x",)).inc(7, x="1")
    registry.gauge("t_g", "g").set(3.5)
    h = registry.histogram("t_h", "h", buckets=(1.0, 2.0))
    h.observe(0.5)
    h.observe(9.0)
    snap = registry.to_json()
    rebuilt = registry_from_json(json.loads(json.dumps(snap)))
    assert rebuilt.to_prometheus() == registry.to_prometheus()


def test_registry_idempotent_and_conflicting_registration(registry):
    a = registry.counter("t_same", "x", labelnames=("l",))
    b = registry.counter("t_same", "x", labelnames=("l",))
    assert a is b
    with pytest.raises(ValueError, match="already registered"):
        registry.gauge("t_same", "x")


# ---------------------------------------------------------------- journal


def test_journal_schema_roundtrip(tmp_path, registry):
    from microrank_tpu.pipeline.results import WindowResult

    j = RunJournal(tmp_path / "journal.jsonl")
    j.run_start(pipeline="test", kernel="auto")
    r = WindowResult(start="s", end="e", anomaly=True, n_traces=10)
    r.ranking = [("op", 1.0)]
    r.rank_iterations = 25
    r.rank_residual = 1e-6
    r.kernel = "coo"
    j.window(r, queue_depth=1)
    j.run_end(windows=1, ranked=1)
    events = read_journal(tmp_path / "journal.jsonl")
    assert [e["event"] for e in events] == ["run_start", "window", "run_end"]
    for e in events:
        assert e["schema"] == 1 and "ts" in e
    w = events[1]
    assert w["outcome"] == "ranked"
    assert w["rank_iterations"] == 25
    assert w["rank_residual"] == pytest.approx(1e-6)
    assert w["kernel"] == "coo"
    assert w["queue_depth"] == 1
    assert w["top1"] == "op"
    assert "norm_load" in w["host"] and "steal_ratio" in w["host"]
    assert "telemetry" in events[2]


def test_table_run_journal_reconciles_with_results(tmp_path, registry):
    """A TableRCA run's journal carries per-window rank timings and the
    device iteration count for every ranked window (acceptance: the
    journal reconciles with the run's own totals)."""
    from microrank_tpu.native import load_span_table
    from microrank_tpu.pipeline.table_runner import TableRCA
    from microrank_tpu.testing.synthetic import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(n_operations=30, n_kinds=8, n_traces=100, seed=11),
        3,
        [0, 1, 2],
    )
    normal_csv = tmp_path / "normal.csv"
    abn_csv = tmp_path / "abn.csv"
    tl.normal.to_csv(normal_csv, index=False)
    tl.timeline.to_csv(abn_csv, index=False)
    cfg = MicroRankConfig(
        window=WindowConfig(detect_minutes=tl.window_minutes, skip_minutes=0.0)
    )
    rca = TableRCA(cfg)
    rca.fit_baseline(load_span_table(normal_csv))
    out = tmp_path / "out"
    results = rca.run(load_span_table(abn_csv), out_dir=out)
    ranked = [r for r in results if r.ranking]
    assert ranked, "timeline should rank at least one window"

    events = read_journal(out / "journal.jsonl")
    assert events[0]["event"] == "run_start"
    assert events[-1]["event"] == "run_end"
    windows = [e for e in events if e["event"] == "window"]
    journal_ranked = [w for w in windows if w["outcome"] == "ranked"]
    assert len(windows) == len(results)
    assert len(journal_ranked) == len(ranked)
    assert events[-1]["ranked"] == len(ranked)
    for w in journal_ranked:
        # Device-side convergence made it out of the jitted program.
        assert w["rank_iterations"] == cfg.pagerank.iterations
        assert w["rank_residual"] is not None
        assert w["kernel"] is not None
        assert "rank_wait" in w["timings"] or "rank_dispatch" in w["timings"]
        assert w["queue_depth"] is not None
    # Registry counters agree with the run's own accounting.
    iters = registry.get("microrank_rank_iterations")
    total_iters = sum(s["count"] for s in iters.samples())
    assert total_iters == len(ranked)
    # Registered on every dispatch; samples appear only when the jit
    # cache actually grows (an earlier test in the same process may
    # already have compiled these program shapes).
    retraces = registry.get("microrank_jit_retraces_total")
    assert retraces is not None
    staged = registry.get("microrank_staged_bytes_total")
    assert sum(s["value"] for s in staged.samples()) > 0
    # WindowResults mirror the journal (same objects that hit the sink).
    for r in ranked:
        assert r.rank_iterations == cfg.pagerank.iterations


# ------------------------------------------------- convergence-trace parity


def _halves(df):
    tids = list(df["traceID"].unique())
    return tids[: len(tids) // 2], tids[len(tids) // 2 :]


@pytest.mark.parametrize("kernel", ["coo", "csr", "pcsr", "packed", "dense"])
def test_convergence_trace_parity_oracle_vs_device(kernel, registry):
    """The device residual trace matches the numpy oracle's (same
    definition: post-normalization L-inf change per partition) within
    f32-vs-f64 tolerance, per kernel."""
    from microrank_tpu.rank_backends import NumpyRefBackend
    from microrank_tpu.rank_backends.jax_tpu import JaxBackend

    case = generate_case(
        SyntheticConfig(n_operations=20, n_kinds=6, n_traces=80, seed=7)
    )
    nrm, abn = _halves(case.abnormal)
    cfg = MicroRankConfig(
        runtime=RuntimeConfig(kernel=kernel, prefer_bf16=False)
    )
    jb = JaxBackend(cfg)
    jb.rank_window(case.abnormal, nrm, abn)
    ob = NumpyRefBackend(cfg)
    ob.rank_window(case.abnormal, nrm, abn)
    conv_j, conv_o = jb.last_convergence, ob.last_convergence
    assert conv_j is not None and conv_o is not None
    assert conv_j["iterations"] == cfg.pagerank.iterations
    assert conv_o["iterations"] == cfg.pagerank.iterations
    for side in ("normal", "abnormal"):
        dev = np.asarray(conv_j["residuals"][side])
        ora = np.asarray(conv_o["residuals"][side])
        assert dev.shape == ora.shape
        np.testing.assert_allclose(
            dev, ora, rtol=0.05, atol=1e-4,
            err_msg=f"{kernel} {side} residual trace diverged",
        )


def test_convergence_trace_tol_iterations_parity(registry):
    """iterations-to-tolerance: the device while_loop and the oracle
    early-exit agree (joint vs per-partition stop differs by at most
    one boundary step)."""
    from microrank_tpu.rank_backends import NumpyRefBackend
    from microrank_tpu.rank_backends.jax_tpu import JaxBackend

    case = generate_case(
        SyntheticConfig(n_operations=20, n_kinds=6, n_traces=80, seed=9)
    )
    nrm, abn = _halves(case.abnormal)
    cfg = MicroRankConfig(
        pagerank=PageRankConfig(tol=1e-3, iterations=60),
        runtime=RuntimeConfig(kernel="coo", prefer_bf16=False),
    )
    jb = JaxBackend(cfg)
    jb.rank_window(case.abnormal, nrm, abn)
    ob = NumpyRefBackend(cfg)
    ob.rank_window(case.abnormal, nrm, abn)
    it_j = jb.last_convergence["iterations"]
    it_o = ob.last_convergence["iterations"]
    assert it_j < 60, "tol should stop the loop early"
    assert abs(it_j - it_o) <= 1
    assert jb.last_convergence["final_residual"] <= 1e-3 * 1.05


def test_convergence_trace_survives_device_checks(registry, tmp_path):
    """Regression for the carried-over PR 2 gap: the checkify program
    now has a residual-traced twin (rank_window_checked_traced), so
    convergence telemetry must flow — not silently drop — under
    ``device_checks=True``: the backend's last_convergence populates and
    the pipeline's WindowResult carries rank_residual."""
    from microrank_tpu.rank_backends.jax_tpu import JaxBackend

    case = generate_case(
        SyntheticConfig(n_operations=20, n_kinds=6, n_traces=80, seed=7)
    )
    nrm, abn = _halves(case.abnormal)
    for blob in (True, False):
        cfg = MicroRankConfig(
            runtime=RuntimeConfig(
                device_checks=True,
                convergence_trace=True,
                blob_staging=blob,
                prefer_bf16=False,
            )
        )
        jb = JaxBackend(cfg)
        jb.rank_window(case.abnormal, nrm, abn)
        conv = jb.last_convergence
        assert conv is not None, "conv trace dropped under device_checks"
        assert conv["iterations"] == cfg.pagerank.iterations
        assert conv["final_residual"] is not None
        assert len(conv["residuals"]["normal"]) == conv["iterations"]

    # Pipeline level: a ranked WindowResult carries the residual fields.
    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.pipeline.table_runner import TableRCA

    case.normal.to_csv(tmp_path / "n.csv", index=False)
    case.abnormal.to_csv(tmp_path / "a.csv", index=False)
    rca = TableRCA(
        MicroRankConfig(
            runtime=RuntimeConfig(device_checks=True, prefer_bf16=False)
        )
    )
    rca.fit_baseline(native.load_span_table(tmp_path / "n.csv"))
    results = rca.run(native.load_span_table(tmp_path / "a.csv"))
    ranked = [r for r in results if r.ranking]
    assert ranked, "no window ranked — fixture drifted"
    for r in ranked:
        assert r.rank_residual is not None
        assert r.rank_iterations is not None


def test_batched_traced_matches_per_window(registry):
    """The vmapped traced program returns per-window traces equal to the
    single-window ones."""
    import jax

    from microrank_tpu.graph.build import build_window_graph
    from microrank_tpu.parallel.sharded_rank import (
        rank_windows_batched_traced,
        stack_window_graphs,
    )
    from microrank_tpu.rank_backends.jax_tpu import rank_window_traced_device

    cfg = MicroRankConfig()
    graphs = []
    for seed in (1, 2):
        case = generate_case(
            SyntheticConfig(n_operations=15, n_kinds=5, n_traces=50, seed=seed)
        )
        nrm, abn = _halves(case.abnormal)
        g, _, _, _ = build_window_graph(case.abnormal, nrm, abn, aux="none")
        graphs.append(g)
    stacked = stack_window_graphs(graphs)
    ti_b, ts_b, nv_b, res_b, it_b = jax.device_get(
        rank_windows_batched_traced(
            stacked, cfg.pagerank, cfg.spectrum, "coo"
        )
    )
    for b, g in enumerate(graphs):
        ti, ts, nv, res, it = jax.device_get(
            rank_window_traced_device(g, cfg.pagerank, cfg.spectrum, None, "coo")
        )
        assert int(it_b[b]) == int(it)
        np.testing.assert_allclose(res_b[b], res, rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------- sentinel


def test_contention_sentinel_smoke(registry):
    from microrank_tpu.obs.host import ContentionSentinel

    s = ContentionSentinel()
    first = s.sample()
    second = s.sample()
    for sample in (first, second):
        assert set(sample) >= {
            "load1", "load5", "cpus", "norm_load", "steal_ratio",
            "contended",
        }
        assert sample["cpus"] >= 1
        assert 0.0 <= sample["steal_ratio"] <= 1.0
        assert sample["norm_load"] >= 0.0
        assert isinstance(sample["contended"], bool)
    # The gauges mirror the last sample.
    assert registry.get("microrank_host_norm_load") is not None


def test_sentinel_flags_high_load(registry):
    from microrank_tpu.obs.host import ContentionSentinel

    s = ContentionSentinel(load_threshold=-1.0)  # everything is contended
    assert s.sample()["contended"] is True


# ---------------------------------------------------------- follow counters


def _follow_rca(tmp_path, tl):
    from microrank_tpu.native import load_span_table
    from microrank_tpu.pipeline.table_runner import TableRCA

    cfg = MicroRankConfig(
        window=WindowConfig(detect_minutes=tl.window_minutes, skip_minutes=0.0)
    )
    rca = TableRCA(cfg)
    normal_csv = tmp_path / "normal.csv"
    if not normal_csv.exists():
        tl.normal.to_csv(normal_csv, index=False)
    rca.fit_baseline(load_span_table(normal_csv))
    return rca


@pytest.fixture(scope="module")
def follow_timeline():
    from microrank_tpu.testing.synthetic import generate_timeline

    return generate_timeline(
        SyntheticConfig(n_operations=30, n_kinds=8, n_traces=90, seed=13),
        3,
        [0, 1, 2],
    )


def test_follow_parse_failures_count_toward_idle_exit(
    tmp_path, registry, follow_timeline
):
    """A permanently unparseable tail must trip idle_exit (advisor r5:
    it used to retry forever without ever counting as idle) and emit
    follow_parse_failures."""
    from microrank_tpu.pipeline.follow import follow_table

    csv = tmp_path / "stream.csv"
    csv.write_text("totally,not\na traces file\n")
    rca = _follow_rca(tmp_path, follow_timeline)
    sizes = iter([10, 20, 30, 40, 50])

    def grow(_):
        # Grow the (still unparseable) file every poll so the no-growth
        # idle path never triggers — only the parse-failure path can.
        csv.write_text("garbage," * next(sizes) + "\n")

    polls = follow_table(
        rca, csv, tmp_path / "out", poll_seconds=0.0, idle_exit=3,
        sleep=grow,
    )
    with pytest.raises(StopIteration):
        next(polls)
    failures = registry.get("microrank_follow_parse_failures_total")
    assert failures is not None and failures.value() >= 3


def test_follow_detects_rotation(tmp_path, registry, follow_timeline):
    """Shrinking the file (rotation/truncation) is detected, counted,
    and the follower re-reads instead of treating it as idle."""
    from microrank_tpu.pipeline.follow import follow_table

    tl = follow_timeline

    def window_frame(w):
        w0 = tl.start + pd.Timedelta(minutes=w * tl.window_minutes)
        w1 = w0 + pd.Timedelta(minutes=tl.window_minutes)
        df = tl.timeline
        return df[(df["startTime"] >= w0) & (df["startTime"] < w1)]

    csv = tmp_path / "stream.csv"
    out = tmp_path / "out"
    pd.concat([window_frame(0), window_frame(1), window_frame(2)]).to_csv(
        csv, index=False
    )
    rca = _follow_rca(tmp_path, follow_timeline)
    polls = follow_table(
        rca, csv, out, poll_seconds=0.0, idle_exit=2, sleep=lambda s: None
    )
    first = next(polls)
    assert sum(1 for r in first if r.ranking) == 2  # windows 0+1 closed

    # Rotate: the collector replaced the file with a shorter one.
    window_frame(0).to_csv(csv, index=False)
    second = next(polls)
    # Nothing NEW ranks (the cursor is past the rotated-in content)...
    assert sum(1 for r in second if r.ranking) == 0
    # ...but the rotation was seen and counted, not mistaken for growth.
    rotations = registry.get("microrank_follow_rotations_total")
    assert rotations is not None and rotations.value() == 1
    with pytest.raises(StopIteration):
        next(polls)


# ---------------------------------------------------------------- contracts


def test_detect_batch_contract_enforced(registry):
    from microrank_tpu.detect import compute_slo
    from microrank_tpu.graph.build import build_detect_batch
    from microrank_tpu.utils.guards import ContractError, contract_checks

    case = generate_case(
        SyntheticConfig(n_operations=10, n_kinds=4, n_traces=30, seed=3)
    )
    vocab, _ = compute_slo(case.normal)
    with contract_checks(True):
        batch, tids = build_detect_batch(case.abnormal, vocab)
    assert batch.op.dtype == np.int32

    from microrank_tpu.analysis.contracts import contract

    @contract(batch="detectbatch")
    def consume(batch):
        return batch

    with contract_checks(True):
        consume(batch)
        with pytest.raises(ContractError, match="dtype"):
            consume(batch._replace(duration_us=batch.duration_us.astype(np.float64)))
        with pytest.raises(ContractError, match="span axis"):
            consume(batch._replace(trace=batch.trace[:-1]))
        with pytest.raises(ContractError, match="DetectBatch"):
            consume((1, 2))


def test_blob_codec_contract_roundtrip(registry):
    import jax

    from microrank_tpu.graph.build import build_window_graph
    from microrank_tpu.rank_backends.blob import (
        pack_graph_blob,
        unpack_graph_blob,
    )
    from microrank_tpu.utils.guards import ContractError, contract_checks

    case = generate_case(
        SyntheticConfig(n_operations=12, n_kinds=4, n_traces=40, seed=5)
    )
    nrm, abn = _halves(case.abnormal)
    graph, _, _, _ = build_window_graph(case.abnormal, nrm, abn, aux="all")
    with contract_checks(True):
        blob, layout = pack_graph_blob(graph)
        assert blob.dtype == np.uint32
        rebuilt = unpack_graph_blob(jax.numpy.asarray(blob), layout)
        # Round-trip is bit-exact on every leaf.
        for pname in ("normal", "abnormal"):
            a, b = getattr(graph, pname), getattr(rebuilt, pname)
            for f in a._fields:
                np.testing.assert_array_equal(
                    np.asarray(getattr(a, f)), np.asarray(getattr(b, f)),
                    err_msg=f"{pname}.{f}",
                )
        # A dtype-corrupted graph fails the pack contract.
        bad = graph._replace(
            normal=graph.normal._replace(
                sr_val=graph.normal.sr_val.astype(np.float64)
            )
        )
        with pytest.raises(ContractError, match="dtype"):
            pack_graph_blob(bad)


# ------------------------------------------------------------------ cli


def test_cli_stats_emits_prometheus(tmp_path, registry, capsys):
    """`cli run` writes the snapshot + journal; `cli stats` re-emits
    valid Prometheus text covering retraces, staged bytes and the
    per-kernel convergence metrics (the acceptance surface)."""
    from microrank_tpu.cli.main import main
    from microrank_tpu.testing.synthetic import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(n_operations=25, n_kinds=8, n_traces=80, seed=17),
        2,
        [0, 1],
    )
    normal_csv = tmp_path / "normal.csv"
    abn_csv = tmp_path / "abn.csv"
    tl.normal.to_csv(normal_csv, index=False)
    tl.timeline.to_csv(abn_csv, index=False)
    out = tmp_path / "out"
    rc = main(
        [
            "run",
            "--normal", str(normal_csv),
            "--abnormal", str(abn_csv),
            "-o", str(out),
            "--detect-minutes", str(tl.window_minutes),
            "--skip-minutes", "0",
        ]
    )
    assert rc == 0
    assert (out / "metrics.json").exists()
    assert (out / "metrics.prom").exists()
    assert (out / "journal.jsonl").exists()
    capsys.readouterr()

    rc = main(["stats", str(out), "--journal"])
    assert rc == 0
    text = capsys.readouterr().out
    assert "# TYPE microrank_jit_retraces_total counter" in text
    assert "microrank_staged_bytes_total" in text
    assert "# TYPE microrank_rank_iterations histogram" in text
    assert "microrank_rank_final_residual" in text
    assert re.search(r'microrank_rank_iterations_count\{kernel="\w+"\} \d+', text)

    rc = main(["stats", str(out), "--format", "json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert "microrank_rank_iterations" in data["metrics"]


def test_diff_registries_subtracts_counters_and_histograms():
    from microrank_tpu.obs import diff_registries

    before, after = MetricsRegistry(), MetricsRegistry()
    before.counter("c_total", "x", ("k",)).inc(3, k="a")
    after.counter("c_total", "x", ("k",)).inc(5, k="a")
    after.counter("c_total", "x", ("k",)).inc(2, k="b")  # new label set
    before.gauge("g", "x").set(7)
    after.gauge("g", "x").set(4)
    hb = before.histogram("h", "x", buckets=(1, 10))
    ha = after.histogram("h", "x", buckets=(1, 10))
    hb.observe(0.5)
    ha.observe(0.5)
    ha.observe(5.0)
    # A counter that went DOWN (process restart) clamps at zero.
    before.counter("reset_total", "x").inc(9)
    after.counter("reset_total", "x").inc(2)

    delta = diff_registries(before, after)
    assert delta.get("c_total").value(k="a") == 2
    assert delta.get("c_total").value(k="b") == 2
    assert delta.get("g").value() == 4  # gauges keep the after reading
    snap = delta.get("h").snapshot()
    assert snap["count"] == 1 and snap["counts"] == [0, 1, 0]
    assert delta.get("reset_total").value() == 0


def test_cli_stats_diff_between_snapshots(tmp_path, registry, capsys):
    """`cli stats --diff before/ after/`: after-minus-before deltas in
    both exposition formats (the PR 2 follow-up)."""
    from microrank_tpu.cli.main import main

    for name, windows in (("before", 2), ("after", 5)):
        reg = MetricsRegistry()
        reg.counter(
            "microrank_windows_total", "w", ("outcome",)
        ).inc(windows, outcome="ranked")
        d = tmp_path / name
        reg.write_snapshot(d)
    rc = main(
        ["stats", "--diff", str(tmp_path / "before"), str(tmp_path / "after")]
    )
    assert rc == 0
    text = capsys.readouterr().out
    assert 'microrank_windows_total{outcome="ranked"} 3' in text

    rc = main(
        [
            "stats", "--diff",
            str(tmp_path / "before"), str(tmp_path / "after"),
            "--format", "json",
        ]
    )
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    samples = data["metrics"]["microrank_windows_total"]["samples"]
    assert samples == [
        {"labels": {"outcome": "ranked"}, "value": 3.0}
    ]

    # Wrong arity is a usage error, not a crash.
    assert main(["stats", "--diff", str(tmp_path / "before")]) == 2


def test_metrics_http_server(registry):
    import urllib.request

    from microrank_tpu.obs.server import start_metrics_server

    registry.counter("t_live_total", "x").inc(4)
    server = start_metrics_server(0, registry)
    try:
        base = f"http://127.0.0.1:{server.port}"
        body = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "t_live_total 4" in body
        jbody = json.loads(
            urllib.request.urlopen(f"{base}/metrics.json").read()
        )
        assert jbody["metrics"]["t_live_total"]["samples"][0]["value"] == 4
        assert (
            urllib.request.urlopen(f"{base}/healthz").read() == b"ok\n"
        )
    finally:
        server.close()


# ------------------------------------------------------- pad-waste audit


def test_pad_waste_audit_vs_estimate_on_known_window():
    """The staged_pad_bytes metric now AUDITS actual staged leaf shapes
    (graph_staging_audit) instead of estimating from mean live
    fractions; regression-compare the two on a known window."""
    from microrank_tpu.detect import compute_slo, detect_partition
    from microrank_tpu.graph.build import build_window_graph
    from microrank_tpu.obs.metrics import (
        graph_staging_audit,
        graph_staging_stats,
    )

    case = generate_case(
        SyntheticConfig(n_operations=20, n_traces=150, seed=3)
    )
    vocab, baseline = compute_slo(case.normal)
    cfg = MicroRankConfig()
    flag, nrm, abn = detect_partition(cfg, vocab, baseline, case.abnormal)
    assert flag and nrm and abn
    graph, _, _, _ = build_window_graph(
        case.abnormal, nrm, abn, pad_policy="pow2", aux="all"
    )
    total_e, pad_e = graph_staging_stats(graph)
    total_a, pad_a = graph_staging_audit(graph)
    # Same staged leaves, so identical totals; both see real pow2 waste.
    assert total_a == total_e
    assert 0 < pad_e < total_e and 0 < pad_a < total_a
    # The audit counts the bitmaps' op-ROW waste (padded vocab rows
    # beyond n_ops) that the estimate folds at the last-axis ratio only,
    # and the indptrs' true live+1 offsets; the two agree within the
    # estimate's error band but are NOT the same number.
    assert pad_a == pytest.approx(pad_e, rel=0.6)
    assert pad_a != pad_e
    # The audit follows what is ACTUALLY staged: stripping the fields
    # the packed kernel never reads (device_subset) shrinks the report.
    from microrank_tpu.rank_backends.jax_tpu import device_subset

    total_s, pad_s = graph_staging_audit(device_subset(graph, "packed"))
    assert total_s < total_a and pad_s < pad_a
