"""Detector tests: numpy vs jax agreement, fault sensitivity, edge rules."""

import jax.numpy as jnp
import pytest
import numpy as np

from microrank_tpu.config import DetectorConfig
from microrank_tpu.detect import compute_slo, detect_jax, detect_numpy
from microrank_tpu.graph import build_detect_batch
from microrank_tpu.graph.structures import pad_to


def _run_both(case, cfg=DetectorConfig()):
    vocab, baseline = compute_slo(case.normal)
    batch, trace_ids = build_detect_batch(case.abnormal, vocab)
    res_np = detect_numpy(batch, baseline, cfg)
    thresh = jnp.asarray(baseline.mean_ms + cfg.k_sigma * baseline.std_ms)
    t_pad = pad_to(int(batch.n_traces))
    res_jx = detect_jax(batch, thresh, t_pad, cfg)
    return res_np, res_jx, trace_ids


def test_numpy_jax_agree(small_case):
    res_np, res_jx, trace_ids = _run_both(small_case)
    t = len(trace_ids)
    np.testing.assert_array_equal(
        res_np.abnormal[:t], np.asarray(res_jx.abnormal)[:t]
    )
    np.testing.assert_array_equal(res_np.valid[:t], np.asarray(res_jx.valid)[:t])
    np.testing.assert_allclose(
        res_np.expected_ms[:t], np.asarray(res_jx.expected_ms)[:t], rtol=1e-5
    )
    np.testing.assert_allclose(
        res_np.real_ms[:t], np.asarray(res_jx.real_ms)[:t], rtol=1e-6
    )
    assert bool(res_np.flag) == bool(res_jx.flag)


def test_abnormal_window_flags(small_case):
    res_np, _, _ = _run_both(small_case)
    assert bool(res_np.flag)
    assert res_np.abnormal.sum() > 0


def test_normal_window_mostly_clean(small_case):
    # Detection over the normal window itself: 3-sigma threshold on sums of
    # inclusive spans leaves a generous margin, so no trace should flag.
    case = small_case
    vocab, baseline = compute_slo(case.normal)
    batch, _ = build_detect_batch(case.normal, vocab)
    res = detect_numpy(batch, baseline, DetectorConfig())
    assert res.abnormal.sum() == 0


def test_unknown_ops_contribute_zero(small_case):
    # Reference quirk: ops unseen in the SLO baseline add 0 expected time
    # (bare except, anormaly_detector.py:66-67). With an empty vocab every
    # op is unknown -> expected = 0 -> every valid trace is abnormal.
    from microrank_tpu.io.interning import Vocab
    from microrank_tpu.graph.structures import SloBaseline

    case = small_case
    vocab = Vocab(["nonexistent_op"])
    baseline = SloBaseline(
        mean_ms=np.zeros(1, np.float32), std_ms=np.zeros(1, np.float32)
    )
    batch, trace_ids = build_detect_batch(case.normal, vocab)
    res = detect_numpy(batch, baseline, DetectorConfig())
    assert res.abnormal.sum() == res.valid.sum() == len(trace_ids)


def test_slack_variant(small_case):
    # The single-trace path's 1-sigma + 50ms slack variant runs through the
    # same kernel (C5/C6 unification).
    cfg = DetectorConfig.single_trace_variant()
    res_np, res_jx, _ = _run_both(small_case, cfg)
    assert bool(res_np.flag) == bool(res_jx.flag)


def test_p90_slo_lanes_agree(small_case, tmp_path):
    # p90 variant: pandas groupby.quantile vs the table lane's
    # sorted-searchsorted percentile must agree.
    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.graph.table_ops import compute_slo_from_table

    case = small_case
    case.normal.to_csv(tmp_path / "n.csv", index=False)
    table = native.load_span_table(tmp_path / "n.csv")
    v1, b1 = compute_slo(case.normal, stat="p90")
    v2, b2 = compute_slo_from_table(table, stat="p90")
    m1 = dict(zip(v1.names, b1.mean_ms))
    m2 = dict(zip(v2.names, b2.mean_ms))
    assert set(m1) == set(m2)
    for op in m1:
        assert m1[op] == pytest.approx(m2[op], abs=2e-4), op
    # p90 center sits above the mean for right-skewed lognormal durations.
    _, b_mean = compute_slo(case.normal, stat="mean")
    assert (b1.mean_ms >= b_mean.mean_ms - 1e-3).mean() > 0.9


def test_unknown_slo_stat_raises(small_case):
    with pytest.raises(ValueError, match="unknown SLO statistic"):
        compute_slo(small_case.normal, stat="median")
    with pytest.raises(ValueError, match="percentile out of range"):
        compute_slo(small_case.normal, stat="p0")
    with pytest.raises(ValueError, match="unknown SLO statistic"):
        compute_slo(small_case.normal, stat="pxx")


def test_arbitrary_percentile_slo(small_case, tmp_path):
    # Any "pNN" percentile works in both lanes and orders sensibly.
    native = pytest.importorskip("microrank_tpu.native")
    if not native.native_available():
        pytest.skip("native loader unavailable")
    from microrank_tpu.graph.table_ops import compute_slo_from_table

    case = small_case
    case.normal.to_csv(tmp_path / "n99.csv", index=False)
    table = native.load_span_table(tmp_path / "n99.csv")
    v1, b1 = compute_slo(case.normal, stat="p99")
    v2, b2 = compute_slo_from_table(table, stat="p99")
    m1 = dict(zip(v1.names, b1.mean_ms))
    m2 = dict(zip(v2.names, b2.mean_ms))
    assert set(m1) == set(m2)
    for op in m1:
        assert m1[op] == pytest.approx(m2[op], abs=2e-4), op
    _, b90 = compute_slo(case.normal, stat="p90")
    assert (b1.mean_ms >= b90.mean_ms - 1e-3).all()
