"""Worker for tests/test_distributed.py: one process of a two-process
CPU mesh (not collected by pytest — no test_ prefix).

Each process: join the distributed runtime (env-driven), build the SAME
four window graphs deterministically, form one global (2, 4) mesh over
both processes' devices, rank via the unchanged shard_map/psum program,
allgather, and dump the full result to JSON. The driver asserts both
processes' dumps equal the single-process ranking.
"""

import json
import os
import sys


def main() -> int:
    out_path = sys.argv[1]

    from microrank_tpu.parallel.distributed import (
        fetch_replicated,
        initialize_distributed,
        is_primary,
    )

    active = initialize_distributed()
    assert active, "distributed runtime did not come up"

    import jax
    import numpy as np

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    from microrank_tpu.config import MicroRankConfig
    from microrank_tpu.detect import compute_slo, detect_numpy
    from microrank_tpu.graph import build_detect_batch, build_window_graph
    from microrank_tpu.parallel import make_mesh, stack_window_graphs
    from microrank_tpu.parallel.distributed import global_put
    from microrank_tpu.parallel.sharded_rank import (
        SHARD_AXIS,
        WINDOW_AXIS,
        _partition_specs,
        rank_windows_sharded,
    )
    from microrank_tpu.graph.structures import WindowGraph
    from microrank_tpu.testing import SyntheticConfig, generate_case

    cfg = MicroRankConfig()
    graphs = []
    for seed in (1, 2, 3, 4):
        case = generate_case(
            SyntheticConfig(n_operations=20, n_traces=100, seed=seed)
        )
        vocab, baseline = compute_slo(case.normal)
        batch, tids = build_detect_batch(case.abnormal, vocab)
        det = detect_numpy(batch, baseline, cfg.detector)
        abn = [t for t, a in zip(tids, det.abnormal) if a]
        nrm = [
            t
            for t, a, v in zip(tids, det.abnormal, det.valid)
            if v and not a
        ]
        graph, _, _, _ = build_window_graph(case.abnormal, nrm, abn)
        graphs.append(graph)

    mesh = make_mesh((2, 4))
    stacked = stack_window_graphs(graphs, shard_multiple=4)
    pspecs = _partition_specs(WINDOW_AXIS, SHARD_AXIS)
    specs = WindowGraph(normal=pspecs, abnormal=pspecs)
    batched = global_put(stacked, mesh, specs)

    top_idx, top_scores, n_valid = rank_windows_sharded(
        batched, cfg.pagerank, cfg.spectrum, mesh
    )
    top_idx, top_scores, n_valid = fetch_replicated(
        (top_idx, top_scores, n_valid)
    )
    result = {
        "process_index": int(jax.process_index()),
        "is_primary": bool(is_primary()),
        "top_idx": np.asarray(top_idx).tolist(),
        "top_scores": np.asarray(top_scores, np.float64).tolist(),
        "n_valid": np.asarray(n_valid).tolist(),
    }

    # Full pipeline over the same distributed mesh: TableRCA with a
    # process-spanning (1, 8) mesh (global_put staging + allgather
    # fetch) over a shared CSV pair written by the test driver.
    table_dir = sys.argv[2] if len(sys.argv) > 2 else None
    if table_dir:
        from microrank_tpu.config import RuntimeConfig
        from microrank_tpu.native import load_span_table
        from microrank_tpu.pipeline import TableRCA

        tcfg = MicroRankConfig(runtime=RuntimeConfig(mesh_shape=(8,)))
        rca = TableRCA(tcfg)
        # cache=False: two processes must not race on the sidecar file.
        rca.fit_baseline(
            load_span_table(os.path.join(table_dir, "n.csv"), cache=False)
        )
        records = rca.run(
            load_span_table(os.path.join(table_dir, "a.csv"), cache=False)
        )
        result["table_rankings"] = [
            [[n, float(s)] for n, s in r.ranking] if r.ranking else None
            for r in records
        ]

    with open(out_path, "w") as f:
        json.dump(result, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
