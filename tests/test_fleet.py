"""Fleet-scale streaming (fleet/): partitioning determinism and
filter/cursor durability, tie-aware cross-host verdict merging, the
coordinator's watermark sealing + exactly-one-incident guarantee,
heartbeat-lease expiry + partition reassignment + rejoin rebalance,
worker-side report buffering while the coordinator is unreachable, the
fleet chaos seams (host-scoped specs, heartbeat_drop), the engine's
whole-checkpoint rejection on a partition-assignment mismatch (the
ISSUE-11 bugfix), an in-process worker end-to-end run, and THE
acceptance path: a 3-process `cli stream --fleet` replay whose seeded
``host_kill`` SIGKILLs one worker mid-incident — lease expiry,
partition reassignment, supervised rejoin with --resume, zero
duplicate incidents, zero lost or duplicate windows."""

import dataclasses
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pandas as pd
import pytest

from microrank_tpu.chaos import (
    configure_chaos,
    reset_breakers,
    set_chaos_host,
)
from microrank_tpu.config import ChaosConfig, FleetConfig, MicroRankConfig
from microrank_tpu.fleet import (
    CoordinatorClient,
    FleetCoordinator,
    FleetServer,
    FleetTracker,
    PartitionSet,
    PartitionedSource,
    fleet_watermark,
    merge_rankings,
    partition_of,
    run_fleet_worker,
    split_partitions,
)
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.stream import ReplaySource, SyntheticSource


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Chaos plan / host scope / breakers are process globals — every
    test starts and ends disarmed."""
    configure_chaos(MicroRankConfig())
    set_chaos_host(None)
    reset_breakers()
    yield
    configure_chaos(MicroRankConfig())
    set_chaos_host(None)
    reset_breakers()


def _chaos_cfg(*faults):
    return MicroRankConfig(
        chaos=ChaosConfig(enabled=True, faults=tuple(faults))
    )


def _fleet_cfg(**fleet_kwargs) -> MicroRankConfig:
    cfg = MicroRankConfig()
    if fleet_kwargs:
        cfg = cfg.replace(
            fleet=dataclasses.replace(cfg.fleet, **fleet_kwargs)
        )
    return cfg


# ------------------------------------------------------------ partition


def test_partition_of_stable_and_covering():
    # crc32-based: identical across processes/restarts (unlike hash()),
    # and a realistic id population covers every partition.
    assert partition_of("trace-123", 4) == partition_of("trace-123", 4)
    hit = {partition_of(f"trace-{i}", 4) for i in range(200)}
    assert hit == {0, 1, 2, 3}
    assert partition_of("anything", 1) == 0


def test_split_partitions_deterministic_round_robin():
    # Sorted-host order: every process computes the same map.
    assert split_partitions(4, ["b", "a"]) == {"a": [0, 2], "b": [1, 3]}
    assert split_partitions(2, ["a", "b", "c"]) == {
        "a": [0], "b": [1], "c": [],
    }


def _span_frame(n=60):
    t0 = pd.Timestamp("2025-03-01 00:00:00")
    return pd.DataFrame(
        {
            "traceID": [f"t{i}" for i in range(n)],
            "serviceName": [f"svc{i % 5}" for i in range(n)],
            "startTime": [
                t0 + pd.Timedelta(seconds=i) for i in range(n)
            ],
        }
    )


def test_partitioned_source_filters_disjoint_union():
    frame = _span_frame()
    chunks_by_host = {}
    for parts in ([0], [1], [0, 1]):
        src = PartitionedSource(
            ReplaySource(frame, chunk_spans=25),
            PartitionSet(parts),
            n_partitions=2,
        )
        chunks_by_host[tuple(parts)] = pd.concat(
            list(src), ignore_index=True
        )
    h0, h1, both = (
        chunks_by_host[(0,)], chunks_by_host[(1,)], chunks_by_host[(0, 1)]
    )
    assert len(h0) + len(h1) == len(frame) == len(both)
    assert set(h0.traceID) & set(h1.traceID) == set()
    assert set(h0.traceID) | set(h1.traceID) == set(frame.traceID)
    # Full assignment short-circuits the hash entirely.
    assert len(both) == len(frame)


def test_partitioned_source_reassignment_mid_stream():
    frame = _span_frame()
    assignment = PartitionSet([0])
    src = PartitionedSource(
        ReplaySource(frame, chunk_spans=20),
        assignment,
        n_partitions=2,
    )
    seen = []
    for i, chunk in enumerate(src):
        seen.append(chunk)
        if i == 0:
            # The heartbeat thread's move: survivors absorb a dead
            # host's partitions — later chunks pass the wider filter.
            assignment.set([0, 1])
    total = sum(len(c) for c in seen)
    only_p0 = sum(
        partition_of(t, 2) == 0 for t in frame.traceID
    )
    assert total > only_p0  # the widened filter let partition 1 through
    assert assignment.changes == 1


def test_partitioned_source_restore_rejects_mismatch_whole():
    frame = _span_frame()
    inner = ReplaySource(frame, chunk_spans=30)
    src = PartitionedSource(
        inner, PartitionSet([0]), n_partitions=2
    )
    state = {
        "type": "partitioned",
        "partition_by": "trace",
        "n_partitions": 2,
        "partitions": [0],
        "inner": {"type": "replay", "row": 30},
    }
    src.restore_state(dict(state))          # matching: accepted
    assert inner._skip_rows == 30
    inner._skip_rows = 0
    for bad in (
        {**state, "partitions": [0, 1]},    # assignment moved
        {**state, "n_partitions": 3},       # cursor-count mismatch
        {**state, "partition_by": "service"},
        {**state, "type": "replay"},
    ):
        with pytest.raises(ValueError):
            src.restore_state(bad)
        # The inner cursor was never touched by a rejected restore.
        assert inner._skip_rows == 0
    # reset_cursor clears a stashed cursor through the wrapper.
    src.restore_state(dict(state))
    src.reset_cursor()
    assert inner._skip_rows == 0


# ---------------------------------------------------------------- merge


def test_merge_rankings_sums_and_breaks_ties_by_name():
    merged = merge_rankings(
        [
            [("op_b", 0.5), ("op_a", 0.25)],
            [("op_c", 0.5), ("op_b", 0.25)],
        ]
    )
    assert merged[0] == ("op_b", 0.75)
    # op_a and op_c tie exactly at 0.25+0.25 vs 0.5... c=0.5, a=0.25:
    assert merged[1] == ("op_c", 0.5)
    assert merged[2] == ("op_a", 0.25)
    # Exact tie: ascending name — the device path's two-key sort.
    tied = merge_rankings([[("z_op", 1.0)], [("a_op", 1.0)]])
    assert tied == [("a_op", 1.0), ("z_op", 1.0)]


def test_fleet_watermark_min_and_blocking():
    assert fleet_watermark([3, 7, 5]) == 3
    assert fleet_watermark([3, None]) is None   # unreported host blocks
    assert fleet_watermark([]) is None


# ---------------------------------------------------------- coordinator


def _report(host, w, outcome="healthy", ranking=(), coord=None):
    resp = coord.report(
        host,
        {
            "start": f"w{w}",
            "start_us": w * 300_000_000,
            "outcome": outcome,
            "ranking": [[n, s] for n, s in ranking],
            "n_spans": 100,
        },
    )
    assert resp["ok"]
    return resp


def test_coordinator_exactly_one_incident_across_hosts(registry):
    coord = FleetCoordinator(_fleet_cfg(), expected_workers=3)
    hosts = ["host0", "host1", "host2"]
    for h in hosts:
        coord.register(h)
    # Two faulted windows; each host blames the same fault with its own
    # partial scores (one host permutes an exact tie — the merge and
    # the tie-aware fingerprint must still dedup into ONE incident).
    for w in range(6):
        for h in hosts:
            if w in (2, 3):
                ranking = [("op_fault", 0.9), ("op_noise", 0.1)]
                if h == "host2":
                    ranking = [("op_fault", 0.9), ("op_other", 0.1)]
                _report(h, w, "ranked", ranking, coord=coord)
            else:
                _report(h, w, coord=coord)
    st = coord.status()
    assert st["sealed"] == 6
    assert st["incidents_opened"] == 1
    assert st["incidents_resolved"] == 1    # w4, w5 healthy streak
    ranked = [s for s in coord.sealed if s["outcome"] == "ranked"]
    assert [s["start"] for s in ranked] == ["w2", "w3"]
    # Merged verdict pooled the three hosts' evidence.
    assert all(len(s["hosts"]) == 3 for s in coord.sealed)


def test_coordinator_seals_in_order_at_the_watermark(registry):
    coord = FleetCoordinator(_fleet_cfg(), expected_workers=2)
    coord.register("host0")
    coord.register("host1")
    for w in range(3):
        _report("host0", w, coord=coord)
    # host1 has not reported: nothing seals (its stream position is
    # unknown — the fleet watermark blocks).
    assert coord.status()["sealed"] == 0
    _report("host1", 0, coord=coord)
    assert coord.status()["sealed"] == 1
    _report("host1", 2, coord=coord)        # host1 jumped to w2
    st = coord.status()
    assert st["sealed"] == 3
    assert [s["start"] for s in coord.sealed] == ["w0", "w1", "w2"]


def test_coordinator_dedups_duplicate_and_late_reports(registry):
    coord = FleetCoordinator(_fleet_cfg(), expected_workers=2)
    coord.register("host0")
    coord.register("host1")
    r = _report("host0", 0, coord=coord)
    assert r["report"] == "accepted"
    r = _report("host0", 0, coord=coord)    # resume re-report, unsealed
    assert r["report"] == "duplicate"
    _report("host1", 0, coord=coord)        # seals w0
    assert coord.status()["sealed"] == 1
    r = _report("host0", 0, coord=coord)    # resume re-report, sealed
    assert r["report"] == "late"
    st = coord.status()
    assert st["duplicate_reports"] == 1
    assert st["late_reports"] == 1
    assert st["sealed"] == 1                # never re-sealed


def test_lease_expiry_reassigns_partitions_and_rejoin_rebalances(
    registry,
):
    clock = type("C", (), {"t": 0.0})()
    cfg = _fleet_cfg(lease_seconds=5.0, partitions=4)
    coord = FleetCoordinator(
        cfg, expected_workers=2, clock=lambda: clock.t
    )
    coord.register("host0")
    coord.register("host1")
    assert coord.workers["host0"].partitions == [0, 2]
    assert coord.workers["host1"].partitions == [1, 3]
    clock.t = 4.0
    coord.heartbeat("host0", spans=100, uptime_s=4.0)
    clock.t = 6.0                     # host1's lease (t=5) expired
    coord.tick()
    assert coord.workers["host1"].state == "dead"
    assert coord.workers["host0"].partitions == [0, 1, 2, 3]
    assert coord.status()["reassignments"] >= 1
    before = coord.status()["reassignments"]
    resp = coord.register("host1", resume=True)     # the rejoin
    assert coord.workers["host1"].state == "alive"
    assert sorted(resp["partitions"]) == [1, 3]
    assert coord.workers["host0"].partitions == [0, 2]
    assert coord.status()["reassignments"] > before
    # A heartbeat from a host that merely looked dead also recovers it.
    clock.t = 20.0
    coord.tick()
    assert coord.workers["host1"].state == "dead"
    coord.heartbeat("host1", uptime_s=1.0)
    assert coord.workers["host1"].state == "alive"


def test_pending_worker_blocks_sealing_until_grace(registry):
    """Expected-but-unregistered hosts hold the watermark through a
    startup grace (3 leases), then reap like any dead host — a slow
    worker is waited for, a missing one cannot stall the fleet."""
    clock = type("C", (), {"t": 0.0})()
    coord = FleetCoordinator(
        _fleet_cfg(lease_seconds=2.0),
        expected_workers=2,
        clock=lambda: clock.t,
    )
    coord.register("host0")
    _report("host0", 0, coord=coord)
    assert coord.status()["sealed"] == 0    # host1 still pending
    clock.t = 5.0                           # inside host1's 3-lease grace
    coord.heartbeat("host0", uptime_s=5.0)  # keeps host0's lease fresh
    assert coord.status()["sealed"] == 0
    clock.t = 6.5                           # past 3 * lease for host1
    coord.tick()
    assert coord.workers["host1"].state == "dead"
    assert coord.status()["sealed"] == 1


# --------------------------------------------------- client + seams


def test_client_buffers_while_unreachable_then_flushes_in_order(
    registry,
):
    coord = FleetCoordinator(_fleet_cfg(), expected_workers=1)
    server = FleetServer(coord).start()
    try:
        client = CoordinatorClient(server.url, "host0", timeout=1.0)
        client.register()
        # Every send fails twice per retry_call (policy max_attempts=2)
        # — the first two reports park; the third call's flush drains
        # everything in order once the seam stops firing.
        configure_chaos(
            _chaos_cfg(
                {
                    "seam": "coordinator_unreachable",
                    "kind": "fail",
                    "count": 4,
                }
            )
        )
        assert client.report(
            {"start": "w0", "start_us": 0, "outcome": "healthy",
             "ranking": []}
        ) is None
        assert client.report(
            {"start": "w1", "start_us": 300_000_000,
             "outcome": "healthy", "ranking": []}
        ) is None
        assert client.pending() == 2
        # Four consecutive failures opened the fleet_report breaker
        # (FLEET_REPORT_POLICY.breaker_threshold=4): sends now fail
        # fast until the reset window elapses and the half-open probe
        # goes through.
        from microrank_tpu.fleet.worker import FLEET_REPORT_POLICY

        time.sleep(FLEET_REPORT_POLICY.breaker_reset_s + 0.2)
        resp = client.report(
            {"start": "w2", "start_us": 600_000_000,
             "outcome": "healthy", "ranking": []}
        )
        assert resp is not None and resp["ok"]
        assert client.pending() == 0
        assert coord.status()["sealed"] == 3
        assert [s["start"] for s in coord.sealed] == ["w0", "w1", "w2"]
        prom = registry.to_prometheus()
        assert 'status="buffered"' in prom
    finally:
        server.shutdown()


def test_client_buffer_bounded_drops_oldest(registry):
    client = CoordinatorClient(
        "http://127.0.0.1:9", "host0", timeout=0.1, max_queue=2
    )
    configure_chaos(
        _chaos_cfg(
            {"seam": "coordinator_unreachable", "kind": "fail",
             "count": -1}
        )
    )
    for w in range(4):
        client.report(
            {"start": f"w{w}", "start_us": w, "outcome": "healthy",
             "ranking": []}
        )
    assert client.pending() == 2
    assert client.dropped == 2
    assert [w["start"] for w in client.buffered_state()] == ["w2", "w3"]


def test_heartbeat_drop_seam_skips_sends(registry):
    from microrank_tpu.fleet.worker import _HeartbeatLoop

    class StubClient:
        def __init__(self):
            self.beats = []

        def heartbeat(self, spans, windows, uptime_s, extra=None):
            self.beats.append(spans)
            return {"partitions": [0], "incident_open": False}

    class StubEngine:
        summary = type("S", (), {"spans": 7, "windows": 1})()

    configure_chaos(
        _chaos_cfg({"seam": "heartbeat_drop", "kind": "drop", "count": 2})
    )
    client = StubClient()
    tracker = FleetTracker.__new__(FleetTracker)  # status sink only
    tracker.opened = tracker.resolved = 0
    tracker._open = False
    loop = _HeartbeatLoop(
        client, StubEngine(), PartitionSet([0]), tracker, interval=0.02
    )
    loop.start()
    deadline = time.monotonic() + 5
    while (
        len(client.beats) < 2 or loop.drops < 2
    ) and time.monotonic() < deadline:
        time.sleep(0.01)
    loop.stop()
    loop.join(timeout=2)
    assert loop.drops == 2          # the first two beats were dropped
    assert len(client.beats) >= 2   # later beats got through


def test_host_scoped_chaos_spec_fires_only_on_matching_host():
    from microrank_tpu.chaos import get_fault_plan, maybe_inject

    cfg = _chaos_cfg(
        {"seam": "host_kill", "kind": "drop", "count": 1,
         "host": "host1"}
    )
    configure_chaos(cfg)
    set_chaos_host("host0")
    assert maybe_inject("host_kill") is None       # scoped to host1
    set_chaos_host("host1")
    assert maybe_inject("host_kill") is not None   # fires here
    assert len(get_fault_plan().injected) == 1


# ----------------------------------- engine whole-checkpoint rejection


def _mini_timeline(n_windows=4):
    return SyntheticSource(
        n_windows=n_windows,
        faulted=[],
        synth_config=None,
        pace_seconds=0.0,
    )


def test_resume_rejects_partition_mismatch_whole_cold_start(
    registry, tmp_path
):
    """The ISSUE-11 bugfix: a checkpoint whose source cursor was taken
    under a different partition assignment is rejected WHOLE — the old
    code restored baseline/tracker/windower in place first, so the
    late source failure left a half-restored engine."""
    from microrank_tpu.stream.engine import StreamEngine

    src1 = _mini_timeline()
    inner1 = ReplaySource(src1.timeline.timeline, chunk_spans=2000)
    engine1 = StreamEngine(
        MicroRankConfig(),
        PartitionedSource(inner1, PartitionSet([0, 1]), n_partitions=2),
        out_dir=tmp_path,
        normal_df=src1.normal,
    )
    s1 = engine1.run()
    assert s1.windows >= 3
    assert (tmp_path / "state.ckpt").exists()

    # Resume under a DIFFERENT assignment: whole rejection, cold start.
    src2 = _mini_timeline()
    inner2 = ReplaySource(src2.timeline.timeline, chunk_spans=2000)
    engine2 = StreamEngine(
        MicroRankConfig(),
        PartitionedSource(inner2, PartitionSet([0]), n_partitions=2),
        out_dir=tmp_path,
        normal_df=src2.normal,
        resume=True,
    )
    assert engine2.resumed is False
    # NOTHING survived the rejected restore: fresh windower, zeroed
    # summary, reset lifecycle, inner cursor back at row 0, and the
    # baseline re-seeded (not the checkpointed moments).
    assert engine2.windower.origin_us is None
    assert engine2.windower._next == 0
    assert engine2.summary.windows == 0
    assert engine2.tracker._window_no == 0
    assert inner2._skip_rows == 0
    assert engine2.baseline.seeded
    prom = registry.to_prometheus()
    assert 'event="rejected"' in prom

    # Same assignment: the checkpoint restores whole.
    src3 = _mini_timeline()
    inner3 = ReplaySource(src3.timeline.timeline, chunk_spans=2000)
    engine3 = StreamEngine(
        MicroRankConfig(),
        PartitionedSource(inner3, PartitionSet([0, 1]), n_partitions=2),
        out_dir=tmp_path,
        normal_df=src3.normal,
        resume=True,
    )
    assert engine3.resumed is True
    assert engine3.summary.windows == s1.windows


def test_fleet_and_single_tracker_states_do_not_mix():
    from microrank_tpu.stream import IncidentTracker

    single = IncidentTracker()
    with pytest.raises(ValueError):
        single.restore({"type": "fleet", "buffered": []})
    client = CoordinatorClient("http://127.0.0.1:9", "h0")
    fleet = FleetTracker(client, "h0")
    with pytest.raises(ValueError):
        fleet.restore(single.to_state())
    # Round trip of the fleet proxy's own state (buffered reports).
    client.restore_buffer([{"start": "w0"}])
    st = fleet.to_state()
    client.reset_buffer()
    fleet.restore(st)
    assert client.pending() == 1


# ------------------------------------------------- worker end to end


def test_fleet_worker_end_to_end_in_process(registry, tmp_path):
    cfg = _fleet_cfg(heartbeat_seconds=0.1, lease_seconds=3.0)
    coord = FleetCoordinator(cfg, expected_workers=1)
    server = FleetServer(coord).start()
    try:
        src = SyntheticSource(n_windows=6, faulted=[3])
        summary, engine = run_fleet_worker(
            cfg,
            src,
            out_dir=tmp_path,
            host_id="host0",
            coordinator_url=server.url,
        )
    finally:
        server.shutdown()
    coord.finalize()
    st = coord.status()
    assert st["sealed"] == 6
    assert st["incidents_opened"] == 1
    assert st["incidents_resolved"] == 1
    assert summary.windows == 6 and summary.ranked == 1
    assert summary.spans > 0
    # The worker's lifecycle mirror followed the coordinator.
    assert engine.tracker.opened == 1
    # The fleet verdict carries the injected fault top-1.
    ranked = [s for s in coord.sealed if s["outcome"] == "ranked"]
    assert len(ranked) == 1
    prom = registry.to_prometheus()
    assert 'microrank_fleet_heartbeats_total{host="host0"}' in prom
    assert 'status="accepted"' in prom


# --------------------------------------------- SIGKILL + rejoin e2e


def _metric_total(prom_text: str, name: str, label: str = None) -> float:
    total = 0.0
    for line in prom_text.splitlines():
        if not line.startswith(name):
            continue
        if label is not None and label not in line:
            continue
        total += float(line.rsplit(" ", 1)[1])
    return total


def test_fleet_host_kill_rejoin_e2e(tmp_path):
    """THE acceptance path (ISSUE 11): a 3-process synthetic fleet
    replay; a seeded host-scoped ``host_kill`` SIGKILLs host0 mid-run
    (after its 4th window — inside the fault burst); the supervisor
    restarts it with --resume after the lease expired. Exactly one
    global incident opens AND resolves, the sealed window sequence has
    no loss and no duplicates, the rejoin's re-reports dedup as
    late/duplicate, and per-host spans/s lands in the journal."""
    out_dir = tmp_path / "fleet"
    plan = tmp_path / "plan.json"
    plan.write_text(
        json.dumps(
            {
                "seed": 7,
                "faults": [
                    {
                        "seam": "host_kill",
                        "kind": "kill",
                        "after": 3,
                        "count": 1,
                        "host": "host0",
                    }
                ],
            }
        )
    )
    import os

    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(Path(__file__).parent.parent),
    }
    proc = subprocess.run(
        [
            sys.executable, "-m", "microrank_tpu.cli", "stream",
            "--fleet", "3",
            "--source", "synthetic",
            "--windows", "8",
            "--fault-windows", "3,4",
            "--pace-seconds", "0.4",
            "--lease-seconds", "3",
            "--heartbeat-seconds", "0.5",
            "--fleet-restart-delay", "4",
            "--chaos", str(plan),
            "-o", str(out_dir),
        ],
        env=env, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # Exactly ONE global incident across three hosts and a host loss.
    inc = [
        json.loads(line)
        for line in (out_dir / "incidents.jsonl").read_text().splitlines()
    ]
    opens = [e for e in inc if e["event"] == "incident_open"]
    resolves = [e for e in inc if e["event"] == "incident_resolve"]
    assert len(opens) == 1, "duplicate incident across the host kill"
    assert len(resolves) == 1
    assert opens[0]["incident_id"] == resolves[0]["incident_id"]

    from microrank_tpu.obs import read_journal

    jev = read_journal(out_dir / "journal.jsonl")
    events = {e["event"] for e in jev}
    # The full robustness story is journaled: death, reassignment,
    # rejoin, per-host throughput.
    assert {"worker_dead", "partition_reassigned",
            "fleet_host_stats"} <= events
    rejoins = [
        e
        for e in jev
        if e["event"] == "worker_registered" and e.get("rejoin")
    ]
    assert rejoins and rejoins[0]["host"] == "host0"
    # No lost, no duplicate windows at fleet scope.
    sealed = [e for e in jev if e["event"] == "fleet_window"]
    starts = [e["start"] for e in sealed]
    assert len(starts) == len(set(starts)) == 8
    assert starts == sorted(starts)
    # Per-host spans/s recorded for every host.
    stats = {
        e["host"]: e["spans_per_second"]
        for e in jev
        if e["event"] == "fleet_host_stats"
    }
    assert set(stats) == {"host0", "host1", "host2"}
    assert all(v > 0 for v in stats.values())

    # Each worker's own journal: unique ordered window starts across
    # the kill + resume (host0's second run re-processed only windows
    # its checkpoint had not sealed).
    for host in ("host0", "host1", "host2"):
        wj = read_journal(out_dir / host / "journal.jsonl")
        wstarts = [e["start"] for e in wj if e["event"] == "window"]
        assert len(wstarts) == len(set(wstarts)), host
        assert wstarts == sorted(wstarts), host
    h0 = read_journal(out_dir / "host0" / "journal.jsonl")
    h0_runs = [e for e in h0 if e["event"] == "run_start"]
    assert len(h0_runs) == 2 and h0_runs[1]["resumed"] is True

    # Fleet metrics landed in the snapshot.
    prom = (out_dir / "metrics.prom").read_text()
    assert _metric_total(prom, "microrank_fleet_heartbeats_total") > 0
    assert (
        _metric_total(prom, "microrank_fleet_reassignments_total") >= 1
    )
    assert "microrank_fleet_host_spans_per_second" in prom
    assert (
        _metric_total(
            prom, "microrank_fleet_sealed_windows_total{",
            'outcome="ranked"',
        )
        >= 1
    )
    # The rejoin restored host0's checkpoint (partitions back via the
    # stable rebalance), so its re-reports start where its cursor left
    # off: any overlap with already-sealed windows dedups as late/
    # duplicate — NEVER re-seals (the count above pinned 8 unique).
    assert _metric_total(prom, "microrank_fleet_reports_total") >= 24
