"""Unified multi-tenant device scheduler (sched/): the parked-window
store's dequeue policy — priority lanes, weighted fair share, soft
token-bucket quotas, deadline expiry at dequeue — plus the
DeviceScheduler thread, co-deployed serve + stream + replay sharing one
device with verdict parity vs each lane alone, and the shape-faithful
warm restart (first-window latency ~ steady state).

Property tests drive the store directly (deterministic: time is passed
in, no thread in the loop); the e2e tests wire real services through
one DeviceScheduler on CPU jax.
"""

import random
import threading
import time

import pytest

from microrank_tpu.config import (
    MicroRankConfig,
    SchedConfig,
    ServeConfig,
    StreamConfig,
    WarehouseConfig,
)
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.sched import (
    DeviceScheduler,
    LANE_BACKFILL,
    LANE_INCIDENT,
    LANE_SERVE,
    ParkedEntry,
    ParkedWindowStore,
    TokenBucket,
    WeightedFairQueue,
)
from microrank_tpu.testing import SyntheticConfig, generate_case


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


@pytest.fixture(scope="module")
def case():
    return generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )


def _store(**sched_kw):
    serve_cfg = sched_kw.pop("serve_cfg", None)
    return ParkedWindowStore(SchedConfig(**sched_kw), serve_cfg=serve_cfg)


def _entry(lane, tenant, key=None, deadline=None, cost=1.0):
    ran = []
    e = ParkedEntry(
        lane, tenant, key if key is not None else ("k", object()),
        payload=tenant, runner=ran.append, deadline=deadline, cost=cost,
    )
    return e


# ------------------------------------------------------------ token bucket


def test_token_bucket_refills_and_carries_debt():
    b = TokenBucket(rate=2.0, burst=4.0, now=100.0)
    assert b.tokens == 4.0
    b.take(6.0)                      # whole batch dispatches; debt
    assert b.tokens == -2.0
    b.refill(101.0)                  # +2 tokens/s
    assert b.tokens == 0.0
    b.refill(200.0)                  # capped at burst
    assert b.tokens == 4.0
    z = TokenBucket(rate=0.0, burst=4.0, now=0.0)
    z.refill(1e9)
    assert z.tokens == 0.0           # rate 0 never accrues


# -------------------------------------------------- weighted fair share


def test_fair_share_converges_to_configured_weights():
    """Stride scheduling: with weights 1/2/4 the dispatch-order prefix
    shares track the weights within 10% at every window boundary."""
    store = _store(tenant_weights=(("a", 1.0), ("b", 2.0), ("c", 4.0)))
    per_tenant = 80
    for i in range(per_tenant):
        for t in ("a", "b", "c"):
            store.park(_entry(LANE_BACKFILL, t))
    order = [
        b[0].tenant for b in store.take_ready(force=True)
    ]
    assert len(order) == 3 * per_tenant
    total_w = 7.0
    for n in (35, 70, 140):
        prefix = order[:n]
        for t, w in (("a", 1.0), ("b", 2.0), ("c", 4.0)):
            expected = n * w / total_w
            got = prefix.count(t)
            assert abs(got - expected) <= max(1.0, 0.1 * expected), (
                f"tenant {t}: {got} of first {n} dispatches, "
                f"expected ~{expected:.1f}"
            )


def test_weighted_fair_queue_shares_and_round_robin_default():
    q = WeightedFairQueue({"a": 1.0, "b": 3.0})
    for i in range(40):
        q.push("a", ("a", i))
        q.push("b", ("b", i))
    first = [q.pop()[0] for _ in range(40)]
    # b gets ~3x the turns of a in any prefix.
    assert abs(first.count("b") - 30) <= 3
    # Equal weights reproduce round-robin in arrival order.
    q2 = WeightedFairQueue()
    for i in range(3):
        q2.push("x", f"x{i}")
        q2.push("y", f"y{i}")
    assert [q2.pop() for _ in range(6)] == [
        "x0", "y0", "x1", "y1", "x2", "y2",
    ]
    assert q2.pop() is None and not q2


# --------------------------------------------------------- quotas


def test_zero_quota_tenant_sorts_last_but_nothing_starves():
    """A rate-0 tenant is permanently out of quota: every in-quota
    tenant's work dispatches first — but the store is work-conserving,
    so the throttled tenant's windows still ALL dispatch (ordered
    behind, never dropped, never idling the device)."""
    store = _store(tenant_rates=(("bg", 0.0),))
    for i in range(20):
        store.park(_entry(LANE_BACKFILL, "bg"))
        store.park(_entry(LANE_BACKFILL, "fg"))
    order = [b[0].tenant for b in store.take_ready(force=True)]
    assert len(order) == 40                       # nothing dropped
    assert order[:20] == ["fg"] * 20              # in-quota first
    assert order[20:] == ["bg"] * 20              # throttled still runs
    shares = store.tenant_shares()
    assert shares == {"fg": 20, "bg": 20}


def test_quota_throttle_is_temporary_and_metered(registry):
    """A tenant over its rate sorts behind until the bucket refills —
    deterministic via injected ``now``."""
    store = _store(tenant_rates=(("meter", 1.0),), burst=2.0)
    t0 = time.monotonic()
    for i in range(4):
        store.park(_entry(LANE_BACKFILL, "meter"))
        store.park(_entry(LANE_BACKFILL, "free"))
    order = [
        b[0].tenant for b in store.take_ready(force=True, now=t0)
    ]
    # burst=2 covers two windows; the rest sort behind "free".
    assert order[:2] == ["meter", "free"] or order[:2] == [
        "free", "meter",
    ]
    assert order.count("meter") == 4              # work-conserving
    assert (
        registry.get("microrank_sched_throttled_total").value(
            tenant="meter"
        )
        >= 1
    )
    # After a long refill the same tenant is in quota again.
    for i in range(2):
        store.park(_entry(LANE_BACKFILL, "meter"))
        store.park(_entry(LANE_BACKFILL, "free"))
    order2 = [
        b[0].tenant
        for b in store.take_ready(force=True, now=t0 + 3600.0)
    ]
    assert order2[0] == "meter" or order2[1] == "meter"


# ----------------------------------------------------- deadline expiry


def test_deadline_expired_entries_expire_at_dequeue_under_contention(
    registry,
):
    expired_payloads = []
    store = _store(serve_cfg=ServeConfig(max_batch_windows=8))
    now = time.monotonic()
    live = ParkedEntry(
        LANE_SERVE, "t", ("bucket",), "live", runner=lambda p: None,
        deadline=now + 60.0,
    )
    dead = [
        ParkedEntry(
            LANE_SERVE, "t", ("bucket",), f"dead{i}",
            runner=lambda p: None, expire=expired_payloads.append,
            deadline=now - 0.001,
        )
        for i in range(3)
    ]
    store.park(dead[0])
    store.park(live)
    store.park(dead[1])
    store.park(dead[2])
    # Contention: other lanes hold work too.
    store.park(_entry(LANE_INCIDENT, "hot"))
    store.park(_entry(LANE_BACKFILL, "cold"))
    batches = store.take_ready(force=True, now=now)
    dispatched = [e.payload for b in batches for e in b]
    assert sorted(expired_payloads) == ["dead0", "dead1", "dead2"]
    assert "live" in dispatched
    assert not any(p.startswith("dead") for p in dispatched)
    assert store.expired == 3
    assert (
        registry.get("microrank_sched_expired_total").value() == 3
    )
    assert store.pending() == 0


# --------------------------------------------------- priority lanes


def test_priority_inversion_impossible_under_adversarial_mixes():
    """Property: for random adversarial park orders, tenant mixes,
    costs, and quota states, every take_ready output orders ALL
    incident batches before any serve batch before any backfill batch.
    Lane priority is structural — no tenant state can invert it."""
    rng = random.Random(0)
    for trial in range(25):
        store = _store(
            tenant_weights=(("a", rng.choice([0.5, 1, 8])),),
            tenant_rates=(("b", rng.choice([0.0, 0.5])),),
            serve_cfg=ServeConfig(
                max_batch_windows=rng.choice([1, 2, 4]),
                max_wait_ms=0.0,
            ),
        )
        n = rng.randint(5, 30)
        for i in range(n):
            lane = rng.choice(
                [LANE_INCIDENT, LANE_SERVE, LANE_BACKFILL]
            )
            store.park(_entry(
                lane, rng.choice(["a", "b", "c"]),
                key=("k", rng.randint(0, 3)) if lane == LANE_SERVE
                else None,
                cost=rng.choice([0.5, 1.0, 3.0]),
            ))
        lanes_out = [
            b[0].lane for b in store.take_ready(force=True)
        ]
        assert lanes_out == sorted(lanes_out), (
            f"trial {trial}: lane order {lanes_out} inverted priority"
        )
        assert store.pending() == 0


def test_open_incident_work_preempts_parked_backfill():
    """Backfill parked FIRST (older, lower seq, smaller vt) still
    dequeues after incident-lane work parked later."""
    store = _store()
    for i in range(5):
        store.park(_entry(LANE_BACKFILL, "backfill"))
    store.park(_entry(LANE_INCIDENT, "stream"))
    order = [b[0].lane for b in store.take_ready(force=True)]
    assert order[0] == LANE_INCIDENT
    assert order[1:] == [LANE_BACKFILL] * 5


# ------------------------------------------------- DeviceScheduler thread


def test_device_scheduler_runs_thunks_and_reenters(registry):
    store = _store()
    sched = DeviceScheduler(store, name="mr-sched-test")
    sched.start()
    try:
        fut = sched.submit_thunk(LANE_BACKFILL, "t", lambda: 41 + 1)
        assert fut.result(timeout=30) == 42
        # run_on from OFF-thread blocks for the result; a thunk that
        # re-enters run_on executes inline (no self-deadlock).
        nested = sched.run_on(
            LANE_SERVE, "t",
            lambda: sched.run_on(LANE_INCIDENT, "t", lambda: "inner"),
        )
        assert nested == "inner"
        # Exceptions relay to the caller; the scheduler survives.
        with pytest.raises(ValueError, match="boom"):
            sched.run_on(
                LANE_BACKFILL, "t",
                lambda: (_ for _ in ()).throw(ValueError("boom")),
            )
        assert sched.is_alive()
        assert sched.wait_idle(timeout=30)
        reg = registry.get("microrank_sched_dispatch_windows_total")
        assert (
            sum(s["value"] for s in reg.samples()) >= 3
        )
    finally:
        sched.stop(drain=True, timeout=30)
    assert not sched.is_alive()


def test_device_scheduler_drain_stop_flushes_everything():
    store = _store(serve_cfg=ServeConfig(max_wait_ms=60_000.0))
    sched = DeviceScheduler(store, name="mr-sched-drain")
    sched.start()
    done = []
    store.park(ParkedEntry(
        LANE_SERVE, "t", ("b",), "w1",
        runner=lambda p: done.extend(p),
    ))
    # Parked under a 60s max_wait: only the drain flushes it.
    time.sleep(0.05)
    assert done == []
    sched.stop(drain=True, timeout=30)
    assert done == ["w1"]
    assert store.pending() == 0


# --------------------------------------- co-deploy e2e: one device


def _serve_config(**serve_kw):
    serve_kw.setdefault("warmup", False)
    serve_kw.setdefault("max_batch_windows", 2)
    serve_kw.setdefault("max_wait_ms", 2000.0)
    return MicroRankConfig(serve=ServeConfig(**serve_kw))


def _rank_once(svc, records, request_id, tenant="default"):
    from microrank_tpu.serve import RankRequest

    fut = svc.submit(RankRequest(
        request_id=request_id, tenant=tenant, spans=records,
    ))
    return fut.result(timeout=120)


def _records(case):
    df = case.abnormal.copy()
    df["startTime"] = df["startTime"].astype(str)
    df["endTime"] = df["endTime"].astype(str)
    return df.to_dict("records")


@pytest.mark.slow
def test_codeploy_serve_stream_replay_share_one_device(
    case, registry, tmp_path
):
    """Serve + stream + warehouse-replay backfill co-deployed through
    ONE ParkedWindowStore/DeviceScheduler: every lane's verdict is
    tie-aware identical to its solo run, fair-share accounting sees all
    tenants, and no dispatch errors or drops occur."""
    from microrank_tpu.serve import ServeService
    from microrank_tpu.stream import StreamEngine, SyntheticSource
    from microrank_tpu.utils.ranking_compare import (
        tie_aware_topk_agreement,
    )
    from microrank_tpu.warehouse import replay_range

    records = _records(case)

    def _stream_cfg():
        return MicroRankConfig(
            stream=StreamConfig(allowed_lateness_seconds=5.0),
            warehouse=WarehouseConfig(enabled=True),
            sched=SchedConfig(
                tenant_weights=(("serve", 2.0), ("stream", 2.0)),
                tenant_rates=(("backfill", 50.0),),
            ),
        )

    def _source():
        return SyntheticSource(
            n_windows=6, faulted=[3],
            synth_config=SyntheticConfig(
                n_operations=12, n_traces=50, seed=11
            ),
            pace_seconds=0.01, sleep=lambda s: None,
        )

    # --- solo baselines -------------------------------------------------
    svc = ServeService(_serve_config())
    svc.fit_baseline(case.normal)
    svc.start()
    solo_serve = _rank_once(svc, records, "solo")
    svc.shutdown(drain=True)

    solo_out = tmp_path / "stream_solo"
    solo_stream = StreamEngine(
        _stream_cfg(), _source(), out_dir=solo_out
    ).run()
    assert solo_stream.incidents_opened == 1

    # --- co-deployed ----------------------------------------------------
    cfg = _stream_cfg()
    serve_cfg2 = _serve_config()
    store = ParkedWindowStore(cfg.sched, serve_cfg=serve_cfg2.serve)
    sched = DeviceScheduler(store)
    sched.start()
    co_out = tmp_path / "stream_co"
    try:
        svc2 = ServeService(serve_cfg2, sched=sched)
        svc2.fit_baseline(case.normal)
        svc2.start()
        eng = StreamEngine(cfg, _source(), out_dir=co_out, sched=sched)
        stream_result = {}
        t_stream = threading.Thread(
            target=lambda: stream_result.update(s=eng.run()),
            name="co-stream",
        )
        replay_result = {}
        t_replay = threading.Thread(
            target=lambda: replay_result.update(r=replay_range(
                solo_out, config=_stream_cfg(), sched=sched,
            )),
            name="co-replay",
        )
        t_stream.start()
        t_replay.start()
        co_serve = _rank_once(svc2, records, "co")
        t_stream.join(timeout=300)
        t_replay.join(timeout=300)
        assert not t_stream.is_alive() and not t_replay.is_alive()
        svc2.shutdown(drain=True)
    finally:
        sched.stop(drain=True, timeout=60)

    # Serve verdict parity (tie-aware, top-5).
    ok, reason = tie_aware_topk_agreement(
        [n for n, _ in solo_serve.ranking],
        [s for _, s in solo_serve.ranking],
        [n for n, _ in co_serve.ranking],
        [s for _, s in co_serve.ranking],
        min(5, len(solo_serve.ranking)),
    )
    assert ok, f"serve verdict diverged co-deployed: {reason}"
    # Stream verdict parity: same windows, same single incident.
    s = stream_result["s"]
    assert s.windows == solo_stream.windows
    assert s.ranked == solo_stream.ranked
    assert s.incidents_opened == 1 and s.incidents_resolved == 1
    # Replay backfill: zero dropped verdicts, tie-aware match.
    r = replay_result["r"]
    assert r["verdict"] == "match", r["mismatched"]
    assert r["ranked"] == r["matched"] > 0
    # One device: every dispatch ran on the scheduler thread.
    assert sched.errors == 0
    shares = store.tenant_shares()
    assert shares.get("backfill", 0) > 0
    assert shares.get("stream", 0) > 0
    assert shares.get("default", 0) or shares.get("serve", 0)
    assert store.pending() == 0


def test_serve_codeploy_minimal_parity(case, registry):
    """Fast (tier-1) co-deploy check: serve through a DeviceScheduler
    matches solo serve tie-aware, and the serve lane's dispatches are
    accounted to its tenant in the shared store."""
    from microrank_tpu.serve import ServeService
    from microrank_tpu.utils.ranking_compare import (
        tie_aware_topk_agreement,
    )

    records = _records(case)
    svc = ServeService(_serve_config(max_batch_windows=1))
    svc.fit_baseline(case.normal)
    svc.start()
    solo = _rank_once(svc, records, "solo")
    svc.shutdown(drain=True)

    cfg = _serve_config(max_batch_windows=1)
    store = ParkedWindowStore(cfg.sched, serve_cfg=cfg.serve)
    sched = DeviceScheduler(store)
    sched.start()
    try:
        svc2 = ServeService(cfg, sched=sched)
        svc2.fit_baseline(case.normal)
        svc2.start()
        co = _rank_once(svc2, records, "co", tenant="t1")
        svc2.shutdown(drain=True)
    finally:
        sched.stop(drain=True, timeout=60)
    ok, reason = tie_aware_topk_agreement(
        [n for n, _ in solo.ranking], [s for _, s in solo.ranking],
        [n for n, _ in co.ranking], [s for _, s in co.ranking],
        min(5, len(solo.ranking)),
    )
    assert ok, reason
    assert store.tenant_shares().get("t1") == 1
    assert sched.errors == 0


# ------------------------------------ shape-faithful warm restart


def test_warm_restart_first_window_latency_near_steady_state(
    case, registry, tmp_path, monkeypatch
):
    """Restart gap: a first process serves production windows (their
    pad-bucket shapes land in the warmup manifest); after a simulated
    restart (jax caches cleared), a warmed second process re-traces the
    EXACT production shapes at startup — so its first request pays no
    compile and lands within 2x the steady-state p99."""
    import jax

    from microrank_tpu.serve import ServeService

    monkeypatch.setenv("MICRORANK_JIT_CACHE", str(tmp_path / "jit"))
    records = _records(case)

    cfg1 = _serve_config(max_batch_windows=1)
    svc1 = ServeService(cfg1)
    svc1.fit_baseline(case.normal)
    svc1.start()
    for i in range(2):
        assert _rank_once(svc1, records, f"p{i}").ranking
    svc1.shutdown(drain=True)

    from microrank_tpu.dispatch import manifest_shapes

    shapes = manifest_shapes(str(tmp_path / "jit"), "serve")
    assert shapes, "production shapes never reached the manifest"

    jax.clear_caches()  # simulate a fresh process: in-memory jit gone

    cfg2 = _serve_config(
        warmup=True, warmup_occupancies=(1,), max_batch_windows=1,
    )
    svc2 = ServeService(cfg2)
    svc2.fit_baseline(case.normal)
    svc2.start()   # warmup replays manifest occupancies + shapes
    assert (
        registry.get("microrank_warm_shapes_total").value(
            outcome="warmed"
        )
        >= 1
    )
    t0 = time.monotonic()
    assert _rank_once(svc2, records, "first").ranking
    first_s = time.monotonic() - t0
    steady = []
    for i in range(6):
        t0 = time.monotonic()
        assert _rank_once(svc2, records, f"s{i}").ranking
        steady.append(time.monotonic() - t0)
    svc2.shutdown(drain=True)
    steady.sort()
    p99 = steady[-1]
    # 2x steady-state p99 (+50 ms of scheduler-wakeup jitter headroom —
    # far below the several-hundred-ms compile a cold shape would pay).
    assert first_s <= 2.0 * p99 + 0.05, (
        f"warm-restart first window took {first_s * 1e3:.0f} ms vs "
        f"steady p99 {p99 * 1e3:.0f} ms — shape warmup missed"
    )
