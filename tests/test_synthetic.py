"""Generator invariants: schema conformance, inclusive durations, fault."""

import numpy as np

from microrank_tpu.io.schema import REQUIRED_COLUMNS, validate_columns
from microrank_tpu.testing import SyntheticConfig, generate_case


def test_schema(small_case):
    for df in (small_case.normal, small_case.abnormal):
        validate_columns(df.columns)
        assert set(REQUIRED_COLUMNS) <= set(df.columns)
        assert (df["duration"] > 0).all()


def test_root_span_is_trace_max(small_case):
    # Inclusive durations: the reference's trace duration = max span
    # duration (preprocess_data.py:110) must pick the root span.
    df = small_case.normal
    root = df[df["ParentSpanId"] == ""]
    assert len(root) == df["traceID"].nunique()
    gmax = df.groupby("traceID")["duration"].max()
    for _, row in root.head(20).iterrows():
        assert row["duration"] == gmax[row["traceID"]]


def test_parent_links_resolve(small_case):
    df = small_case.abnormal
    non_root = df[df["ParentSpanId"] != ""]
    assert non_root["ParentSpanId"].isin(set(df["spanID"])).all()


def test_fault_increases_duration():
    cfg = SyntheticConfig(n_operations=12, n_traces=100, seed=3)
    case = generate_case(cfg)
    svc = f"svc{case.fault_op:03d}"
    n_faulty = case.normal[case.normal["serviceName"] == svc]["duration"]
    a_all = case.abnormal[case.abnormal["podName"] == f"{svc}-{case.fault_pod}"]
    a_faulty = a_all["duration"]
    assert a_faulty.mean() > n_faulty.mean() + cfg.fault_latency_ms * 1000 * 0.5


def test_determinism():
    cfg = SyntheticConfig(n_operations=10, n_traces=30, seed=5)
    a, b = generate_case(cfg), generate_case(cfg)
    assert a.normal.equals(b.normal)
    assert a.abnormal.equals(b.abnormal)
    assert a.fault_pod_op == b.fault_pod_op


def test_large_op_ids_do_not_collide():
    # Regression: np.char.zfill truncates ids wider than its width arg,
    # collapsing ops >= 1000 into shared names at 5k-op scale.
    cfg = SyntheticConfig(n_operations=1500, n_kinds=40, n_traces=60, seed=0)
    case = generate_case(cfg)
    svc_ids = {int(s[3:]) for s in case.abnormal["serviceName"].unique()}
    assert max(svc_ids) >= 1000
    assert case.fault_op in svc_ids
    svc = f"svc{case.fault_op:04d}"
    assert (case.abnormal["serviceName"] == svc).any()


def test_fault_path_overlap_control():
    # The two-fault hardness control: chosen fault ops' root-path overlap
    # must hit the target (0 = disjoint paths, 1 = nested), and the
    # achieved statistic is recorded on the case.
    from microrank_tpu.testing.synthetic import path_overlap

    for target in (0.0, 1.0):
        for seed in range(4):
            case = generate_case(
                SyntheticConfig(
                    n_operations=30, n_traces=20, n_kinds=24,
                    child_keep_prob=0.6, n_faults=2,
                    fault_path_overlap=target, seed=seed,
                )
            )
            assert case.fault_overlap == target, (target, seed)
            (a, _), (b, _) = case.faults
            assert path_overlap(case.topology.parent, a, b) == target


def test_fault_overlap_none_preserves_historical_choice():
    # fault_path_overlap=None must reproduce the pre-control fault pick
    # bit-for-bit (fixed-seed cases across the suite depend on it).
    base = SyntheticConfig(n_operations=24, n_traces=30, seed=7)
    a = generate_case(base)
    b = generate_case(
        SyntheticConfig(
            n_operations=24, n_traces=30, seed=7, fault_path_overlap=None
        )
    )
    assert a.faults == b.faults
    assert a.fault_overlap is None  # single fault: no pairwise statistic
