"""Accuracy harness: R@k / Exam Score semantics and CLI."""

import json

import pytest

from microrank_tpu.config import MicroRankConfig
from microrank_tpu.evaluation import EvalConfig, evaluate
from microrank_tpu.testing import SyntheticConfig, generate_case


def test_single_fault_accuracy():
    rep = evaluate(
        MicroRankConfig(),
        EvalConfig(n_cases=5, n_operations=20, n_traces=120, seed0=100),
    )
    assert len(rep.cases) == 5
    assert rep.detection_rate == 1.0
    # Paper-level accuracy on single faults (Table 4: R@1=94%, R@3=96%).
    assert rep.recall_at[1] >= 0.6
    assert rep.recall_at[3] == 1.0
    assert rep.exam_score < 0.2
    # Monotone in k.
    assert rep.recall_at[1] <= rep.recall_at[3] <= rep.recall_at[5]


def test_two_fault_cases_scored_per_fault():
    rep = evaluate(
        MicroRankConfig(),
        EvalConfig(
            n_cases=3, n_operations=20, n_traces=150, n_faults=2, seed0=300
        ),
    )
    assert all(len(c.faults) == 2 and len(c.ranks) == 2 for c in rep.cases)


def test_multi_fault_generator():
    case = generate_case(
        SyntheticConfig(n_operations=20, n_traces=100, n_faults=2, seed=1)
    )
    assert len(case.faults) == 2
    assert len(set(op for op, _ in case.faults)) == 2
    assert len(case.fault_pod_ops) == 2
    assert case.fault_pod_ops[0] == case.fault_pod_op
    # Both faulty services really exist in the abnormal dump.
    svcs = set(case.abnormal["serviceName"].unique())
    for op, _ in case.faults:
        assert f"svc{op:03d}" in svcs


def test_cli_eval(tmp_path):
    from microrank_tpu.cli import main

    out = tmp_path / "report.json"
    rc = main(
        ["eval", "--cases", "3", "--operations", "16", "--traces", "100",
         "--json", str(out)]
    )
    assert rc == 0
    report = json.loads(out.read_text())
    assert set(report) >= {"recall_at", "exam_score", "cases"}
    assert len(report["cases"]) == 3


def test_detection_evaluation():
    # Big faults must be perfectly detected across a timeline (100% P/R);
    # a tiny fault must NOT produce false positives on clean windows.
    from microrank_tpu.config import MicroRankConfig
    from microrank_tpu.evaluation import EvalConfig, evaluate_detection

    cfg = EvalConfig(n_cases=2, n_operations=16, n_traces=80)
    rep = evaluate_detection(MicroRankConfig(), cfg, n_windows=6)
    assert rep.tp + rep.fn == 2 * 3  # half the windows faulted
    assert rep.precision == 1.0 and rep.recall == 1.0
    tiny = EvalConfig(
        n_cases=2, n_operations=16, n_traces=80, fault_latency_ms=0.1
    )
    rep2 = evaluate_detection(MicroRankConfig(), tiny, n_windows=6)
    assert rep2.fp == 0  # clean windows never flag


def test_timeline_generator_layout():
    from microrank_tpu.testing import SyntheticConfig
    from microrank_tpu.testing.synthetic import generate_timeline

    tl = generate_timeline(
        SyntheticConfig(n_operations=12, n_traces=50, seed=3),
        4,
        [1, 3],
    )
    assert tl.window_faulted == [False, True, False, True]
    # Each window's traces start inside its bounds.
    import pandas as pd

    for w in range(4):
        w0 = tl.start + pd.Timedelta(minutes=w * tl.window_minutes)
        w1 = w0 + pd.Timedelta(minutes=tl.window_minutes)
        spans = tl.timeline[tl.timeline["traceID"].str.startswith(f"w{w}x")]
        assert len(spans)
        assert (spans["startTime"] >= w0).all()
        assert (spans["startTime"] < w1).all()


def test_overlap_ablation_smoke():
    # The two-fault ablation runner: one report per target overlap, each
    # generated under the constrained fault placement.
    from microrank_tpu.evaluation import evaluate_overlap_ablation

    cfg = EvalConfig(n_cases=2, n_operations=20, n_traces=80, n_faults=2)
    reports = evaluate_overlap_ablation(
        MicroRankConfig(), cfg, overlaps=(0.0, 1.0)
    )
    assert set(reports) == {0.0, 1.0}
    for rep in reports.values():
        assert len(rep.cases) == 2
        assert all(len(c.faults) == 2 for c in rep.cases)


# ---------------------------------------------------- tie-aware metrics
# Hand-computed fixtures for the shared ranking-metric helpers the
# scenario matrix, bench.py and this harness all score with.


def test_tie_aware_ranks_hand_fixture():
    from microrank_tpu.evaluation import tie_aware_ranks

    names = ["a", "b", "c", "d", "e"]
    scores = [5.0, 5.0, 5.0, 3.0, 1.0]
    # Three-way tie at the top: all share rank 1; d is 4th, e 5th.
    assert tie_aware_ranks(names, scores) == {
        "a": 1, "b": 1, "c": 1, "d": 4, "e": 5,
    }
    # Head-anchored grouping: a chain of near-ties cannot drift — each
    # member must tie the group HEAD, not just its neighbor.
    drift = [1.0, 1.0 - 4e-7, 1.0 - 8e-7, 1.0 - 1.2e-6]
    r = tie_aware_ranks(["w", "x", "y", "z"], drift, rtol=1e-6)
    assert r["w"] == r["x"] == r["y"] == 1  # all within rtol of head
    assert r["z"] == 4                      # past the head's tolerance


def test_topk_exact_hand_fixture():
    from microrank_tpu.evaluation import topk_exact

    names = ["a", "b", "c", "d"]
    scores = [5.0, 5.0, 3.0, 1.0]
    assert topk_exact(names, scores, ["b"], 1)      # tie expands top-1
    assert topk_exact(names, scores, ["a", "b"], 1)
    assert not topk_exact(names, scores, ["c"], 2)  # c's rank is 3
    assert topk_exact(names, scores, ["c"], 3)
    assert not topk_exact(names, scores, ["z"], 4)  # unranked culprit
    assert not topk_exact(names, scores, [], 1)     # no truth: vacuous


def test_average_precision_hand_fixture():
    from microrank_tpu.evaluation import average_precision

    names = ["a", "b", "c", "d"]
    scores = [5.0, 4.0, 3.0, 1.0]
    # Truth {b, d}: ranks 2 and 4 -> (1/2 + 2/4) / 2 = 0.5.
    assert average_precision(names, scores, ["b", "d"]) == 0.5
    # Truth {a}: rank 1 -> AP 1.0; unranked culprit halves it.
    assert average_precision(names, scores, ["a"]) == 1.0
    assert average_precision(names, scores, ["a", "zz"]) == 0.5


def test_reciprocal_rank_and_metrics_bundle():
    from microrank_tpu.evaluation import ranking_metrics, reciprocal_rank

    names = ["a", "b", "c", "d"]
    scores = [5.0, 4.0, 3.0, 1.0]
    assert reciprocal_rank(names, scores, ["c", "d"]) == 1 / 3
    assert reciprocal_rank(names, scores, ["zz"]) == 0.0
    m = ranking_metrics(names, scores, ["c"], ks=(1, 3))
    assert m["ranks"] == {"c": 3}
    assert m["topk_exact"] == {1: False, 3: True}
    assert m["rr"] == 1 / 3 and m["ap"] == 1 / 3
