"""Accuracy harness: R@k / Exam Score semantics and CLI."""

import json

import pytest

from microrank_tpu.config import MicroRankConfig
from microrank_tpu.evaluation import EvalConfig, evaluate
from microrank_tpu.testing import SyntheticConfig, generate_case


def test_single_fault_accuracy():
    rep = evaluate(
        MicroRankConfig(),
        EvalConfig(n_cases=5, n_operations=20, n_traces=120, seed0=100),
    )
    assert len(rep.cases) == 5
    assert rep.detection_rate == 1.0
    # Paper-level accuracy on single faults (Table 4: R@1=94%, R@3=96%).
    assert rep.recall_at[1] >= 0.6
    assert rep.recall_at[3] == 1.0
    assert rep.exam_score < 0.2
    # Monotone in k.
    assert rep.recall_at[1] <= rep.recall_at[3] <= rep.recall_at[5]


def test_two_fault_cases_scored_per_fault():
    rep = evaluate(
        MicroRankConfig(),
        EvalConfig(
            n_cases=3, n_operations=20, n_traces=150, n_faults=2, seed0=300
        ),
    )
    assert all(len(c.faults) == 2 and len(c.ranks) == 2 for c in rep.cases)


def test_multi_fault_generator():
    case = generate_case(
        SyntheticConfig(n_operations=20, n_traces=100, n_faults=2, seed=1)
    )
    assert len(case.faults) == 2
    assert len(set(op for op, _ in case.faults)) == 2
    assert len(case.fault_pod_ops) == 2
    assert case.fault_pod_ops[0] == case.fault_pod_op
    # Both faulty services really exist in the abnormal dump.
    svcs = set(case.abnormal["serviceName"].unique())
    for op, _ in case.faults:
        assert f"svc{op:03d}" in svcs


def test_cli_eval(tmp_path):
    from microrank_tpu.cli import main

    out = tmp_path / "report.json"
    rc = main(
        ["eval", "--cases", "3", "--operations", "16", "--traces", "100",
         "--json", str(out)]
    )
    assert rc == 0
    report = json.loads(out.read_text())
    assert set(report) >= {"recall_at", "exam_score", "cases"}
    assert len(report["cases"]) == 3
