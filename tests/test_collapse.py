"""Trace-kind collapse (graph.build.collapse_window_graph) parity.

The collapse merges identical p_sr columns — the reference's own
trace-kind equivalence (pagerank.py:54-66) — into one column carrying
its multiplicity. These tests pin the exactness argument: every kernel's
ranking on the collapsed graph equals its ranking on the uncollapsed
graph (scores within f32 reassociation tolerance), across the
single-device, batched and sharded dispatch paths, and the collapsed
device ranking still matches the float64 sparse oracle ranking the
UNCOLLAPSED graph.
"""

import numpy as np
import pytest

import jax

from microrank_tpu.config import MicroRankConfig, RuntimeConfig
from microrank_tpu.graph.build import (
    build_window_graph,
    collapse_window_graph,
)
from microrank_tpu.rank_backends.jax_tpu import (
    choose_kernel,
    rank_window_device,
)
from microrank_tpu.rank_backends.sparse_oracle import rank_window_sparse
from microrank_tpu.testing import SyntheticConfig, generate_case

from conftest import partition_case

CFG = MicroRankConfig()


@pytest.fixture(scope="module")
def kind_case():
    """A case with strong kind structure (few distinct trace shapes)."""
    return generate_case(
        SyntheticConfig(n_operations=60, n_kinds=6, n_traces=400, seed=3)
    )


@pytest.fixture(scope="module")
def graphs(kind_case):
    nrm, abn = partition_case(kind_case)
    g0, names, _, _ = build_window_graph(
        kind_case.abnormal, nrm, abn, aux="all", collapse="off"
    )
    g1, names1, _, _ = build_window_graph(
        kind_case.abnormal, nrm, abn, aux="all", collapse="on"
    )
    assert names == names1
    return g0, g1, names, (nrm, abn)


def _ranked_names(graph, names, kernel):
    ti, ts, nv = jax.device_get(
        rank_window_device(graph, CFG.pagerank, CFG.spectrum, None, kernel)
    )
    n = int(nv)
    return (
        [names[int(i)] for i in ti[:n]],
        np.asarray(ts[:n], dtype=np.float64),
    )


def test_collapse_shrinks_and_marks(graphs):
    g0, g1, _, _ = graphs
    assert int(g0.normal.n_cols) == -1
    assert int(g1.normal.n_cols) >= 0
    # The generator samples traces from 6 kind templates.
    assert int(g1.normal.n_cols) <= 8
    assert int(g1.abnormal.n_cols) <= 8
    # True trace counts are preserved (the spectrum needs them).
    assert int(g1.normal.n_traces) == int(g0.normal.n_traces)
    assert int(g1.abnormal.n_traces) == int(g0.abnormal.n_traces)
    # kind carries the multiplicity; it must re-total to the trace count.
    n = int(g1.normal.n_cols)
    assert int(np.asarray(g1.normal.kind[:n]).sum()) == int(
        g0.normal.n_traces
    )


@pytest.mark.parametrize(
    "kernel", ["packed", "packed_bf16", "packed_blocked", "coo", "csr",
               "pcsr", "dense"]
)
def test_collapse_rank_parity_per_kernel(graphs, kernel):
    """Collapse must be score-exact up to f32 reassociation, not merely
    rank-stable: measured drift on this case is <= ~2e-6 relative for
    every f32 kernel (the compensated csr prefix sum holds it near its
    ~1e-7 weight drift), so the f32 tolerance pins at 2e-5 — a 100x
    tightening over the pre-compensation 2e-3. bf16 kernels wobble at
    bf16 rounding (~2e-3 measured) and keep a matching tolerance."""
    g0, g1, names, _ = graphs
    names0, scores0 = _ranked_names(g0, names, kernel)
    names1, scores1 = _ranked_names(g1, names, kernel)
    assert names0 == names1
    rtol = 5e-3 if kernel.endswith("bf16") else 2e-5
    np.testing.assert_allclose(scores0, scores1, rtol=rtol, atol=1e-5)


@pytest.mark.parametrize(
    "kernel", ["coo", "csr", "pcsr", "dense", "packed", "packed_blocked"]
)
def test_collapse_cross_kernel_parity(graphs, kernel):
    """Regression pin for the csr collapse-parity failure: the synthetic
    kind case holds an EXACT float64 score tie (ops 012/044 both at
    47.798213540 under the oracle), and the csr kernel's plain-f32
    global cumsum once rounded the two rows differently on the collapsed
    entry layout, swapping them past the tie-break. With the compensated
    prefix sum (ops.segment.compensated_cumsum) every kernel must
    produce the SAME name ranking as the coo kernel on the uncollapsed
    graph — on both the collapsed and uncollapsed builds."""
    g0, g1, names, _ = graphs
    base, base_scores = _ranked_names(g0, names, "coo")
    for g in (g0, g1):
        ranked, scores = _ranked_names(g, names, kernel)
        assert ranked == base, kernel
        # Pin cross-kernel SCORES too (not just names): every f32
        # kernel's scores on both builds sit within reassociation
        # distance of the uncollapsed coo baseline (measured <= 2.3e-6
        # relative on this case).
        np.testing.assert_allclose(
            scores, base_scores, rtol=2e-5, atol=1e-5
        )


def test_collapsed_device_matches_uncollapsed_float64_oracle(graphs):
    g0, g1, names, _ = graphs
    top_o, _ = rank_window_sparse(g0, names, CFG.pagerank, CFG.spectrum)
    names1, _ = _ranked_names(g1, names, "packed")
    assert names1[:5] == top_o[:5]


def test_sparse_oracle_rejects_collapsed_graphs(graphs):
    _, g1, names, _ = graphs
    with pytest.raises(ValueError, match="UNCOLLAPSED"):
        rank_window_sparse(g1, names, CFG.pagerank, CFG.spectrum)


def test_collapse_auto_skips_when_no_shrink(kind_case):
    """collapse='auto' on an all-unique-kind window keeps the per-trace
    layout (and still builds the aux views the core build skipped)."""
    nrm, abn = partition_case(kind_case)
    g0, _, _, _ = build_window_graph(
        kind_case.abnormal, nrm, abn, aux="all", collapse="off"
    )
    g_auto = collapse_window_graph(g0, aux="all", collapse="auto")
    # The kind case shrinks, so auto collapses.
    assert int(g_auto.normal.n_cols) >= 0

    # An all-unique-kind window (every trace covers a distinct op set):
    # auto must keep the per-trace layout AND construct the aux views
    # the collapse-bound core build (aux="none") skipped.
    import pandas as pd

    rows = []
    for t in range(6):
        for o in range(t + 1):  # trace t covers ops 0..t — all distinct
            rows.append(
                {
                    "traceID": f"t{t}",
                    "spanID": f"t{t}-s{o}",
                    "ParentSpanId": f"t{t}-s{o - 1}" if o else "",
                    "operationName": f"op{o}",
                    "serviceName": f"svc{o}",
                    "podName": f"svc{o}-0",
                    "duration": 1000,
                    "startTime": pd.Timestamp("2025-01-01 00:00:00"),
                    "endTime": pd.Timestamp("2025-01-01 00:00:01"),
                }
            )
    df = pd.DataFrame(rows)
    g_uniq, _, _, _ = build_window_graph(
        df, ["t0", "t1", "t2"], ["t3", "t4", "t5"], aux="all",
        collapse="auto",
    )
    assert int(g_uniq.normal.n_cols) == -1
    assert g_uniq.normal.cov_bits.shape[-1] > 0  # aux views present
    # collapse="on" still collapses (1:1) and marks the axis.
    g_on, _, _, _ = build_window_graph(
        df, ["t0", "t1", "t2"], ["t3", "t4", "t5"], aux="all",
        collapse="on",
    )
    assert int(g_on.normal.n_cols) == int(g_on.normal.n_traces)


def test_collapse_preference_forms(graphs):
    """Both preference forms ('reference' code form and paper Eq (7))
    stay rank-identical under collapse."""
    import dataclasses

    g0, g1, names, _ = graphs
    for pref in ("reference", "paper"):
        cfg = dataclasses.replace(CFG.pagerank, preference=pref)
        a = jax.device_get(
            rank_window_device(g0, cfg, CFG.spectrum, None, "packed")
        )
        b = jax.device_get(
            rank_window_device(g1, cfg, CFG.spectrum, None, "packed")
        )
        n = int(a[2])
        assert int(b[2]) == n
        assert [names[int(i)] for i in a[0][:n]] == [
            names[int(i)] for i in b[0][:n]
        ]


def test_collapse_auto_kernel_resolution(graphs):
    _, g1, _, _ = graphs
    assert choose_kernel(g1) == "packed"
    assert choose_kernel(g1, prefer_bf16=True) == "packed_bf16"


def test_collapsed_batched_and_sharded_paths(graphs):
    """Stacked-batch vmap and the 2D-mesh shard_map paths rank collapsed
    windows identically to the uncollapsed single-device ranking."""
    from microrank_tpu.parallel.mesh import (
        SHARD_AXIS,
        WINDOW_AXIS,
        make_mesh,
    )
    from microrank_tpu.parallel.sharded_rank import (
        rank_windows_batched,
        rank_windows_sharded,
        stack_window_graphs,
    )

    g0, g1, names, _ = graphs
    base, _ = _ranked_names(g0, names, "packed")

    stacked = stack_window_graphs([g1, g1])
    ti, ts, nv = jax.device_get(
        rank_windows_batched(stacked, CFG.pagerank, CFG.spectrum, "packed")
    )
    for b in range(2):
        n = int(nv[b])
        assert [names[int(i)] for i in ti[b][:n]] == base

    if len(jax.devices()) >= 4:
        # Sharded ranking of the COLLAPSED graph vs the same kernel's
        # single-device ranking of the SAME collapsed graph (isolates
        # the sharding; summation-tree differences across kernels can
        # permute exact tail ties) — plus top-5 agreement with the
        # uncollapsed baseline.
        mesh = make_mesh((2, 2), (WINDOW_AXIS, SHARD_AXIS))
        for kernel in ("packed", "packed_bf16", "coo", "csr"):
            single, _ = _ranked_names(g1, names, kernel)
            stacked = stack_window_graphs(
                [g1, g1], shard_multiple=2, trace_multiple=16
            )
            ti, ts, nv = jax.device_get(
                rank_windows_sharded(
                    jax.device_put(stacked),
                    CFG.pagerank,
                    CFG.spectrum,
                    mesh,
                    kernel,
                )
            )
            for b in range(2):
                n = int(nv[b])
                ranked = [names[int(i)] for i in ti[b][:n]]
                assert ranked == single, kernel
                assert ranked[:5] == base[:5], kernel


def test_runtime_config_plumbs_collapse(kind_case, tmp_path):
    """TableRCA with collapse_kinds='auto'/'on' matches 'off' end to end
    (native lane, real pipeline)."""
    from microrank_tpu.native import load_span_table
    from microrank_tpu.pipeline.table_runner import TableRCA

    kind_case.normal.to_csv(tmp_path / "normal.csv", index=False)
    kind_case.abnormal.to_csv(tmp_path / "abnormal.csv", index=False)

    def run(rt):
        rca = TableRCA(MicroRankConfig(runtime=rt))
        rca.fit_baseline(load_span_table(tmp_path / "normal.csv"))
        res = rca.run(load_span_table(tmp_path / "abnormal.csv"))
        return [
            [n for n, _ in r.ranking] if r.ranking else None for r in res
        ]

    base = run(RuntimeConfig(collapse_kinds="off", prefer_bf16=False))
    assert run(RuntimeConfig(collapse_kinds="auto", prefer_bf16=False)) == base
    assert run(RuntimeConfig(collapse_kinds="on", prefer_bf16=True)) == base
