"""Fleet telemetry plane (obs/fleetplane.py, obs/watchdog.py) and its
coordinator/CLI wiring: the merge law (K sharded registries == one
registry — counters and histogram buckets exactly, quantile estimates
within bucket resolution), the exactly-once heartbeat delta protocol
(torn / stale / version-mismatched / out-of-sync deltas rejected WHOLE,
retransmits idempotent, truncation lossless, worker-restart epochs),
the host-cardinality cap, clock-offset estimation and the merged fleet
journal/trace, W3C traceparent round-trips, the ``stage:`` chaos seam,
the SLO self-watchdog lifecycle (breach opens exactly one self-incident
naming the stage, resolves on recovery, zero on healthy data), and
``cli stats --merge``."""

import json
import random

import pytest

from microrank_tpu.chaos import configure_chaos, reset_breakers, set_chaos_host
from microrank_tpu.config import ChaosConfig, MicroRankConfig, WatchdogConfig
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.obs.fleetplane import (
    FLEET_JOURNAL_NAME,
    FLEET_TRACE_NAME,
    FleetPlane,
    MetricsDeltaSender,
    delta_crc,
    fold_into,
    histogram_quantile,
    write_fleet_journal,
    write_fleet_trace,
)
from microrank_tpu.obs.registry import (
    DEFAULT_BUCKETS,
    merge_registries,
    registry_from_json,
)


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


@pytest.fixture(autouse=True)
def _clean_chaos():
    configure_chaos(MicroRankConfig())
    set_chaos_host(None)
    reset_breakers()
    yield
    configure_chaos(MicroRankConfig())
    set_chaos_host(None)
    reset_breakers()


def _chaos_cfg(*faults):
    return MicroRankConfig(
        chaos=ChaosConfig(enabled=True, faults=tuple(faults))
    )


# ------------------------------------------------------- the merge law


def _sharded_and_full(n_shards=3, n_events=300, seed=7):
    rnd = random.Random(seed)
    full = MetricsRegistry()
    shards = [MetricsRegistry() for _ in range(n_shards)]
    values = []
    for _ in range(n_events):
        shard = rnd.choice(shards)
        op = rnd.choice(["build", "rank"])
        amt = rnd.uniform(0.5, 2.0)
        for reg in (shard, full):
            reg.counter("mr_work_total", "w", ("op",)).inc(amt, op=op)
        v = 10 ** rnd.uniform(-4, 1)
        values.append(v)
        for reg in (shard, full):
            reg.histogram("mr_lat_seconds", "l", ("stage",)).observe(
                v, stage=op
            )
    return shards, full, values


def test_merge_matches_single_registry_exactly():
    shards, full, _ = _sharded_and_full()
    merged = merge_registries(
        [(f"host{i}", s) for i, s in enumerate(shards)]
    )
    got = {
        tuple(sorted(s["labels"].items())): s["value"]
        for s in merged.get("mr_work_total").samples()
    }
    want = {
        tuple(sorted(s["labels"].items())): s["value"]
        for s in full.get("mr_work_total").samples()
    }
    assert set(got) == set(want)
    for k in want:
        assert got[k] == pytest.approx(want[k])
    mh = {
        s["labels"]["stage"]: s
        for s in merged.get("mr_lat_seconds").samples()
    }
    fh = {
        s["labels"]["stage"]: s
        for s in full.get("mr_lat_seconds").samples()
    }
    assert set(mh) == set(fh)
    for stage in fh:
        assert mh[stage]["buckets"] == fh[stage]["buckets"]  # exact
        assert mh[stage]["count"] == fh[stage]["count"]
        assert mh[stage]["sum"] == pytest.approx(fh[stage]["sum"])


def test_merged_quantiles_within_bucket_resolution():
    shards, full, values = _sharded_and_full()
    merged = merge_registries(
        [(f"host{i}", s) for i, s in enumerate(shards)]
    )
    for q in (0.5, 0.9, 0.99):
        per_stage = {}
        for s in full.get("mr_lat_seconds").samples():
            per_stage[s["labels"]["stage"]] = s
        for stage, fs in per_stage.items():
            ms = next(
                s
                for s in merged.get("mr_lat_seconds").samples()
                if s["labels"]["stage"] == stage
            )
            est_m = histogram_quantile(DEFAULT_BUCKETS, ms["buckets"], q)
            est_f = histogram_quantile(DEFAULT_BUCKETS, fs["buckets"], q)
            # Identical bucket counts => identical estimates; and the
            # estimate lands inside the bucket holding the true
            # empirical quantile (the resolution histograms have).
            assert est_m == pytest.approx(est_f)
            svals = sorted(values)
            true_q = svals[min(len(svals) - 1, int(q * len(svals)))]
            hi_idx = next(
                (
                    i
                    for i, b in enumerate(DEFAULT_BUCKETS)
                    if b >= true_q
                ),
                len(DEFAULT_BUCKETS) - 1,
            )
            # One-bucket slack either way: linear interpolation's rank
            # convention can differ from the empirical index by one.
            hi = DEFAULT_BUCKETS[
                min(hi_idx + 1, len(DEFAULT_BUCKETS) - 1)
            ]
            lo = DEFAULT_BUCKETS[hi_idx - 2] if hi_idx >= 2 else 0.0
            assert lo <= est_m <= hi * (1 + 1e-9)


def test_merge_gauges_gain_host_label_and_keep_existing():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.gauge("mr_temp", "t").set(1.0)
    b.gauge("mr_temp", "t").set(2.0)
    # Already host-labeled series keep their shape (no double label).
    a.gauge("mr_lag", "l", ("host",)).set(5.0, host="host0")
    b.gauge("mr_lag", "l", ("host",)).set(7.0, host="host1")
    merged = merge_registries([("host0", a), ("host1", b)])
    temp = merged.get("mr_temp")
    assert temp.labelnames == ("host",)
    got = {s["labels"]["host"]: s["value"] for s in temp.samples()}
    assert got == {"host0": 1.0, "host1": 2.0}
    lag = merged.get("mr_lag")
    assert lag.labelnames == ("host",)
    got = {s["labels"]["host"]: s["value"] for s in lag.samples()}
    assert got == {"host0": 5.0, "host1": 7.0}


# ------------------------------------------- the heartbeat delta wire


def _counter_value(reg, name, **labels):
    m = reg.get(name)
    if m is None:
        return 0.0
    return sum(
        float(s["value"])
        for s in m.samples()
        if all(s["labels"].get(k) == v for k, v in labels.items())
    )


def test_delta_protocol_exactly_once_with_retransmit(registry):
    work = MetricsRegistry()
    c = work.counter("mr_jobs_total", "j")
    c.inc(5)
    sender = MetricsDeltaSender("host0")
    plane = FleetPlane()
    p1 = sender.payload(work)
    assert plane.ingest("host0", p1) == {"ack": 1}
    # Ack lost: the retransmit is the SAME payload and folds nowhere.
    assert sender.payload(work) is p1
    ack = plane.ingest("host0", p1)
    assert ack["ack"] == 1
    sender.handle_ack(ack)
    c.inc(3)
    p2 = sender.payload(work)
    assert p2["seq"] == 1
    sender.handle_ack(plane.ingest("host0", p2))
    view = plane.fleet_view()
    assert _counter_value(view, "mr_jobs_total") == pytest.approx(8.0)
    assert _counter_value(
        registry, "microrank_fleet_metric_deltas_total", status="applied"
    ) == 2
    assert _counter_value(
        registry, "microrank_fleet_metric_deltas_total", status="stale"
    ) == 1


def test_delta_increments_between_build_and_ack_ride_next_delta(registry):
    work = MetricsRegistry()
    c = work.counter("mr_jobs_total", "j")
    c.inc(2)
    sender = MetricsDeltaSender("host0")
    plane = FleetPlane()
    p1 = sender.payload(work)
    c.inc(4)  # lands AFTER the payload snapshot, before the ack
    sender.handle_ack(plane.ingest("host0", p1))
    sender.handle_ack(plane.ingest("host0", sender.payload(work)))
    assert _counter_value(
        plane.fleet_view(), "mr_jobs_total"
    ) == pytest.approx(6.0)


def test_torn_and_version_mismatched_deltas_rejected_whole(registry):
    work = MetricsRegistry()
    work.counter("mr_jobs_total", "j").inc(5)
    sender = MetricsDeltaSender("host0")
    plane = FleetPlane()
    p = sender.payload(work)
    torn = {**p, "metrics": {"metrics": {}}}  # body/crc disagree
    ack = plane.ingest("host0", torn)
    assert ack["ack"] == 0 and "resync" not in ack
    wrong_v = {**p, "v": 99}
    assert plane.ingest("host0", wrong_v)["ack"] == 0
    assert _counter_value(
        plane.fleet_view(), "mr_jobs_total"
    ) == 0.0  # nothing folded
    assert _counter_value(
        registry, "microrank_fleet_metric_deltas_total", status="torn"
    ) == 1
    assert _counter_value(
        registry, "microrank_fleet_metric_deltas_total", status="version"
    ) == 1
    # The intact original still applies: rejection poisoned nothing.
    sender.handle_ack(plane.ingest("host0", p))
    assert _counter_value(
        plane.fleet_view(), "mr_jobs_total"
    ) == pytest.approx(5.0)


def test_out_of_sync_sender_resyncs_via_full_snapshot(registry):
    work = MetricsRegistry()
    c = work.counter("mr_jobs_total", "j")
    c.inc(5)
    sender = MetricsDeltaSender("host0")
    plane_a = FleetPlane()
    sender.handle_ack(plane_a.ingest("host0", sender.payload(work)))
    c.inc(3)
    sender.handle_ack(plane_a.ingest("host0", sender.payload(work)))
    # Coordinator restarts: a fresh plane sees seq=2 but expects 0.
    plane_b = FleetPlane()
    ack = plane_b.ingest("host0", sender.payload(work))
    assert ack.get("resync") is True
    sender.handle_ack(ack)
    # The next delta is a FULL snapshot and REPLACES (no double count).
    resync_payload = sender.payload(work)
    assert resync_payload["seq"] == 0
    sender.handle_ack(plane_b.ingest("host0", resync_payload))
    assert _counter_value(
        plane_b.fleet_view(), "mr_jobs_total"
    ) == pytest.approx(8.0)
    assert _counter_value(
        registry, "microrank_fleet_metric_deltas_total", status="ahead"
    ) == 1


def test_worker_restart_epoch_accumulates_across_incarnations(registry):
    plane = FleetPlane()
    work1 = MetricsRegistry()
    work1.counter("mr_jobs_total", "j").inc(5)
    s1 = MetricsDeltaSender("host0")
    s1.handle_ack(plane.ingest("host0", s1.payload(work1)))
    # Restarted incarnation: fresh registry, fresh epoch, seq from 0.
    work2 = MetricsRegistry()
    work2.counter("mr_jobs_total", "j").inc(2)
    s2 = MetricsDeltaSender("host0")
    s2.epoch = s1.epoch + "-reborn"
    s2.handle_ack(plane.ingest("host0", s2.payload(work2)))
    assert _counter_value(
        plane.fleet_view(), "mr_jobs_total"
    ) == pytest.approx(7.0)


def test_oversize_delta_truncates_losslessly(registry):
    # Each metric fits the 1024-byte floor ALONE but not together:
    # truncation sheds whole metrics largest-first and the shed one
    # rides the next delta (a metric larger than max_bytes by itself
    # can never ship — final totals for that case come from the
    # on-disk ledger reconciliation instead).
    work = MetricsRegistry()
    big = work.counter("mr_big_total", "b", ("k",))
    for i in range(16):
        big.inc(1.0, k=f"key-{i:04d}")
    mid = work.counter("mr_mid_total", "m", ("k",))
    for i in range(10):
        mid.inc(1.0, k=f"key-{i:04d}")
    work.counter("mr_small_total", "s").inc(3)
    sender = MetricsDeltaSender("host0", max_bytes=1024)
    plane = FleetPlane()
    p1 = sender.payload(work)
    assert p1["truncated"] > 0
    assert "mr_big_total" not in p1["metrics"]["metrics"]
    sender.handle_ack(plane.ingest("host0", p1))
    # The shed metric rides the next delta in full.
    p2 = sender.payload(work)
    assert "mr_big_total" in p2["metrics"]["metrics"]
    sender.handle_ack(plane.ingest("host0", p2))
    view = plane.fleet_view()
    assert _counter_value(view, "mr_small_total") == pytest.approx(3.0)
    assert _counter_value(view, "mr_big_total") == pytest.approx(16.0)
    assert _counter_value(view, "mr_mid_total") == pytest.approx(10.0)
    assert _counter_value(
        registry, "microrank_fleet_metric_deltas_total",
        status="truncated",
    ) >= 1


def test_host_cardinality_cap_drops_overflow(registry):
    plane = FleetPlane(expected_hosts=2, grace=1)
    work = MetricsRegistry()
    work.counter("mr_jobs_total", "j").inc(1)
    for i in range(3):
        s = MetricsDeltaSender(f"host{i}")
        assert "dropped" not in plane.ingest(f"host{i}", s.payload(work))
    s = MetricsDeltaSender("host-extra")
    ack = plane.ingest("host-extra", s.payload(work))
    assert ack.get("dropped") is True
    assert "host-extra" not in plane.host_names()
    assert _counter_value(
        registry, "microrank_fleet_series_dropped_total"
    ) == 1


# -------------------------------------------------- clocks + artifacts


def test_clock_offsets_ewma_and_clamp():
    plane = FleetPlane(max_skew_seconds=5.0)
    plane.note_clock("host0", wall=1000.0, rtt=0.2, recv_wall=999.0)
    assert plane.offsets()["host0"] == pytest.approx(1.1)
    # An implausible reading moves the EWMA but the OFFSET is clamped.
    plane.note_clock("host0", wall=1100.0, rtt=0.0, recv_wall=999.0)
    assert plane.offsets()["host0"] == 5.0


def test_fleet_journal_merges_with_offset_correction(tmp_path):
    (tmp_path / "journal.jsonl").write_text(
        json.dumps({"event": "a", "ts": 10.0}) + "\n"
        + json.dumps({"event": "c", "ts": 20.0}) + "\n"
    )
    hdir = tmp_path / "host0"
    hdir.mkdir()
    (hdir / "journal.jsonl").write_text(
        json.dumps({"event": "b", "ts": 15.5}) + "\n" + "{torn"
    )
    path = write_fleet_journal(
        tmp_path, {"host0": hdir}, {"host0": 0.5}
    )
    assert path == tmp_path / FLEET_JOURNAL_NAME
    events = [
        json.loads(line) for line in path.read_text().splitlines()
    ]
    assert [e["event"] for e in events] == ["a", "b", "c"]
    assert events[1]["host"] == "host0"
    assert events[1]["ts"] == pytest.approx(15.0)  # skew-corrected
    assert events[1]["clock_offset_s"] == pytest.approx(0.5)
    assert events[0]["host"] == "coordinator"


def test_fleet_trace_merges_processes_sharing_trace_ids(tmp_path):
    from microrank_tpu.obs.spans import SpanTracer

    tracer = SpanTracer(enabled=True)
    ctx = tracer.new_trace("win-1000")
    with tracer.span("seal", service="fleet", ctx=ctx):
        pass
    dump_dir = tmp_path / "host0" / "flight" / "0001-incident"
    dump_dir.mkdir(parents=True)
    (dump_dir / "trace.json").write_text(
        json.dumps(
            {
                "traceEvents": [
                    {
                        "name": "build", "ph": "X", "ts": 2_000_000,
                        "dur": 10, "pid": 1, "tid": 1,
                        "args": {"trace_id": "win-1000"},
                    }
                ]
            }
        )
    )
    path = write_fleet_trace(
        tmp_path,
        tracer.snapshot(),
        {"host0": tmp_path / "host0"},
        {"host0": 0.5},
    )
    assert path == tmp_path / FLEET_TRACE_NAME
    doc = json.loads(path.read_text())
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    pids = {e["pid"] for e in xs}
    assert len(pids) == 2  # coordinator + host0, distinct tracks
    by_trace = {
        e["args"].get("trace_id")
        for e in xs
        if e["args"].get("trace_id") == "win-1000"
    }
    assert by_trace == {"win-1000"}  # the shared cross-process trace
    assert {
        e["pid"] for e in xs if e["args"].get("trace_id") == "win-1000"
    } == pids
    host_ev = next(e for e in xs if e["name"] == "build")
    assert host_ev["ts"] == 2_000_000 - 500_000  # offset-corrected
    names = [
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("ph") == "M" and e["name"] == "process_name"
    ]
    assert "coordinator" in names and "host0" in names


# ---------------------------------------------------- W3C traceparent


def test_format_traceparent_round_trips_and_is_deterministic():
    from microrank_tpu.serve.protocol import (
        format_traceparent,
        parse_traceparent,
    )

    hex_id = "ab" * 16
    hdr = format_traceparent(hex_id, "s0000002a")
    tid, sid = parse_traceparent(hdr)
    assert tid == hex_id
    assert sid == "000000000000002a"
    # Native window ids hash deterministically: same string -> same
    # header on every host (that sameness IS the cross-process join).
    h1 = format_traceparent("win-17000000", "s00000001")
    h2 = format_traceparent("win-17000000", "s00000001")
    assert h1 == h2
    assert parse_traceparent(h1) is not None


def test_stage_chaos_seam_slows_the_span(registry):
    from microrank_tpu.obs.spans import SpanTracer

    configure_chaos(
        _chaos_cfg(
            {"seam": "stage:detect", "kind": "latency", "value": 60,
             "count": 1}
        )
    )
    tracer = SpanTracer(enabled=True)
    with tracer.span("detect"):
        pass
    with tracer.span("detect"):  # count=1: second span is clean
        pass
    spans = tracer.snapshot()
    assert spans[0].dur_us >= 50_000
    assert spans[1].dur_us < 50_000


def test_stage_chaos_seam_host_scoped(registry):
    from microrank_tpu.obs.spans import SpanTracer

    configure_chaos(
        _chaos_cfg(
            {"seam": "stage:detect", "kind": "latency", "value": 60,
             "count": 1, "host": "host1"}
        )
    )
    set_chaos_host("host0")
    tracer = SpanTracer(enabled=True)
    with tracer.span("detect"):
        pass
    assert tracer.snapshot()[0].dur_us < 50_000  # scoped elsewhere


# ------------------------------------------------- SLO self-watchdog


def _watchdog(tmp_path, registry_view, **cfg_kwargs):
    from microrank_tpu.obs.watchdog import SELF_INCIDENT_LOG, SLOWatchdog
    from microrank_tpu.stream.incidents import (
        IncidentTracker,
        JsonlIncidentSink,
    )

    defaults = dict(
        eval_seconds=0.0,
        fast_windows=2,
        slow_windows=10,
        min_samples=1,
        stage_budget_ms=100.0,
        stage_error_budget=0.1,
        resolve_after_evals=2,
        cooldown_evals=1,
    )
    defaults.update(cfg_kwargs)
    cfg = WatchdogConfig(**defaults)
    log_path = tmp_path / SELF_INCIDENT_LOG
    tracker = IncidentTracker(
        resolve_after=cfg.resolve_after_evals,
        cooldown_windows=cfg.cooldown_evals,
        sinks=[JsonlIncidentSink(log_path)],
    )
    wd = SLOWatchdog(cfg, tracker=tracker, view=lambda: registry_view)
    return wd, tracker, log_path


def test_watchdog_opens_one_attributed_incident_and_resolves(
    registry, tmp_path
):
    view = MetricsRegistry()
    hist = view.histogram("microrank_stage_seconds", "s", ("stage",))
    host_ms = view.gauge(
        "microrank_fleet_host_stage_ms", "ms", ("host", "stage")
    )
    wd, tracker, log_path = _watchdog(tmp_path, view)
    hist.observe(0.005, stage="detect")
    assert wd.evaluate(force=True) == []  # baseline eval, healthy
    # The injected fault: host1's detect blows its 100 ms budget.
    for _ in range(4):
        hist.observe(0.75, stage="detect")
    host_ms.set(750.0, host="host1", stage="detect")
    host_ms.set(5.0, host="host0", stage="detect")
    breaching = wd.evaluate(force=True)
    assert breaching == ["stage:detect@host1"]  # stage AND host named
    assert tracker.opened == 1
    # Sustained breach dedups into the SAME incident.
    for _ in range(2):
        hist.observe(0.75, stage="detect")
        wd.evaluate(force=True)
    assert tracker.opened == 1
    # Recovery: healthy observations only -> burn decays -> resolve.
    for _ in range(6):
        hist.observe(0.002, stage="detect")
        wd.evaluate(force=True)
        if tracker.resolved:
            break
    assert tracker.resolved == 1
    lines = [
        json.loads(line) for line in log_path.read_text().splitlines()
    ]
    opens = [e for e in lines if e.get("event") == "incident_open"]
    assert len(opens) == 1
    assert any(
        "stage:detect@host1" in json.dumps(e) for e in opens
    )
    assert any(e.get("event") == "incident_resolve" for e in lines)


def test_watchdog_healthy_run_opens_nothing(registry, tmp_path):
    view = MetricsRegistry()
    hist = view.histogram("microrank_stage_seconds", "s", ("stage",))
    wd, tracker, log_path = _watchdog(tmp_path, view)
    for _ in range(10):
        hist.observe(0.003, stage="detect")
        hist.observe(0.02, stage="build")
        wd.evaluate(force=True)
    assert tracker.opened == 0
    assert not log_path.exists() or not log_path.read_text().strip()


def test_watchdog_gauge_signal_needs_fast_and_slow(registry, tmp_path):
    view = MetricsRegistry()
    lag = view.gauge(
        "microrank_fleet_host_watermark_lag_seconds", "l", ("host",)
    )
    wd, tracker, _ = _watchdog(
        tmp_path, view, watermark_lag_budget_seconds=10.0,
        fast_windows=2, slow_windows=4,
    )
    lag.set(5.0, host="host0")  # burn 0.5: under threshold
    for _ in range(3):
        assert wd.evaluate(force=True) == []
    # A transient spike (2.4 burn units) saturates the fast window
    # ((0.5+0.5+2.4)/3 >= 1) but NOT the slow one ((1.5+2.4)/4 < 1):
    # no breach — flap damping.
    lag.set(24.0, host="host0")
    assert wd.evaluate(force=True) == []
    assert tracker.opened == 0
    # Sustained at the same level the slow window fills too: breach.
    for _ in range(3):
        out = wd.evaluate(force=True)
    assert out == ["watermark_lag"]
    assert tracker.opened == 1


# ---------------------------------------------- coordinator round-trip


def test_coordinator_fleet_view_and_ledger_reconcile(
    registry, tmp_path
):
    from microrank_tpu.fleet.coordinator import FleetCoordinator

    coord = FleetCoordinator(
        MicroRankConfig(), out_dir=tmp_path, expected_workers=2
    )
    coord.register("host0")
    coord.register("host1")
    work = MetricsRegistry()
    work.counter("mr_jobs_total", "j").inc(5)
    sender = MetricsDeltaSender("host0")
    resp = coord.heartbeat(
        "host0", spans=10, windows=1, uptime_s=1.0, queue_depth=3,
        wall=1000.0, rtt=0.2, metrics=sender.payload(work),
    )
    assert resp["metrics_ack"] == {"ack": 1}
    prom = coord.fleet_metrics_text()
    assert "mr_jobs_total 5" in prom
    assert (
        'microrank_fleet_host_queue_depth{host="host0"} 3' in prom
    )
    # Finalize reconciliation: the on-disk ledger is the durable truth.
    ledger = MetricsRegistry()
    ledger.counter("mr_jobs_total", "j").inc(9)
    (tmp_path / "host0").mkdir()
    (tmp_path / "host0" / "metrics.json").write_text(
        json.dumps(ledger.to_json())
    )
    coord.goodbye("host0")
    coord.goodbye("host1")
    coord.finalize()
    arts = coord.write_fleet_artifacts()
    assert "metrics" in arts
    fleet_prom = (tmp_path / "metrics.prom").read_text()
    assert "mr_jobs_total 9" in fleet_prom  # ledger replaced the fold
    fleet_doc = json.loads((tmp_path / "metrics.json").read_text())
    assert registry_from_json(fleet_doc).get("mr_jobs_total") is not None


def test_coordinator_requests_worker_dumps_on_incident(
    registry, tmp_path
):
    from microrank_tpu.fleet.coordinator import FleetCoordinator

    coord = FleetCoordinator(
        MicroRankConfig(), out_dir=tmp_path, expected_workers=2
    )
    coord.register("host0")
    coord.register("host1")
    ranked = [["svc-a", 3.0], ["svc-b", 1.0]]
    for host in ("host0", "host1"):
        coord.report(
            host,
            {
                "start": "w0", "start_us": 1000, "outcome": "ranked",
                "ranking": ranked,
            },
        )
    # Advance both hosts so w0 seals at the watermark.
    for host in ("host0", "host1"):
        coord.report(
            host,
            {
                "start": "w1", "start_us": 2000, "outcome": "healthy",
                "ranking": [],
            },
        )
    assert coord.tracker.opened == 1
    resp = coord.heartbeat("host0", spans=1, windows=2, uptime_s=1.0)
    assert resp.get("dump") == "incident"
    # One pop per host: the second heartbeat is clean.
    assert "dump" not in coord.heartbeat(
        "host0", spans=1, windows=2, uptime_s=1.0
    )
    coord.service_flight()
    dumps = list((tmp_path / "flight").glob("*-fleet-incident"))
    assert len(dumps) == 1
    manifest = json.loads((dumps[0] / "manifest.json").read_text())
    fleet = manifest["fleet"]
    assert fleet["reason"] == "incident"
    assert "host1" in fleet["worker_dumps_requested"]


# ----------------------------------------------------- cli stats merge


def _write_host_snapshots(tmp_path, values):
    fleet = tmp_path / "fleet"
    for i, v in enumerate(values):
        reg = MetricsRegistry()
        reg.counter("mr_jobs_total", "j").inc(v)
        reg.gauge("mr_depth", "d").set(float(i))
        hdir = fleet / f"host{i}"
        hdir.mkdir(parents=True)
        (hdir / "metrics.json").write_text(json.dumps(reg.to_json()))
    return fleet


def test_cli_stats_merge_federates_hosts(tmp_path, capsys):
    from microrank_tpu.cli.main import main

    fleet = _write_host_snapshots(tmp_path, [5.0, 7.0])
    rc = main(
        ["stats", "--merge", str(fleet / "host0"), str(fleet / "host1")]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "mr_jobs_total 12" in out
    assert 'mr_depth{host="host0"} 0' in out
    assert 'mr_depth{host="host1"} 1' in out
    # A fleet dir expands to its host*/metrics.json children.
    rc = main(["stats", "--merge", str(fleet)])
    assert rc == 0
    assert "mr_jobs_total 12" in capsys.readouterr().out


def test_cli_stats_merge_composes_with_diff(tmp_path, capsys):
    from microrank_tpu.cli.main import main

    before = _write_host_snapshots(tmp_path / "before", [5.0, 7.0])
    after = _write_host_snapshots(tmp_path / "after", [6.0, 10.0])
    rc = main(["stats", "--merge", "--diff", str(before), str(after)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "mr_jobs_total 4" in out  # (6+10) - (5+7)
    rc = main(["stats", "--merge", "--diff", str(before)])
    assert rc == 2  # exactly two targets


def test_fold_into_is_the_shared_accumulation_law():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("mr_jobs_total", "j").inc(2)
    b.counter("mr_jobs_total", "j").inc(3)
    b.histogram("mr_lat_seconds", "l").observe(0.01)
    b.gauge("mr_depth", "d").set(4.0)
    fold_into(a, b)
    assert _counter_value(a, "mr_jobs_total") == pytest.approx(5.0)
    assert a.get("mr_lat_seconds").samples()[0]["count"] == 1
    assert a.get("mr_depth").samples()[0]["value"] == 4.0
    # CRC is canonical-serialization stable (reordering is not a tear).
    doc = {"metrics": {"x": {"type": "counter", "samples": []}}}
    doc2 = {"metrics": {"x": {"samples": [], "type": "counter"}}}
    assert delta_crc(doc) == delta_crc(doc2)
