"""mrshape suite: the interprocedural shape/dtype provenance lattice
(analysis.shapes), the compile-key-space model, and the runtime compile
witness (analysis.mrsan) that mirrors R13-R16.

The rule-level positive/negative behavior lives in the mrlint fixture
corpus (tests/data/mrlint/R13..R16); this file covers the machinery
those rules stand on — lattice algebra, interprocedural propagation,
the bucket-extent predicate, key-space admission, and the witness's
observe/dedupe/report/journal loop.
"""

import json

import pytest

from microrank_tpu.analysis.shapes import (
    BOT,
    BUCKET,
    CONST,
    TOP,
    WIDEN_LIMIT,
    AbsVal,
    CompileKeySpace,
    Prov,
    is_bucketed_extent,
    p_const,
    predict_key_space,
)


@pytest.fixture
def registry():
    """Install a fresh process metrics registry; restore after."""
    from microrank_tpu.obs import (
        MetricsRegistry,
        get_registry,
        set_registry,
    )

    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


# ------------------------------------------------------------- lattice


def test_prov_join_is_monotone_on_levels():
    bot = Prov(BOT)
    top = Prov(TOP)
    bucket = Prov(BUCKET)
    c = p_const(4)
    assert bot.join(c).level == CONST
    assert c.join(bucket).level == BUCKET
    assert bucket.join(top).level == TOP
    # join is commutative and idempotent
    assert c.join(bucket) == bucket.join(c)
    assert top.join(top) == top


def test_const_join_unions_value_sets():
    a = p_const(1)
    b = p_const(2)
    j = a.join(b)
    assert j.level == CONST
    assert j.values == frozenset({1, 2})


def test_const_widening_drops_values_past_limit():
    acc = p_const(0)
    for i in range(1, WIDEN_LIMIT + 2):
        acc = acc.join(p_const(i))
    assert acc.level == CONST
    # Past the widening limit the set becomes unenumerable (None), but
    # stays CONST: bounded, just not finitely listed.
    assert acc.values is None
    assert not acc.enumerable


def test_absval_join_is_pointwise_and_cast_is_conjunctive():
    a = AbsVal(
        prov=p_const(8), dtypes=frozenset({"float32"}), is_array=True,
        cast=True,
    )
    b = AbsVal(
        prov=Prov(TOP), dtypes=frozenset({"bfloat16"}), is_array=True,
        cast=False,
    )
    j = a.join(b)
    assert j.prov.level == TOP
    assert j.dtypes == frozenset({"float32", "bfloat16"})
    assert j.is_array
    assert not j.cast  # one uncast branch taints the join


# ------------------------------------------- interprocedural propagation


def _events(source, tmp_path, kinds=None):
    from microrank_tpu.analysis.core import Project, parse_module

    f = tmp_path / "mod.py"
    f.write_text(source)
    project = Project([parse_module(f)])
    evs = project.shapes.events
    if kinds is not None:
        evs = [e for e in evs if e.kind in kinds]
    return evs


def test_bucket_provenance_survives_helper_chain(tmp_path):
    """A pad_to-bucketed extent stays BUCKET through two helper calls,
    so the array built from it does NOT trip the pad-bucket-escape
    check at a dispatch seam."""
    src = """
import numpy as np
from microrank_tpu.graph.structures import pad_to

def bucketed(table):
    return pad_to(len(table))

def build(table):
    n = bucketed(table)
    return np.zeros((n, n), dtype=np.float32)

def serve(table, pagerank_cfg, spectrum_cfg):
    graph = build(table)
    return stage_rank_window(graph, pagerank_cfg, spectrum_cfg, "kind", True)
"""
    assert _events(src, tmp_path, kinds={"bucket-escape"}) == []


def test_measured_provenance_survives_helper_chain(tmp_path):
    """The same chain WITHOUT the pad_to stays TOP and fires."""
    src = """
import numpy as np

def measured(table):
    return len(table)

def build(table):
    n = measured(table)
    return np.zeros((n, n), dtype=np.float32)

def serve(table, pagerank_cfg, spectrum_cfg):
    graph = build(table)
    return stage_rank_window(graph, pagerank_cfg, spectrum_cfg, "kind", True)
"""
    evs = _events(src, tmp_path, kinds={"bucket-escape"})
    assert len(evs) == 1


def test_recompile_bomb_through_helper(tmp_path):
    src = """
import jax

def n_rows(table):
    return len(table)

def rank(x, n):
    return x * n

rank_jit = jax.jit(rank, static_argnums=(1,))

def serve(table, x):
    return rank_jit(x, n_rows(table))
"""
    evs = _events(src, tmp_path, kinds={"recompile-bomb"})
    assert len(evs) == 1
    assert "static" in evs[0].message


def test_const_static_arg_is_clean(tmp_path):
    src = """
import jax

def rank(x, n):
    return x * n

rank_jit = jax.jit(rank, static_argnums=(1,))

def serve(x):
    return rank_jit(x, 8)
"""
    assert _events(src, tmp_path, kinds={"recompile-bomb"}) == []


# ------------------------------------------------- bucket-extent predicate


@pytest.mark.parametrize("policy", ["pow2", "pow2q"])
def test_bucketed_extents_admit_buckets_and_derived_rows(policy):
    from microrank_tpu.graph.structures import pad_to

    for live in (3, 17, 40, 100, 333):
        bucket = pad_to(live, policy)
        assert is_bucketed_extent(bucket, policy)
        # indptr arrays carry bucket+1 rows
        assert is_bucketed_extent(bucket + 1, policy)
        # packbits byte columns carry bucket/8 columns
        if bucket % 8 == 0:
            assert is_bucketed_extent(bucket // 8, policy)


def test_unbucketed_extent_rejected():
    # 37 is not a pow2q bucket, not bucket+1 (36 isn't either), and
    # 37*8=296 isn't a bucket — a live measurement escaped.
    assert not is_bucketed_extent(37, "pow2q")
    # but anything at or under the pad floor is always fine
    assert is_bucketed_extent(7, "pow2q")
    # and the batch-occupancy axis is admitted when it matches
    assert is_bucketed_extent(37, "pow2q", occupancy=37)


# ------------------------------------------------------ key-space model


def test_key_space_admits_bucketed_and_rejects_measured():
    space = CompileKeySpace(pad_policy="pow2q")
    assert space.admits("p", "kind", 4, [(64, 64), (65,)]) is None
    reason = space.admits("p", "kind", 4, [(37, 37)])
    assert reason is not None and "37" in reason


def test_key_space_exact_policy_predicts_nothing_about_extents():
    space = CompileKeySpace(pad_policy="exact")
    assert space.admits("p", "kind", 1, [(37, 41)]) is None


def test_key_space_rejects_unknown_kernel_and_occupancy():
    space = CompileKeySpace(
        pad_policy="pow2q", kernels=frozenset({"kind"}),
        occupancies=frozenset({1, 4}),
    )
    assert space.admits("p", "mystery", 1, []) is not None
    assert space.admits("p", "kind", 3, []) is not None
    assert space.admits("p", "kind", 4, []) is None


def test_predict_key_space_reads_config_and_manifest(tmp_path):
    import dataclasses

    from microrank_tpu.config import MicroRankConfig
    from microrank_tpu.dispatch.cache import record_manifest_entry

    cfg = MicroRankConfig()
    cfg = cfg.replace(
        runtime=dataclasses.replace(cfg.runtime, pad_policy="pow2")
    )
    record_manifest_entry(tmp_path, "table", "kind", [1, 4])
    space = predict_key_space(
        cfg, cache_dir=tmp_path, pipeline="table"
    )
    assert space.pad_policy == "pow2"
    assert space.occupancies == frozenset({1, 4})


# ------------------------------------------------------ compile witness


@pytest.fixture()
def witness():
    from microrank_tpu.analysis import mrsan

    mrsan.disarm_witness()
    yield mrsan
    mrsan.disarm_witness()


def test_witness_observes_dedupes_and_flags_escapes(witness, registry):
    import numpy as np

    witness.arm_witness(CompileKeySpace(pad_policy="pow2q"))
    good = {"a": np.zeros((64, 64), dtype=np.float32)}
    bad = {"a": np.zeros((37, 37), dtype=np.float32)}
    witness.observe_compile_key("p", kernel="kind", graph=good, occupancy=4)
    witness.observe_compile_key("p", kernel="kind", graph=good, occupancy=4)
    witness.observe_compile_key("p", kernel="kind", graph=bad, occupancy=4)
    rep = witness.witness_report()
    assert rep["programs"] == {"p": 2}  # dedupe: 3 observations, 2 keys
    assert rep["keys_total"] == 2
    assert len(rep["unpredicted"]) == 1
    assert "37" in rep["unpredicted"][0]["reason"]
    misses = registry.get("microrank_jit_cache_misses_total")
    assert sum(s["value"] for s in misses.samples()) == 2
    viols = registry.get("microrank_mrsan_violations_total")
    by_kind = {
        s["labels"]["kind"]: s["value"] for s in viols.samples()
    }
    assert by_kind.get("compile-witness") == 1


def test_witness_journals_misses(witness, registry, tmp_path):
    import numpy as np

    from microrank_tpu.obs import (
        RunJournal,
        read_journal,
        set_current_journal,
    )

    journal = RunJournal(tmp_path / "journal.jsonl")
    set_current_journal(journal)
    try:
        witness.arm_witness(CompileKeySpace(pad_policy="pow2q"))
        witness.observe_compile_key(
            "p", kernel="kind",
            graph={"a": np.zeros((64,), dtype=np.float32)}, occupancy=1,
        )
    finally:
        set_current_journal(None)
    events = read_journal(tmp_path / "journal.jsonl")
    assert len(events) == 1
    ev = events[0]
    assert ev["event"] == "jit_cache_miss"
    assert ev["program"] == "p"
    assert ev["kernel"] == "kind"
    assert ev["predicted"] is True
    assert ev["key"] == [[64]]


def test_configure_sanitizers_does_not_disarm_external_witness(witness):
    """The bench arms the witness around a TableRCA.run; the run entry's
    configure_sanitizers (sanitizers off) must leave it armed."""
    from microrank_tpu.analysis.mrsan import configure_sanitizers
    from microrank_tpu.config import MicroRankConfig

    witness.arm_witness(CompileKeySpace(pad_policy="pow2q"))
    configure_sanitizers(MicroRankConfig())  # sanitizers default off
    assert witness.witness_armed()
    # but a config-armed witness IS released by the disabled config
    witness.disarm_witness()
    witness.arm_witness(CompileKeySpace(pad_policy="pow2q"), owner="config")
    configure_sanitizers(MicroRankConfig())
    assert not witness.witness_armed()


def test_sanitizers_on_arms_witness_from_config(witness):
    import dataclasses

    from microrank_tpu.analysis.mrsan import configure_sanitizers
    from microrank_tpu.config import MicroRankConfig
    from microrank_tpu.utils.guards import set_sanitizers

    cfg = MicroRankConfig()
    cfg = cfg.replace(
        runtime=dataclasses.replace(cfg.runtime, sanitizers=True)
    )
    try:
        configure_sanitizers(cfg)
        assert witness.witness_armed()
    finally:
        configure_sanitizers(MicroRankConfig())
        set_sanitizers(False)


def test_pipeline_run_observes_only_predicted_keys(witness, tmp_path, registry):
    """End-to-end acceptance: a real TableRCA run over a synthetic
    faulted timeline observes ≥1 compile key and ZERO keys outside the
    static prediction — the compile-witness criterion CI enforces on
    the bench replay."""
    from microrank_tpu.config import MicroRankConfig, WindowConfig
    from microrank_tpu.native import load_span_table
    from microrank_tpu.pipeline.table_runner import TableRCA
    from microrank_tpu.testing.synthetic import (
        SyntheticConfig,
        generate_timeline,
    )

    tl = generate_timeline(
        SyntheticConfig(n_operations=30, n_kinds=8, n_traces=100, seed=11),
        3,
        [0, 1, 2],
    )
    normal_csv = tmp_path / "normal.csv"
    abn_csv = tmp_path / "abn.csv"
    tl.normal.to_csv(normal_csv, index=False)
    tl.timeline.to_csv(abn_csv, index=False)
    cfg = MicroRankConfig(
        window=WindowConfig(
            detect_minutes=tl.window_minutes, skip_minutes=0.0
        )
    )
    witness.arm_witness(predict_key_space(cfg))
    rca = TableRCA(cfg)
    rca.fit_baseline(load_span_table(normal_csv))
    results = rca.run(load_span_table(abn_csv))
    assert any(r.ranking for r in results)
    rep = witness.witness_report()
    assert rep["keys_total"] >= 1
    assert rep["unpredicted"] == []


# ------------------------------------------------------- witness CLI


def test_witness_cli_replays_journal(tmp_path, capsys):
    from microrank_tpu.cli.main import main

    journal = tmp_path / "journal.jsonl"
    lines = [
        {"event": "run_start", "pad_policy": "pow2q"},
        {
            "event": "jit_cache_miss", "program": "p", "kernel": "kind",
            "occupancy": 1, "key": [[64, 64]], "predicted": True,
        },
    ]
    journal.write_text(
        "".join(json.dumps(e) + "\n" for e in lines)
    )
    assert main(["witness", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "inside the predicted space" in out

    lines.append({
        "event": "jit_cache_miss", "program": "p", "kernel": "kind",
        "occupancy": 1, "key": [[37, 37]], "predicted": False,
    })
    journal.write_text(
        "".join(json.dumps(e) + "\n" for e in lines)
    )
    assert main(["witness", str(journal)]) == 1
    out = capsys.readouterr().out
    assert "ESCAPE" in out
