"""Adaptive dispatch router (dispatch/): size-threshold + occupancy
routing with tie-aware parity between the sharded and vmapped routes,
burst coalescing (stream dispatches < abnormal windows under a
same-bucket burst), double-buffered staging (prestage consumed by the
next dispatch; correctness under an injected dispatch failure — the
serve degrade path stays per-member), and the persistent compile cache
+ warmup manifest (warm restart replays recorded occupancies and
observes cache hits). All on the 8-device virtual CPU mesh.
"""

import dataclasses
import json

import jax
import numpy as np
import pytest

from conftest import partition_case
from microrank_tpu.config import (
    DispatchConfig,
    MicroRankConfig,
    StreamConfig,
)
from microrank_tpu.dispatch import (
    CompileCacheProbe,
    DispatchRouter,
    bucket_key,
    load_manifest,
    manifest_occupancies,
    record_manifest_entry,
    warm_occupancies,
)
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.rank_backends.jax_tpu import (
    graph_device_bytes,
    prepare_window_graph,
)
from microrank_tpu.testing import SyntheticConfig, generate_case


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


@pytest.fixture(scope="module")
def prepared():
    """One prepared abnormal window (graph already kernel-stripped)."""
    cfg = MicroRankConfig()
    case = generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )
    nrm, abn = partition_case(case)
    graph, names, kernel = prepare_window_graph(
        case.abnormal, nrm, abn, cfg
    )
    return cfg, graph, names, kernel


def _mesh_config(cfg, threshold=0, **dispatch_kw):
    return cfg.replace(
        runtime=dataclasses.replace(cfg.runtime, mesh_shape=(2, 4)),
        dispatch=DispatchConfig(
            sharded_bytes_threshold=threshold, **dispatch_kw
        ),
    )


# ------------------------------------------------------------------ plan


def test_plan_decision_table(prepared, registry):
    cfg, graph, _, kernel = prepared
    footprint = graph_device_bytes(graph)
    assert footprint > 0

    # No mesh: always vmapped, threshold irrelevant.
    r = DispatchRouter(cfg.replace(dispatch=DispatchConfig(
        sharded_bytes_threshold=0)))
    assert r.plan([graph], kernel)[0] == "vmapped"

    # Mesh + footprint below threshold + occupancy below windows axis.
    r = DispatchRouter(_mesh_config(cfg, threshold=footprint * 10))
    route, _, fp = r.plan([graph], kernel)
    assert route == "vmapped" and fp == footprint

    # Size trigger: batch footprint crosses the threshold.
    route, shard_kernel, _ = r.plan([graph] * 20, kernel)
    assert route == "sharded"
    from microrank_tpu.parallel.sharded_rank import SHARD_KERNELS

    assert shard_kernel in SHARD_KERNELS

    # Occupancy trigger: windows axis (2) fills even under threshold.
    assert r.plan([graph, graph], kernel)[0] == "sharded"
    r_no_occ = DispatchRouter(
        _mesh_config(
            cfg,
            threshold=footprint * 10,
            shard_on_full_occupancy=False,
        )
    )
    assert r_no_occ.plan([graph, graph], kernel)[0] == "vmapped"

    # Zero threshold: everything a mesh can take shards.
    r0 = DispatchRouter(_mesh_config(cfg, threshold=0))
    assert r0.plan([graph], kernel)[0] == "sharded"


# ---------------------------------------------------------- route parity


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)
def test_route_parity_tie_aware(prepared, registry):
    """The acceptance pin: sharded-route windows must match the vmapped
    route's FULL ranked list, tie-aware (exact ties may legally permute
    across summation trees; everything else is positional)."""
    from microrank_tpu.utils.ranking_compare import (
        tie_aware_topk_agreement,
    )

    cfg, graph, names, kernel = prepared
    vm = DispatchRouter(cfg)
    sh = DispatchRouter(_mesh_config(cfg, threshold=0))
    outs_v, info_v = vm.rank_batch([graph] * 3, kernel)
    outs_s, info_s = sh.rank_batch([graph] * 3, kernel)
    assert info_v.route == "vmapped" and info_s.route == "sharded"
    for b in range(3):
        nv, ns = int(outs_v[2][b]), int(outs_s[2][b])
        assert nv == ns
        names_v = [names[int(i)] for i in outs_v[0][b][:nv]]
        names_s = [names[int(i)] for i in outs_s[0][b][:ns]]
        scores_v = [float(s) for s in outs_v[1][b][:nv]]
        scores_s = [float(s) for s in outs_s[1][b][:ns]]
        ok, detail = tie_aware_topk_agreement(
            names_v, scores_v, names_s, scores_s, k=nv, rtol=1e-3
        )
        assert ok, detail
    # Both routes recorded.
    assert registry.get(
        "microrank_dispatch_route_total"
    ).value(route="vmapped") == 1
    assert registry.get(
        "microrank_dispatch_route_total"
    ).value(route="sharded") == 1


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)
def test_sharded_route_pads_batch_to_windows_axis(prepared, registry):
    # 3 windows on a (2, 4) mesh: padded to 4 internally, sliced back.
    cfg, graph, _, kernel = prepared
    r = DispatchRouter(_mesh_config(cfg, threshold=0))
    outs, info = r.rank_batch([graph] * 3, kernel)
    assert info.route == "sharded" and info.windows == 3
    assert all(np.asarray(o).shape[0] == 3 for o in outs)


# -------------------------------------------------------- double buffer


def test_double_buffer_prestage_consumed(prepared, registry):
    cfg, graph, _, kernel = prepared
    r = DispatchRouter(cfg)
    b1, b2 = [graph], [graph, graph]
    _, info1 = r.rank_batch(b1, kernel, next_batch=(b2, kernel))
    assert not info1.prestaged
    assert r._prestaged is not None
    _, info2 = r.rank_batch(b2, kernel)
    assert info2.prestaged          # staging happened behind batch 1
    assert r._prestaged is None
    # Overlapped staging seconds landed in the metric.
    assert (
        registry.get(
            "microrank_dispatch_overlap_seconds_total"
        ).value()
        > 0
    )
    # A mismatched prestage is dropped, not misused.
    _, info3 = r.rank_batch(b1, kernel, next_batch=(b2, kernel))
    _, info4 = r.rank_batch(b1, kernel)     # NOT the prestaged batch
    assert not info4.prestaged


def test_double_buffer_survives_dispatch_failure(prepared, registry):
    """Injected dispatch failure between prestage and consume: the
    failing batch raises to its caller (serve retries then degrades
    per-member), the prestaged NEXT batch still dispatches correctly,
    and a retry of the failed batch restages cleanly."""
    cfg, graph, _, kernel = prepared
    r = DispatchRouter(cfg)
    orig = r._dispatch_program
    fail = {"n": 0}

    def flaky(staged, conv):
        if fail["n"] > 0:
            fail["n"] -= 1
            raise RuntimeError("injected dispatch failure")
        return orig(staged, conv)

    r._dispatch_program = flaky
    b1, b2 = [graph], [graph, graph]
    r.rank_batch(b1, kernel, next_batch=(b2, kernel))  # prestages b2
    fail["n"] = 1
    with pytest.raises(RuntimeError, match="injected"):
        r.rank_batch(b2, kernel)       # consumed prestage, then failed
    # Retry restages from scratch and succeeds.
    outs, info = r.rank_batch(b2, kernel)
    assert not info.prestaged and int(outs[2][0]) > 0


def test_serve_degrade_stays_per_member_with_double_buffer(registry):
    """Two ready batches through the serve batcher's pipelined
    dispatch_ready: the first batch's dispatch fails twice (injected)
    and degrades to numpy_ref PER MEMBER; the second batch — whose
    staging was already double-buffered behind the failing dispatch —
    still ranks on device."""
    from concurrent.futures import Future

    from microrank_tpu.config import ServeConfig
    from microrank_tpu.pipeline.results import WindowResult
    from microrank_tpu.serve import RankRequest, ServeService
    from microrank_tpu.serve.batcher import PendingWindow

    case = generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )
    cfg = MicroRankConfig(
        serve=ServeConfig(warmup=False, inject_dispatch_failures=2)
    )
    svc = ServeService(cfg)
    svc.fit_baseline(case.normal)
    nrm, abn = partition_case(case)
    graph, names, kernel = prepare_window_graph(
        case.abnormal, nrm, abn, cfg
    )

    import time as _t

    def _pw(rid):
        return PendingWindow(
            request=RankRequest(request_id=rid, tenant="t"),
            result=WindowResult(start="", end="", anomaly=True),
            span_df=case.abnormal,
            normal_ids=nrm,
            abnormal_ids=abn,
            graph=graph,
            op_names=names,
            kernel=kernel,
            future=Future(),
            enqueued=_t.monotonic(),
            built=_t.monotonic(),
        )

    batch1 = [_pw("a1"), _pw("a2")]
    batch2 = [_pw("b1")]
    svc.scheduler.batcher.dispatch_ready([batch1, batch2])
    # Batch 1: both members answered by the numpy_ref fallback.
    for pw in batch1:
        res = pw.future.result(timeout=60)
        assert res.degraded and res.ranking
        assert res.kernel == "numpy_ref"
    # Batch 2: device path, not degraded.
    res2 = batch2[0].future.result(timeout=60)
    assert not res2.degraded and res2.ranking
    assert res2.route == "vmapped"


# ------------------------------------------------------ burst coalescing


def test_stream_burst_coalesces_dispatches(registry, tmp_path):
    """The acceptance invariant: a same-bucket abnormal burst produces
    FEWER device dispatches than ranked windows — pending windows
    coalesce into the head's vmapped dispatch."""
    from microrank_tpu.stream import StreamEngine, SyntheticSource

    src = SyntheticSource(
        n_windows=8,
        faulted=[3, 4, 5],
        synth_config=SyntheticConfig(
            n_operations=24, n_traces=200, n_kinds=16, seed=5
        ),
        pace_seconds=0.0,
        sleep=lambda s: None,
    )
    cfg = MicroRankConfig(
        stream=StreamConfig(
            allowed_lateness_seconds=5.0, pipeline_windows=3
        ),
        # Session-local cache dir so the manifest test below is isolated.
        dispatch=DispatchConfig(),
    )
    import os

    _old_jit_cache = os.environ.get("MICRORANK_JIT_CACHE")
    os.environ["MICRORANK_JIT_CACHE"] = str(tmp_path / "jit")
    try:
        eng = StreamEngine(cfg, src, out_dir=tmp_path)
        s = eng.run()
    finally:
        if _old_jit_cache is None:
            os.environ.pop("MICRORANK_JIT_CACHE", None)
        else:
            os.environ["MICRORANK_JIT_CACHE"] = _old_jit_cache
    assert s.ranked == 3
    assert s.dispatches < s.ranked, (s.dispatches, s.ranked)
    disp_metric = registry.get(
        "microrank_stream_dispatches_total"
    ).value()
    assert disp_metric == s.dispatches
    # Coalesced windows carry their shared occupancy + route.
    ranked = [r for r in s.results if r.ranking]
    assert any((r.batch_windows or 1) > 1 for r in ranked)
    assert all(r.route == "vmapped" for r in ranked)
    # Window order was preserved through the group dispatch.
    assert [r.start for r in s.results] == sorted(
        r.start for r in s.results
    )
    # One deduped incident for the whole burst, resolved after recovery.
    assert s.incidents_opened == 1 and s.incidents_resolved == 1
    # The engine's manifest recorded the warmed occupancies for restart.
    occs = manifest_occupancies(str(tmp_path / "jit"), "stream")
    assert occs and max(occs) >= 2


def test_coalesce_respects_cap_and_bucket(prepared, registry):
    """coalesce_windows=1 disables coalescing entirely."""
    from microrank_tpu.stream import StreamEngine, SyntheticSource

    src = SyntheticSource(
        n_windows=8,
        faulted=[3, 4, 5],
        synth_config=SyntheticConfig(
            n_operations=24, n_traces=200, n_kinds=16, seed=5
        ),
        pace_seconds=0.0,
        sleep=lambda s: None,
    )
    cfg = MicroRankConfig(
        stream=StreamConfig(
            allowed_lateness_seconds=5.0, pipeline_windows=3
        ),
        dispatch=DispatchConfig(coalesce_windows=1, warmup_manifest=False),
    )
    eng = StreamEngine(cfg, src)
    s = eng.run()
    assert s.ranked == 3 and s.dispatches == 3


# ------------------------------------------------- compile cache/manifest


def test_manifest_merge_round_trip(tmp_path, registry):
    cache = str(tmp_path / "jit")
    assert load_manifest(cache) == []
    record_manifest_entry(cache, "serve", "packed_bf16", [1, 2])
    record_manifest_entry(cache, "serve", "packed_bf16", [2, 4])
    record_manifest_entry(cache, "stream", "csr", [1])
    entries = load_manifest(cache)
    assert len(entries) == 2
    assert manifest_occupancies(cache, "serve") == [1, 2, 4]
    assert manifest_occupancies(cache, "stream") == [1]
    assert manifest_occupancies(None, "serve") == []
    # Corrupt manifest is ignored, not fatal.
    (tmp_path / "jit" / "warmup_manifest.json").write_text("{nope")
    assert load_manifest(cache) == []
    assert (
        registry.get("microrank_compile_cache_events_total").value(
            event="manifest_write"
        )
        == 3
    )


def test_warmup_probe_classifies_hits(prepared, registry, tmp_path):
    """Warm restart shape, in-process: with the jit tracing caches
    cleared (= a fresh process), the first warmup pass over a fresh
    persistent cache dir compiles for real (misses land entries on
    disk); clearing again and re-warming observes no entry growth —
    every compile reloaded from the persistent cache (hits)."""
    import os

    import jax as _jax

    from microrank_tpu.dispatch import configure_compile_cache

    cfg, _, _, _ = prepared
    cache = tmp_path / "jit"
    _old_jit_cache = os.environ.get("MICRORANK_JIT_CACHE")
    os.environ["MICRORANK_JIT_CACHE"] = str(cache)
    try:
        assert configure_compile_cache(None) == str(cache)
        router = DispatchRouter(cfg)
        _jax.clear_caches()                # simulate a fresh process
        probe = CompileCacheProbe(str(cache))
        warm_occupancies(router, cfg, [1, 2], probe=probe)
        first_misses = probe.misses
        assert first_misses >= 1           # cold: programs persisted
        _jax.clear_caches()                # second "process"
        probe2 = CompileCacheProbe(str(cache))
        warm_occupancies(router, cfg, [1, 2], probe=probe2)
        assert probe2.misses == 0 and probe2.hits == 2
        reg = registry.get("microrank_compile_cache_events_total")
        assert reg.value(event="hit") >= 2
        assert reg.value(event="miss") == first_misses
    finally:
        if _old_jit_cache is None:
            os.environ.pop("MICRORANK_JIT_CACHE", None)
        else:
            os.environ["MICRORANK_JIT_CACHE"] = _old_jit_cache
        _jax.config.update("jax_compilation_cache_dir", None)


def test_stream_warm_restart_replays_manifest(registry, tmp_path):
    """A second engine over the same cache dir finds the first run's
    manifest, replays its occupancies at startup (warm_start event),
    and the replayed compiles hit the persistent cache."""
    import os

    from microrank_tpu.stream import StreamEngine, SyntheticSource

    def _run():
        src = SyntheticSource(
            n_windows=6,
            faulted=[2],
            synth_config=SyntheticConfig(
                n_operations=16, n_traces=120, n_kinds=12, seed=9
            ),
            pace_seconds=0.0,
            sleep=lambda s: None,
        )
        cfg = MicroRankConfig(
            stream=StreamConfig(allowed_lateness_seconds=5.0)
        )
        return StreamEngine(cfg, src).run()

    _old_jit_cache = os.environ.get("MICRORANK_JIT_CACHE")
    os.environ["MICRORANK_JIT_CACHE"] = str(tmp_path / "jit")
    try:
        s1 = _run()
        assert s1.ranked == 1
        assert manifest_occupancies(str(tmp_path / "jit"), "stream")
        reg1 = get_registry().get("microrank_compile_cache_events_total")
        assert reg1.value(event="warm_start") == 0
        s2 = _run()
        assert s2.ranked == 1
        reg = get_registry().get("microrank_compile_cache_events_total")
        assert reg.value(event="warm_start") == 1
        assert reg.value(event="hit") >= 1
    finally:
        if _old_jit_cache is None:
            os.environ.pop("MICRORANK_JIT_CACHE", None)
        else:
            os.environ["MICRORANK_JIT_CACHE"] = _old_jit_cache


# ------------------------------------------------------------ bucket key


def test_bucket_key_separates_shapes_and_kernels(prepared):
    cfg, graph, _, kernel = prepared
    assert bucket_key(graph, kernel) == bucket_key(graph, kernel)
    assert bucket_key(graph, kernel) != bucket_key(graph, "coo")
    other = generate_case(
        SyntheticConfig(n_operations=48, n_traces=300, seed=3)
    )
    nrm, abn = partition_case(other)
    g2, _, k2 = prepare_window_graph(other.abnormal, nrm, abn, cfg)
    assert bucket_key(g2, kernel) != bucket_key(graph, kernel)
