"""Online RCA service (serve/): protocol validation, admission control
(429 + Retry-After), cross-request micro-batching (>= 2 concurrent
requests -> ONE device dispatch), per-tenant fair dequeue, numpy_ref
graceful degradation under injected dispatch failure, drain-on-shutdown,
and the end-to-end CLI SIGTERM smoke.

HTTP tests speak real HTTP to a fully wired service on a background
event loop (ServeHandle); scheduler/batcher unit tests drive the
components directly.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import pytest

from microrank_tpu.config import MicroRankConfig, ServeConfig
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.serve import (
    AdmissionController,
    ProtocolError,
    RankRequest,
    ServeHandle,
    ServeService,
    parse_rank_request,
    spans_to_frame,
)
from microrank_tpu.testing import SyntheticConfig, generate_case


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


@pytest.fixture(scope="module")
def case():
    return generate_case(
        SyntheticConfig(n_operations=24, n_traces=120, seed=7)
    )


@pytest.fixture(scope="module")
def spans_payload(case):
    df = case.abnormal.copy()
    df["startTime"] = df["startTime"].astype(str)
    df["endTime"] = df["endTime"].astype(str)
    return {"spans": df.to_dict("records")}


def _service(case, tmp_path=None, **serve_kw):
    serve_kw.setdefault("warmup", False)
    serve_kw.setdefault("max_wait_ms", 2000.0)
    cfg = MicroRankConfig(serve=ServeConfig(**serve_kw))
    svc = ServeService(
        cfg, out_dir=None if tmp_path is None else tmp_path
    )
    svc.fit_baseline(case.normal)
    return svc


def _post(port, payload, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/rank",
        data=json.dumps(payload).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as r:
        return r.status, r.read()


# ---------------------------------------------------------------- protocol


def test_parse_rank_request_validates():
    with pytest.raises(ProtocolError, match="not JSON"):
        parse_rank_request(b"{nope")
    with pytest.raises(ProtocolError, match="JSON object"):
        parse_rank_request(b"[1]")
    with pytest.raises(ProtocolError, match="exactly one"):
        parse_rank_request(b"{}")
    with pytest.raises(ProtocolError, match="exactly one"):
        parse_rank_request(b'{"spans": [{}], "dataset": "d"}')
    with pytest.raises(ProtocolError, match="non-empty"):
        parse_rank_request(b'{"spans": []}')
    r = parse_rank_request(b'{"dataset": "d", "tenant": "t1"}')
    assert r.dataset == "d" and r.tenant == "t1" and r.request_id
    r2 = parse_rank_request(
        b'{"spans": [{"a": 1}], "request_id": "abc"}'
    )
    assert r2.request_id == "abc" and r2.tenant == "default"


def test_spans_to_frame_enforces_schema(spans_payload):
    df = spans_to_frame(spans_payload["spans"])
    assert len(df) == len(spans_payload["spans"])
    with pytest.raises(ProtocolError, match="missing required columns"):
        spans_to_frame([{"traceID": "t1"}])


# --------------------------------------------------------------- admission


def test_admission_controller_bounds_depth(registry):
    adm = AdmissionController(max_depth=2)
    assert adm.try_admit() and adm.try_admit()
    assert not adm.try_admit()
    assert adm.depth == 2
    adm.release()
    assert adm.try_admit()
    adm.close()
    adm.release()
    assert not adm.try_admit()  # closed admits nothing


# ------------------------------------------------------------ fair dequeue


def test_scheduler_pops_round_robin_across_tenants(case, registry):
    svc = _service(case)
    sched = svc.scheduler  # thread NOT started: we drive _pop_fair
    order = []
    for tenant, rid in [
        ("a", "a1"), ("a", "a2"), ("a", "a3"), ("b", "b1"), ("b", "b2"),
    ]:
        sched.submit(RankRequest(request_id=rid, tenant=tenant))
    while True:
        entry = sched._pop_fair(timeout=0)
        if entry is None:
            break
        order.append(entry[0].request_id)
    # One chatty tenant (a, 3 queued) cannot starve tenant b: pops
    # alternate while both have work.
    assert order == ["a1", "b1", "a2", "b2", "a3"]


# ------------------------------------------------- batching + degradation


def test_concurrent_requests_coalesce_into_one_dispatch(
    case, spans_payload, registry, tmp_path
):
    """Acceptance: >= 2 concurrent requests -> ONE device dispatch
    (batch-occupancy metric > 1), every request answered."""
    svc = _service(case, tmp_path=tmp_path, max_batch_windows=4)
    svc.add_dataset("case7", case.abnormal)
    svc.start()
    handle = ServeHandle(svc)
    port = handle.start()
    try:
        payloads = [
            {**spans_payload, "tenant": "t0"},
            {"dataset": "case7", "tenant": "t1"},
            {**spans_payload, "tenant": "t2"},
            {"dataset": "case7", "tenant": "t3"},
        ]
        with ThreadPoolExecutor(4) as ex:
            results = [
                f.result()
                for f in [ex.submit(_post, port, p) for p in payloads]
            ]
        for status, body, _ in results:
            assert status == 200
            assert body["anomaly"] is True
            assert body["ranking"]
            assert body["degraded"] is False
            # All four landed in one stacked vmapped program.
            assert body["batch_windows"] == 4
        assert svc.scheduler.batcher.dispatches == 1
        occupancy = registry.get(
            "microrank_serve_last_batch_windows"
        ).value()
        assert occupancy > 1
        # The /metrics scrape exposes the occupancy histogram.
        _, prom = _get(port, "/metrics")
        assert b"microrank_serve_batch_windows_bucket" in prom
        _, health = _get(port, "/healthz")
        assert json.loads(health)["status"] == "ok"
    finally:
        handle.stop()
    # Journal carries one serve_batch event with all four requests.
    from microrank_tpu.obs import read_journal

    events = read_journal(tmp_path / "journal.jsonl")
    batches = [e for e in events if e["event"] == "serve_batch"]
    assert len(batches) == 1 and batches[0]["occupancy"] == 4
    assert len([e for e in events if e["event"] == "window"]) == 4


def test_admission_control_answers_429_with_retry_after(
    case, spans_payload, registry
):
    svc = _service(
        case,
        max_batch_windows=8,
        max_wait_ms=4000.0,
        max_queue_depth=2,
        retry_after_seconds=2.0,
    )
    svc.start()
    handle = ServeHandle(svc)
    port = handle.start()
    try:
        with ThreadPoolExecutor(2) as ex:
            parked = [
                ex.submit(_post, port, {**spans_payload, "tenant": t})
                for t in ("a", "b")
            ]
            deadline = time.monotonic() + 10
            while (
                svc.admission.depth < 2 and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            status, body, headers = _post(
                port, {**spans_payload, "tenant": "c"}
            )
            assert status == 429
            assert "queue is full" in body["error"]
            assert headers.get("Retry-After") == "2"
            # The admitted requests are NOT dropped by the shed.
            for f in parked:
                s, b, _ = f.result()
                assert s == 200 and b["ranking"]
        rejected = registry.get(
            "microrank_serve_requests_total"
        ).value(outcome="rejected")
        assert rejected >= 1
    finally:
        handle.stop()


def test_injected_dispatch_failure_degrades_to_numpy(
    case, spans_payload, registry
):
    """Acceptance: device dispatch fails (injected) + retry fails ->
    every batch member re-ranked on numpy_ref, responses carry
    degraded=true, no request dropped."""
    svc = _service(
        case,
        max_batch_windows=2,
        inject_dispatch_failures=2,  # initial dispatch + its retry
    )
    svc.start()
    handle = ServeHandle(svc)
    port = handle.start()
    try:
        with ThreadPoolExecutor(2) as ex:
            results = [
                f.result()
                for f in [
                    ex.submit(
                        _post, port, {**spans_payload, "tenant": t}
                    )
                    for t in ("a", "b")
                ]
            ]
        for status, body, _ in results:
            assert status == 200
            assert body["degraded"] is True
            assert body["kernel"] == "numpy_ref"
            assert body["ranking"]
        assert registry.get(
            "microrank_serve_degraded_total"
        ).value() == 2
        # The device path recovered for later requests (injection spent).
        status, body, _ = _post(port, spans_payload)
        assert status == 200 and body["degraded"] is False
    finally:
        handle.stop()


def test_failed_dispatch_without_fallback_answers_500(
    case, spans_payload, registry
):
    svc = _service(
        case, fallback=False, inject_dispatch_failures=2,
        max_batch_windows=1,
    )
    svc.start()
    handle = ServeHandle(svc)
    port = handle.start()
    try:
        status, body, _ = _post(port, spans_payload)
        assert status == 500
        assert "injected" in body["error"]
    finally:
        handle.stop()


# ------------------------------------------------------- clean / invalid


def test_clean_window_and_bad_requests(case, registry):
    svc = _service(case, max_wait_ms=50.0)
    svc.start()
    handle = ServeHandle(svc)
    port = handle.start()
    try:
        # Normal-period spans: no anomaly, no ranking, immediate answer.
        df = case.normal.copy()
        df["startTime"] = df["startTime"].astype(str)
        df["endTime"] = df["endTime"].astype(str)
        status, body, _ = _post(port, {"spans": df.to_dict("records")})
        assert status == 200
        assert body["anomaly"] is False and body["ranking"] == []
        # Unknown dataset -> 400.
        status, body, _ = _post(port, {"dataset": "nope"})
        assert status == 400 and "unknown dataset" in body["error"]
        # Malformed body -> 400.
        status, body, _ = _post(port, {"tenant": "x"})
        assert status == 400
        # Unknown route -> 404.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/nope", method="GET"
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 404
    finally:
        handle.stop()


# ------------------------------------------------------------------ drain


def test_drain_completes_parked_requests(case, registry):
    """Shutdown with drain: requests parked in a bucket (max_wait not
    yet reached) are force-flushed and answered before the scheduler
    thread exits — the SIGTERM semantics, driven directly."""
    svc = _service(case, max_batch_windows=8, max_wait_ms=60_000.0)
    svc.start()
    df = case.abnormal.copy()
    df["startTime"] = df["startTime"].astype(str)
    df["endTime"] = df["endTime"].astype(str)
    records = df.to_dict("records")
    futs = [
        svc.submit(
            RankRequest(
                request_id=f"r{i}", tenant=f"t{i}", spans=records
            )
        )
        for i in range(2)
    ]
    # Wait until both are built and PARKED (no dispatch: 60s max_wait).
    deadline = time.monotonic() + 30
    while (
        svc.scheduler.batcher.pending() < 2
        and time.monotonic() < deadline
    ):
        time.sleep(0.02)
    assert svc.scheduler.batcher.pending() == 2
    assert svc.scheduler.batcher.dispatches == 0
    svc.shutdown(drain=True)
    for f in futs:
        result = f.result(timeout=60)
        assert result.ranking and result.batch_windows == 2
    assert not svc.scheduler.is_alive()


def test_shutdown_without_drain_fails_queued_fast(case, registry):
    svc = _service(case)
    svc.start()
    # Stop the scheduler from consuming by enqueueing AFTER stop began:
    # drain=False fails queued entries instead of ranking them.
    svc.scheduler.stop(drain=False, timeout=30)
    fut = svc.scheduler.submit(
        RankRequest(request_id="late", tenant="t", spans=[{"a": 1}])
    )
    from microrank_tpu.serve import ShutdownError

    with pytest.raises(ShutdownError):
        fut.result(timeout=10)


# ------------------------------------------------------------- CLI smoke


def test_serve_cli_sigterm_drains(tmp_path):
    """End to end through the CLI: start `cli serve`, POST one window
    over HTTP, SIGTERM the process, expect a clean drain (exit 0) with
    journal + metrics snapshot written."""
    case = generate_case(
        SyntheticConfig(n_operations=16, n_traces=80, seed=3)
    )
    normal_csv = tmp_path / "normal.csv"
    case.normal.to_csv(normal_csv, index=False)
    abnormal_csv = tmp_path / "abnormal.csv"
    case.abnormal.to_csv(abnormal_csv, index=False)
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    out_dir = tmp_path / "serve_out"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(Path(__file__).parent.parent),
    }
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "microrank_tpu.cli", "serve",
            "--normal", str(normal_csv),
            "--dataset", f"case={abnormal_csv}",
            "--port", str(port),
            "-o", str(out_dir),
            "--no-warmup",
            "--max-wait-ms", "50",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.monotonic() + 120
        up = False
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            try:
                status, _ = _get(port, "/healthz")
                up = status == 200
                break
            except (urllib.error.URLError, ConnectionError, OSError):
                time.sleep(0.25)
        assert up, (proc.poll(), proc.stdout and "server never came up")
        status, body, _ = _post(port, {"dataset": "case"}, timeout=120)
        assert status == 200 and body["ranking"]
        status, prom = _get(port, "/metrics")
        assert b"microrank_serve_requests_total" in prom
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0, out[-2000:]
        assert "drained" in out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
    assert (out_dir / "journal.jsonl").exists()
    assert (out_dir / "metrics.json").exists()
    events = [
        json.loads(line)
        for line in (out_dir / "journal.jsonl").read_text().splitlines()
    ]
    assert events[0]["event"] == "run_start"
    assert events[-1]["event"] == "run_end"
    assert any(e["event"] == "serve_batch" for e in events)


# ------------------------------------------------- build pool + warmup


def test_builds_run_off_scheduler_thread(case, spans_payload, registry):
    """Satellite: the scheduler routes host graph builds through the
    shared build worker pool (stream.pool), so request-path builds
    overlap device dispatch instead of serializing on the scheduler
    thread."""
    svc = _service(case, max_wait_ms=50.0)
    svc.start()
    handle = ServeHandle(svc)
    port = handle.start()
    try:
        status, body, _ = _post(port, spans_payload)
        assert status == 200 and body["ranking"]
        assert svc.build_pool is not None
        assert svc.build_pool.builds >= 1
        # Every build ran on a pool worker, never the scheduler thread.
        assert svc.scheduler.ident not in svc.build_pool.build_threads
    finally:
        handle.stop()


def test_serial_builds_without_pool_still_serve(
    case, spans_payload, registry
):
    svc = _service(case, max_wait_ms=50.0, build_workers=0)
    assert svc.build_pool is None
    svc.start()
    handle = ServeHandle(svc)
    port = handle.start()
    try:
        status, body, _ = _post(port, spans_payload)
        assert status == 200 and body["ranking"]
    finally:
        handle.stop()


def test_warmup_occupancies_configurable(
    case, registry, tmp_path, monkeypatch
):
    # Hermetic cache dir: the shared manifest now also carries
    # production pad-bucket shapes recorded by every serve dispatch
    # (shape-faithful warmup), which would add replay dispatches here.
    monkeypatch.setenv("MICRORANK_JIT_CACHE", str(tmp_path / "jit"))
    svc = _service(
        case,
        warmup=True,
        warmup_occupancies=(1,),
        max_batch_windows=4,
    )
    svc.start()
    try:
        # Exactly one warmup dispatch (occupancy 1) instead of the old
        # hardcoded {1, 2}. Warmup goes through the router directly
        # (PR 5), so the router counts it; the batcher never sees it.
        assert svc.router.dispatches == 1
        assert svc.scheduler.batcher.dispatches == 0
    finally:
        svc.shutdown()


def test_warmup_occupancies_validated_against_max_batch(case, registry):
    svc = _service(
        case,
        warmup=True,
        warmup_occupancies=(1, 9),
        max_batch_windows=4,
    )
    with pytest.raises(ValueError, match="warmup_occupancies"):
        svc.start()
    svc.shutdown()
