"""Streaming RCA engine (stream/): event-time windower edge cases
(out-of-order within lateness, late-drop counting, empty windows,
sliding overlap), online SLO baselines (EW moments, P^2 quantiles,
freeze semantics), incident lifecycle (tie-aware fingerprints, dedup,
resolve, cooldown suppression), the build worker pool, and the
end-to-end acceptance run: a synthetic paced source with one injected
fault window ranks ONLY abnormal windows (gated dispatches < windows),
opens exactly one fingerprint-deduped incident with the fault in its
top-5, and resolves it after recovery. All on CPU jax.
"""

import json
import os
import subprocess
import sys
import threading
from pathlib import Path

import numpy as np
import pandas as pd
import pytest

from microrank_tpu.config import MicroRankConfig, StreamConfig
from microrank_tpu.obs import MetricsRegistry, get_registry, set_registry
from microrank_tpu.stream import (
    BuildWorkerPool,
    FileTailSource,
    IncidentTracker,
    OnlineBaseline,
    P2Quantile,
    ReplaySource,
    StreamEngine,
    StreamWindower,
    SyntheticSource,
    WebhookIncidentSink,
    ranking_fingerprint,
)
from microrank_tpu.testing import SyntheticConfig, generate_case

T0 = pd.Timestamp("2025-03-01 00:00:00")


@pytest.fixture
def registry():
    old = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(old)


def _spans(*offsets_s, tag="s"):
    """Minimal span frame for windower tests: startTime only matters."""
    return pd.DataFrame(
        {
            "traceID": [f"{tag}{i}" for i in range(len(offsets_s))],
            "startTime": [
                T0 + pd.Timedelta(seconds=o) for o in offsets_s
            ],
            "off": list(offsets_s),
        }
    )


# ----------------------------------------------------------- windower


def test_windower_tumbling_closes_in_order(registry):
    w = StreamWindower(width_us=60_000_000)
    first = w.add(_spans(10, 70))
    # Watermark 70 seals the epoch-aligned minute window [0,60) only.
    assert [c.start_us for c in first] == [int(T0.value // 1000)]
    assert sorted(first[0].frame["off"]) == [10]
    closed = w.add(_spans(130))
    assert [c.start_us for c in closed] == [
        int(T0.value // 1000) + 60_000_000,
    ]
    assert sorted(closed[0].frame["off"]) == [70]
    assert w.dropped_late == 0


def test_windower_out_of_order_within_lateness_lands_in_window(registry):
    w = StreamWindower(width_us=60_000_000, lateness_us=30_000_000)
    assert w.add(_spans(10, 80)) == []      # watermark 50: [0,60) open
    assert w.add(_spans(50, tag="late")) == []   # out of order, in bound
    closed = w.add(_spans(200))
    assert sorted(closed[0].frame["off"]) == [10, 50]
    assert w.dropped_late == 0


def test_windower_late_past_watermark_increments_dropped(registry):
    w = StreamWindower(width_us=60_000_000)
    w.add(_spans(10))
    w.add(_spans(130))                       # seals [0,60) and [60,120)
    closed = w.add(_spans(30, tag="late"))   # window long gone
    assert closed == []
    assert w.dropped_late == 1
    assert (
        registry.get("microrank_stream_late_spans_total").value() == 1
    )
    # The late span is nowhere: flush yields only the live window.
    left = w.flush()
    assert [sorted(c.frame["off"]) for c in left if c.frame is not None] == [
        [130]
    ]


def test_windower_emits_empty_windows_through_gaps(registry):
    w = StreamWindower(width_us=60_000_000)
    w.add(_spans(10))
    closed = w.add(_spans(400))              # gap: minutes 1..5 empty
    assert len(closed) == 6
    assert closed[0].n_spans == 1
    assert all(c.n_spans == 0 for c in closed[1:])
    assert all(c.frame is None for c in closed[1:])


def test_windower_sliding_span_lands_in_overlapping_windows(registry):
    w = StreamWindower(width_us=120_000_000, slide_us=60_000_000)
    w.add(_spans(70))
    closed = w.flush()
    hits = [c for c in closed if c.n_spans]
    # [0,120) and [60,180) both hold the span.
    assert [c.start_us for c in hits] == [
        int(T0.value // 1000),
        int(T0.value // 1000) + 60_000_000,
    ]


# ----------------------------------------------------------- baseline


def test_p2_quantile_tracks_numpy():
    rng = np.random.default_rng(0)
    xs = rng.lognormal(mean=1.0, sigma=0.6, size=5000)
    p2 = P2Quantile(0.9)
    for x in xs:
        p2.update(x)
    exact = float(np.quantile(xs, 0.9))
    assert abs(p2.value() - exact) / exact < 0.05


def test_p2_batch_update_matches_scalar():
    """The vectorized marker update (PR 5, the ROADMAP stream
    follow-up): window-shaped batches through update_batch must land on
    the same quantile as the per-sample scalar path — exactly through
    the 5-sample seed phase, and within a few percent of both the
    scalar estimator and the true quantile thereafter (the chunked
    batch form freezes marker heights within a chunk, so trajectories
    differ; destinations must not)."""
    rng = np.random.default_rng(3)
    # Seed-phase exactness: fewer than five samples is bit-identical.
    for n in (1, 3, 5):
        xs = rng.lognormal(size=n)
        a, b = P2Quantile(0.9), P2Quantile(0.9)
        for x in xs:
            a.update(x)
        b.update_batch(xs)
        assert a.value() == b.value()
        assert a.heights == b.heights and a.n == b.n
    for q in (0.5, 0.9, 0.99):
        xs = rng.lognormal(mean=1.0, sigma=0.6, size=6000)
        scalar, batch = P2Quantile(q), P2Quantile(q)
        for x in xs:
            scalar.update(x)
        # Feed window-sized batches — the engine's actual call shape.
        for lo in range(0, len(xs), 400):
            batch.update_batch(xs[lo : lo + 400])
        exact = float(np.quantile(xs, q))
        assert batch.n == scalar.n == len(xs)
        assert abs(batch.value() - exact) / exact < 0.08, q
        assert (
            abs(batch.value() - scalar.value())
            / max(abs(scalar.value()), 1e-12)
            < 0.08
        ), q


def test_online_baseline_batch_percentile_matches_scalar_loop():
    """OnlineBaseline.update now feeds P^2 via update_batch; the
    resulting percentile baseline must match a scalar-fed twin."""
    rng = np.random.default_rng(11)
    n = 500
    frame = _op_frame(1.0, n=n)
    frame["duration"] = (
        rng.lognormal(mean=2.0, sigma=0.5, size=n) * 1000
    ).astype(int)
    ob = OnlineBaseline(decay=0.5, slo_stat="p90")
    ob.update(frame)
    scalar = P2Quantile(0.9)
    for x in np.sort(frame["duration"].to_numpy()) / 1000.0:
        # any order works for the reference; use sorted for determinism
        scalar.update(x)
    _, base = ob.snapshot()
    assert (
        abs(base.mean_ms[0] - scalar.value())
        / max(abs(scalar.value()), 1e-12)
        < 0.15
    )


def _op_frame(dur_ms, op="opA", n=20, tag="t"):
    return pd.DataFrame(
        {
            "traceID": [f"{tag}{i}" for i in range(n)],
            "serviceName": ["svcA"] * n,
            "operationName": [op] * n,
            "duration": [int(dur_ms * 1000)] * n,
            "startTime": [T0] * n,
            "endTime": [T0] * n,
        }
    )


def test_online_baseline_updates_decay_and_freeze():
    ob = OnlineBaseline(decay=0.5, min_windows=1)
    ob.update(_op_frame(100.0))
    vocab, base = ob.snapshot()
    assert vocab.name(0) == "svcA_opA"
    assert base.mean_ms[0] == pytest.approx(100.0)
    ob.freeze()
    assert not ob.update(_op_frame(900.0))   # frozen: no poisoning
    _, base2 = ob.snapshot()
    assert base2.mean_ms[0] == pytest.approx(100.0)
    ob.thaw()
    ob.update(_op_frame(900.0))
    _, base3 = ob.snapshot()
    # EW with decay 0.5: halfway toward the new window mean.
    assert base3.mean_ms[0] == pytest.approx(500.0)
    assert ob.n_frozen_skips == 1


def test_online_baseline_seed_matches_batch_slo():
    from microrank_tpu.detect import compute_slo

    case = generate_case(
        SyntheticConfig(n_operations=16, n_traces=120, seed=4)
    )
    ob = OnlineBaseline(decay=0.2)
    ob.seed(case.normal)
    assert ob.ready
    vocab, base = ob.snapshot()
    bvocab, bbase = compute_slo(case.normal)
    assert vocab.names == bvocab.names
    np.testing.assert_allclose(
        base.mean_ms, bbase.mean_ms, rtol=1e-3, atol=1e-3
    )
    np.testing.assert_allclose(
        base.std_ms, bbase.std_ms, rtol=1e-3, atol=1e-3
    )


def test_online_baseline_percentile_stat():
    ob = OnlineBaseline(decay=0.5, slo_stat="p90")
    rng = np.random.default_rng(1)
    n = 400
    frame = _op_frame(1.0, n=n)
    dur_ms = rng.lognormal(mean=2.0, sigma=0.5, size=n)
    frame["duration"] = (dur_ms * 1000).astype(int)
    ob.update(frame)
    _, base = ob.snapshot()
    exact = float(np.quantile(frame["duration"] / 1000.0, 0.9))
    assert abs(base.mean_ms[0] - exact) / exact < 0.15


# ---------------------------------------------------------- incidents


def test_ranking_fingerprint_expands_exact_ties():
    ranking = [
        ("a", 1.0), ("b", 0.9), ("c", 0.5), ("d", 0.5), ("e", 0.5),
        ("f", 0.4),
    ]
    assert ranking_fingerprint(ranking, 3) == frozenset("abcde")
    assert ranking_fingerprint(ranking, 6) == frozenset("abcdef")
    assert ranking_fingerprint([], 5) == frozenset()


def test_incident_tracker_open_update_resolve_cooldown(registry):
    events = []

    class Sink:
        def emit(self, e):
            events.append(e)

    tr = IncidentTracker(
        top_k=3, resolve_after=2, cooldown_windows=2, sinks=[Sink()]
    )
    rank = [("a", 1.0), ("b", 0.8), ("c", 0.6)]
    inc = tr.observe_ranked("w1", rank)
    assert inc is not None and tr.has_open and tr.opened == 1
    # Consecutive window, same fingerprint: dedup into the SAME incident.
    assert tr.observe_ranked("w2", rank).incident_id == inc.incident_id
    assert tr.opened == 1 and inc.windows == 2
    # One healthy window is not enough to resolve.
    assert tr.observe_healthy("w3") == []
    assert tr.has_open
    resolved = tr.observe_healthy("w4")
    assert [i.incident_id for i in resolved] == [inc.incident_id]
    assert not tr.has_open and tr.resolved == 1
    # Re-flag inside the cooldown: suppressed, not reopened.
    assert tr.observe_ranked("w5", rank) is None
    assert tr.suppressed == 1 and tr.opened == 1
    # Past the cooldown: a fresh incident opens.
    tr.observe_healthy("w6")
    tr.observe_healthy("w7")
    inc2 = tr.observe_ranked("w8", rank)
    assert inc2 is not None and inc2.incident_id != inc.incident_id
    kinds = [e["event"] for e in events]
    assert kinds == [
        "incident_open", "incident_update", "incident_resolve",
        "incident_open",
    ]


def test_incident_tracker_jaccard_dedups_tail_wobble(registry):
    tr = IncidentTracker(top_k=5, resolve_after=2, jaccard=0.5)
    inc = tr.observe_ranked(
        "w1", [("a", 1.0), ("b", 0.9), ("c", 0.8), ("d", 0.7), ("e", 0.6)]
    )
    # Same fault, wobbled tail: 4/6 Jaccard overlap -> same incident.
    same = tr.observe_ranked(
        "w2", [("a", 1.0), ("b", 0.9), ("c", 0.8), ("d", 0.7), ("x", 0.6)]
    )
    assert same.incident_id == inc.incident_id
    # A disjoint suspect set is a DIFFERENT incident.
    other = tr.observe_ranked(
        "w3", [("p", 1.0), ("q", 0.9), ("r", 0.8), ("s", 0.7), ("t", 0.6)]
    )
    assert other.incident_id != inc.incident_id
    assert tr.opened == 2


def test_incident_update_flags_score_drift(registry):
    """Drift-aware dedup (PR 5): same top-k suspect SET but a moved
    score vector -> the update event carries drifted:true instead of a
    silent dedup; a stable vector stays drifted:false."""
    events = []

    class Sink:
        def emit(self, e):
            events.append(e)

    tr = IncidentTracker(
        top_k=3, resolve_after=2, score_drift=0.25, sinks=[Sink()]
    )
    tr.observe_ranked("w1", [("a", 1.0), ("b", 0.8), ("c", 0.6)])
    # Same set, same shape: plain update.
    tr.observe_ranked("w2", [("a", 1.0), ("b", 0.81), ("c", 0.6)])
    # Same set, dominant suspect flipped: drifted update.
    inc = tr.observe_ranked("w3", [("b", 1.0), ("a", 0.4), ("c", 0.35)])
    assert tr.opened == 1 and inc.windows == 3
    assert inc.drift_events == 1
    kinds = [(e["event"], e.get("drifted")) for e in events]
    assert kinds == [
        ("incident_open", None),
        ("incident_update", False),
        ("incident_update", True),
    ]
    assert events[2]["score_drift"] >= 0.25
    # score_drift <= 0 disables flagging entirely.
    tr2 = IncidentTracker(top_k=3, score_drift=0.0, sinks=[])
    tr2.observe_ranked("w1", [("a", 1.0), ("b", 0.8)])
    inc2 = tr2.observe_ranked("w2", [("b", 1.0), ("a", 0.1)])
    assert inc2.drift_events == 0


def test_webhook_sink_counts_failures_without_raising():
    sink = WebhookIncidentSink(
        "http://127.0.0.1:9/nope", timeout=0.2
    )
    sink.emit({"event": "incident_open", "top": []})
    assert sink.failures == 1


# --------------------------------------------------------- build pool


def test_build_pool_runs_off_caller_thread(registry):
    pool = BuildWorkerPool(workers=2)
    try:
        fut = pool.submit(lambda: threading.get_ident())
        ident = fut.result(timeout=30)
        assert ident != threading.get_ident()
        assert ident in pool.build_threads
        boom = pool.submit(lambda: 1 / 0)
        assert isinstance(
            boom.exception(timeout=30), ZeroDivisionError
        )
        assert pool.inflight == 0 and pool.builds == 2
    finally:
        pool.shutdown()


# ------------------------------------------------------------ sources


def test_replay_source_chunks_in_event_order_and_paces():
    sleeps = []
    df = _spans(30, 10, 20, 40)
    src = ReplaySource(
        df, chunk_spans=2, pace_seconds=0.5, sleep=sleeps.append
    )
    chunks = list(src)
    assert [list(c["off"]) for c in chunks] == [[10, 20], [30, 40]]
    assert sleeps == [0.5]


def test_file_tail_source_yields_only_new_rows(tmp_path, registry):
    case = generate_case(
        SyntheticConfig(n_operations=10, n_traces=40, seed=2)
    )
    df = case.normal
    csv = tmp_path / "grow.csv"
    half = len(df) // 2
    df.iloc[:half].to_csv(csv, index=False)
    batches = []
    src = FileTailSource(csv, poll_seconds=0, max_polls=3, sleep=lambda s: None)
    it = iter(src)
    batches.append(next(it))
    df.iloc[half:].to_csv(csv, mode="a", header=False, index=False)
    batches.append(next(it))
    assert len(batches[0]) == half
    assert len(batches[1]) == len(df) - half
    assert registry.get("microrank_follow_polls_total").value() >= 2


# ------------------------------------------------------------- engine


def _engine_config(**stream_kw):
    stream_kw.setdefault("allowed_lateness_seconds", 5.0)
    return MicroRankConfig(stream=StreamConfig(**stream_kw))


def test_stream_engine_acceptance_gated_incident_lifecycle(
    registry, tmp_path
):
    """Acceptance: paced synthetic source, one injected fault window ->
    only abnormal windows rank (gated dispatches < windows), exactly one
    fingerprint-deduped incident opens with the fault op in its top-5,
    and it resolves after recovery."""
    src = SyntheticSource(
        n_windows=8,
        faulted=[3],
        synth_config=SyntheticConfig(
            n_operations=24, n_traces=200, n_kinds=16, seed=5
        ),
        pace_seconds=0.01,
        sleep=lambda s: None,
    )
    eng = StreamEngine(_engine_config(), src, out_dir=tmp_path)
    s = eng.run()
    assert s.windows == 8
    assert s.ranked == 1 and s.dispatches == 1
    assert s.clean == 7 and s.warmup == 0       # seeded baseline
    assert s.late_spans == 0
    assert s.incidents_opened == 1 and s.incidents_resolved == 1
    # The gate in /metrics: dispatch counter < window counter.
    dispatches = registry.get(
        "microrank_stream_dispatches_total"
    ).value()
    windows = sum(
        smp["value"]
        for smp in registry.get(
            "microrank_stream_windows_total"
        ).samples()
    )
    assert dispatches == 1 and dispatches < windows == 8
    # Incident log: one open with the fault in its top-5, one resolve.
    events = [
        json.loads(line)
        for line in (tmp_path / "incidents.jsonl")
        .read_text()
        .splitlines()
    ]
    assert [e["event"] for e in events] == [
        "incident_open", "incident_resolve",
    ]
    top5 = [n for n, _ in events[0]["top"][:5]]
    assert src.fault_pod_op in top5
    assert events[0]["incident_id"] == events[1]["incident_id"]
    # Journal: run envelopes, one window event per window, incidents.
    from microrank_tpu.obs import read_journal

    jev = read_journal(tmp_path / "journal.jsonl")
    assert jev[0]["event"] == "run_start"
    assert jev[0]["pipeline"] == "stream"
    assert len([e for e in jev if e["event"] == "window"]) == 8
    assert any(e["event"] == "incident_open" for e in jev)
    assert jev[-1]["event"] == "run_end"
    assert jev[-1]["dispatches"] == 1
    # Metrics snapshot written for offline `cli stats`.
    assert (tmp_path / "metrics.json").exists()
    # Ranked window results landed in the normal sink too.
    assert (tmp_path / "windows.jsonl").exists()


def test_stream_engine_empty_window_journals_without_dispatch(
    registry, tmp_path
):
    case = generate_case(
        SyntheticConfig(n_operations=12, n_traces=100, seed=6)
    )
    # Two clean windows with a one-window gap between them.
    shifted = case.normal.copy()
    for col in ("startTime", "endTime"):
        shifted[col] = shifted[col] + pd.Timedelta(minutes=10)
    shifted["traceID"] = "g" + shifted["traceID"].astype(str)
    frames = pd.concat(
        [case.normal, shifted], ignore_index=True
    )
    eng = StreamEngine(
        _engine_config(),
        ReplaySource(frames, chunk_spans=100_000),
        out_dir=tmp_path,
        normal_df=case.normal,
    )
    s = eng.run()
    assert s.empty == 1 and s.dispatches == 0 and s.ranked == 0
    from microrank_tpu.obs import read_journal

    empties = [
        e
        for e in read_journal(tmp_path / "journal.jsonl")
        if e["event"] == "window"
        and e.get("skipped_reason") == "empty_window"
    ]
    assert len(empties) == 1
    assert (
        registry.get("microrank_stream_windows_total").value(
            outcome="empty"
        )
        == 1
    )
    assert registry.get("microrank_stream_dispatches_total").value() == 0


def test_stream_engine_cold_start_warms_baseline(registry, tmp_path):
    case = generate_case(
        SyntheticConfig(n_operations=12, n_traces=100, seed=8)
    )
    eng = StreamEngine(
        _engine_config(min_healthy_windows=1),
        ReplaySource(case.normal, chunk_spans=100_000),
        out_dir=tmp_path,
    )
    s = eng.run()
    # Unseeded: the first window feeds the baseline instead of detecting.
    assert s.warmup == 1 and s.dispatches == 0
    assert eng.baseline.ready


# ---------------------------------------------------------- CLI smoke


def test_stream_cli_smoke(tmp_path):
    out_dir = tmp_path / "stream_out"
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(Path(__file__).parent.parent),
    }
    proc = subprocess.run(
        [
            sys.executable, "-m", "microrank_tpu.cli", "stream",
            "--source", "synthetic",
            "--windows", "6", "--fault-windows", "2",
            "--operations", "16", "--traces", "120", "--kinds", "12",
            "--seed", "9", "--lateness-seconds", "5",
            "-o", str(out_dir),
        ],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "stream done" in proc.stderr or "stream done" in proc.stdout
    events = [
        json.loads(line)
        for line in (out_dir / "incidents.jsonl")
        .read_text()
        .splitlines()
    ]
    kinds = [e["event"] for e in events]
    assert "incident_open" in kinds and "incident_resolve" in kinds
    assert (out_dir / "metrics.json").exists()
    assert (out_dir / "journal.jsonl").exists()
